"""Sparse NDArray storage types (reference: python/mxnet/ndarray/sparse.py +
the stype machinery in include/mxnet/ndarray.h — SURVEY.md §2.1).

Two formats, as in the reference:
- ``CSRNDArray`` — compressed sparse row (data/indices/indptr), the LibSVM
  dataset format; used for sparse features and sparse dot.
- ``RowSparseNDArray`` — a subset of rows present (data/indices), the
  gradient format of large embeddings; powers lazy optimizer updates that
  touch only the rows a batch used.

TPU-native design: XLA has no first-class CSR kernels, so compute maps to
what the hardware likes — ``dot(csr, dense)`` lowers through
``jax.experimental.sparse.BCOO`` (which XLA turns into gather+segment-sum),
row_sparse optimizer updates are pure scatter ops on the dense weight
(HBM-bandwidth proportional to touched rows, the exact benefit the
reference's row_sparse kernels deliver), and everything else densifies
explicitly — never silently: ``tostype`` is the only densification door,
matching the reference's storage-fallback warnings.
"""
from __future__ import annotations

from typing import Optional, Tuple, Union

import numpy as _np

from .base import MXNetError
from .context import Context, current_context
from .ndarray import NDArray, array as nd_array

__all__ = ["CSRNDArray", "RowSparseNDArray", "BaseSparseNDArray",
           "csr_matrix", "row_sparse_array", "zeros", "dot", "retain",
           "cast_storage", "add", "elemwise_add"]


class BaseSparseNDArray:
    """Common surface of the sparse storage types."""

    stype = "undefined"

    def __init__(self, shape: Tuple[int, ...], dtype, ctx: Context):
        self._shape = tuple(int(s) for s in shape)
        self._dtype = _np.dtype(dtype)
        self._ctx = ctx

    @property
    def shape(self) -> Tuple[int, ...]:
        return self._shape

    @property
    def dtype(self):
        return self._dtype

    @property
    def context(self) -> Context:
        return self._ctx

    @property
    def ndim(self) -> int:
        return len(self._shape)

    def asnumpy(self) -> _np.ndarray:
        return self.todense().asnumpy()

    def todense(self) -> NDArray:
        raise NotImplementedError

    def tostype(self, stype: str):
        if stype == "default":
            return self.todense()
        if stype == self.stype:
            return self
        return cast_storage(self, stype)

    def copyto(self, other):
        self.todense().copyto(other)

    def __repr__(self):
        return (f"<{type(self).__name__} {self._shape} "
                f"{self._dtype.name} @{self._ctx}>")


class CSRNDArray(BaseSparseNDArray):
    stype = "csr"

    def __init__(self, data, indices, indptr, shape, dtype=None, ctx=None):
        data = _np.asarray(data)
        dtype = dtype or data.dtype
        super().__init__(shape, dtype, ctx or current_context())
        if len(self._shape) != 2:
            raise MXNetError("CSRNDArray must be 2-D")
        self.data = _np.asarray(data, dtype=dtype)
        self.indices = _np.asarray(indices, dtype=_np.int64)
        self.indptr = _np.asarray(indptr, dtype=_np.int64)
        if len(self.indptr) != self._shape[0] + 1:
            raise MXNetError(
                f"indptr length {len(self.indptr)} != rows+1 "
                f"({self._shape[0] + 1})")

    @property
    def nnz(self) -> int:
        return int(self.data.size)

    @staticmethod
    def from_dense(arr: NDArray) -> "CSRNDArray":
        # single vectorized pass — this sits on the LibSVMIter hot path
        a = arr.asnumpy()
        rows, cols = a.shape
        r_idx, c_idx = _np.nonzero(a)           # row-major order
        indptr = _np.concatenate(
            [[0], _np.cumsum(_np.bincount(r_idx, minlength=rows))])
        return CSRNDArray(a[r_idx, c_idx], c_idx, indptr, a.shape,
                          ctx=arr.context)

    def todense(self) -> NDArray:
        out = _np.zeros(self._shape, dtype=self._dtype)
        row_ids = _np.repeat(_np.arange(self._shape[0]),
                             _np.diff(self.indptr))
        out[row_ids, self.indices] = self.data
        return nd_array(out, ctx=self._ctx)

    def _to_bcoo(self):
        from jax.experimental import sparse as jsparse
        import jax.numpy as jnp
        row_ids = _np.repeat(_np.arange(self._shape[0]),
                             _np.diff(self.indptr))
        idx = _np.stack([row_ids, self.indices], axis=1)
        return jsparse.BCOO((jnp.asarray(self.data), jnp.asarray(idx)),
                            shape=self._shape)

    def asscipy(self):
        from scipy.sparse import csr_matrix as sp_csr
        return sp_csr((self.data, self.indices, self.indptr),
                      shape=self._shape)

    def __getitem__(self, key) -> "CSRNDArray":
        if isinstance(key, slice):
            start, stop, step = key.indices(self._shape[0])
            if step != 1:
                raise MXNetError("CSRNDArray slicing requires step 1")
            stop = max(stop, start)
            lo, hi = self.indptr[start], self.indptr[stop]
            return CSRNDArray(self.data[lo:hi], self.indices[lo:hi],
                              self.indptr[start:stop + 1] - lo,
                              (stop - start, self._shape[1]),
                              ctx=self._ctx)
        raise MXNetError("CSRNDArray supports row-slice indexing only")


class RowSparseNDArray(BaseSparseNDArray):
    stype = "row_sparse"

    def __init__(self, data, indices, shape, dtype=None, ctx=None):
        data = _np.asarray(data)
        dtype = dtype or data.dtype
        super().__init__(shape, dtype, ctx or current_context())
        self.data = _np.asarray(data, dtype=dtype)
        self.indices = _np.asarray(indices, dtype=_np.int64)
        if self.data.shape[0] != self.indices.shape[0]:
            raise MXNetError("data rows must match indices length")

    @staticmethod
    def from_dense(arr: NDArray) -> "RowSparseNDArray":
        a = arr.asnumpy()
        nz_rows = _np.nonzero(_np.any(
            a.reshape(a.shape[0], -1) != 0, axis=1))[0]
        return RowSparseNDArray(a[nz_rows], nz_rows, a.shape,
                                ctx=arr.context)

    def todense(self) -> NDArray:
        out = _np.zeros(self._shape, dtype=self._dtype)
        out[self.indices] = self.data
        return nd_array(out, ctx=self._ctx)

    def retain(self, indices) -> "RowSparseNDArray":
        keep = _np.asarray(indices, dtype=_np.int64)
        mask = _np.isin(self.indices, keep)
        return RowSparseNDArray(self.data[mask], self.indices[mask],
                                self._shape, ctx=self._ctx)


# ---------------------------------------------------------------------------
# constructors (reference: mx.nd.sparse.csr_matrix / row_sparse_array)
# ---------------------------------------------------------------------------

def csr_matrix(arg1, shape=None, ctx=None, dtype=None) -> CSRNDArray:
    if isinstance(arg1, CSRNDArray):
        return arg1
    if isinstance(arg1, NDArray):
        return CSRNDArray.from_dense(arg1)
    if isinstance(arg1, _np.ndarray):
        return CSRNDArray.from_dense(nd_array(arg1, ctx=ctx))
    if isinstance(arg1, tuple) and len(arg1) == 3:
        data, indices, indptr = arg1
        if shape is None:
            raise MXNetError("shape required for (data, indices, indptr)")
        return CSRNDArray(data, indices, indptr, shape, dtype=dtype,
                          ctx=ctx)
    raise MXNetError("unsupported csr_matrix argument")


def row_sparse_array(arg1, shape=None, ctx=None,
                     dtype=None) -> RowSparseNDArray:
    if isinstance(arg1, RowSparseNDArray):
        return arg1
    if isinstance(arg1, NDArray):
        return RowSparseNDArray.from_dense(arg1)
    if isinstance(arg1, _np.ndarray):
        return RowSparseNDArray.from_dense(nd_array(arg1, ctx=ctx))
    if isinstance(arg1, tuple) and len(arg1) == 2:
        data, indices = arg1
        if shape is None:
            raise MXNetError("shape required for (data, indices)")
        return RowSparseNDArray(data, indices, shape, dtype=dtype, ctx=ctx)
    raise MXNetError("unsupported row_sparse_array argument")


def zeros(stype: str, shape, ctx=None, dtype=_np.float32):
    shape = tuple(shape)
    if stype == "csr":
        return CSRNDArray(_np.zeros(0, dtype), _np.zeros(0, _np.int64),
                          _np.zeros(shape[0] + 1, _np.int64), shape,
                          ctx=ctx)
    if stype == "row_sparse":
        return RowSparseNDArray(_np.zeros((0,) + shape[1:], dtype),
                                _np.zeros(0, _np.int64), shape, ctx=ctx)
    if stype == "default":
        from .ndarray import zeros as nd_zeros
        return nd_zeros(shape, ctx=ctx, dtype=dtype)
    raise MXNetError(f"unknown stype {stype!r}")


# ---------------------------------------------------------------------------
# ops
# ---------------------------------------------------------------------------

def cast_storage(arr, stype: str):
    """reference: cast_storage op (src/operator/tensor/cast_storage.cc)."""
    if stype == "default":
        return arr.todense() if isinstance(arr, BaseSparseNDArray) else arr
    dense = arr if isinstance(arr, NDArray) else arr.todense()
    if stype == "csr":
        return CSRNDArray.from_dense(dense)
    if stype == "row_sparse":
        return RowSparseNDArray.from_dense(dense)
    raise MXNetError(f"unknown stype {stype!r}")


def dot(lhs, rhs, transpose_a: bool = False,
        transpose_b: bool = False):
    """Sparse-aware dot.  csr×dense runs through BCOO (XLA gather+segsum);
    csr^T×dense produces the row_sparse result shape the reference's
    sparse-embedding training relies on."""
    import jax.numpy as jnp
    if isinstance(lhs, CSRNDArray) and isinstance(rhs, NDArray):
        mat = lhs._to_bcoo()
        if transpose_a:
            mat = mat.T
        rv = rhs._read()
        if transpose_b:
            rv = rv.T
        return NDArray(mat @ rv, ctx=rhs.context)
    if isinstance(lhs, NDArray) and isinstance(rhs, CSRNDArray):
        lv = lhs._read()
        if transpose_a:
            lv = lv.T
        # dense @ csr == (csr^T @ dense^T)^T, keeping the sparse operand
        # on the left of the BCOO matmul
        mat = rhs._to_bcoo()
        mat = mat if transpose_b else mat.T
        return NDArray((mat @ lv.T).T, ctx=lhs.context)
    if isinstance(lhs, NDArray) and isinstance(rhs, NDArray):
        from .ndarray import dot as nd_dot
        return nd_dot(lhs, rhs, transpose_a=transpose_a,
                      transpose_b=transpose_b)
    raise MXNetError(
        f"unsupported dot storage types {type(lhs)}/{type(rhs)}")


def retain(arr: RowSparseNDArray, indices) -> RowSparseNDArray:
    """reference: _sparse_retain."""
    if not isinstance(arr, RowSparseNDArray):
        raise MXNetError("retain expects a RowSparseNDArray")
    if isinstance(indices, NDArray):
        indices = indices.asnumpy()
    return arr.retain(indices)


def elemwise_add(lhs, rhs):
    if isinstance(lhs, RowSparseNDArray) and isinstance(rhs, NDArray):
        out = rhs.asnumpy().copy()
        _np.add.at(out, lhs.indices, lhs.data)
        return nd_array(out, ctx=rhs.context)
    if isinstance(rhs, RowSparseNDArray) and isinstance(lhs, NDArray):
        return elemwise_add(rhs, lhs)
    if isinstance(lhs, RowSparseNDArray) and \
            isinstance(rhs, RowSparseNDArray):
        # vectorized: union1d is sorted, so positions come from searchsorted
        idx = _np.union1d(lhs.indices, rhs.indices)
        data = _np.zeros((len(idx),) + lhs.data.shape[1:], lhs.data.dtype)
        for src in (lhs, rhs):
            _np.add.at(data, _np.searchsorted(idx, src.indices), src.data)
        return RowSparseNDArray(data, idx, lhs.shape, ctx=lhs.context)
    from .ndarray.register import invoke_by_name
    return invoke_by_name("broadcast_add", [lhs, rhs], {})


add = elemwise_add
