"""Sparse NDArray storage types (reference: python/mxnet/ndarray/sparse.py +
the stype machinery in include/mxnet/ndarray.h — SURVEY.md §2.1).

Two formats, as in the reference:
- ``CSRNDArray`` — compressed sparse row (data/indices/indptr), the LibSVM
  dataset format; used for sparse features and sparse dot.
- ``RowSparseNDArray`` — a subset of rows present (data/indices), the
  gradient format of large embeddings; powers lazy optimizer updates that
  touch only the rows a batch used.

TPU-native design: XLA has no first-class CSR kernels, so compute maps to
what the hardware likes — ``dot(csr, dense)`` lowers through
``jax.experimental.sparse.BCOO`` (which XLA turns into gather+segment-sum),
row_sparse optimizer updates are pure scatter ops on the dense weight
(HBM-bandwidth proportional to touched rows, the exact benefit the
reference's row_sparse kernels deliver), and everything else densifies
explicitly — never silently: ``tostype`` is the only densification door,
matching the reference's storage-fallback warnings.

Storage backing: ``RowSparseNDArray`` data/indices are DEVICE arrays and
its elemwise/retain/todense paths run as eager jax ops — the gradient
fast path (gluon Trainer sparse exchange, lazy optimizer updates) never
round-trips through host numpy.  CSR structure stays host-side
(numpy/scipy — structure algebra is host work, exactly the reference's
cpu FComputeEx role); the single sanctioned device→host sync for
building CSR structure from dense operands is :func:`_host_ingest`.
"""
from __future__ import annotations

from typing import Optional, Tuple, Union

import numpy as _np

from .base import MXNetError
from .context import Context, current_context
from .ndarray import NDArray, array as nd_array

__all__ = ["CSRNDArray", "RowSparseNDArray", "BaseSparseNDArray",
           "csr_matrix", "row_sparse_array", "zeros", "dot", "retain",
           "cast_storage", "add", "elemwise_add", "elemwise_sub",
           "elemwise_mul", "elemwise_div", "subtract", "multiply", "divide",
           "minimum", "maximum", "sqrt", "square", "abs", "sign", "relu",
           "sin", "tanh", "ceil", "floor", "trunc", "rint", "expm1",
           "log1p", "negative"]


class BaseSparseNDArray:
    """Common surface of the sparse storage types."""

    stype = "undefined"

    def __init__(self, shape: Tuple[int, ...], dtype, ctx: Context):
        self._shape = tuple(int(s) for s in shape)
        self._dtype = _np.dtype(dtype)
        self._ctx = ctx

    @property
    def shape(self) -> Tuple[int, ...]:
        return self._shape

    @property
    def dtype(self):
        return self._dtype

    @property
    def context(self) -> Context:
        return self._ctx

    @property
    def ndim(self) -> int:
        return len(self._shape)

    def asnumpy(self) -> _np.ndarray:
        # the explicit export API — syncing is this method's contract
        return self.todense().asnumpy()  # mxlint: disable=hidden-host-sync — explicit host-export API

    def todense(self) -> NDArray:
        raise NotImplementedError

    def tostype(self, stype: str):
        if stype == "default":
            return self.todense()
        if stype == self.stype:
            return self
        return cast_storage(self, stype)

    def copyto(self, other):
        self.todense().copyto(other)

    def copy(self):
        raise NotImplementedError            # per-subclass deep copy

    def as_in_context(self, ctx):
        # sparse structure lives host-side; only the context tag moves
        if ctx == self._ctx:
            return self
        out = self.copy()
        out._ctx = ctx
        return out

    def __repr__(self):
        return (f"<{type(self).__name__} {self._shape} "
                f"{self._dtype.name} @{self._ctx}>")


    # arithmetic routes through the storage-aware elemwise family below
    # (reference: the stype-dispatched FComputeEx kernels of
    # elemwise_binary_op_basic.cc; scalars that break zero-preservation
    # densify explicitly, never silently)
    def __add__(self, other):
        return elemwise_add(self, other)

    def __radd__(self, other):
        return elemwise_add(self, other)

    def __sub__(self, other):
        return elemwise_sub(self, other)

    def __rsub__(self, other):
        return negative(elemwise_sub(self, other))

    def __mul__(self, other):
        return elemwise_mul(self, other)

    def __rmul__(self, other):
        return elemwise_mul(self, other)

    def __truediv__(self, other):
        return elemwise_div(self, other)

    def __rtruediv__(self, other):
        # scalar / sparse breaks zero-preservation (s/0 = inf) — densify
        # explicitly like the reference's _rdiv_scalar storage fallback
        return _dense_fallback("broadcast_div",
                               nd_array(_np.asarray(other,
                                                    dtype=self.dtype)),
                               self)

    def __neg__(self):
        return negative(self)


def _host_ingest(arr: NDArray) -> _np.ndarray:
    """The ONE sanctioned device→host sync of this module: CSR structure
    (indptr/indices algebra) is host work, so dense operands entering a
    CSR build or a CSR⊕dense elemwise cross here — every other sparse
    path stays on-device."""
    return arr.asnumpy()  # mxlint: disable=hidden-host-sync — CSR host-structure ingestion boundary


class CSRNDArray(BaseSparseNDArray):
    stype = "csr"

    def __init__(self, data, indices, indptr, shape, dtype=None, ctx=None):
        data = _np.asarray(data)
        dtype = dtype or data.dtype
        super().__init__(shape, dtype, ctx or current_context())
        if len(self._shape) != 2:
            raise MXNetError("CSRNDArray must be 2-D")
        self.data = _np.asarray(data, dtype=dtype)
        self.indices = _np.asarray(indices, dtype=_np.int64)
        self.indptr = _np.asarray(indptr, dtype=_np.int64)
        if len(self.indptr) != self._shape[0] + 1:
            raise MXNetError(
                f"indptr length {len(self.indptr)} != rows+1 "
                f"({self._shape[0] + 1})")

    @property
    def nnz(self) -> int:
        return int(self.data.size)

    @staticmethod
    def from_dense(arr: NDArray) -> "CSRNDArray":
        # single vectorized pass — this sits on the LibSVMIter hot path
        a = _host_ingest(arr)
        rows, cols = a.shape
        r_idx, c_idx = _np.nonzero(a)           # row-major order
        indptr = _np.concatenate(
            [[0], _np.cumsum(_np.bincount(r_idx, minlength=rows))])
        return CSRNDArray(a[r_idx, c_idx], c_idx, indptr, a.shape,
                          ctx=arr.context)

    def todense(self) -> NDArray:
        out = _np.zeros(self._shape, dtype=self._dtype)
        row_ids = _np.repeat(_np.arange(self._shape[0]),
                             _np.diff(self.indptr))
        out[row_ids, self.indices] = self.data
        return nd_array(out, ctx=self._ctx)

    def _to_bcoo(self):
        from jax.experimental import sparse as jsparse
        import jax.numpy as jnp
        row_ids = _np.repeat(_np.arange(self._shape[0]),
                             _np.diff(self.indptr))
        idx = _np.stack([row_ids, self.indices], axis=1)
        return jsparse.BCOO((jnp.asarray(self.data), jnp.asarray(idx)),
                            shape=self._shape)

    def asscipy(self):
        from scipy.sparse import csr_matrix as sp_csr
        return sp_csr((self.data, self.indices, self.indptr),
                      shape=self._shape)

    def copy(self) -> "CSRNDArray":
        return CSRNDArray(self.data.copy(), self.indices.copy(),
                          self.indptr.copy(), self._shape, ctx=self._ctx)

    def __getitem__(self, key) -> "CSRNDArray":
        if isinstance(key, slice):
            start, stop, step = key.indices(self._shape[0])
            if step != 1:
                raise MXNetError("CSRNDArray slicing requires step 1")
            stop = max(stop, start)
            lo, hi = self.indptr[start], self.indptr[stop]
            return CSRNDArray(self.data[lo:hi], self.indices[lo:hi],
                              self.indptr[start:stop + 1] - lo,
                              (stop - start, self._shape[1]),
                              ctx=self._ctx)
        raise MXNetError("CSRNDArray supports row-slice indexing only")


class RowSparseNDArray(BaseSparseNDArray):
    """Device-backed row-sparse storage: ``data`` ((nnz_rows,) + row
    shape) and ``indices`` ((nnz_rows,) int32) are jax arrays, so the
    gradient fast path — from_dense extraction, exchange, retain, the
    optimizer's lazy scatter — runs without a host round-trip.  jax
    arrays are immutable; derive new instances instead of writing
    ``.data`` in place."""

    stype = "row_sparse"

    def __init__(self, data, indices, shape, dtype=None, ctx=None):
        import jax.numpy as jnp
        data = jnp.asarray(data)
        dtype = dtype or data.dtype
        super().__init__(shape, dtype, ctx or current_context())
        self.data = jnp.asarray(data, dtype=_np.dtype(dtype))
        self.indices = jnp.asarray(indices, dtype=jnp.int32)
        if self.data.shape[0] != self.indices.shape[0]:
            raise MXNetError("data rows must match indices length")

    @staticmethod
    def from_dense(arr: NDArray) -> "RowSparseNDArray":
        import jax.numpy as jnp
        a = arr._read()
        nz_rows = jnp.nonzero(jnp.any(
            a.reshape(a.shape[0], -1) != 0, axis=1))[0]
        return RowSparseNDArray(jnp.take(a, nz_rows, axis=0), nz_rows,
                                a.shape, ctx=arr.context)

    def todense(self) -> NDArray:
        import jax.numpy as jnp
        out = jnp.zeros(self._shape, dtype=self._dtype)
        return NDArray(out.at[self.indices].set(self.data), ctx=self._ctx)

    def retain(self, indices) -> "RowSparseNDArray":
        import jax.numpy as jnp
        keep = jnp.asarray(indices, dtype=jnp.int32)
        mask = jnp.isin(self.indices, keep)
        return RowSparseNDArray(self.data[mask], self.indices[mask],
                                self._shape, ctx=self._ctx)

    def copy(self) -> "RowSparseNDArray":
        # jax buffers are immutable — sharing them IS a deep copy
        return RowSparseNDArray(self.data, self.indices,
                                self._shape, ctx=self._ctx)


# ---------------------------------------------------------------------------
# constructors (reference: mx.nd.sparse.csr_matrix / row_sparse_array)
# ---------------------------------------------------------------------------

def csr_matrix(arg1, shape=None, ctx=None, dtype=None) -> CSRNDArray:
    if isinstance(arg1, CSRNDArray):
        return arg1
    if isinstance(arg1, NDArray):
        return CSRNDArray.from_dense(arg1)
    if isinstance(arg1, _np.ndarray):
        return CSRNDArray.from_dense(nd_array(arg1, ctx=ctx))
    if isinstance(arg1, tuple) and len(arg1) == 3:
        data, indices, indptr = arg1
        if shape is None:
            raise MXNetError("shape required for (data, indices, indptr)")
        return CSRNDArray(data, indices, indptr, shape, dtype=dtype,
                          ctx=ctx)
    raise MXNetError("unsupported csr_matrix argument")


def row_sparse_array(arg1, shape=None, ctx=None,
                     dtype=None) -> RowSparseNDArray:
    if isinstance(arg1, RowSparseNDArray):
        return arg1
    if isinstance(arg1, NDArray):
        return RowSparseNDArray.from_dense(arg1)
    if isinstance(arg1, _np.ndarray):
        return RowSparseNDArray.from_dense(nd_array(arg1, ctx=ctx))
    if isinstance(arg1, tuple) and len(arg1) == 2:
        data, indices = arg1
        if shape is None:
            raise MXNetError("shape required for (data, indices)")
        return RowSparseNDArray(data, indices, shape, dtype=dtype, ctx=ctx)
    raise MXNetError("unsupported row_sparse_array argument")


def zeros(stype: str, shape, ctx=None, dtype=_np.float32):
    shape = tuple(shape)
    if stype == "csr":
        return CSRNDArray(_np.zeros(0, dtype), _np.zeros(0, _np.int64),
                          _np.zeros(shape[0] + 1, _np.int64), shape,
                          ctx=ctx)
    if stype == "row_sparse":
        return RowSparseNDArray(_np.zeros((0,) + shape[1:], dtype),
                                _np.zeros(0, _np.int64), shape, ctx=ctx)
    if stype == "default":
        from .ndarray import zeros as nd_zeros
        return nd_zeros(shape, ctx=ctx, dtype=dtype)
    raise MXNetError(f"unknown stype {stype!r}")


# ---------------------------------------------------------------------------
# ops
# ---------------------------------------------------------------------------

def cast_storage(arr, stype: str):
    """reference: cast_storage op (src/operator/tensor/cast_storage.cc)."""
    if stype == "default":
        return arr.todense() if isinstance(arr, BaseSparseNDArray) else arr
    dense = arr if isinstance(arr, NDArray) else arr.todense()
    if stype == "csr":
        return CSRNDArray.from_dense(dense)
    if stype == "row_sparse":
        return RowSparseNDArray.from_dense(dense)
    raise MXNetError(f"unknown stype {stype!r}")


def dot(lhs, rhs, transpose_a: bool = False,
        transpose_b: bool = False):
    """Sparse-aware dot.  csr×dense runs through BCOO (XLA gather+segsum);
    csr^T×dense produces the row_sparse result shape the reference's
    sparse-embedding training relies on."""
    import jax.numpy as jnp
    if isinstance(lhs, CSRNDArray) and isinstance(rhs, NDArray):
        mat = lhs._to_bcoo()
        if transpose_a:
            mat = mat.T
        rv = rhs._read()
        if transpose_b:
            rv = rv.T
        return NDArray(mat @ rv, ctx=rhs.context)
    if isinstance(lhs, NDArray) and isinstance(rhs, CSRNDArray):
        lv = lhs._read()
        if transpose_a:
            lv = lv.T
        # dense @ csr == (csr^T @ dense^T)^T, keeping the sparse operand
        # on the left of the BCOO matmul
        mat = rhs._to_bcoo()
        mat = mat if transpose_b else mat.T
        return NDArray((mat @ lv.T).T, ctx=lhs.context)
    if isinstance(lhs, NDArray) and isinstance(rhs, NDArray):
        from .ndarray import dot as nd_dot
        return nd_dot(lhs, rhs, transpose_a=transpose_a,
                      transpose_b=transpose_b)
    raise MXNetError(
        f"unsupported dot storage types {type(lhs)}/{type(rhs)}")


def retain(arr: RowSparseNDArray, indices) -> RowSparseNDArray:
    """reference: _sparse_retain."""
    if not isinstance(arr, RowSparseNDArray):
        raise MXNetError("retain expects a RowSparseNDArray")
    if isinstance(indices, NDArray):
        indices = indices._read()
    return arr.retain(indices)


def _from_scipy(sp, shape, ctx) -> CSRNDArray:
    sp = sp.tocsr()
    sp.sort_indices()
    return CSRNDArray(sp.data, sp.indices, sp.indptr, shape, ctx=ctx)


def _csr_csr(lhs: CSRNDArray, rhs: CSRNDArray, op: str) -> CSRNDArray:
    """csr ⊕ csr with a sparse result — structure algebra delegated to
    scipy on the host (the reference's cpu FComputeEx kernels are the same
    role: sparse structure work stays on the host/CPU side; only dense
    compute belongs on the TPU)."""
    a, b = lhs.asscipy(), rhs.asscipy()
    if op == "add":
        out = a + b
    elif op == "sub":
        out = a - b
    elif op == "mul":
        out = a.multiply(b)
    elif op == "maximum":
        out = a.maximum(b)
    elif op == "minimum":
        out = a.minimum(b)
    else:
        raise MXNetError(f"unsupported csr op {op!r}")
    return _from_scipy(out, lhs.shape, lhs.context)


def _rsp_union(lhs: RowSparseNDArray, rhs: RowSparseNDArray, rhs_sign=1.0):
    """row_sparse ⊕ row_sparse over the union of row sets (add/sub) —
    eager device ops end to end (union1d/searchsorted/scatter-add)."""
    import jax.numpy as jnp
    idx = jnp.union1d(lhs.indices, rhs.indices)
    data = jnp.zeros((idx.shape[0],) + lhs.data.shape[1:],
                     jnp.result_type(lhs.data, rhs.data))
    data = data.at[jnp.searchsorted(idx, lhs.indices)].add(lhs.data)
    data = data.at[jnp.searchsorted(idx, rhs.indices)].add(
        rhs_sign * rhs.data)
    return RowSparseNDArray(data, idx, lhs.shape, ctx=lhs.context)


def _rsp_pointwise(lhs: RowSparseNDArray, rhs: RowSparseNDArray, op: str,
                   intersect: bool):
    """mul/min/max on row_sparse pairs.  mul keeps only the row
    intersection (0·x = 0); min/max need the union with zero rows."""
    import jax.numpy as jnp
    fn = getattr(jnp, op)
    if intersect:
        common, li, ri = jnp.intersect1d(lhs.indices, rhs.indices,
                                         return_indices=True)
        return RowSparseNDArray(fn(lhs.data[li], rhs.data[ri]), common,
                                lhs.shape, ctx=lhs.context)
    idx = jnp.union1d(lhs.indices, rhs.indices)
    shape_tail = lhs.data.shape[1:]
    dt = jnp.result_type(lhs.data, rhs.data)
    a = jnp.zeros((idx.shape[0],) + shape_tail, dt)
    b = jnp.zeros((idx.shape[0],) + shape_tail, dt)
    a = a.at[jnp.searchsorted(idx, lhs.indices)].set(lhs.data)
    b = b.at[jnp.searchsorted(idx, rhs.indices)].set(rhs.data)
    return RowSparseNDArray(fn(a, b), idx, lhs.shape, ctx=lhs.context)


def _dense_fallback(name, lhs, rhs):
    """Explicit densification — mirrors the reference's storage-fallback
    log so silent dense blowups cannot hide (SURVEY.md §2.2 sparse note)."""
    import warnings
    warnings.warn(f"sparse {name}: falling back to dense storage",
                  stacklevel=3)
    dl = lhs.todense() if isinstance(lhs, BaseSparseNDArray) else lhs
    dr = rhs.todense() if isinstance(rhs, BaseSparseNDArray) else rhs
    from .ndarray.register import invoke_by_name
    return invoke_by_name(name, [dl, dr], {})


def _scalar_apply(arr, np_fn):
    if isinstance(arr, CSRNDArray):
        return CSRNDArray(np_fn(arr.data), arr.indices, arr.indptr,
                          arr.shape, ctx=arr.context)
    return RowSparseNDArray(np_fn(arr.data), arr.indices, arr.shape,
                            ctx=arr.context)


def _scalar_scale(arr, s):
    s = float(s)
    return _scalar_apply(arr, lambda d: d * s)


def elemwise_add(lhs, rhs):
    if isinstance(lhs, NDArray) and isinstance(rhs, BaseSparseNDArray):
        return elemwise_add(rhs, lhs)
    if isinstance(lhs, RowSparseNDArray) and isinstance(rhs, NDArray):
        # device scatter-add: rsp rows fold into the dense operand
        # without leaving the chip
        return NDArray(rhs._read().at[lhs.indices].add(lhs.data),
                       ctx=rhs.context)
    if isinstance(lhs, CSRNDArray) and isinstance(rhs, NDArray):
        out = _host_ingest(rhs).copy()
        row_ids = _np.repeat(_np.arange(lhs.shape[0]),
                             _np.diff(lhs.indptr))
        _np.add.at(out, (row_ids, lhs.indices), lhs.data)
        return nd_array(out, ctx=rhs.context)
    if isinstance(lhs, RowSparseNDArray) and \
            isinstance(rhs, RowSparseNDArray):
        return _rsp_union(lhs, rhs)
    if isinstance(lhs, CSRNDArray) and isinstance(rhs, CSRNDArray):
        return _csr_csr(lhs, rhs, "add")
    if isinstance(lhs, BaseSparseNDArray) and _np.isscalar(rhs):
        return _dense_fallback("_plus_scalar",
                               lhs, nd_array(_np.asarray(rhs)))
    from .ndarray.register import invoke_by_name
    return invoke_by_name("broadcast_add", [lhs, rhs], {})


def elemwise_sub(lhs, rhs):
    if isinstance(lhs, RowSparseNDArray) and \
            isinstance(rhs, RowSparseNDArray):
        return _rsp_union(lhs, rhs, rhs_sign=-1.0)
    if isinstance(lhs, CSRNDArray) and isinstance(rhs, CSRNDArray):
        return _csr_csr(lhs, rhs, "sub")
    if isinstance(lhs, BaseSparseNDArray) or \
            isinstance(rhs, BaseSparseNDArray):
        return _dense_fallback("broadcast_sub", lhs, rhs)
    from .ndarray.register import invoke_by_name
    return invoke_by_name("broadcast_sub", [lhs, rhs], {})


def elemwise_mul(lhs, rhs):
    if isinstance(lhs, NDArray) and isinstance(rhs, BaseSparseNDArray):
        return elemwise_mul(rhs, lhs)
    if isinstance(lhs, BaseSparseNDArray) and _np.isscalar(rhs):
        return _scalar_scale(lhs, rhs)
    if isinstance(lhs, RowSparseNDArray) and isinstance(rhs, NDArray):
        import jax.numpy as jnp
        d = rhs._read()
        return RowSparseNDArray(
            lhs.data * jnp.take(d, lhs.indices, axis=0), lhs.indices,
            lhs.shape, ctx=lhs.context)
    if isinstance(lhs, CSRNDArray) and isinstance(rhs, NDArray):
        d = _host_ingest(rhs)
        row_ids = _np.repeat(_np.arange(lhs.shape[0]),
                             _np.diff(lhs.indptr))
        return CSRNDArray(lhs.data * d[row_ids, lhs.indices], lhs.indices,
                          lhs.indptr, lhs.shape, ctx=lhs.context)
    if isinstance(lhs, RowSparseNDArray) and \
            isinstance(rhs, RowSparseNDArray):
        return _rsp_pointwise(lhs, rhs, "multiply", intersect=True)
    if isinstance(lhs, CSRNDArray) and isinstance(rhs, CSRNDArray):
        return _csr_csr(lhs, rhs, "mul")
    from .ndarray.register import invoke_by_name
    return invoke_by_name("broadcast_mul", [lhs, rhs], {})


def elemwise_div(lhs, rhs):
    if isinstance(lhs, BaseSparseNDArray) and _np.isscalar(rhs):
        # true division, not reciprocal-multiply: /0 must yield inf (the
        # reference _div_scalar contract) and rounding must match numpy
        s = float(rhs)

        def _div(d):
            with _np.errstate(divide="ignore", invalid="ignore"):
                return d / s
        return _scalar_apply(lhs, _div)
    if isinstance(lhs, RowSparseNDArray) and isinstance(rhs, NDArray):
        import jax.numpy as jnp
        d = rhs._read()
        return RowSparseNDArray(
            lhs.data / jnp.take(d, lhs.indices, axis=0), lhs.indices,
            lhs.shape, ctx=lhs.context)
    if isinstance(lhs, CSRNDArray) and isinstance(rhs, NDArray):
        d = _host_ingest(rhs)
        row_ids = _np.repeat(_np.arange(lhs.shape[0]),
                             _np.diff(lhs.indptr))
        return CSRNDArray(lhs.data / d[row_ids, lhs.indices], lhs.indices,
                          lhs.indptr, lhs.shape, ctx=lhs.context)
    if isinstance(lhs, BaseSparseNDArray) or \
            isinstance(rhs, BaseSparseNDArray):
        # 0/0 territory — the reference densifies here too
        return _dense_fallback("broadcast_div", lhs, rhs)
    from .ndarray.register import invoke_by_name
    return invoke_by_name("broadcast_div", [lhs, rhs], {})


def minimum(lhs, rhs):
    if isinstance(lhs, RowSparseNDArray) and \
            isinstance(rhs, RowSparseNDArray):
        return _rsp_pointwise(lhs, rhs, "minimum", intersect=False)
    if isinstance(lhs, CSRNDArray) and isinstance(rhs, CSRNDArray):
        return _csr_csr(lhs, rhs, "minimum")
    if isinstance(lhs, BaseSparseNDArray) or \
            isinstance(rhs, BaseSparseNDArray):
        return _dense_fallback("broadcast_minimum", lhs, rhs)
    from .ndarray.register import invoke_by_name
    return invoke_by_name("broadcast_minimum", [lhs, rhs], {})


def maximum(lhs, rhs):
    if isinstance(lhs, RowSparseNDArray) and \
            isinstance(rhs, RowSparseNDArray):
        return _rsp_pointwise(lhs, rhs, "maximum", intersect=False)
    if isinstance(lhs, CSRNDArray) and isinstance(rhs, CSRNDArray):
        return _csr_csr(lhs, rhs, "maximum")
    if isinstance(lhs, BaseSparseNDArray) or \
            isinstance(rhs, BaseSparseNDArray):
        return _dense_fallback("broadcast_maximum", lhs, rhs)
    from .ndarray.register import invoke_by_name
    return invoke_by_name("broadcast_maximum", [lhs, rhs], {})


add = elemwise_add
subtract = elemwise_sub
multiply = elemwise_mul
divide = elemwise_div


# ---------------------------------------------------------------------------
# zero-preserving unary family (reference: the FComputeEx registrations of
# elemwise_unary_op_basic.cc — f(0)=0 ops keep the sparse structure and
# apply to stored values only)
# ---------------------------------------------------------------------------

def _unary_sparse(op_name: str, np_fn):
    def fn(arr):
        if isinstance(arr, CSRNDArray):
            return CSRNDArray(np_fn(arr.data), arr.indices, arr.indptr,
                              arr.shape, ctx=arr.context)
        if isinstance(arr, RowSparseNDArray):
            # rsp values live on device: resolve the jnp twin so the op
            # stays on-chip instead of bouncing through numpy
            import jax.numpy as jnp
            if op_name == "relu":
                jfn = lambda d: jnp.maximum(d, 0)  # noqa: E731
            else:
                jfn = getattr(jnp, op_name)
            return RowSparseNDArray(jfn(arr.data), arr.indices,
                                    arr.shape, ctx=arr.context)
        from .ndarray.register import invoke_by_name
        return invoke_by_name(op_name, [arr], {})
    fn.__name__ = op_name
    return fn


sqrt = _unary_sparse("sqrt", _np.sqrt)
square = _unary_sparse("square", _np.square)
abs = _unary_sparse("abs", _np.abs)            # noqa: A001 — reference name
sign = _unary_sparse("sign", _np.sign)
relu = _unary_sparse("relu", lambda d: _np.maximum(d, 0))
sin = _unary_sparse("sin", _np.sin)
tanh = _unary_sparse("tanh", _np.tanh)
ceil = _unary_sparse("ceil", _np.ceil)
floor = _unary_sparse("floor", _np.floor)
trunc = _unary_sparse("trunc", _np.trunc)
rint = _unary_sparse("rint", _np.rint)
expm1 = _unary_sparse("expm1", _np.expm1)
log1p = _unary_sparse("log1p", _np.log1p)
negative = _unary_sparse("negative", _np.negative)
