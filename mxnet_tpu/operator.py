"""Custom operators (reference: python/mxnet/operator.py +
src/operator/custom/custom.cc — SURVEY.md §2.2).

``CustomOp``/``CustomOpProp`` + ``register`` reproduce the reference's
Python-callback custom-op surface; ``mx.nd.Custom(..., op_type=name)``
invokes them.  The reference ran these callbacks on a dedicated engine
thread to keep the async engine flowing; here imperative execution is
already eager-with-async-buffers, so the callback runs inline under
``autograd.pause()`` and registers a tape node whose vjp calls the user's
``backward`` — identical autograd semantics.  (For a jit-compatible custom
op use ``jax.pure_callback`` or a Pallas kernel via mx.rtc instead.)
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence

from .base import MXNetError
from . import autograd as _ag

__all__ = ["CustomOp", "CustomOpProp", "register", "get_all_registered",
           "Custom"]

_custom_registry: Dict[str, type] = {}


class CustomOp:
    """User kernel: implement forward/backward with self.assign."""

    def assign(self, dst, req: str, src) -> None:
        """Write src into dst honoring the grad_req (reference helper)."""
        if req in ("null", None):
            return
        if req == "add":
            dst += src
        else:                      # 'write' / 'inplace'
            dst._set_data(src._read() if hasattr(src, "_read") else src)

    def forward(self, is_train, req, in_data, out_data, aux):
        raise NotImplementedError

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        raise NotImplementedError


class CustomOpProp:
    """Shape/type metadata + operator factory (reference: CustomOpProp)."""

    def __init__(self, need_top_grad: bool = True):
        self.need_top_grad_ = need_top_grad

    def list_arguments(self) -> List[str]:
        return ["data"]

    def list_outputs(self) -> List[str]:
        return ["output"]

    def list_auxiliary_states(self) -> List[str]:
        return []

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]], []

    def infer_type(self, in_type):
        return in_type, [in_type[0]], []

    def create_operator(self, ctx, in_shapes, in_dtypes) -> CustomOp:
        raise NotImplementedError


def register(reg_name: str) -> Callable[[type], type]:
    """Decorator registering a CustomOpProp under op_type=reg_name."""
    def do(prop_cls: type) -> type:
        if not issubclass(prop_cls, CustomOpProp):
            raise MXNetError("register() expects a CustomOpProp subclass")
        _custom_registry[reg_name] = prop_cls
        return prop_cls
    return do


def get_all_registered() -> List[str]:
    return sorted(_custom_registry)


def Custom(*inputs, op_type: Optional[str] = None, **kwargs):
    """Invoke a registered custom op (reference: mx.nd.Custom)."""
    from .ndarray import NDArray, zeros as nd_zeros
    from .context import current_context
    if op_type is None or op_type not in _custom_registry:
        raise MXNetError(f"unknown custom op_type {op_type!r}; "
                         f"registered: {get_all_registered()}")
    prop = _custom_registry[op_type](**kwargs)
    ctx = inputs[0].context if inputs and isinstance(inputs[0], NDArray) \
        else current_context()
    in_shapes = [x.shape for x in inputs]
    arg_shapes, out_shapes, _ = prop.infer_shape(in_shapes)
    op = prop.create_operator(ctx, arg_shapes,
                              [x.dtype for x in inputs])

    class _Bridge(_ag.Function):
        def forward(self, *ins):
            outs = [nd_zeros(s, ctx=ctx) for s in out_shapes]
            op.forward(is_train=_ag.is_training(),
                       req=["write"] * len(outs), in_data=list(ins),
                       out_data=outs, aux=[])
            self.save_for_backward(*ins, *outs)
            self._n_in = len(ins)
            return outs[0] if len(outs) == 1 else tuple(outs)

        def backward(self, *ograds):
            saved = self.saved_tensors
            ins = list(saved[:self._n_in])
            outs = list(saved[self._n_in:])
            igrads = [nd_zeros(s, ctx=ctx) for s in
                      [x.shape for x in ins]]
            op.backward(req=["write"] * len(igrads),
                        out_grad=list(ograds), in_data=ins, out_data=outs,
                        in_grad=igrads, aux=[])
            return igrads[0] if len(igrads) == 1 else tuple(igrads)

    bridge = _Bridge()
    bridge.__class__.__name__ = f"Custom[{op_type}]"
    return bridge(*inputs)
