"""RecordIO: the reference's packed binary record format.

Reference parity: 3rdparty/dmlc-core recordio + python/mxnet/recordio.py
(SURVEY.md §2.4) — magic-delimited records, 29-bit length + 3-bit
continuation flag, 4-byte alignment; `IRHeader` (flag, label, id, id2) and
``pack``/``unpack``/``pack_img``/``unpack_img``; MXIndexedRecordIO adds the
``.idx`` offset sidecar.  The binary framing here matches the reference
byte-for-byte so existing .rec files read unchanged; image encode/decode uses
PIL or cv2 when present and falls back to a raw-ndarray payload otherwise
(this image has no OpenCV).
"""
from __future__ import annotations

import collections
import io
import os
import struct
from typing import Optional

import numpy as _np

from .base import MXNetError

__all__ = ["MXRecordIO", "MXIndexedRecordIO", "IRHeader", "pack", "unpack",
           "pack_img", "unpack_img"]

_MAGIC = 0xced7230a
_HDR_FMT = "IfQQ"
_HDR_SIZE = struct.calcsize(_HDR_FMT)

IRHeader = collections.namedtuple("HEADER", ["flag", "label", "id", "id2"])


class MXRecordIO:
    """Sequential reader/writer for .rec files."""

    def __init__(self, uri: str, flag: str):
        self.uri = uri
        self.flag = flag
        self.open()

    def open(self):
        if self.flag == "w":
            self._fp = open(self.uri, "wb")
            self.writable = True
        elif self.flag == "r":
            self._fp = open(self.uri, "rb")
            self.writable = False
        else:
            raise MXNetError("flag must be 'r' or 'w'")
        self.is_open = True

    def close(self):
        if self.is_open:
            self._fp.close()
            self.is_open = False

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()

    def reset(self):
        self.close()
        self.open()

    def tell(self) -> int:
        return self._fp.tell()

    def seek(self, pos: int) -> None:
        self._fp.seek(pos)

    def write(self, buf: bytes) -> None:
        if not self.writable:
            raise MXNetError("not opened for writing")
        length = len(buf)
        # upper 3 bits: continuation flag (0 = complete record)
        lrec = length & ((1 << 29) - 1)
        self._fp.write(struct.pack("<II", _MAGIC, lrec))
        self._fp.write(buf)
        pad = (4 - length % 4) % 4
        if pad:
            self._fp.write(b"\x00" * pad)

    def read(self) -> Optional[bytes]:
        if self.writable:
            raise MXNetError("not opened for reading")
        head = self._fp.read(8)
        if len(head) < 8:
            return None
        magic, lrec = struct.unpack("<II", head)
        if magic != _MAGIC:
            raise MXNetError(f"{self.uri}: bad record magic {magic:#x}")
        length = lrec & ((1 << 29) - 1)
        data = self._fp.read(length)
        pad = (4 - length % 4) % 4
        if pad:
            self._fp.read(pad)
        return data


class MXIndexedRecordIO(MXRecordIO):
    """Random-access reader/writer with a .idx sidecar (key\\toffset)."""

    def __init__(self, idx_path: str, uri: str, flag: str, key_type=int):
        self.idx_path = idx_path
        self.idx = {}
        self.keys = []
        self.key_type = key_type
        super().__init__(uri, flag)

    def open(self):
        super().open()
        self.idx = {}
        self.keys = []
        if not self.writable and os.path.isfile(self.idx_path):
            with open(self.idx_path) as f:
                for line in f:
                    line = line.strip().split("\t")
                    key = self.key_type(line[0])
                    self.idx[key] = int(line[1])
                    self.keys.append(key)

    def close(self):
        if self.is_open and self.writable:
            with open(self.idx_path, "w") as f:
                for k in self.keys:
                    f.write(f"{k}\t{self.idx[k]}\n")
        super().close()

    def read_idx(self, idx) -> bytes:
        self.seek(self.idx[idx])
        return self.read()

    def write_idx(self, idx, buf: bytes) -> None:
        pos = self.tell()
        self.write(buf)
        self.idx[self.key_type(idx)] = pos
        self.keys.append(self.key_type(idx))


def pack(header: IRHeader, s: bytes) -> bytes:
    header = IRHeader(*header)
    if isinstance(header.label, (int, float)):
        hdr = struct.pack(_HDR_FMT, 0, float(header.label), header.id,
                          header.id2)
    else:
        label = _np.asarray(header.label, dtype=_np.float32)
        hdr = struct.pack(_HDR_FMT, label.size, 0.0, header.id, header.id2) \
            + label.tobytes()
    return hdr + s


def unpack(s: bytes):
    flag, label, id_, id2 = struct.unpack(_HDR_FMT, s[:_HDR_SIZE])
    s = s[_HDR_SIZE:]
    if flag > 0:
        label = _np.frombuffer(s[:flag * 4], dtype=_np.float32)
        s = s[flag * 4:]
    return IRHeader(flag, label, id_, id2), s


def _try_encode_img(img: _np.ndarray, quality: int, img_fmt: str):
    try:
        from PIL import Image
        buf = io.BytesIO()
        Image.fromarray(img).save(buf, format="JPEG" if "jpg" in img_fmt
                                  or "jpeg" in img_fmt else "PNG",
                                  quality=quality)
        return buf.getvalue()
    except ImportError:
        return None


def pack_img(header: IRHeader, img, quality: int = 95,
             img_fmt: str = ".jpg") -> bytes:
    img = _np.asarray(img, dtype=_np.uint8)
    encoded = _try_encode_img(img, quality, img_fmt)
    if encoded is None:
        # raw fallback payload: magic + ndim + shape + bytes
        encoded = b"RAWN" + struct.pack("<B", img.ndim) + \
            struct.pack(f"<{img.ndim}I", *img.shape) + img.tobytes()
    return pack(header, encoded)


def unpack_img(s: bytes, iscolor: int = -1):
    header, payload = unpack(s)
    if payload[:4] == b"RAWN":
        ndim = struct.unpack("<B", payload[4:5])[0]
        shape = struct.unpack(f"<{ndim}I", payload[5:5 + 4 * ndim])
        img = _np.frombuffer(payload[5 + 4 * ndim:], dtype=_np.uint8) \
            .reshape(shape)
        return header, img
    try:
        from PIL import Image
        img = _np.asarray(Image.open(io.BytesIO(payload)))
        return header, img
    except ImportError as e:
        raise MXNetError("no image decoder available (PIL missing) and "
                         "payload is not raw format") from e
