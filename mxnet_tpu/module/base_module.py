"""BaseModule: the symbolic-training driver (`mod.fit`).

Reference parity: python/mxnet/module/base_module.py (SURVEY.md §2.5, §3.4)
— the epoch loop (forward/backward/update/metric/callbacks/checkpoint) every
Symbol-era user script (including Sockeye) drives.
"""
from __future__ import annotations

import logging
import time
from typing import Any, Dict, List, Optional, Sequence

from ..base import MXNetError
from ..model import BatchEndParam
from .. import metric as metric_mod
from ..ndarray import NDArray

__all__ = ["BaseModule"]


def _as_metric(m):
    if isinstance(m, metric_mod.EvalMetric):
        return m
    return metric_mod.create(m)


class BaseModule:
    """Shared high-level API; subclasses implement the *_impl surface
    (bind / init_params / forward / backward / update / get_outputs)."""

    def __init__(self, logger=logging):
        self.logger = logger
        self.binded = False
        self.params_initialized = False
        self.optimizer_initialized = False
        self.for_training = False
        self.inputs_need_grad = False

    # ------------------------------------------------------------------ #
    # subclass surface                                                   #
    # ------------------------------------------------------------------ #
    @property
    def data_names(self) -> List[str]:
        raise NotImplementedError

    @property
    def output_names(self) -> List[str]:
        raise NotImplementedError

    @property
    def data_shapes(self):
        raise NotImplementedError

    @property
    def label_shapes(self):
        raise NotImplementedError

    @property
    def output_shapes(self):
        raise NotImplementedError

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        raise NotImplementedError

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False,
                    allow_extra=False):
        raise NotImplementedError

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=None, force_init=False):
        raise NotImplementedError

    def forward(self, data_batch, is_train=None):
        raise NotImplementedError

    def backward(self, out_grads=None):
        raise NotImplementedError

    def update(self):
        raise NotImplementedError

    def get_outputs(self, merge_multi_context=True):
        raise NotImplementedError

    def get_params(self):
        raise NotImplementedError

    def update_metric(self, eval_metric, labels):
        raise NotImplementedError

    # ------------------------------------------------------------------ #
    # generic conveniences (reference: base_module.py)                   #
    # ------------------------------------------------------------------ #
    def forward_backward(self, data_batch) -> None:
        self.forward(data_batch, is_train=True)
        self.backward()

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True, allow_extra=False) -> None:
        self.init_params(initializer=None, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init, allow_extra=allow_extra)

    def score(self, eval_data, eval_metric, num_batch=None,
              batch_end_callback=None, reset=True, epoch=0) -> list:
        """Evaluate on a DataIter; returns name/value pairs."""
        if not self.binded or not self.params_initialized:
            raise MXNetError("score() requires bind + init_params")
        eval_metric = _as_metric(eval_metric)
        if reset:
            eval_data.reset()
        eval_metric.reset()
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            self.update_metric(eval_metric, eval_batch.label)
            if batch_end_callback is not None:
                param = BatchEndParam(epoch=epoch, nbatch=nbatch,
                                      eval_metric=eval_metric, locals=None)
                for cb in _as_list(batch_end_callback):
                    cb(param)
        return eval_metric.get_name_value()

    def predict(self, eval_data, num_batch=None, merge_batches=True,
                reset=True, always_output_list=False):
        """Forward over an iterator and collect outputs."""
        if not self.binded or not self.params_initialized:
            raise MXNetError("predict() requires bind + init_params")
        if reset:
            eval_data.reset()
        output_list: List[List[NDArray]] = []
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            outs = self.get_outputs()
            if eval_batch.pad:
                outs = [o[0:o.shape[0] - eval_batch.pad] for o in outs]
            output_list.append(outs)
        if not output_list:
            return output_list
        if merge_batches:
            num_outputs = len(output_list[0])
            from ..ndarray import concat as nd_concat
            merged = [nd_concat(*[o[i] for o in output_list], dim=0)
                      for i in range(num_outputs)]
            if num_outputs == 1 and not always_output_list:
                return merged[0]
            return merged
        return output_list

    def iter_predict(self, eval_data, num_batch=None, reset=True):
        if reset:
            eval_data.reset()
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            yield self.get_outputs(), nbatch, eval_batch

    def fit(self, train_data, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None,
            kvstore="local", optimizer="sgd",
            optimizer_params=None, eval_end_callback=None,
            eval_batch_end_callback=None, initializer=None,
            arg_params=None, aux_params=None, allow_missing=False,
            force_rebind=False, force_init=False, begin_epoch=0,
            num_epoch=None, validation_metric=None, monitor=None) -> None:
        """The reference's canonical symbolic training loop (§3.4)."""
        if num_epoch is None:
            raise MXNetError("fit() requires num_epoch")
        optimizer_params = optimizer_params or {"learning_rate": 0.01}
        self.bind(data_shapes=train_data.provide_data,
                  label_shapes=train_data.provide_label,
                  for_training=True, force_rebind=force_rebind)
        self.init_params(initializer=initializer, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init)
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                            optimizer_params=optimizer_params,
                            force_init=force_init)
        eval_metric = _as_metric(eval_metric)
        validation_metric = _as_metric(validation_metric) \
            if validation_metric is not None else eval_metric

        if monitor is not None:
            monitor.install()

        try:
            for epoch in range(begin_epoch, num_epoch):
                tic = time.time()
                eval_metric.reset()
                for nbatch, data_batch in enumerate(train_data):
                    if monitor is not None:
                        monitor.tic()
                    self.forward_backward(data_batch)
                    self.update()
                    if monitor is not None:
                        monitor.toc_print()
                    self.update_metric(eval_metric, data_batch.label)
                    if batch_end_callback is not None:
                        param = BatchEndParam(epoch=epoch, nbatch=nbatch,
                                              eval_metric=eval_metric,
                                              locals=None)
                        for cb in _as_list(batch_end_callback):
                            cb(param)
                for name, val in eval_metric.get_name_value():
                    self.logger.info("Epoch[%d] Train-%s=%f", epoch, name,
                                     val)
                self.logger.info("Epoch[%d] Time cost=%.3f", epoch,
                                 time.time() - tic)
                if epoch_end_callback is not None:
                    arg, aux = self.get_params()
                    for cb in _as_list(epoch_end_callback):
                        cb(epoch, self.symbol, arg, aux)
                if eval_data is not None:
                    res = self.score(
                        eval_data, validation_metric,
                        batch_end_callback=eval_batch_end_callback,
                        epoch=epoch)
                    for name, val in res:
                        self.logger.info("Epoch[%d] Validation-%s=%f",
                                         epoch, name, val)
                train_data.reset()
        finally:
            # the monitor taps the process-global engine; leaving it
            # installed would keep per-dispatch timing on forever
            if monitor is not None:
                monitor.uninstall()


def _as_list(x):
    return x if isinstance(x, (list, tuple)) else [x]
