"""Module: bound Symbol + params + optimizer (reference:
python/mxnet/module/module.py, SURVEY.md §3.4).
"""
from __future__ import annotations

import logging
from typing import Dict, List, Optional, Sequence

import numpy as _np

from ..base import MXNetError
from ..context import Context, current_context
from ..initializer import Uniform, create as init_create
from ..model import save_checkpoint as _save_ckpt, \
    load_checkpoint as _load_ckpt
from ..ndarray import NDArray, zeros as nd_zeros
from .. import optimizer as opt_mod
from .. import kvstore as kv_mod
from .base_module import BaseModule
from .executor_group import DataParallelExecutorGroup

__all__ = ["Module"]


class Module(BaseModule):
    def __init__(self, symbol, data_names: Sequence[str] = ("data",),
                 label_names: Sequence[str] = ("softmax_label",),
                 logger=logging, context=None, work_load_list=None,
                 fixed_param_names=None, state_names=None,
                 group2ctxs=None, compression_params=None):
        super().__init__(logger)
        if context is None:
            context = [current_context()]
        if isinstance(context, Context):
            context = [context]
        self._context = list(context)
        self._symbol = symbol
        self._data_names = list(data_names)
        self._label_names = list(label_names or [])
        self._fixed_param_names = set(fixed_param_names or [])
        arg_names = symbol.list_arguments()
        input_names = set(self._data_names) | set(self._label_names)
        self._param_names = [n for n in arg_names if n not in input_names]
        self._aux_names = symbol.list_auxiliary_states()
        self._arg_params: Dict[str, NDArray] = {}
        self._aux_params: Dict[str, NDArray] = {}
        self._exec_group: Optional[DataParallelExecutorGroup] = None
        self._optimizer = None
        self._updater = None
        self._kvstore = None
        self._data_shapes = None
        self._label_shapes = None

    # -- introspection -----------------------------------------------------
    @property
    def symbol(self):
        return self._symbol

    @property
    def data_names(self) -> List[str]:
        return self._data_names

    @property
    def label_names(self) -> List[str]:
        return self._label_names

    @property
    def output_names(self) -> List[str]:
        return self._symbol.list_outputs()

    @property
    def data_shapes(self):
        return self._data_shapes

    @property
    def label_shapes(self):
        return self._label_shapes

    @property
    def output_shapes(self):
        outs = self.get_outputs()
        return list(zip(self.output_names, [o.shape for o in outs]))

    # -- bind --------------------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write") -> None:
        if self.binded and not force_rebind:
            self.logger.warning("Already bound, ignoring bind()")
            return
        from ..io import DataDesc
        norm = lambda lst: [d if isinstance(d, DataDesc) else DataDesc(*d)
                            for d in (lst or [])]
        self._data_shapes = norm(data_shapes)
        self._label_shapes = norm(label_shapes)
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        shared_group = shared_module._exec_group if shared_module else None
        self._exec_group = DataParallelExecutorGroup(
            self._symbol, self._context, self._data_shapes,
            self._label_shapes, self._param_names, for_training,
            inputs_need_grad, shared_group, grad_req)
        if shared_module is not None and shared_module.params_initialized:
            # share parameter values with the bucketing master module
            self._arg_params = shared_module._arg_params
            self._aux_params = shared_module._aux_params
            self.params_initialized = True
            self._exec_group.set_params(self._arg_params, self._aux_params)
        self.binded = True

    # -- params ------------------------------------------------------------
    def init_params(self, initializer=None, arg_params=None,
                    aux_params=None, allow_missing=False, force_init=False,
                    allow_extra=False) -> None:
        if self.params_initialized and not force_init:
            return
        if not self.binded:
            raise MXNetError("init_params requires bind()")
        if initializer is None:
            initializer = Uniform(0.01)
        exe = self._exec_group.execs[0]
        attrs = self._symbol.attr_dict() if hasattr(self._symbol,
                                                    "attr_dict") else {}
        for name in self._param_names:
            arr = exe.arg_dict[name]
            if arg_params and name in arg_params:
                val = arg_params[name]
                self._arg_params[name] = val.copyto(arr.context) \
                    if val.context != arr.context else val.copy()
            else:
                if arg_params is not None and not allow_missing and \
                        arg_params != {}:
                    raise MXNetError(f"missing parameter {name!r}")
                dst = nd_zeros(arr.shape, ctx=arr.context)
                node_attrs = attrs.get(name, {})
                if node_attrs.get("__init__"):
                    # per-variable initializer attr: ONE mechanism —
                    # InitDesc handling in Initializer.__call__ (accepts
                    # both the plain-name and JSON ["name", {kw}] forms)
                    from ..initializer import InitDesc
                    initializer(InitDesc(name, node_attrs), dst)
                else:
                    initializer(name, dst)
                self._arg_params[name] = dst
        for name in self._aux_names:
            arr = exe.aux_dict[name]
            if aux_params and name in aux_params:
                self._aux_params[name] = aux_params[name].copy()
            else:
                dst = nd_zeros(arr.shape, ctx=arr.context)
                initializer(name, dst)
                self._aux_params[name] = dst
        self._exec_group.set_params(self._arg_params, self._aux_params,
                                    allow_extra=allow_extra)
        self.params_initialized = True

    def get_params(self):
        if self._exec_group is not None and self.params_initialized:
            self._exec_group.get_params(self._arg_params, self._aux_params)
        return self._arg_params, self._aux_params

    # -- optimizer ---------------------------------------------------------
    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=None, force_init=False) -> None:
        if not self.binded or not self.params_initialized:
            raise MXNetError("init_optimizer requires bind + init_params")
        if self.optimizer_initialized and not force_init:
            return
        optimizer_params = dict(optimizer_params or {})
        if isinstance(optimizer, str):
            # reference rescale convention: grads are summed over the whole
            # (global) batch; normalize by batch size across all devices
            optimizer_params.setdefault(
                "rescale_grad", 1.0 / self._data_shapes[0].shape[0])
            idx2name = dict(enumerate(self._param_names))
            optimizer = opt_mod.create(optimizer,
                                       param_idx2name=idx2name,
                                       **optimizer_params)
        self._optimizer = optimizer
        self._updater = opt_mod.get_updater(optimizer)
        self._param_index = {n: i for i, n in
                             enumerate(self._param_names)}
        if isinstance(kvstore, str):
            kvstore = kv_mod.create(kvstore) if kvstore else None
        self._kvstore = kvstore
        if kvstore is not None:
            for i, name in enumerate(self._param_names):
                kvstore.init(i, self._arg_params[name])
        states_file = getattr(self, "_preloaded_states", None)
        if states_file is not None:
            with open(states_file, "rb") as f:
                self._updater.set_states(f.read())
            self._preloaded_states = None
        self.optimizer_initialized = True

    def borrow_optimizer(self, shared_module: "Module") -> None:
        """Share optimizer/updater state with another module — one set of
        momenta across all buckets (reference: Module.borrow_optimizer,
        required for BucketingModule correctness)."""
        if not shared_module.optimizer_initialized:
            raise MXNetError("shared module has no optimizer")
        self._optimizer = shared_module._optimizer
        self._updater = shared_module._updater
        self._kvstore = shared_module._kvstore
        self._param_index = shared_module._param_index
        self.optimizer_initialized = True

    # -- execution ---------------------------------------------------------
    def forward(self, data_batch, is_train=None) -> None:
        if not self.binded or not self.params_initialized:
            raise MXNetError("forward requires bind + init_params")
        self._exec_group.forward(data_batch, is_train)

    def backward(self, out_grads=None) -> None:
        self._exec_group.backward(out_grads)

    def update(self) -> None:
        """KVStore push/pull + optimizer step per param (reference §3.2)."""
        if not self.optimizer_initialized:
            raise MXNetError("update requires init_optimizer")
        for name in self._param_names:
            if name in self._fixed_param_names:
                continue
            i = self._param_index.get(name)
            if i is None:       # param unknown to the shared optimizer
                continue
            grads = self._exec_group.grad_arrays_of(name)
            if not grads:
                continue
            if self._kvstore is not None:
                self._kvstore.push(i, grads)
                agg = self._kvstore.pull(i)
            else:
                agg = grads[0]
                for g in grads[1:]:
                    agg = agg + g.as_in_context(agg.context)
            weight = self._arg_params[name]
            self._updater(i, agg.as_in_context(weight.context), weight)
        self._exec_group.set_params(self._arg_params, self._aux_params)
        # aux states (e.g. BN running stats) flow back from executor 0
        exe = self._exec_group.execs[0]
        for name in self._aux_names:
            self._aux_params[name]._set_data(exe.aux_dict[name]._read())

    def get_outputs(self, merge_multi_context=True):
        return self._exec_group.get_outputs(merge_multi_context)

    def get_input_grads(self, merge_multi_context=True):
        if not self.inputs_need_grad:
            raise MXNetError("bind with inputs_need_grad=True first")
        grads = []
        for name in self._data_names:
            gs = self._exec_group.grad_arrays_of(name)
            grads.append(gs[0] if len(gs) == 1 else gs)
        return grads

    def update_metric(self, eval_metric, labels) -> None:
        self._exec_group.update_metric(eval_metric, labels)

    # -- checkpoint --------------------------------------------------------
    def save_checkpoint(self, prefix: str, epoch: int,
                        save_optimizer_states: bool = False) -> None:
        arg, aux = self.get_params()
        _save_ckpt(prefix, epoch, self._symbol, arg, aux)
        if save_optimizer_states and self._updater is not None:
            with open(f"{prefix}-{epoch:04d}.states", "wb") as f:
                f.write(self._updater.get_states())

    @staticmethod
    def load(prefix: str, epoch: int, load_optimizer_states: bool = False,
             **kwargs) -> "Module":
        sym, arg, aux = _load_ckpt(prefix, epoch)
        mod = Module(sym, **kwargs)
        mod._preloaded = (arg, aux)
        mod._preloaded_states = f"{prefix}-{epoch:04d}.states" \
            if load_optimizer_states else None
        return mod

    def fit(self, train_data, **kwargs) -> None:
        pre = getattr(self, "_preloaded", None)
        if pre is not None and "arg_params" not in kwargs:
            kwargs["arg_params"], kwargs["aux_params"] = pre
            kwargs.setdefault("allow_missing", False)
        super().fit(train_data, **kwargs)
