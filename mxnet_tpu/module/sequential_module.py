"""SequentialModule + PythonModule (reference:
python/mxnet/module/sequential_module.py, python_module.py — SURVEY.md
§2.5 Module API row).

SequentialModule chains Modules: each stage's outputs become the next
stage's data, with backward gradients flowing back through
``out_grads``.  PythonModule is the computation-in-Python escape hatch
(its canonical subclass PythonLossModule implements a loss head whose
gradient is supplied in Python).
"""
from __future__ import annotations

from typing import List, Optional

from ..base import MXNetError
from ..io import DataBatch, DataDesc
from .base_module import BaseModule

__all__ = ["SequentialModule", "PythonModule", "PythonLossModule"]


class SequentialModule(BaseModule):
    """Chain of modules executed in order (reference SequentialModule).

    ``add(mod, take_labels=True)`` marks the stage that receives the
    batch labels (typically the final loss stage)."""

    META_TAKE_LABELS = "take_labels"
    META_AUTO_WIRING = "auto_wiring"

    def __init__(self, logger=None):
        super().__init__(logger) if logger is not None else \
            super().__init__()
        self._modules: List[BaseModule] = []
        self._metas: List[dict] = []
        self._label_shapes = None
        self.binded = False
        self.params_initialized = False
        self.optimizer_initialized = False

    def add(self, module: BaseModule, **kwargs) -> "SequentialModule":
        self._modules.append(module)
        self._metas.append(kwargs)
        return self

    # -- shapes ------------------------------------------------------------
    @property
    def data_names(self):
        return self._modules[0].data_names if self._modules else []

    @property
    def output_names(self):
        return self._modules[-1].output_names if self._modules else []

    @property
    def data_shapes(self):
        return self._modules[0].data_shapes

    @property
    def label_shapes(self):
        return self._label_shapes

    @property
    def output_shapes(self):
        return self._modules[-1].output_shapes

    # -- lifecycle ---------------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, **kwargs):
        if self.binded and not force_rebind:
            return
        if not self._modules:
            raise MXNetError("SequentialModule.bind: no modules added")
        self._label_shapes = label_shapes
        cur_shapes = data_shapes
        for i, (mod, meta) in enumerate(zip(self._modules, self._metas)):
            takes_labels = meta.get(self.META_TAKE_LABELS, False)
            # stage 0 honors the CALLER's inputs_need_grad (reference
            # behavior); later stages always need input grads to keep the
            # backward chain flowing
            mod.bind(cur_shapes,
                     label_shapes if takes_labels else None,
                     for_training=for_training,
                     inputs_need_grad=inputs_need_grad if i == 0 else True,
                     force_rebind=force_rebind)
            if i == len(self._modules) - 1:
                break
            # next stage's data = this stage's outputs, wired by position
            # onto the next module's declared data names; symbolic stages
            # infer shapes from the graph, PythonModule-style stages
            # report them via output_shapes (computed by their bind)
            if hasattr(mod, "symbol"):
                shape_feed = {d.name: d.shape for d in cur_shapes}
                _, out_shapes, _ = mod.symbol.infer_shape(**shape_feed)
            else:
                out_shapes = [s.shape if hasattr(s, "shape") else s[1]
                              for s in mod.output_shapes]
            nxt = self._modules[i + 1]
            if len(nxt.data_names) != len(out_shapes):
                raise MXNetError(
                    f"SequentialModule: stage {i} emits "
                    f"{len(out_shapes)} outputs but stage {i + 1} "
                    f"declares {len(nxt.data_names)} data inputs")
            cur_shapes = [DataDesc(n, s)
                          for n, s in zip(nxt.data_names, out_shapes)]
        self.binded = True

    def init_params(self, initializer=None, arg_params=None,
                    aux_params=None, allow_missing=False,
                    force_init=False, **kwargs):
        for mod in self._modules:
            mod.init_params(initializer=initializer,
                            arg_params=arg_params, aux_params=aux_params,
                            allow_missing=True, force_init=force_init)
        self.params_initialized = True

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=None, force_init=False):
        for mod in self._modules:
            mod.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                               optimizer_params=optimizer_params,
                               force_init=force_init)
        self.optimizer_initialized = True

    # -- execution ---------------------------------------------------------
    def forward(self, data_batch, is_train=None):
        batch = data_batch
        for i, (mod, meta) in enumerate(zip(self._modules, self._metas)):
            mod.forward(batch, is_train=is_train)
            if i == len(self._modules) - 1:
                break
            outs = mod.get_outputs()
            nxt = self._modules[i + 1]
            batch = DataBatch(
                data=outs,
                label=data_batch.label,
                pad=getattr(data_batch, "pad", 0),
                provide_data=[DataDesc(n, o.shape)
                              for n, o in zip(nxt.data_names, outs)],
                provide_label=getattr(data_batch, "provide_label", None))

    def backward(self, out_grads=None):
        for i in reversed(range(len(self._modules))):
            mod = self._modules[i]
            mod.backward(out_grads=out_grads)
            if i == 0:
                break
            if not hasattr(mod, "get_input_grads"):
                # out_grads=None would mean ones-cotangents for the stage
                # below — silently wrong gradients; fail loudly instead
                raise MXNetError(
                    f"SequentialModule stage {i} "
                    f"({type(mod).__name__}) does not implement "
                    "get_input_grads; the backward chain cannot continue")
            out_grads = mod.get_input_grads()

    def get_input_grads(self, merge_multi_context=True):
        return self._modules[0].get_input_grads(merge_multi_context)

    def update(self):
        for mod in self._modules:
            mod.update()

    def get_outputs(self, merge_multi_context=True):
        return self._modules[-1].get_outputs(merge_multi_context)

    def get_params(self):
        arg, aux = {}, {}
        for mod in self._modules:
            a, x = mod.get_params()
            arg.update(a)
            aux.update(x)
        return arg, aux

    def update_metric(self, eval_metric, labels):
        for mod, meta in zip(self._modules, self._metas):
            if meta.get(self.META_TAKE_LABELS, False):
                mod.update_metric(eval_metric, labels)
                return
        self._modules[-1].update_metric(eval_metric, labels)


class PythonModule(BaseModule):
    """Module whose computation is written directly in Python (reference
    PythonModule) — subclass and override ``forward``/``backward``."""

    def __init__(self, data_names, label_names, output_names, logger=None):
        super().__init__()
        self._data_names = list(data_names)
        self._label_names = list(label_names or [])
        self._output_names = list(output_names)
        self._data_shapes = None
        self._label_shapes = None
        self._output_shapes = None
        self.binded = False
        self.params_initialized = False
        self.optimizer_initialized = False

    @property
    def data_names(self):
        return self._data_names

    @property
    def output_names(self):
        return self._output_names

    @property
    def data_shapes(self):
        return self._data_shapes

    @property
    def label_shapes(self):
        return self._label_shapes

    @property
    def output_shapes(self):
        return self._output_shapes

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, **kwargs):
        if self.binded and not force_rebind:
            return
        self._data_shapes = data_shapes
        self._label_shapes = label_shapes
        self._output_shapes = self._compute_output_shapes()
        self.binded = True

    def _compute_output_shapes(self):
        raise NotImplementedError

    # parameter-free by default (the reference convention)
    def init_params(self, *args, **kwargs):
        self.params_initialized = True

    def init_optimizer(self, *args, **kwargs):
        self.optimizer_initialized = True

    def get_params(self):
        return {}, {}

    def update(self):
        pass

    def update_metric(self, eval_metric, labels):
        if self._label_shapes is not None:
            eval_metric.update(labels, self.get_outputs())


class PythonLossModule(PythonModule):
    """Loss head in Python (reference PythonLossModule): forward caches
    the scores, ``backward`` computes the gradient with a user function
    (default: identity pass-through of scores as CE-style grads)."""

    def __init__(self, name="pyloss", data_names=("data",),
                 label_names=("softmax_label",), logger=None,
                 grad_func=None):
        super().__init__(data_names, label_names,
                         [name + "_output"], logger)
        self._name = name
        self._scores = None
        self._labels = None
        self._scores_grad = None
        self._grad_func = grad_func

    def _compute_output_shapes(self):
        return [(self._name + "_output", self._data_shapes[0].shape)]

    def forward(self, data_batch, is_train=None):
        self._scores = data_batch.data[0]
        if data_batch.label:
            self._labels = data_batch.label[0]

    def get_outputs(self, merge_multi_context=True):
        return [self._scores]

    def backward(self, out_grads=None):
        if self._grad_func is not None:
            self._scores_grad = self._grad_func(self._scores, self._labels)
        else:
            from .. import ndarray as nd
            # default: softmax CE gradient p - onehot(label)
            p = nd.softmax(self._scores)
            oh = nd.one_hot(self._labels, depth=self._scores.shape[-1])
            self._scores_grad = p - oh

    def get_input_grads(self, merge_multi_context=True):
        return [self._scores_grad]
