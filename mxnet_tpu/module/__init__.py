"""``mx.mod``: the symbolic training API (reference: python/mxnet/module/).

Module = bound Symbol + params + optimizer; BucketingModule = one jitted
executable per bucket shape sharing a single parameter set (SURVEY.md §3.4,
§5.7).
"""
from .base_module import BaseModule
from .module import Module
from .bucketing_module import BucketingModule
from .sequential_module import SequentialModule, PythonModule, \
    PythonLossModule

__all__ = ["BaseModule", "Module", "BucketingModule", "SequentialModule",
           "PythonModule", "PythonLossModule"]
