"""DataParallelExecutorGroup: one bound executor per context, batch sliced
along the batch axis.

Reference parity: python/mxnet/module/executor_group.py (SURVEY.md §2.3) —
the Module-era data-parallel mechanism.  On TPU the *performant* data
parallelism is the pjit/shard_map path (mxnet_tpu.parallel); this class
keeps the Module API semantics (per-context executors, kvstore reduction
above it) so Symbol-era scripts run unchanged, and degenerates to a single
jitted executor in the common one-device case.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as _np

from ..base import MXNetError
from ..context import Context
from ..io import DataDesc
from ..ndarray import NDArray, array as nd_array

__all__ = ["DataParallelExecutorGroup"]


def _split_desc(desc: DataDesc, k: int, n: int) -> tuple:
    """Shape of the k-th of n slices along the batch axis."""
    axis = DataDesc.get_batch_axis(desc.layout)
    shape = list(desc.shape)
    per = shape[axis] // n
    lo = k * per
    hi = shape[axis] if k == n - 1 else lo + per
    shape[axis] = hi - lo
    return tuple(shape), axis, lo, hi


class DataParallelExecutorGroup:
    def __init__(self, symbol, contexts: Sequence[Context],
                 data_shapes: List[DataDesc],
                 label_shapes: Optional[List[DataDesc]],
                 param_names: List[str], for_training: bool,
                 inputs_need_grad: bool = False, shared_group=None,
                 grad_req: str = "write"):
        self.symbol = symbol
        self.contexts = list(contexts)
        self.param_names = param_names
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self.data_shapes = [DataDesc(*d) if not isinstance(d, DataDesc)
                            else d for d in data_shapes]
        self.label_shapes = [DataDesc(*d) if not isinstance(d, DataDesc)
                             else d for d in (label_shapes or [])]
        self.grad_req = grad_req if for_training else "null"
        arg_names = symbol.list_arguments()
        self._input_names = [d.name for d in self.data_shapes] + \
            [l.name for l in self.label_shapes]
        for name in self._input_names:
            if name not in arg_names and name not in \
                    symbol.list_auxiliary_states():
                raise MXNetError(
                    f"input {name!r} not an argument of the symbol "
                    f"(arguments: {arg_names})")
        n = len(self.contexts)
        self.execs = []
        for k, ctx in enumerate(self.contexts):
            shapes = {}
            for d in self.data_shapes + self.label_shapes:
                shapes[d.name] = _split_desc(d, k, n)[0]
            # params get the full (replicated) shape on every context
            exe = symbol.simple_bind(ctx=ctx, grad_req=self.grad_req,
                                     **shapes)
            self.execs.append(exe)
        self._outputs_per_exec = len(symbol.list_outputs())

    # -- params ------------------------------------------------------------
    def set_params(self, arg_params: Dict[str, NDArray],
                   aux_params: Dict[str, NDArray],
                   allow_extra: bool = False) -> None:
        for exe in self.execs:
            exe.copy_params_from(arg_params, aux_params,
                                 allow_extra_params=allow_extra)

    def get_params(self, arg_params: Dict[str, NDArray],
                   aux_params: Dict[str, NDArray]) -> None:
        """Copy (first-replica) values out into the given dicts."""
        exe = self.execs[0]
        for name, arr in exe.arg_dict.items():
            if name in arg_params:
                arg_params[name]._set_data(arr._read())
        for name, arr in exe.aux_dict.items():
            if name in aux_params:
                aux_params[name]._set_data(arr._read())

    # -- execution ---------------------------------------------------------
    def forward(self, data_batch, is_train: Optional[bool] = None) -> None:
        if is_train is None:
            is_train = self.for_training
        n = len(self.execs)
        feeds = {d.name: v for d, v in zip(self.data_shapes,
                                           data_batch.data)}
        if self.label_shapes and data_batch.label:
            feeds.update({l.name: v for l, v in zip(self.label_shapes,
                                                    data_batch.label)})
        descs = {d.name: d for d in self.data_shapes + self.label_shapes}
        for k, exe in enumerate(self.execs):
            kw = {}
            for name, val in feeds.items():
                _, axis, lo, hi = _split_desc(descs[name], k, n)
                v = val
                if n > 1:
                    idx = [slice(None)] * len(descs[name].shape)
                    idx[axis] = slice(lo, hi)
                    v = val[tuple(idx)]
                kw[name] = v if isinstance(v, NDArray) \
                    else nd_array(_np.asarray(v), ctx=exe._ctx)
            exe.forward(is_train=is_train, **kw)

    def backward(self, out_grads=None) -> None:
        for exe in self.execs:
            exe.backward(out_grads)

    def get_outputs(self, merge_multi_context: bool = True):
        if len(self.execs) == 1:
            return list(self.execs[0].outputs)
        if not merge_multi_context:
            return [list(e.outputs) for e in self.execs]
        from ..ndarray import concat as nd_concat
        merged = []
        for i in range(self._outputs_per_exec):
            merged.append(nd_concat(*[e.outputs[i] for e in self.execs],
                                    dim=0))
        return merged

    def grad_arrays_of(self, name: str) -> List[NDArray]:
        out = []
        for exe in self.execs:
            g = exe.grad_dict.get(name)
            if g is not None:
                out.append(g)
        return out

    def update_metric(self, eval_metric, labels) -> None:
        eval_metric.update(labels, self.get_outputs())
