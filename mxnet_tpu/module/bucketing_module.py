"""BucketingModule: per-sequence-length executors sharing one parameter set.

Reference parity: python/mxnet/module/bucketing_module.py (SURVEY.md §5.7) —
the long/variable-sequence story of the Symbol era and Sockeye's engine
(BASELINE config #4).  TPU-native: each bucket is its own jitted executable
(XLA compile cache keyed by shape — exactly the pad-to-bucket policy §5.7
prescribes); parameters live in the master module and are shared by
reference, so switching buckets never copies weights.
"""
from __future__ import annotations

import logging
from typing import Callable, Dict, Optional

from ..base import MXNetError
from .base_module import BaseModule
from .module import Module

__all__ = ["BucketingModule"]


class BucketingModule(BaseModule):
    def __init__(self, sym_gen: Callable, default_bucket_key=None,
                 logger=logging, context=None, work_load_list=None,
                 fixed_param_names=None, state_names=None,
                 group2ctxs=None, compression_params=None):
        super().__init__(logger)
        if default_bucket_key is None:
            raise MXNetError("default_bucket_key is required")
        self._sym_gen = sym_gen
        self._default_bucket_key = default_bucket_key
        self._mod_kwargs = dict(context=context,
                                fixed_param_names=fixed_param_names,
                                logger=logger)
        self._buckets: Dict = {}
        self._curr_module: Optional[Module] = None
        self._curr_bucket_key = None
        self._opt_config = None

    # -- introspection -----------------------------------------------------
    @property
    def default_bucket_key(self):
        return self._default_bucket_key

    @property
    def symbol(self):
        return self._curr_module.symbol

    @property
    def data_names(self):
        return self._curr_module.data_names

    @property
    def output_names(self):
        return self._curr_module.output_names

    @property
    def data_shapes(self):
        return self._curr_module.data_shapes

    @property
    def label_shapes(self):
        return self._curr_module.label_shapes

    @property
    def output_shapes(self):
        return self._curr_module.output_shapes

    def _gen_module(self, bucket_key) -> Module:
        sym, data_names, label_names = self._sym_gen(bucket_key)
        return Module(sym, data_names=data_names, label_names=label_names,
                      **self._mod_kwargs)

    # -- bind --------------------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write") -> None:
        if self.binded and not force_rebind:
            self.logger.warning("Already bound, ignoring bind()")
            return
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        module = self._gen_module(self._default_bucket_key)
        module.bind(data_shapes, label_shapes, for_training,
                    inputs_need_grad, force_rebind=False,
                    shared_module=None, grad_req=grad_req)
        self._buckets[self._default_bucket_key] = module
        self._curr_module = module
        self._curr_bucket_key = self._default_bucket_key
        self.binded = True

    def switch_bucket(self, bucket_key, data_shapes,
                      label_shapes=None) -> None:
        """Bind (or reuse) the executor for this bucket; parameters are
        shared with the default-bucket master module."""
        if not self.binded:
            raise MXNetError("switch_bucket requires bind()")
        if bucket_key not in self._buckets:
            master = self._buckets[self._default_bucket_key]
            module = self._gen_module(bucket_key)
            module.bind(data_shapes, label_shapes, self.for_training,
                        self.inputs_need_grad, shared_module=master)
            if master.optimizer_initialized:
                # ONE optimizer state set across buckets (momenta must see
                # every step regardless of which bucket produced it)
                module.borrow_optimizer(master)
            self._buckets[bucket_key] = module
        self._curr_module = self._buckets[bucket_key]
        self._curr_bucket_key = bucket_key

    # -- params ------------------------------------------------------------
    def init_params(self, initializer=None, arg_params=None,
                    aux_params=None, allow_missing=False, force_init=False,
                    allow_extra=False) -> None:
        if self.params_initialized and not force_init:
            return
        master = self._buckets[self._default_bucket_key]
        master.init_params(initializer=initializer, arg_params=arg_params,
                           aux_params=aux_params,
                           allow_missing=allow_missing,
                           force_init=force_init, allow_extra=allow_extra)
        self.params_initialized = True

    def get_params(self):
        return self._buckets[self._default_bucket_key].get_params()

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=None, force_init=False) -> None:
        self._opt_config = dict(kvstore=kvstore, optimizer=optimizer,
                                optimizer_params=optimizer_params,
                                force_init=force_init)
        master = self._buckets[self._default_bucket_key]
        master.init_optimizer(**self._opt_config)
        for module in self._buckets.values():
            if module is not master:
                module.borrow_optimizer(master)
        self.optimizer_initialized = True

    # -- execution ---------------------------------------------------------
    def forward(self, data_batch, is_train=None) -> None:
        key = getattr(data_batch, "bucket_key", None)
        if key is None:
            key = self._curr_bucket_key
        self.switch_bucket(key, data_batch.provide_data,
                           data_batch.provide_label)
        self._curr_module.forward(data_batch, is_train)

    def backward(self, out_grads=None) -> None:
        self._curr_module.backward(out_grads)

    def update(self) -> None:
        # grads live in the current bucket's executors; params are shared
        self._curr_module.update()
        # propagate refreshed params into every other bound bucket
        arg, aux = self._curr_module._arg_params, \
            self._curr_module._aux_params
        for key, module in self._buckets.items():
            if module is not self._curr_module:
                module._exec_group.set_params(arg, aux)

    def get_outputs(self, merge_multi_context=True):
        return self._curr_module.get_outputs(merge_multi_context)

    def get_input_grads(self, merge_multi_context=True):
        return self._curr_module.get_input_grads(merge_multi_context)

    def update_metric(self, eval_metric, labels) -> None:
        self._curr_module.update_metric(eval_metric, labels)

    def save_checkpoint(self, prefix: str, epoch: int,
                        save_optimizer_states: bool = False) -> None:
        self._buckets[self._default_bucket_key].save_checkpoint(
            prefix, epoch, save_optimizer_states)
