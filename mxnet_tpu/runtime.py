"""Runtime feature discovery (reference: src/libinfo.cc +
python/mxnet/runtime.py, SURVEY.md §2.1).

``feature_list()`` / ``Features`` report what this build can do, resolved
lazily from the live JAX install instead of compile-time flags.

Large-tensor support: the reference gates int64 tensor sizes behind the
MXNET_ENABLE_LARGE_TENSOR *compile* flag (reported as INT64_TENSOR_SIZE in
runtime.Features); here it is a *runtime* switch — JAX truncates int64 to
int32 unless ``jax_enable_x64`` is on, so ``enable_large_tensor()`` flips
that config and the feature report follows the live value.
"""
from __future__ import annotations

from typing import Dict, List

__all__ = ["Features", "feature_list", "is_enabled",
           "enable_large_tensor", "large_tensor_enabled"]


def enable_large_tensor(enabled: bool = True) -> None:
    """Enable true int64 tensors/indices (reference: the
    MXNET_ENABLE_LARGE_TENSOR build, tests/nightly/test_large_array.py).
    Affects computations traced after the call; existing compiled graphs
    keep their dtypes."""
    import jax
    jax.config.update("jax_enable_x64", bool(enabled))


def large_tensor_enabled() -> bool:
    import jax
    return bool(jax.config.jax_enable_x64)


class Feature:
    def __init__(self, name: str, enabled: bool):
        self.name = name
        self.enabled = enabled

    def __repr__(self):
        return f"[{'✔' if self.enabled else '✖'} {self.name}]"


import functools


@functools.lru_cache(maxsize=1)
def _detect_cached():
    return tuple(sorted(_detect().items()))


def _detect() -> Dict[str, bool]:
    feats: Dict[str, bool] = {}
    try:
        import jax
        feats["XLA"] = True
        platforms = {d.platform for d in jax.devices()}
        feats["TPU"] = bool(platforms & {"tpu", "axon"})
        feats["CPU"] = True
        feats["CUDA"] = "gpu" in platforms or "cuda" in platforms
    except Exception:
        feats.update({"XLA": False, "TPU": False, "CPU": True,
                      "CUDA": False})
    try:
        import jax.experimental.pallas  # noqa: F401
        feats["PALLAS"] = True
    except Exception:
        feats["PALLAS"] = False
    try:
        import jax.experimental.sparse  # noqa: F401
        feats["SPARSE"] = True
    except Exception:
        feats["SPARSE"] = False
    try:
        from PIL import Image  # noqa: F401
        feats["IMAGE_DECODE"] = True     # reference: OPENCV
    except Exception:
        feats["IMAGE_DECODE"] = False
    feats["BF16"] = True                  # native on TPU; emulated on CPU
    feats["DIST_KVSTORE"] = True          # jax.distributed collectives
    try:
        from . import _native               # noqa: F401
        feats["NATIVE_RUNTIME"] = _native.available()
    except Exception:
        feats["NATIVE_RUNTIME"] = False
    return feats


class Features(dict):
    """Mapping name -> Feature (reference: mx.runtime.Features)."""

    def __init__(self):
        # feature set is fixed per process — detect once (lru_cache);
        # INT64_TENSOR_SIZE alone is live (a runtime switch here)
        super().__init__({k: Feature(k, v) for k, v in _detect_cached()})
        self["INT64_TENSOR_SIZE"] = Feature("INT64_TENSOR_SIZE",
                                            large_tensor_enabled())

    def is_enabled(self, name: str) -> bool:
        f = self.get(name)
        return bool(f and f.enabled)

    def __repr__(self):
        return ", ".join(repr(v) for v in self.values())


def feature_list() -> List[Feature]:
    return list(Features().values())


def is_enabled(name: str) -> bool:
    return Features().is_enabled(name)
