"""KVStore: the gradient-aggregation seam.

Reference parity: src/kvstore/ + python/mxnet/kvstore.py (SURVEY.md §2.3,
§5.8) — `create('local'/'device'/'nccl'/'dist_sync'/...)`, init/push/pull/
pushpull, `set_optimizer` for server-side updates, rank/num_workers.

TPU-native design: all in-process backends ('local'/'device'/'nccl') are one
implementation — push reduces replica gradients (XLA handles cross-device
movement; on a real multi-chip mesh the sharded trainer path in
mxnet_tpu.parallel rides `lax.psum` over ICI instead of this object-level
loop).  'dist_sync' maps to the same synchronous semantics over a
multi-process JAX mesh; 'dist_async' (stale parameter-server updates) is
intentionally unsupported-by-design on TPU, as SURVEY.md §5.8 prescribes.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Union

from .base import MXNetError
from .ndarray import NDArray

__all__ = ["KVStore", "create"]


def _reduce(values: List[NDArray]) -> NDArray:
    """Sum replicas onto the first value's device."""
    if len(values) == 1:
        return values[0]
    acc = values[0].copy()
    for v in values[1:]:
        acc += v.as_in_context(acc.context)
    return acc


class KVStore:
    """In-process key-value store with optional server-side optimizer."""

    def __init__(self, kv_type: str = "local"):
        self._type = kv_type
        self._store: Dict[Any, NDArray] = {}
        self._updater = None
        self._optimizer = None

    # -- identity ----------------------------------------------------------
    @property
    def type(self) -> str:
        return self._type

    @property
    def rank(self) -> int:
        import jax
        return jax.process_index() if self._type.startswith("dist") else 0

    @property
    def num_workers(self) -> int:
        import jax
        return jax.process_count() if self._type.startswith("dist") else 1

    # -- data plane --------------------------------------------------------
    def init(self, key, value) -> None:
        keys, values = _pair(key, value)
        for k, v in zip(keys, values):
            vv = v[0] if isinstance(v, (list, tuple)) else v
            self._store[k] = vv.copy()

    def push(self, key, value, priority: int = 0) -> None:
        keys, values = _pair(key, value)
        for k, v in zip(keys, values):
            vlist = list(v) if isinstance(v, (list, tuple)) else [v]
            reduced = _reduce(vlist)
            if k not in self._store:
                self._store[k] = reduced.copy()
                continue
            if self._updater is not None:
                # server-side optimizer: stored value is the weight
                self._updater(_key_int(k), reduced, self._store[k])
            else:
                # default updater is assign (reference KVStoreLocal behavior)
                self._store[k] = reduced

    def pull(self, key, out=None, priority: int = 0,
             ignore_sparse: bool = True):
        keys, outs = _pair(key, out)
        results = []
        for k, o in zip(keys, outs):
            if k not in self._store:
                raise MXNetError(f"key {k!r} not initialized in kvstore")
            src = self._store[k]
            if o is None:
                results.append(src.copy())
                continue
            olist = list(o) if isinstance(o, (list, tuple)) else [o]
            for tgt in olist:
                src.copyto(tgt)
            results.append(o)
        if out is None:
            return results[0] if not isinstance(key, (list, tuple)) \
                else results
        return out

    def pushpull(self, key, value, out=None, priority: int = 0):
        self.push(key, value, priority)
        return self.pull(key, out if out is not None else value, priority)

    def row_sparse_pull(self, *a, **kw):
        raise MXNetError("sparse storage is not supported on TPU (dense "
                         "embeddings ride the MXU instead)")

    # -- optimizer plane ---------------------------------------------------
    def set_optimizer(self, optimizer) -> None:
        from . import optimizer as opt_mod
        self._optimizer = optimizer
        self._updater = opt_mod.get_updater(optimizer)

    def set_updater(self, updater) -> None:
        self._updater = updater

    def set_gradient_compression(self, compression_params) -> None:
        # reference: 2-bit compression for the DCN-bound PS path; XLA
        # collectives over ICI make this a no-op here (documented gap)
        pass

    def save_optimizer_states(self, fname: str, dump_optimizer=False) -> None:
        if self._updater is None:
            raise MXNetError("no optimizer set on kvstore")
        with open(fname, "wb") as f:
            f.write(self._updater.get_states(dump_optimizer))

    def load_optimizer_states(self, fname: str) -> None:
        if self._updater is None:
            raise MXNetError("no optimizer set on kvstore")
        with open(fname, "rb") as f:
            self._updater.set_states(f.read())

    def barrier(self) -> None:
        from .engine import wait_all
        wait_all()

    def __repr__(self):
        return f"KVStore(type={self._type}, keys={len(self._store)})"


def _key_int(k):
    try:
        return int(k)
    except (TypeError, ValueError):
        return k


def _pair(key, value):
    if isinstance(key, (list, tuple)):
        return list(key), list(value) if value is not None \
            else [None] * len(key)
    return [key], [value]


_SUPPORTED = ("local", "device", "nccl", "dist_sync", "dist_device_sync",
              "dist_async", "dist")


def create(name: str = "local") -> KVStore:
    if not isinstance(name, str):
        raise MXNetError("kvstore name must be a string")
    if name not in _SUPPORTED:
        raise MXNetError(f"unknown kvstore type {name!r}")
    if name == "dist_async":
        raise MXNetError(
            "dist_async (stale parameter-server updates) is unsupported by "
            "design on TPU; use dist_sync (synchronous SPMD over the mesh)")
    return KVStore(name)
