"""KVStore: the gradient-aggregation seam.

Reference parity: src/kvstore/ + python/mxnet/kvstore.py (SURVEY.md §2.3,
§5.8) — `create('local'/'device'/'nccl'/'dist_sync'/...)`, init/push/pull/
pushpull, `set_optimizer` for server-side updates, rank/num_workers.

TPU-native design: all in-process backends ('local'/'device'/'nccl') are one
implementation — push reduces replica gradients (XLA handles cross-device
movement; on a real multi-chip mesh the sharded trainer path in
mxnet_tpu.parallel rides `lax.psum` over ICI instead of this object-level
loop).  'dist_sync' maps to the same synchronous semantics over a
multi-process JAX mesh; 'dist_async' (stale parameter-server updates) is
intentionally unsupported-by-design on TPU, as SURVEY.md §5.8 prescribes.
"""
from __future__ import annotations

import functools as _functools
from typing import Any, Dict, List, Optional, Union

from .base import MXNetError
from .ndarray import NDArray

__all__ = ["KVStore", "create"]


def _reduce(values: List[NDArray]) -> NDArray:
    """Sum replicas onto the first value's device (KVStoreLocal: serial
    device-to-device adds, the reference CommCPU shape).  row_sparse
    replicas aggregate over the UNION of their row sets and stay sparse
    (reference: CommCPU::ReduceRowSparse) — the gradient never densifies
    on the way to the server-side optimizer's lazy row update."""
    from .sparse import BaseSparseNDArray, elemwise_add
    if len(values) == 1:
        return values[0]
    n_sparse = sum(isinstance(v, BaseSparseNDArray) for v in values)
    if n_sparse:
        if n_sparse != len(values):
            raise MXNetError(
                "kvstore push got mixed dense and sparse replicas for one "
                "key — all replicas of a key must share a storage type")
        acc = values[0]
        for v in values[1:]:
            acc = elemwise_add(acc, v)
        return acc
    acc = values[0].copy()
    for v in values[1:]:
        acc += v.as_in_context(acc.context)
    return acc


@_functools.lru_cache(maxsize=None)
def _psum_fn(devs: tuple):
    """One compiled XLA collective summing len(devs) per-device shards.

    The reference's CommDevice/NCCL rings become lax.psum over a Mesh of
    the participating devices (SURVEY §2.3: 'the north-star mapping') —
    XLA schedules the reduction over ICI instead of a hand-rolled
    peer-to-peer loop.  Devices are hashable, so they key the jit cache
    directly."""
    import jax
    try:
        from jax import shard_map
    except ImportError:                    # older jax
        from jax.experimental.shard_map import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    mesh = Mesh(list(devs), ("kv",))

    def f(x):
        return jax.lax.psum(x, "kv")

    return jax.jit(shard_map(f, mesh=mesh, in_specs=P("kv"),
                             out_specs=P()))


def _reduce_collective(values: List[NDArray]) -> NDArray:
    """Device-mode reduce: ONE in-graph psum across the values' devices
    (used by kvstore 'device'/'nccl' when replicas sit on distinct
    devices); falls back to the serial path otherwise.  Sparse replicas
    always take the serial union path — their structure algebra is
    host-side, and a dense psum of row-sparse grads would densify."""
    from .sparse import BaseSparseNDArray
    if any(isinstance(v, BaseSparseNDArray) for v in values):
        return _reduce(values)
    devs = []
    for v in values:
        d = v.context.device
        if d in devs:
            return _reduce(values)          # duplicate device: serial path
        devs.append(d)
    if len(devs) < 2:
        return _reduce(values)
    import jax

    # one shard per pushing device (jax.device_put_sharded is deprecated;
    # the explicit-sharding constructor is its modern spelling)
    import numpy as _np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec
    mesh = Mesh(_np.array(devs), ("kv",))
    sharding = NamedSharding(mesh, PartitionSpec("kv"))
    shards = [jax.device_put(v._read()[None], d)
              for v, d in zip(values, devs)]
    stacked = jax.make_array_from_single_device_arrays(
        (len(devs),) + tuple(values[0].shape), sharding, shards)
    fn = _psum_fn(tuple(devs))
    # the psum result is replicated over the mesh; commit one copy to the
    # first pusher's device so downstream (server-side optimizer) sees a
    # single-device array
    out = jax.device_put(fn(stacked).reshape(values[0].shape), devs[0])
    return NDArray(out, ctx=values[0].context)


class KVStore:
    """In-process key-value store with optional server-side optimizer."""

    def __init__(self, kv_type: str = "local"):
        self._type = kv_type
        self._store: Dict[Any, NDArray] = {}
        self._updater = None
        self._optimizer = None
        self._compression = None
        self._compression_residual: Dict[Any, Any] = {}

    @property
    def _dist(self) -> bool:
        """True when push/pull must cross process boundaries."""
        return self._type.startswith("dist") and self.num_workers > 1

    # -- identity ----------------------------------------------------------
    @property
    def type(self) -> str:
        return self._type

    @property
    def rank(self) -> int:
        import jax
        return jax.process_index() if self._type.startswith("dist") else 0

    @property
    def num_workers(self) -> int:
        import jax
        return jax.process_count() if self._type.startswith("dist") else 1

    # -- data plane --------------------------------------------------------
    def init(self, key, value) -> None:
        keys, values = _pair(key, value)
        for k, v in zip(keys, values):
            vv = v[0] if isinstance(v, (list, tuple)) else v
            if self._dist:
                # reference: only rank 0's init value counts; broadcast it
                # so every process starts from identical weights
                from .parallel import dist as _dist
                from . import ndarray as _nd
                self._store[k] = _nd.array(
                    _dist.broadcast_host(vv.asnumpy()), ctx=vv.context,
                    dtype=vv.dtype)
            else:
                self._store[k] = vv.copy()

    def push(self, key, value, priority: int = 0) -> None:
        keys, values = _pair(key, value)
        # 'device'/'nccl' stores reduce multi-device pushes with ONE
        # compiled psum collective; 'local' keeps the serial CPU path
        reducer = _reduce_collective if "device" in self._type \
            or self._type == "nccl" else _reduce
        reduced_list = []
        for k, v in zip(keys, values):
            vlist = list(v) if isinstance(v, (list, tuple)) else [v]
            reduced_list.append(reducer(vlist))
        if self._dist:
            # one coalesced cross-worker sync for the whole key list —
            # push a LIST of keys to get one DCN round-trip per step
            reduced_list = self._allreduce_batched(keys, reduced_list)
        for k, reduced in zip(keys, reduced_list):
            if k not in self._store:
                self._store[k] = reduced.copy()
                continue
            if self._updater is not None:
                # server-side optimizer: stored value is the weight
                self._updater(_key_int(k), reduced, self._store[k])
            else:
                # default updater is assign (reference KVStoreLocal behavior)
                self._store[k] = reduced

    def _allreduce_batched(self, keys, reduced_list):
        """Sum this process's reduced gradients across all workers in ONE
        host collective (DCN path).

        Reference parity: the reference batches and overlaps per-key
        pushes through the engine + ps-lite (SURVEY.md §2.3, §3.4); the
        TPU-native analog is flat-buffer coalescing — all DENSE keys
        concat into one allreduce (or, compressed, one allgather of
        packed codes), so a 161-param ResNet pays one DCN round-trip per
        step, not 161.  row_sparse keys add three fixed collectives
        (counts, then padded indices and rows — the counts must land
        before the payload can be sized), independent of the number of
        sparse keys.
        """
        import numpy as np
        from . import ndarray as _nd
        from .parallel import dist as _dist
        from .sparse import RowSparseNDArray

        # row_sparse gradients cross DCN as (indices, rows), NEVER the
        # dense matrix (reference: kvstore_dist sparse push, the
        # large-vocab embedding flagship).  Row counts differ per worker,
        # so: one allgather of per-key row counts, then one padded
        # allgather each for indices and rows; workers union-reduce.
        # Compression never applies to sparse keys (the reference's 2-bit
        # path is dense-only) — they ride this path regardless.
        sparse_pos = [i for i, r in enumerate(reduced_list)
                      if isinstance(r, RowSparseNDArray)]
        dense_pos = [i for i, r in enumerate(reduced_list)
                     if not isinstance(r, RowSparseNDArray)]
        sparse_out = {}
        if sparse_pos:
            counts = np.asarray([reduced_list[i].indices.size
                                 for i in sparse_pos], np.int64)
            all_counts = _dist.allgather_host(counts)      # (W, K)
            max_n = all_counts.max(axis=0)                 # per-key pad
            idx_parts, row_parts = [], []
            for j, i in enumerate(sparse_pos):
                rs = reduced_list[i]
                pad = int(max_n[j]) - rs.indices.size
                idx_parts.append(np.pad(rs.indices, (0, pad),
                                        constant_values=-1))
                # explicit row width: reshape(0, -1) is invalid numpy, and
                # an empty push (batch touched no rows of this key) must
                # still reach the collective or peers hang
                width = int(np.prod(rs.shape[1:]))
                rows = rs.data.reshape(rs.indices.size, width)
                row_parts.append(np.pad(rows, ((0, pad), (0, 0))))
            all_idx = _dist.allgather_host(
                np.concatenate(idx_parts) if idx_parts
                else np.zeros(0, np.int64))
            flat_rows = np.concatenate(
                [p.ravel() for p in row_parts]) if row_parts \
                else np.zeros(0, np.float32)
            all_rows = _dist.allgather_host(flat_rows)
            offs_i = np.cumsum([0] + [int(m) for m in max_n])
            row_widths = [int(np.prod(reduced_list[i].shape[1:]))
                          for i in sparse_pos]
            offs_r = np.cumsum(
                [0] + [int(m) * w for m, w in zip(max_n, row_widths)])
            for j, i in enumerate(sparse_pos):
                rs = reduced_list[i]
                w = row_widths[j]
                cat_idx, cat_rows = [], []
                for wk in range(all_idx.shape[0]):
                    n = int(all_counts[wk, j])
                    cat_idx.append(
                        all_idx[wk, offs_i[j]:offs_i[j] + n])
                    cat_rows.append(
                        all_rows[wk, offs_r[j]:offs_r[j] + n * w]
                        .reshape(n, w))
                idx = np.concatenate(cat_idx)
                rows = np.concatenate(cat_rows, axis=0)
                uniq, inv = np.unique(idx, return_inverse=True)
                summed = np.zeros((uniq.size, w), rows.dtype)
                np.add.at(summed, inv, rows)
                # the shared transit buffer may have promoted (e.g. a f16
                # key next to a f32 key); the caller's dtype wins
                summed = summed.astype(rs.data.dtype, copy=False)
                sparse_out[i] = RowSparseNDArray(
                    summed.reshape((uniq.size,) + tuple(rs.shape[1:])),
                    uniq, rs.shape, ctx=rs.context)
            if not dense_pos:
                return [sparse_out[i] for i in range(len(reduced_list))]
            keys = [keys[i] for i in dense_pos]
            reduced_list_dense = [reduced_list[i] for i in dense_pos]
        else:
            reduced_list_dense = reduced_list

        gs = [r.asnumpy() for r in reduced_list_dense]
        out = [None] * len(gs)
        if self._compression is not None:
            # deterministic 2-bit threshold compression with error
            # feedback (reference: src/kvstore/gradient_compression.cc):
            # each worker quantizes grad+residual to {-thr, 0, +thr} by
            # fixed threshold comparison, the residual keeps the
            # quantization error, workers sum the quantized values.
            # Codes cross the wire 2-bit packed, all keys in one buffer.
            thr = float(self._compression["threshold"])
            packed_parts = []
            for k, g in zip(keys, gs):
                resid = self._compression_residual.get(k)
                acc = g if resid is None else g + resid
                codes = np.zeros(acc.shape, np.uint8)
                codes[acc >= thr] = 1
                codes[acc <= -thr] = 2
                q = np.where(codes == 1, thr,
                             np.where(codes == 2, -thr, 0)).astype(g.dtype)
                self._compression_residual[k] = acc - q
                packed_parts.append(_pack2bit(codes.ravel()))
            lens = [p.size for p in packed_parts]
            offs = np.cumsum([0] + lens)
            flat = np.concatenate(packed_parts) if packed_parts else \
                np.zeros(0, np.uint8)
            all_flat = _dist.allgather_host(flat)          # ONE sync
            for i, g in enumerate(gs):
                lo, hi = offs[i], offs[i + 1]
                signed = sum(_unpack2bit(w[lo:hi], g.size)
                             for w in all_flat)
                out[i] = (signed.astype(g.dtype) * thr).reshape(g.shape)
        else:
            # group by dtype so the flat concat never promotes; one
            # allreduce per dtype group (normally exactly one)
            by_dtype = {}
            for i, g in enumerate(gs):
                by_dtype.setdefault(g.dtype.str, []).append(i)
            for idxs in by_dtype.values():
                flat = np.concatenate([gs[i].ravel() for i in idxs])
                summed = _dist.allreduce_host(flat)        # ONE sync
                off = 0
                for i in idxs:
                    n = gs[i].size
                    out[i] = summed[off:off + n].reshape(gs[i].shape)
                    off += n
        dense_res = [_nd.array(g, ctx=r.context, dtype=r.dtype)
                     for g, r in zip(out, reduced_list_dense)]
        if not sparse_pos:
            return dense_res
        dense_by_pos = dict(zip(dense_pos, dense_res))
        return [sparse_out.get(i, dense_by_pos.get(i))
                for i in range(len(reduced_list))]

    def pull(self, key, out=None, priority: int = 0,
             ignore_sparse: bool = True):
        keys, outs = _pair(key, out)
        results = []
        for k, o in zip(keys, outs):
            if k not in self._store:
                raise MXNetError(f"key {k!r} not initialized in kvstore")
            src = self._store[k]
            if o is None:
                results.append(src.copy())
                continue
            olist = list(o) if isinstance(o, (list, tuple)) else [o]
            for tgt in olist:
                src.copyto(tgt)
            results.append(o)
        if out is None:
            return results[0] if not isinstance(key, (list, tuple)) \
                else results
        return out

    def pushpull(self, key, value, out=None, priority: int = 0):
        self.push(key, value, priority)
        return self.pull(key, out if out is not None else value, priority)

    def row_sparse_pull(self, key, out=None, priority: int = 0,
                        row_ids=None):
        """Pull only the rows named by ``row_ids`` as RowSparseNDArrays.

        Reference parity: KVStoreLocal::PullRowSparse — the dense stored
        weight is sliced to the requested rows (sparse.retain semantics)
        so embedding-style pulls move only live rows.
        """
        from .sparse import RowSparseNDArray
        if out is None or row_ids is None:
            raise MXNetError("row_sparse_pull requires out= and row_ids=")
        keys, outs = _pair(key, out)
        per_key = (isinstance(key, (list, tuple)) and
                   isinstance(row_ids, (list, tuple)))
        rids = list(row_ids) if per_key else [row_ids] * len(keys)
        import numpy as np
        from . import ndarray as _nd
        for k, o, rid in zip(keys, outs, rids):
            if k not in self._store:
                raise MXNetError(f"key {k!r} not initialized in kvstore")
            dense = self._store[k]
            ids = np.unique(np.asarray(
                rid.asnumpy() if hasattr(rid, "asnumpy") else rid,
                np.int64))
            # gather the live rows ON DEVICE; only the slice crosses to host
            rows = _nd.take(dense, _nd.array(ids, ctx=dense.context,
                                             dtype="int64"), axis=0)
            rs = RowSparseNDArray(rows.asnumpy(), ids, dense.shape,
                                  ctx=dense.context)
            olist = list(o) if isinstance(o, (list, tuple)) else [o]
            for tgt in olist:
                if isinstance(tgt, RowSparseNDArray):
                    tgt.data, tgt.indices = rs.data, rs.indices
                else:
                    rs.todense().copyto(tgt)
        return out

    # -- optimizer plane ---------------------------------------------------
    def set_optimizer(self, optimizer) -> None:
        from . import optimizer as opt_mod
        self._optimizer = optimizer
        self._updater = opt_mod.get_updater(optimizer)

    def set_updater(self, updater) -> None:
        self._updater = updater

    def set_gradient_compression(self, compression_params) -> None:
        """Enable 2-bit gradient compression with error feedback on the
        cross-process push path (reference:
        src/kvstore/gradient_compression.cc; SURVEY.md §2.3).

        Only meaningful for dist types — in-process reduction rides XLA
        collectives over ICI where compression would cost more than it
        saves, so it raises there (never a silent no-op).
        """
        params = dict(compression_params or {})
        ctype = params.get("type", "2bit")
        if ctype != "2bit":
            raise MXNetError(f"unsupported compression type {ctype!r}; "
                             "only '2bit' exists (reference parity)")
        if not self._type.startswith("dist"):
            raise MXNetError(
                "gradient compression applies to the DCN-bound dist_* "
                "kvstores only; in-process reduction is uncompressed over "
                "ICI by design")
        self._compression = {"type": "2bit",
                             "threshold": float(params.get("threshold", .5))}
        self._compression_residual.clear()

    def save_optimizer_states(self, fname: str, dump_optimizer=False) -> None:
        if self._updater is None:
            raise MXNetError("no optimizer set on kvstore")
        with open(fname, "wb") as f:
            f.write(self._updater.get_states(dump_optimizer))

    def load_optimizer_states(self, fname: str) -> None:
        if self._updater is None:
            raise MXNetError("no optimizer set on kvstore")
        with open(fname, "rb") as f:
            self._updater.set_states(f.read())

    def barrier(self) -> None:
        from .engine import wait_all
        wait_all()
        if self._dist:
            from .parallel import dist as _dist
            _dist.barrier()

    def __repr__(self):
        return f"KVStore(type={self._type}, keys={len(self._store)})"


def _key_int(k):
    try:
        return int(k)
    except (TypeError, ValueError):
        return k


def _pair(key, value):
    if isinstance(key, (list, tuple)):
        return list(key), list(value) if value is not None \
            else [None] * len(key)
    return [key], [value]


_SUPPORTED = ("local", "device", "nccl", "dist_sync", "dist_device_sync",
              "dist_async", "dist")


def create(name: str = "local") -> KVStore:
    if not isinstance(name, str):
        raise MXNetError("kvstore name must be a string")
    if name not in _SUPPORTED:
        raise MXNetError(f"unknown kvstore type {name!r}")
    if name == "dist_async":
        raise MXNetError(
            "dist_async (stale parameter-server updates) is unsupported by "
            "design on TPU; use dist_sync (synchronous SPMD over the mesh)")
    if name.startswith("dist"):
        # join the multi-process runtime now (reference: ps-lite bootstrap
        # from DMLC_* env at kvstore creation); raises with guidance when
        # neither env nor an explicit init_process_group() happened, so
        # dist_sync can never silently run process-local
        from .parallel import dist as _dist
        _dist.init_process_group()
    return KVStore(name)


def _pack2bit(codes):
    """Pack an array of 2-bit codes {0,1,2} into bytes, 4 per byte."""
    import numpy as np
    codes = np.asarray(codes, np.uint8)
    pad = (-codes.size) % 4
    if pad:
        codes = np.concatenate([codes, np.zeros(pad, np.uint8)])
    c = codes.reshape(-1, 4)
    return (c[:, 0] | (c[:, 1] << 2) | (c[:, 2] << 4) |
            (c[:, 3] << 6)).astype(np.uint8)


def _unpack2bit(packed, n):
    """Unpack to a signed {-1,0,+1} int32 array of length n
    (code 1 → +1, code 2 → -1)."""
    import numpy as np
    p = np.asarray(packed, np.uint8)
    c = np.stack([p & 3, (p >> 2) & 3, (p >> 4) & 3, (p >> 6) & 3],
                 axis=1).ravel()[:n]
    return np.where(c == 1, 1, np.where(c == 2, -1, 0)).astype(np.int32)
