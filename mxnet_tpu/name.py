"""mx.name — NameManager / Prefix scopes (reference:
python/mxnet/name.py).  Symbol auto-naming consults the active manager,
so ``with mx.name.Prefix('stage1_'):`` namespaces every op created inside
the scope, exactly as Symbol-era model code expects."""
from __future__ import annotations

import threading
from typing import Optional

__all__ = ["NameManager", "Prefix", "current"]

_state = threading.local()


def current() -> "NameManager":
    stack = getattr(_state, "stack", None)
    if not stack:
        _state.stack = [NameManager()]
    return _state.stack[-1]


class NameManager:
    """Default manager: ``{hint}{counter}`` names (the reference
    behavior, shared with symbol._auto_name)."""

    def get(self, name: Optional[str], hint: str) -> str:
        if name is not None:
            return name
        from .symbol.symbol import _auto_name
        return _auto_name(hint)

    def __enter__(self):
        if not hasattr(_state, "stack"):
            _state.stack = [NameManager()]
        _state.stack.append(self)
        return self

    def __exit__(self, *exc):
        _state.stack.pop()


class Prefix(NameManager):
    """Prepends ``prefix`` to every auto-generated name in the scope."""

    def __init__(self, prefix: str):
        self._prefix = prefix

    def get(self, name: Optional[str], hint: str) -> str:
        if name is not None:
            return name            # explicit names are never prefixed
        return self._prefix + super().get(None, hint)
