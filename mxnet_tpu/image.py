"""``mx.image``: python-side image loading/augmentation (reference:
python/mxnet/image/image.py — SURVEY.md §2.4 "Async image API").

The reference built this on OpenCV handles; this build decodes via PIL
(the image in this environment has no OpenCV) into HWC uint8/float numpy,
with the same augmenter-class composition surface (``CreateAugmenter``,
``ImageIter``).  Heavy batch pipelines should prefer io.ImageRecordIter
(threaded) — as in the reference.

Transfer discipline (the mxlint ``hidden-host-sync`` cleanup): every
augmenter works on host numpy through ``apply_np`` and the iterators run
the WHOLE augmenter chain in numpy, so a pipeline pays exactly ONE
device→host ingestion per image (``_ensure_np``, the single sanctioned
sync site in this module) instead of an NDArray↔numpy round trip per
augmenter.  The public per-augmenter ``__call__`` surface still accepts
and returns NDArrays, unchanged.
"""
from __future__ import annotations

import io as _io
import os
import math as _math
import random as _pyrandom
from typing import List, Optional, Sequence, Tuple

import numpy as _np

from .base import MXNetError
from .context import cpu
from .io import DataBatch, DataDesc, DataIter
from .ndarray import NDArray, array as nd_array

__all__ = ["imread", "imdecode", "imresize", "resize_short", "fixed_crop",
           "center_crop", "random_crop", "color_normalize", "HorizontalFlipAug",
           "ResizeAug", "ForceResizeAug", "CenterCropAug", "RandomCropAug",
           "CastAug", "ColorNormalizeAug", "BrightnessJitterAug",
           "ContrastJitterAug", "SaturationJitterAug", "CreateAugmenter",
           "Augmenter", "ImageIter",
           "scale_down", "random_size_crop", "RandomSizedCropAug",
           "HueJitterAug", "RandomOrderAug", "ColorJitterAug",
           "LightingAug", "RandomGrayAug"]


def _pil():
    try:
        from PIL import Image
        return Image
    except ImportError as e:
        raise MXNetError("image ops need PIL (not installed)") from e


def _ensure_np(src) -> _np.ndarray:
    """THE pipeline host-ingestion point: one device→host transfer per
    image at chain entry; every downstream stage stays in numpy."""
    if isinstance(src, NDArray):
        # single ingestion boundary for the whole augmenter chain
        # (was one sync PER augmenter stage)
        # mxlint: disable=hidden-host-sync — the pipeline's ONE ingest
        return src.asnumpy()
    return _np.asarray(src)


def _imdecode_np(buf: bytes, to_rgb: bool = True, flag: int = 1
                 ) -> _np.ndarray:
    img = _np.asarray(_pil().open(_io.BytesIO(buf)).convert(
        "RGB" if flag else "L"))
    if img.ndim == 2:
        img = img[:, :, None]
    if not to_rgb and img.shape[2] == 3:
        img = img[:, :, ::-1]
    return img


def imdecode(buf: bytes, to_rgb: bool = True, flag: int = 1) -> NDArray:
    """Decode an encoded image buffer to an HWC NDArray
    (reference: mx.image.imdecode over cv2.imdecode)."""
    return nd_array(_imdecode_np(buf, to_rgb, flag), ctx=cpu())


def _imread_np(filename: str, to_rgb: bool = True, flag: int = 1
               ) -> _np.ndarray:
    with open(filename, "rb") as f:
        return _imdecode_np(f.read(), to_rgb=to_rgb, flag=flag)


def imread(filename: str, to_rgb: bool = True, flag: int = 1) -> NDArray:
    return nd_array(_imread_np(filename, to_rgb, flag), ctx=cpu())


def _imresize_np(arr: _np.ndarray, w: int, h: int,
                 interp: int = 1) -> _np.ndarray:
    Image = _pil()
    mode = arr.astype(_np.uint8) if arr.dtype != _np.uint8 else arr
    resample = {0: Image.NEAREST, 1: Image.BILINEAR, 2: Image.BICUBIC,
                3: Image.LANCZOS}.get(interp, Image.BILINEAR)
    out = _np.asarray(Image.fromarray(mode.squeeze() if mode.shape[-1] == 1
                                      else mode).resize((w, h), resample))
    if out.ndim == 2:
        out = out[:, :, None]
    return out.astype(arr.dtype)


def imresize(src, w: int, h: int, interp: int = 1) -> NDArray:
    return nd_array(_imresize_np(_ensure_np(src), w, h, interp),
                    ctx=cpu())


def _resize_short_np(arr: _np.ndarray, size: int,
                     interp: int = 1) -> _np.ndarray:
    h, w = arr.shape[:2]
    if h > w:
        nw, nh = size, int(h * size / w)
    else:
        nw, nh = int(w * size / h), size
    return _imresize_np(arr, nw, nh, interp)


def resize_short(src, size: int, interp: int = 1) -> NDArray:
    return nd_array(_resize_short_np(_ensure_np(src), size, interp),
                    ctx=cpu())


def _fixed_crop_np(arr: _np.ndarray, x0: int, y0: int, w: int, h: int,
                   size: Optional[Tuple[int, int]] = None,
                   interp: int = 1) -> _np.ndarray:
    out = arr[y0:y0 + h, x0:x0 + w]
    if size is not None and (w, h) != size:
        return _imresize_np(out, size[0], size[1], interp)
    return out


def fixed_crop(src, x0: int, y0: int, w: int, h: int,
               size: Optional[Tuple[int, int]] = None,
               interp: int = 1) -> NDArray:
    return nd_array(_fixed_crop_np(_ensure_np(src), x0, y0, w, h, size,
                                   interp), ctx=cpu())


def _center_crop_np(arr: _np.ndarray, size: Tuple[int, int],
                    interp: int = 1):
    h, w = arr.shape[:2]
    cw, ch = size
    x0 = max((w - cw) // 2, 0)
    y0 = max((h - ch) // 2, 0)
    out = _fixed_crop_np(arr, x0, y0, min(cw, w), min(ch, h), size, interp)
    return out, (x0, y0, cw, ch)


def center_crop(src, size: Tuple[int, int], interp: int = 1):
    out, coords = _center_crop_np(_ensure_np(src), size, interp)
    return nd_array(out, ctx=cpu()), coords


def _random_crop_np(arr: _np.ndarray, size: Tuple[int, int],
                    interp: int = 1):
    h, w = arr.shape[:2]
    cw, ch = size
    x0 = _pyrandom.randint(0, max(w - cw, 0))
    y0 = _pyrandom.randint(0, max(h - ch, 0))
    out = _fixed_crop_np(arr, x0, y0, min(cw, w), min(ch, h), size, interp)
    return out, (x0, y0, cw, ch)


def random_crop(src, size: Tuple[int, int], interp: int = 1):
    out, coords = _random_crop_np(_ensure_np(src), size, interp)
    return nd_array(out, ctx=cpu()), coords


def scale_down(src_size: Tuple[int, int], size: Tuple[int, int]):
    """Shrink `size` (w, h) to fit within `src_size` keeping aspect
    (reference mx.image.scale_down)."""
    w, h = size
    sw, sh = src_size
    if sh < h:
        w, h = float(w * sh) / h, sh
    if sw < w:
        w, h = sw, float(h * sw) / w
    return int(w), int(h)


def _random_size_crop_np(arr: _np.ndarray, size: Tuple[int, int], area,
                         ratio, interp: int = 1):
    h, w = arr.shape[:2]
    src_area = h * w
    if isinstance(area, (int, float)):
        area = (area, 1.0)
    for _ in range(10):
        target_area = _pyrandom.uniform(area[0], area[1]) * src_area
        log_ratio = (_math.log(ratio[0]), _math.log(ratio[1]))
        new_ratio = _math.exp(_pyrandom.uniform(*log_ratio))
        new_w = int(round(_math.sqrt(target_area * new_ratio)))
        new_h = int(round(_math.sqrt(target_area / new_ratio)))
        if new_w <= w and new_h <= h:
            x0 = _pyrandom.randint(0, w - new_w)
            y0 = _pyrandom.randint(0, h - new_h)
            out = _fixed_crop_np(arr, x0, y0, new_w, new_h, size, interp)
            return out, (x0, y0, new_w, new_h)
    return _center_crop_np(arr, size, interp)      # fallback


def random_size_crop(src, size: Tuple[int, int], area, ratio,
                     interp: int = 1, **kwargs):
    """Random area/aspect crop then resize to `size` (reference
    mx.image.random_size_crop — the inception-style crop)."""
    out, coords = _random_size_crop_np(_ensure_np(src), size, area,
                                       ratio, interp)
    return nd_array(out, ctx=cpu()), coords


def _color_normalize_np(arr: _np.ndarray, mean, std=None) -> _np.ndarray:
    arr = arr.astype(_np.float32) - _np.asarray(mean, dtype=_np.float32)
    if std is not None:
        arr = arr / _np.asarray(std, dtype=_np.float32)
    return arr


def color_normalize(src, mean, std=None) -> NDArray:
    return nd_array(_color_normalize_np(_ensure_np(src), mean, std),
                    ctx=cpu())


# ---------------------------------------------------------------------------
# augmenter classes (reference: mx.image.Augmenter family)
# ---------------------------------------------------------------------------

class Augmenter:
    """Base augmenter.  Subclasses implement ``apply_np`` (host numpy in
    and out — the whole-chain zero-extra-transfer path the iterators
    use); ``__call__`` keeps the reference's NDArray-in/NDArray-out
    surface by wrapping it (a no-op stage hands back ``src`` itself).
    A legacy user augmenter that overrides only ``__call__`` (the
    pre-refactor surface) still works: the base ``apply_np`` routes
    through it."""

    def apply_np(self, arr: _np.ndarray) -> _np.ndarray:
        if type(self).__call__ is Augmenter.__call__:
            raise NotImplementedError(
                f"{type(self).__name__} implements neither apply_np nor "
                f"__call__")
        # legacy augmenter: only __call__ overridden — bridge through
        # the NDArray surface it was written against
        return _ensure_np(self(nd_array(arr, ctx=cpu())))

    def __call__(self, src: NDArray) -> NDArray:
        arr = _ensure_np(src)
        out = self.apply_np(arr)
        if out is arr and isinstance(src, NDArray):
            return src
        return nd_array(out, ctx=cpu())


class ResizeAug(Augmenter):
    def __init__(self, size: int, interp: int = 1):
        self.size, self.interp = size, interp

    def apply_np(self, arr):
        return _resize_short_np(arr, self.size, self.interp)


class ForceResizeAug(Augmenter):
    def __init__(self, size: Tuple[int, int], interp: int = 1):
        self.size, self.interp = size, interp

    def apply_np(self, arr):
        return _imresize_np(arr, self.size[0], self.size[1], self.interp)


class CenterCropAug(Augmenter):
    def __init__(self, size: Tuple[int, int], interp: int = 1):
        self.size, self.interp = size, interp

    def apply_np(self, arr):
        return _center_crop_np(arr, self.size, self.interp)[0]


class RandomCropAug(Augmenter):
    def __init__(self, size: Tuple[int, int], interp: int = 1):
        self.size, self.interp = size, interp

    def apply_np(self, arr):
        return _random_crop_np(arr, self.size, self.interp)[0]


class HorizontalFlipAug(Augmenter):
    def __init__(self, p: float = 0.5):
        self.p = p

    def apply_np(self, arr):
        if _pyrandom.random() < self.p:
            return arr[:, ::-1].copy()
        return arr


class CastAug(Augmenter):
    def __init__(self, dtype="float32"):
        self.dtype = dtype

    def apply_np(self, arr):
        return arr.astype(self.dtype)


class ColorNormalizeAug(Augmenter):
    def __init__(self, mean, std):
        self.mean, self.std = mean, std

    def apply_np(self, arr):
        return _color_normalize_np(arr, self.mean, self.std)


class _JitterAug(Augmenter):
    def __init__(self, jitter: float):
        self.jitter = jitter

    def _coef(self) -> float:
        return 1.0 + _pyrandom.uniform(-self.jitter, self.jitter)


class BrightnessJitterAug(_JitterAug):
    def apply_np(self, arr):
        return arr.astype(_np.float32) * self._coef()


class ContrastJitterAug(_JitterAug):
    def apply_np(self, arr):
        arr = arr.astype(_np.float32)
        mean = arr.mean()
        return (arr - mean) * self._coef() + mean


class SaturationJitterAug(_JitterAug):
    def apply_np(self, arr):
        arr = arr.astype(_np.float32)
        gray = arr.mean(axis=2, keepdims=True)
        c = self._coef()
        return arr * c + gray * (1.0 - c)


class RandomSizedCropAug(Augmenter):
    def __init__(self, size, area, ratio, interp: int = 1):
        self.size, self.area, self.ratio, self.interp = \
            size, area, ratio, interp

    def apply_np(self, arr):
        return _random_size_crop_np(arr, self.size, self.area, self.ratio,
                                    self.interp)[0]


class HueJitterAug(Augmenter):
    """Hue rotation in YIQ space (reference HueJitterAug's tyiq/ityiq
    matrices)."""

    _TYIQ = _np.array([[0.299, 0.587, 0.114],
                       [0.596, -0.274, -0.321],
                       [0.211, -0.523, 0.311]], _np.float32)
    _ITYIQ = _np.array([[1.0, 0.956, 0.621],
                        [1.0, -0.272, -0.647],
                        [1.0, -1.107, 1.705]], _np.float32)

    def __init__(self, hue: float):
        self.hue = hue

    def apply_np(self, arr):
        alpha = _pyrandom.uniform(-self.hue, self.hue)
        u = _math.cos(alpha * _math.pi)
        w = _math.sin(alpha * _math.pi)
        bt = _np.array([[1.0, 0.0, 0.0], [0.0, u, -w], [0.0, w, u]],
                       _np.float32)
        t = self._ITYIQ @ bt @ self._TYIQ
        return arr.astype(_np.float32) @ t.T


class RandomOrderAug(Augmenter):
    """Apply child augmenters in random order (reference RandomOrderAug)."""

    def __init__(self, ts):
        self.ts = list(ts)

    def apply_np(self, arr):
        order = list(self.ts)
        _pyrandom.shuffle(order)
        for t in order:
            arr = t.apply_np(arr)
        return arr


class ColorJitterAug(RandomOrderAug):
    """Brightness/contrast/saturation jitter in random order (reference
    ColorJitterAug)."""

    def __init__(self, brightness: float, contrast: float,
                 saturation: float):
        ts = []
        if brightness > 0:
            ts.append(BrightnessJitterAug(brightness))
        if contrast > 0:
            ts.append(ContrastJitterAug(contrast))
        if saturation > 0:
            ts.append(SaturationJitterAug(saturation))
        super().__init__(ts)


class LightingAug(Augmenter):
    """PCA-noise lighting (reference LightingAug / AlexNet-style)."""

    def __init__(self, alphastd, eigval, eigvec):
        self.alphastd = alphastd
        self.eigval = _np.asarray(eigval, _np.float32)
        self.eigvec = _np.asarray(eigvec, _np.float32)

    def apply_np(self, arr):
        alpha = _np.random.normal(0, self.alphastd, size=(3,))
        rgb = (self.eigvec * alpha) @ self.eigval
        return arr.astype(_np.float32) + rgb


class RandomGrayAug(Augmenter):
    """Convert to 3-channel gray with probability p (reference
    RandomGrayAug)."""

    _MAT = _np.array([[0.21, 0.21, 0.21],
                      [0.72, 0.72, 0.72],
                      [0.07, 0.07, 0.07]], _np.float32)

    def __init__(self, p: float = 0.5):
        self.p = p

    def apply_np(self, arr):
        if _pyrandom.random() < self.p:
            return arr.astype(_np.float32) @ self._MAT
        return arr


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,
                    rand_mirror=False, mean=None, std=None,
                    brightness=0, contrast=0, saturation=0, hue=0,
                    pca_noise=0, rand_gray=0, inter_method=1,
                    **kwargs) -> List[Augmenter]:
    """Standard augmenter pipeline factory (reference: CreateAugmenter)."""
    auglist: List[Augmenter] = []
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))
    crop = (data_shape[2], data_shape[1])
    if rand_resize:
        # inception-style random area/aspect crop (reference: rand_resize
        # implies rand_crop)
        auglist.append(RandomSizedCropAug(crop, (0.08, 1.0),
                                          (3.0 / 4.0, 4.0 / 3.0),
                                          inter_method))
    elif rand_crop:
        auglist.append(RandomCropAug(crop, inter_method))
    else:
        auglist.append(CenterCropAug(crop, inter_method))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    auglist.append(CastAug())
    if brightness or contrast or saturation:
        auglist.append(ColorJitterAug(brightness, contrast, saturation))
    if hue:
        auglist.append(HueJitterAug(hue))
    if pca_noise > 0:
        eigval = _np.array([55.46, 4.794, 1.148])
        eigvec = _np.array([[-0.5675, 0.7192, 0.4009],
                            [-0.5808, -0.0045, -0.8140],
                            [-0.5836, -0.6948, 0.4203]])
        auglist.append(LightingAug(pca_noise, eigval, eigvec))
    if rand_gray > 0:
        auglist.append(RandomGrayAug(rand_gray))
    if mean is True:
        mean = _np.array([123.68, 116.28, 103.53])
    if std is True:
        std = _np.array([58.395, 57.12, 57.375])
    if mean is not None:
        auglist.append(ColorNormalizeAug(mean, std))
    return auglist


class ImageIter(DataIter):
    """Python-side image iterator over (label, path) lists or .lst files
    (reference: mx.image.ImageIter)."""

    def __init__(self, batch_size: int, data_shape: Sequence[int],
                 path_root: str = "", imglist=None, path_imglist: str = "",
                 shuffle: bool = False, aug_list=None,
                 label_width: int = 1, **kwargs):
        super().__init__(batch_size)
        self.data_shape = tuple(data_shape)
        self.path_root = path_root
        self.label_width = label_width
        if imglist is None and path_imglist:
            imglist = []
            with open(path_imglist) as f:
                for line in f:
                    parts = line.strip().split("\t")
                    imglist.append([float(x) for x in
                                    parts[1:1 + label_width]] + [parts[-1]])
        if not imglist:
            raise MXNetError("ImageIter needs imglist or path_imglist")
        self.imglist = list(imglist)
        self.shuffle = shuffle
        self.aug_list = aug_list if aug_list is not None else \
            CreateAugmenter(data_shape)
        self.cur = 0
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc("data", (self.batch_size,) + self.data_shape)]

    @property
    def provide_label(self):
        shape = (self.batch_size,) if self.label_width == 1 else \
            (self.batch_size, self.label_width)
        return [DataDesc("softmax_label", shape)]

    def reset(self):
        if self.shuffle:
            _pyrandom.shuffle(self.imglist)
        self.cur = 0

    def next(self) -> DataBatch:
        if self.cur + self.batch_size > len(self.imglist):
            raise StopIteration
        datas, labels = [], []
        for entry in self.imglist[self.cur:self.cur + self.batch_size]:
            *label, path = entry
            # the whole chain runs in host numpy: zero device round trips
            # until the one batched upload below
            arr = _imread_np(os.path.join(self.path_root, path))
            for aug in self.aug_list:
                arr = aug.apply_np(arr)
            datas.append(arr.transpose(2, 0, 1))
            labels.append(label if self.label_width > 1 else label[0])
        self.cur += self.batch_size
        return DataBatch(
            [nd_array(_np.stack(datas).astype(_np.float32), ctx=cpu())],
            [nd_array(_np.asarray(labels, dtype=_np.float32), ctx=cpu())],
            provide_data=self.provide_data,
            provide_label=self.provide_label)


# ---------------------------------------------------------------------------
# Detection augmentation (reference: python/mxnet/image/detection.py)
# ---------------------------------------------------------------------------
# Labels ride with the image through every augmenter as an (N, 5+) float
# array [cls, x1, y1, x2, y2, ...] with corner coords normalized to [0,1];
# geometric augmenters transform the boxes, photometric ones borrow the
# plain image augmenters unchanged.

class DetAugmenter:
    """Base detection augmenter: ``(src, label) -> (src, label)``.
    Subclasses implement ``apply_np`` (numpy image + label in/out);
    ``__call__`` keeps the NDArray surface, as with :class:`Augmenter`
    (including the legacy-``__call__``-only bridge)."""

    def apply_np(self, arr, label):
        if type(self).__call__ is DetAugmenter.__call__:
            raise NotImplementedError(
                f"{type(self).__name__} implements neither apply_np nor "
                f"__call__")
        out, label = self(nd_array(arr, ctx=cpu()), label)
        return _ensure_np(out), label

    def __call__(self, src, label):
        arr = _ensure_np(src)
        out, label = self.apply_np(arr, label)
        if out is arr and isinstance(src, NDArray):
            return src, label
        return nd_array(out, ctx=cpu()), label


class DetBorrowAug(DetAugmenter):
    """Wrap a geometry-preserving image Augmenter (reference
    DetBorrowAug)."""

    def __init__(self, augmenter: Augmenter):
        self.augmenter = augmenter

    def apply_np(self, arr, label):
        return self.augmenter.apply_np(arr), label


class DetHorizontalFlipAug(DetAugmenter):
    """Mirror image and boxes with probability p."""

    def __init__(self, p: float = 0.5):
        self.p = p

    def apply_np(self, arr, label):
        if _pyrandom.random() < self.p:
            arr = arr[:, ::-1].copy()
            label = label.copy()
            x1 = label[:, 1].copy()
            label[:, 1] = 1.0 - label[:, 3]
            label[:, 3] = 1.0 - x1
        return arr, label


class DetRandomCropAug(DetAugmenter):
    """IoU-constrained random crop (reference DetRandomCropAug): sample a
    crop whose min-object coverage clears the threshold; keep boxes whose
    centers fall inside, clip and renormalize them."""

    def __init__(self, min_object_covered=0.1,
                 aspect_ratio_range=(0.75, 1.33), area_range=(0.05, 1.0),
                 max_attempts=50):
        self.min_object_covered = min_object_covered
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.max_attempts = max_attempts

    def _coverage(self, boxes, crop):
        cx1, cy1, cx2, cy2 = crop
        ix1 = _np.maximum(boxes[:, 1], cx1)
        iy1 = _np.maximum(boxes[:, 2], cy1)
        ix2 = _np.minimum(boxes[:, 3], cx2)
        iy2 = _np.minimum(boxes[:, 4], cy2)
        inter = _np.maximum(ix2 - ix1, 0) * _np.maximum(iy2 - iy1, 0)
        area = (boxes[:, 3] - boxes[:, 1]) * (boxes[:, 4] - boxes[:, 2])
        return inter / _np.maximum(area, 1e-12)

    def apply_np(self, arr, label):
        h, w = arr.shape[0], arr.shape[1]
        for _ in range(self.max_attempts):
            area_f = _pyrandom.uniform(*self.area_range)
            ar = _pyrandom.uniform(*self.aspect_ratio_range)
            cw = min(1.0, _np.sqrt(area_f * ar))
            ch = min(1.0, area_f / max(cw, 1e-12))
            cx = _pyrandom.uniform(0, 1 - cw)
            cy = _pyrandom.uniform(0, 1 - ch)
            crop = (cx, cy, cx + cw, cy + ch)
            if label.shape[0]:
                cov = self._coverage(label, crop)
                if cov.max(initial=0.0) < self.min_object_covered:
                    continue
                centers_x = (label[:, 1] + label[:, 3]) / 2
                centers_y = (label[:, 2] + label[:, 4]) / 2
                keep = ((centers_x >= cx) & (centers_x <= cx + cw) &
                        (centers_y >= cy) & (centers_y <= cy + ch))
                if not keep.any():
                    continue
            else:
                keep = _np.zeros((0,), bool)
            x0, y0 = int(cx * w), int(cy * h)
            pw, ph = max(1, int(cw * w)), max(1, int(ch * h))
            img = _fixed_crop_np(arr, x0, y0, pw, ph)
            new = label[keep].copy()
            if new.shape[0]:
                new[:, 1] = _np.clip((new[:, 1] - cx) / cw, 0, 1)
                new[:, 3] = _np.clip((new[:, 3] - cx) / cw, 0, 1)
                new[:, 2] = _np.clip((new[:, 2] - cy) / ch, 0, 1)
                new[:, 4] = _np.clip((new[:, 4] - cy) / ch, 0, 1)
            return img, new
        return arr, label


class DetRandomPadAug(DetAugmenter):
    """Expand the canvas and place the image randomly (reference
    DetRandomPadAug); boxes shrink into the new frame."""

    def __init__(self, aspect_ratio_range=(0.75, 1.33),
                 area_range=(1.0, 3.0), max_attempts=50,
                 pad_val=(127, 127, 127)):
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.max_attempts = max_attempts
        self.pad_val = pad_val

    def apply_np(self, arr, label):
        h, w, c = arr.shape
        # retry like DetRandomCropAug: keep sampling until the draw
        # actually expands the canvas
        scale = 1.0
        for _ in range(self.max_attempts):
            scale = _pyrandom.uniform(*self.area_range)
            if scale > 1.0:
                break
        if scale <= 1.0:
            return arr, label
        ar = _pyrandom.uniform(*self.aspect_ratio_range)
        nw = int(w * _np.sqrt(scale * ar))
        nh = int(h * scale / max(_np.sqrt(scale * ar), 1e-12))
        nw, nh = max(nw, w), max(nh, h)
        x0 = _pyrandom.randint(0, nw - w)
        y0 = _pyrandom.randint(0, nh - h)
        canvas = _np.empty((nh, nw, c), arr.dtype)
        canvas[:] = _np.asarray(self.pad_val)[:c]
        canvas[y0:y0 + h, x0:x0 + w] = arr
        new = label.copy()
        if new.shape[0]:
            new[:, 1] = (new[:, 1] * w + x0) / nw
            new[:, 3] = (new[:, 3] * w + x0) / nw
            new[:, 2] = (new[:, 2] * h + y0) / nh
            new[:, 4] = (new[:, 4] * h + y0) / nh
        return canvas, new


class DetRandomSelectAug(DetAugmenter):
    """Randomly pick one of several augmenter lists (reference
    DetRandomSelectAug); skip_prob leaves the sample unchanged."""

    def __init__(self, aug_list, skip_prob: float = 0.0):
        self.aug_list = aug_list
        self.skip_prob = skip_prob

    def apply_np(self, arr, label):
        if _pyrandom.random() < self.skip_prob:
            return arr, label
        for aug in _pyrandom.choice(self.aug_list):
            arr, label = aug.apply_np(arr, label)
        return arr, label


def CreateDetAugmenter(data_shape, resize=0, rand_crop=0.0, rand_pad=0.0,
                       rand_mirror=False, mean=None, std=None, brightness=0,
                       contrast=0, saturation=0, pad_val=(127, 127, 127),
                       min_object_covered=0.1,
                       aspect_ratio_range=(0.75, 1.33),
                       area_range=(0.05, 3.0), max_attempts=50,
                       inter_method=1, **kwargs) -> List[DetAugmenter]:
    """Detection pipeline factory (reference CreateDetAugmenter)."""
    auglist: List[DetAugmenter] = []
    if resize > 0:
        auglist.append(DetBorrowAug(ResizeAug(resize, inter_method)))
    if rand_crop > 0:
        crop = DetRandomCropAug(min_object_covered, aspect_ratio_range,
                                (area_range[0], min(1.0, area_range[1])),
                                max_attempts)
        auglist.append(DetRandomSelectAug([[crop]], 1 - rand_crop))
    if rand_pad > 0:
        pad = DetRandomPadAug(aspect_ratio_range,
                              (1.0, max(1.0, area_range[1])), max_attempts,
                              pad_val)
        auglist.append(DetRandomSelectAug([[pad]], 1 - rand_pad))
    if rand_mirror:
        auglist.append(DetHorizontalFlipAug(0.5))
    # final shape: force resize to the network input
    auglist.append(DetBorrowAug(
        ForceResizeAug((data_shape[2], data_shape[1]), inter_method)))
    auglist.append(DetBorrowAug(CastAug()))
    if brightness:
        auglist.append(DetBorrowAug(BrightnessJitterAug(brightness)))
    if contrast:
        auglist.append(DetBorrowAug(ContrastJitterAug(contrast)))
    if saturation:
        auglist.append(DetBorrowAug(SaturationJitterAug(saturation)))
    if mean is True:
        mean = _np.array([123.68, 116.28, 103.53])
    if std is True:
        std = _np.array([58.395, 57.12, 57.375])
    if mean is not None:
        auglist.append(DetBorrowAug(ColorNormalizeAug(mean, std)))
    return auglist


class ImageDetIter(DataIter):
    """Detection iterator (reference mx.image.ImageDetIter): images plus
    variable-count box labels, padded to a fixed (batch, max_objs, 5)
    label tensor with -1 rows — the static shape the SSD target ops (and
    XLA) need."""

    def __init__(self, batch_size: int, data_shape: Sequence[int],
                 path_root: str = "", imglist=None, shuffle: bool = False,
                 aug_list=None, data_name: str = "data",
                 label_name: str = "label", **kwargs):
        super().__init__(batch_size)
        self.data_shape = tuple(data_shape)
        self.path_root = path_root
        self.data_name = data_name
        self.label_name = label_name
        if not imglist:
            raise MXNetError("ImageDetIter needs imglist: entries of "
                             "[label_array (N,5+), path]")
        self.imglist = []
        for lab, path in imglist:
            lab = _np.asarray(lab, _np.float32)
            if lab.ndim == 1:
                lab = lab.reshape(1, -1)
            if lab.ndim != 2 or lab.shape[1] < 5:
                raise MXNetError(
                    f"detection label for {path!r} must be (N, 5+) "
                    f"[cls, x1, y1, x2, y2, ...], got {lab.shape}")
            # extra columns beyond 5 (difficult flags etc.) are dropped;
            # never re-chunk the buffer
            self.imglist.append((lab[:, :5].copy(), path))
        self.max_objs = max(lab.shape[0] for lab, _ in self.imglist)
        self.shuffle = shuffle
        self.aug_list = aug_list if aug_list is not None else \
            CreateDetAugmenter(data_shape)
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc(self.data_name,
                         (self.batch_size,) + self.data_shape)]

    @property
    def provide_label(self):
        return [DataDesc(self.label_name,
                         (self.batch_size, self.max_objs, 5))]

    def reset(self):
        if self.shuffle:
            _pyrandom.shuffle(self.imglist)
        self.cur = 0

    def next(self) -> DataBatch:
        if self.cur + self.batch_size > len(self.imglist):
            raise StopIteration
        datas, labels = [], []
        for lab, path in self.imglist[self.cur:self.cur + self.batch_size]:
            # host-numpy end to end, like ImageIter.next
            arr = _imread_np(os.path.join(self.path_root, path))
            label = lab.copy()
            for aug in self.aug_list:
                arr, label = aug.apply_np(arr, label)
            datas.append(arr.transpose(2, 0, 1))
            pad = _np.full((self.max_objs, 5), -1.0, _np.float32)
            n = min(label.shape[0], self.max_objs)
            if n:
                pad[:n] = label[:n, :5]
            labels.append(pad)
        self.cur += self.batch_size
        return DataBatch(
            [nd_array(_np.stack(datas).astype(_np.float32), ctx=cpu())],
            [nd_array(_np.stack(labels), ctx=cpu())],
            provide_data=self.provide_data,
            provide_label=self.provide_label)


__all__ += ["DetAugmenter", "DetBorrowAug", "DetHorizontalFlipAug",
            "DetRandomCropAug", "DetRandomPadAug", "DetRandomSelectAug",
            "CreateDetAugmenter", "ImageDetIter"]
