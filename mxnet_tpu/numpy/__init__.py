"""``mxnet_tpu.numpy`` (mx.np): the numpy-compatible frontend.

Reference parity: python/mxnet/numpy/ — the 2.x-era interface that lets
numpy-written code run on the accelerator unchanged (SURVEY.md §2.5
frontend tail).  Arrays ARE the framework's NDArray (autograd, device
placement, and the op registry all apply); this module adds numpy's
NAMES and numpy's CONVENTIONS where the legacy nd namespace deliberately
differs:

- comparisons and predicates return BOOL arrays (nd returns 0/1 in the
  input dtype — the 1.x legacy convention);
- ``np.random`` draws ride the same key-threading discipline as
  ``mx.nd.random`` (seeded by ``mx.random.seed``);
- reductions accept ``axis`` tuples and ``keepdims`` with numpy
  defaults.

Everything not wrapped here is reachable via ``mx.nd`` — the two
frontends share the registry, so there is exactly one implementation
per operator (the reference keeps a parallel _npi_* registry; one
registry with two naming surfaces is the TPU-first simplification).
"""
from __future__ import annotations

import numpy as _onp

from ..ndarray import NDArray
from ..ndarray import ndarray as _ndmod
from .. import ndarray as _nd

ndarray = NDArray

__all__ = [
    "einsum", "take", "sort", "argsort", "unique",
    "ndarray", "array", "zeros", "ones", "full", "empty", "arange",
    "linspace", "eye", "reshape", "transpose", "concatenate", "stack",
    "split", "expand_dims", "squeeze", "where", "add", "subtract",
    "multiply", "divide", "power", "mod", "dot", "matmul", "tensordot",
    "exp", "log", "sqrt", "abs", "absolute", "sign", "maximum",
    "minimum", "clip", "tanh", "sin", "cos", "sum", "mean", "max",
    "min", "prod", "argmax", "argmin", "cumsum", "equal", "not_equal",
    "greater", "greater_equal", "less", "less_equal", "logical_and",
    "logical_or", "logical_not", "isnan", "isinf", "isfinite", "random",
]


def _bool(x: NDArray) -> NDArray:
    return x.astype(_onp.bool_)


# -- creation ---------------------------------------------------------------

def array(obj, dtype=None, ctx=None):
    return _nd.array(obj, dtype=dtype, ctx=ctx)


def zeros(shape, dtype=None, ctx=None):
    return _nd.zeros(shape, dtype=dtype or "float32", ctx=ctx)


def ones(shape, dtype=None, ctx=None):
    return _nd.ones(shape, dtype=dtype or "float32", ctx=ctx)


def full(shape, fill_value, dtype=None, ctx=None):
    return _nd.full(shape, fill_value, dtype=dtype, ctx=ctx)


def empty(shape, dtype=None, ctx=None):
    return _nd.empty(shape, dtype=dtype, ctx=ctx)


def arange(start, stop=None, step=1, dtype=None, ctx=None):
    return _nd.arange(start, stop, step, dtype=dtype, ctx=ctx)


def linspace(start, stop, num=50, endpoint=True, dtype=None, ctx=None):
    vals = _onp.linspace(start, stop, num, endpoint=endpoint,
                         dtype=dtype or _onp.float32)
    return _nd.array(vals, ctx=ctx)


def eye(N, M=None, k=0, dtype=None, ctx=None):
    return _nd.array(_onp.eye(N, M, k, dtype=dtype or _onp.float32),
                     ctx=ctx)


# -- manipulation -----------------------------------------------------------

def reshape(a, newshape, order="C"):
    if order != "C":
        raise NotImplementedError(
            "mx.np.reshape supports C order only (XLA row-major); "
            "transpose explicitly for Fortran-order views")
    return _nd.reshape(a, shape=newshape)


def transpose(a, axes=None):
    return _nd.transpose(a) if axes is None else \
        _nd.transpose(a, axes=tuple(axes))


def concatenate(seq, axis=0):
    return _nd.concat(*seq, dim=axis)


def stack(seq, axis=0):
    return _nd.stack(*seq, axis=axis, num_args=len(seq))


def split(a, indices_or_sections, axis=0):
    out = _nd.split_v2(a, indices_or_sections, axis=axis)
    return list(out) if isinstance(out, (list, tuple)) else [out]


def expand_dims(a, axis):
    return _nd.expand_dims(a, axis=axis)


def squeeze(a, axis=None):
    return _nd.squeeze(a) if axis is None else _nd.squeeze(a, axis=axis)


def where(condition, x=None, y=None):
    if x is None and y is None:
        # nonzero form: host-side (value-dependent shape)
        idx = _onp.nonzero(condition.asnumpy())
        # int64 under enable_large_tensor(), int32 otherwise (the
        # documented dtype contract — jax_compute_dtype applies)
        return tuple(_nd.array(i, dtype="int64") for i in idx)
    return _nd.where(condition.astype(x.dtype), x, y)


# -- math -------------------------------------------------------------------

add = _nd.broadcast_add
subtract = _nd.broadcast_sub
multiply = _nd.broadcast_mul
divide = _nd.broadcast_div
power = _nd.broadcast_power
mod = _nd.broadcast_mod
maximum = _nd.broadcast_maximum
minimum = _nd.broadcast_minimum
dot = _nd.dot
tensordot = _nd.tensordot
exp = _nd.exp
log = _nd.log
sqrt = _nd.sqrt
abs = _nd.abs                                       # noqa: A001
absolute = _nd.abs
sign = _nd.sign
tanh = _nd.tanh
sin = _nd.sin
cos = _nd.cos


def matmul(a, b):
    # numpy semantics: stacked matmul with BROADCAST batch dims
    if a.ndim <= 2 and b.ndim <= 2:
        return _nd.dot(a, b)
    batch = _onp.broadcast_shapes(a.shape[:-2], b.shape[:-2])

    def _expand(t):
        lead = len(batch) - (t.ndim - 2)
        if lead:
            t = t.reshape((1,) * lead + t.shape)
        if t.shape[:-2] != batch:
            t = _nd.broadcast_to(t, shape=batch + t.shape[-2:])
        return t

    ae, be = _expand(a), _expand(b)
    out = _nd.batch_dot(ae.reshape((-1,) + ae.shape[-2:]),
                        be.reshape((-1,) + be.shape[-2:]))
    return out.reshape(batch + (a.shape[-2], b.shape[-1]))


def clip(a, a_min, a_max):
    # numpy allows one-sided clipping via None bounds
    lo = float("-inf") if a_min is None else float(a_min)
    hi = float("inf") if a_max is None else float(a_max)
    return _nd.clip(a, a_min=lo, a_max=hi)


# -- reductions (numpy defaults: axis tuples, keepdims) ---------------------

def _reduce(fn):
    def f(a, axis=None, keepdims=False):
        if axis is None:
            return fn(a, keepdims=keepdims)
        ax = axis if isinstance(axis, int) else tuple(axis)
        return fn(a, axis=ax, keepdims=keepdims)
    return f


sum = _reduce(_nd.sum)                              # noqa: A001
mean = _reduce(_nd.mean)
max = _reduce(_nd.max)                              # noqa: A001
min = _reduce(_nd.min)                              # noqa: A001
prod = _reduce(_nd.prod)


def argmax(a, axis=None):
    if axis is None:
        return _nd.argmax(_nd.reshape(a, shape=(-1,)), axis=0) \
            .astype(_onp.int64)
    return _nd.argmax(a, axis=axis).astype(_onp.int64)


def argmin(a, axis=None):
    if axis is None:
        return _nd.argmin(_nd.reshape(a, shape=(-1,)), axis=0) \
            .astype(_onp.int64)
    return _nd.argmin(a, axis=axis).astype(_onp.int64)


def cumsum(a, axis=None, dtype=None):
    out = _nd.cumsum(a) if axis is None else _nd.cumsum(a, axis=axis)
    return out.astype(dtype) if dtype is not None else out


# -- comparisons / predicates (numpy: BOOL dtype) ---------------------------

def _cmp(fn):
    def f(a, b):
        return _bool(fn(a, b))
    return f


equal = _cmp(_nd.broadcast_equal)
not_equal = _cmp(_nd.broadcast_not_equal)
greater = _cmp(_nd.broadcast_greater)
greater_equal = _cmp(_nd.broadcast_greater_equal)
less = _cmp(_nd.broadcast_lesser)
less_equal = _cmp(_nd.broadcast_lesser_equal)
logical_and = _cmp(_nd.broadcast_logical_and)
logical_or = _cmp(_nd.broadcast_logical_or)


def logical_not(a):
    return _bool(_nd.logical_not(a))


def isnan(a):
    return _bool(_nd.isnan(a))


def isinf(a):
    return _bool(_nd.isinf(a))


def isfinite(a):
    return _bool(_nd.isfinite(a))


# -- random -----------------------------------------------------------------

class _Random:
    """np.random over the framework key stream (mx.random.seed)."""

    @staticmethod
    def uniform(low=0.0, high=1.0, size=None, ctx=None):
        return _nd.random.uniform(low, high,
                                  shape=size if size is not None else (),
                                  ctx=ctx)

    @staticmethod
    def normal(loc=0.0, scale=1.0, size=None, ctx=None):
        return _nd.random.normal(loc, scale,
                                 shape=size if size is not None else (),
                                 ctx=ctx)

    @staticmethod
    def randint(low, high=None, size=None, dtype="int32", ctx=None):
        lo, hi = (0, low) if high is None else (low, high)
        return _nd.random.randint(lo, hi,
                                  shape=size if size is not None else (),
                                  dtype=dtype, ctx=ctx)

    @staticmethod
    def shuffle(a):
        # numpy contract: in-place along axis 0
        a[:] = _nd.random.shuffle(a)


random = _Random()


def einsum(subscripts, *operands):
    return _nd.einsum(*operands, subscripts=subscripts)


def take(a, indices, axis=None):
    if axis is None:
        a = _nd.reshape(a, shape=(-1,))
        axis = 0
    idx = indices if isinstance(indices, NDArray) else _nd.array(indices)
    return _nd.take(a, idx.astype(_onp.int32), axis=axis)


def sort(a, axis=-1):
    return _nd.sort(a, axis=axis)


def argsort(a, axis=-1):
    return _nd.argsort(a, axis=axis).astype(_onp.int64)


def unique(ar):
    # value-dependent output shape: host-side, like np.where's nonzero
    vals = _onp.unique(ar.asnumpy())
    return _nd.array(vals)
