"""Profiler (reference: src/profiler/ + python/mxnet/profiler.py,
SURVEY.md §5.1).

Two levels, mirroring the reference:
- **Op events** from the engine's dispatch listener → chrome://tracing JSON
  (``dump()``) and an aggregate table (``dumps()``), the analog of the
  reference's OprBlock begin/end events.  Dispatch wall-time is recorded;
  because XLA dispatch is async, per-op *device* time lives in the XLA
  trace below (the reference had the same split: engine events vs CUDA
  kernels).
- **Device/XLA traces** via ``jax.profiler`` (XPlane/perfetto) when
  ``profile_all=True``: written to ``trace_dir`` if configured, else to
  ``<filename>_xla/`` next to the chrome trace — the analog of nvprof/NVTX.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional

from .base import MXNetError
from .engine import engine

__all__ = ["set_config", "set_state", "state", "pause", "resume", "dump",
           "dumps", "Profiler"]


class Profiler:
    _inst: Optional["Profiler"] = None

    def __init__(self):
        self.filename = "profile_output.json"
        self.profile_all = False
        self.aggregate_stats = True
        self.trace_dir: Optional[str] = None
        self._running = False
        self._paused = False
        self._events: List[dict] = []
        self._agg: Dict[str, List[float]] = {}
        self._lock = threading.Lock()
        self._listener_installed = False
        self._t0 = time.perf_counter()
        # ONE timeline for the whole fleet: pid = this process's host
        # index (resolved lazily — profiling may start before the
        # process group), tid = a small per-thread lane so supervisor
        # steps, loader workers, and engine flushes land on separate
        # rows of the same chrome trace
        self._pid: Optional[int] = None
        self._tids: Dict[int, int] = {}      # thread ident -> lane
        self._tnames: Dict[int, str] = {}    # lane -> thread name

    def _host_pid(self) -> int:
        # cached so the per-event path never probes; start() clears the
        # cache, so each profiling session re-resolves — a session begun
        # AFTER init_process_group gets the real host index even if an
        # earlier pre-init session cached the single-process fallback
        if self._pid is None:
            try:
                from .parallel.dist import is_initialized
                if is_initialized():
                    import jax
                    self._pid = jax.process_index()
                else:
                    self._pid = 0
            except Exception:   # noqa: BLE001 — a broken dist probe must
                self._pid = 0   # not break profiling
        return self._pid

    def _lane_locked(self) -> int:
        """Small stable per-thread tid; callers hold self._lock (the
        ``_locked`` suffix is the lint-checked convention for that)."""
        ident = threading.get_ident()
        lane = self._tids.get(ident)
        if lane is None:
            lane = len(self._tids)
            self._tids[ident] = lane
            self._tnames[lane] = threading.current_thread().name
        return lane

    @classmethod
    def get(cls) -> "Profiler":
        if cls._inst is None:
            cls._inst = Profiler()
        return cls._inst

    # -- engine listener ---------------------------------------------------
    def _on_op(self, op_name: str, outputs, dispatch_us: float = 0.0) -> None:
        if not self._running or self._paused:
            return
        if op_name.startswith("span:"):
            # the engine-listener echo of a trace span — the real event
            # (correct start timestamp, host pid, thread lane) arrives
            # through _on_span; counting this too would double it
            return
        now = (time.perf_counter() - self._t0) * 1e6   # µs
        dur = max(dispatch_us, 0.1)                    # measured, not gap
        pid = self._host_pid()
        with self._lock:
            self._events.append({
                "name": op_name, "ph": "X", "pid": pid,
                "tid": self._lane_locked(), "ts": now - dur, "dur": dur,
                "cat": "operator"})
            self._agg.setdefault(op_name, []).append(dur)

    # -- span listener (trace.span -> unified timeline) --------------------
    def _on_span(self, name: str, t_end: float, dur_us: float,
                 args: Optional[dict] = None) -> None:
        """``trace.span`` exits land here as PROPER duration events:
        supervisor steps, engine flushes, and loader batches appear on
        the same timeline as per-op events, with pid = host index and
        tid = thread lane (nested spans render stacked, chrome-trace
        semantics).  Span ``args`` (step number, batch id, ...) become
        the chrome-trace event's ``args``, so the timeline answers
        "which step was this?" on hover."""
        if not self._running or self._paused:
            return
        ts_end = (t_end - self._t0) * 1e6              # µs
        dur = max(dur_us, 0.1)
        pid = self._host_pid()
        ev = {"name": name, "ph": "X", "pid": pid, "ts": ts_end - dur,
              "dur": dur, "cat": "span"}
        if args:
            ev["args"] = dict(args)
        with self._lock:
            ev["tid"] = self._lane_locked()
            self._events.append(ev)
            self._agg.setdefault(f"span:{name}", []).append(dur)

    def start(self) -> None:
        self._pid = None               # re-resolve host index per session
        self._host_pid()
        if not self._listener_installed:
            engine().add_listener(self._on_op)
            from .observability.trace import add_span_listener
            add_span_listener(self._on_span)
            self._listener_installed = True
        self._running = True
        if self.profile_all and not self.trace_dir:
            # profile_all without an explicit trace_dir: put the XLA trace
            # next to the chrome-trace file (documented behavior)
            self.trace_dir = self.filename + "_xla"
        if self.profile_all and self.trace_dir:
            import jax
            jax.profiler.start_trace(self.trace_dir)

    def stop(self) -> None:
        if self.profile_all and self.trace_dir:
            import jax
            try:
                jax.profiler.stop_trace()
            except RuntimeError:
                pass
        self._running = False
        # drop the engine tap: an installed listener makes every invoke
        # pay dispatch timing AND suspends bulked dispatch — a stopped
        # profiler must cost nothing (start() re-installs)
        if self._listener_installed:
            engine().remove_listener(self._on_op)
            from .observability.trace import remove_span_listener
            remove_span_listener(self._on_span)
            self._listener_installed = False

    # -- output ------------------------------------------------------------
    #: tracing events render on their own tid lanes, offset past the
    #: profiler's per-thread lanes so the two namespaces never collide
    _TRACE_TID_BASE = 64

    def dump(self, finished: bool = True) -> None:
        pid = self._host_pid()
        with self._lock:
            # chrome-trace metadata names the lanes: the process row is
            # the host, each tid row the thread that emitted its events
            meta = [{"name": "process_name", "ph": "M", "pid": pid,
                     "args": {"name": f"host {pid}"}}]
            for lane, tname in sorted(self._tnames.items()):
                meta.append({"name": "thread_name", "ph": "M",
                             "pid": pid, "tid": lane,
                             "args": {"name": tname}})
            events = list(self._events)
        # causal-tracing merge: the tracer's completed-span ring joins
        # the op/span timeline as duration events PLUS flow arrows
        # (parent -> child, batch -> member requests) on the same
        # perf_counter clock — the profiler's view of "what caused
        # what", not just "what ran when"
        try:
            from .observability import tracing as _tracing
            trc = _tracing.tracer()
            tev = trc.chrome_events(base_pc=self._t0,
                                    tid_offset=self._TRACE_TID_BASE)
            if tev:
                for lane, tname in sorted(trc.lane_names().items()):
                    meta.append({"name": "thread_name", "ph": "M",
                                 "pid": pid,
                                 "tid": self._TRACE_TID_BASE + lane,
                                 "args": {"name": f"trace:{tname}"}})
                events += tev
        except Exception:   # noqa: BLE001 — a broken tracer must not
            pass            # break the profile dump
        payload = {"traceEvents": meta + events,
                   "displayTimeUnit": "ms"}
        with open(self.filename, "w") as f:
            json.dump(payload, f)

    def dumps(self, reset: bool = False) -> str:
        with self._lock:
            rows = []
            for name, durs in sorted(self._agg.items()):
                total = sum(durs)
                rows.append((name, len(durs), total, total / len(durs),
                             min(durs), max(durs)))
            if reset:
                self._agg.clear()
        head = (f"{'Name':<32}{'Calls':>8}{'Total(us)':>14}"
                f"{'Avg(us)':>12}{'Min(us)':>12}{'Max(us)':>12}\n")
        lines = [head, "-" * len(head) + "\n"]
        for name, calls, total, avg, mn, mx in rows:
            lines.append(f"{name:<32}{calls:>8}{total:>14.1f}"
                         f"{avg:>12.1f}{mn:>12.1f}{mx:>12.1f}\n")
        # the engine's bulk/dispatch counters ride along.  While the
        # profiler is installed, bulking suspends (listeners need real
        # per-op outputs), so the rows above are true per-op dispatch
        # costs; this footer still reports what bulking did around the
        # profiled window (segments, mean length, fused-exec cache rate)
        s = engine().stats()
        lines.append("\nengine dispatch/bulking stats:\n")
        for k in ("ops_dispatched", "ops_bulked", "segments_flushed",
                  "mean_segment_length", "segment_cache_hits",
                  "segment_cache_misses", "flush_us_p50", "flush_us_p99"):
            lines.append(f"  {k:<24}{s[k]}\n")
        return "".join(lines)

    def reset(self) -> None:
        with self._lock:
            self._events.clear()
            self._agg.clear()


def set_config(**kwargs) -> None:
    """reference: mx.profiler.set_config(profile_all=..., filename=...)"""
    p = Profiler.get()
    if "filename" in kwargs:
        p.filename = kwargs.pop("filename")
    if "profile_all" in kwargs:
        p.profile_all = bool(kwargs.pop("profile_all"))
    if "aggregate_stats" in kwargs:
        p.aggregate_stats = bool(kwargs.pop("aggregate_stats"))
    if "trace_dir" in kwargs:
        p.trace_dir = kwargs.pop("trace_dir")
    # reference accepts (and we ignore) profile_symbolic/imperative/memory/
    # api — one dispatch funnel means one event stream here
    kwargs.pop("profile_symbolic", None)
    kwargs.pop("profile_imperative", None)
    kwargs.pop("profile_memory", None)
    kwargs.pop("profile_api", None)
    if kwargs:
        raise MXNetError(f"unknown profiler config keys {sorted(kwargs)}")


def set_state(state_: str = "stop") -> None:
    """'run' or 'stop' (reference: mx.profiler.set_state)."""
    p = Profiler.get()
    if state_ == "run":
        p.start()
    elif state_ == "stop":
        p.stop()
    else:
        raise MXNetError("state must be 'run' or 'stop'")


def state() -> str:
    return "run" if Profiler.get()._running else "stop"


def pause() -> None:
    Profiler.get()._paused = True


def resume() -> None:
    Profiler.get()._paused = False


def dump(finished: bool = True) -> None:
    Profiler.get().dump(finished)


def dumps(reset: bool = False) -> str:
    return Profiler.get().dumps(reset)
