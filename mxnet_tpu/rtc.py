"""Runtime kernel compilation (reference: src/common/rtc.cc +
python/mxnet/rtc.py — SURVEY.md §2.1 "Engine-level RTC").

The reference let users hand NVRTC a CUDA source string
(``mx.rtc.CudaModule``).  The TPU analog is **Pallas**: users hand us a
Python kernel function written against ``jax.experimental.pallas`` and get
back a launchable module with the same get_kernel/launch workflow.  There
is deliberately no source-string compiler here — on TPU the kernel language
IS Python/Pallas, and Mosaic does the runtime compilation NVRTC did.
"""
from __future__ import annotations

from typing import Callable, Optional, Sequence, Tuple

import numpy as _np

from .base import MXNetError
from .ndarray import NDArray

__all__ = ["PallasModule", "PallasKernel", "CudaModule"]


class PallasKernel:
    """A launchable kernel (reference analog: rtc.CudaKernel)."""

    def __init__(self, kernel_fn: Callable, name: str,
                 out_shape: Optional[Tuple] = None,
                 out_dtype=None, grid=None, **pallas_kwargs):
        self._fn = kernel_fn
        self.name = name
        self._out_shape = out_shape
        self._out_dtype = out_dtype
        self._grid = grid
        self._kwargs = pallas_kwargs
        self._compiled = {}

    def _build(self, shapes, dtypes, out_shape, grid):
        import jax
        from jax.experimental import pallas as pl
        out_shape = out_shape or self._out_shape or shapes[0]
        out_dtype = self._out_dtype or dtypes[0]
        kwargs = dict(self._kwargs)
        g = grid if grid is not None else self._grid
        if g is not None:
            kwargs["grid"] = g
        # Mosaic compiles for TPU; on the CPU test mesh fall back to the
        # pallas interpreter so kernels stay testable everywhere
        if jax.default_backend() == "cpu":
            kwargs.setdefault("interpret", True)
        call = pl.pallas_call(
            self._fn,
            out_shape=jax.ShapeDtypeStruct(tuple(out_shape), out_dtype),
            **kwargs)
        return jax.jit(call)

    def launch(self, args: Sequence[NDArray], grid=None,
               out_shape=None) -> NDArray:
        """Run the kernel; returns a new NDArray (TPU buffers are
        immutable — unlike the reference's in-place CUDA launches, the
        output is the return value)."""
        vals = [a._read() for a in args]
        key = (tuple(v.shape for v in vals),
               tuple(str(v.dtype) for v in vals),
               tuple(out_shape) if out_shape else None,
               grid if not isinstance(grid, list) else tuple(grid))
        fn = self._compiled.get(key)
        if fn is None:
            fn = self._build([v.shape for v in vals],
                             [v.dtype for v in vals], out_shape, grid)
            self._compiled[key] = fn
        out = fn(*vals)
        return NDArray(out, ctx=args[0].context)

    __call__ = launch


class PallasModule:
    """Container of named kernels (reference analog: rtc.CudaModule)."""

    def __init__(self, kernels=None):
        self._kernels = dict(kernels or {})

    def add_kernel(self, name: str, kernel_fn: Callable,
                   **kwargs) -> PallasKernel:
        k = PallasKernel(kernel_fn, name, **kwargs)
        self._kernels[name] = k
        return k

    def get_kernel(self, name: str, signature: str = "") -> PallasKernel:
        if name not in self._kernels:
            raise MXNetError(f"no kernel {name!r}; have "
                             f"{sorted(self._kernels)}")
        return self._kernels[name]


class CudaModule:
    """The reference's CUDA RTC entry point.  Raises with guidance — CUDA
    source strings cannot target a TPU; write a Pallas kernel instead."""

    def __init__(self, *a, **kw):
        raise MXNetError(
            "CudaModule is not supported on TPU builds; use "
            "mx.rtc.PallasModule with a jax.experimental.pallas kernel "
            "function (the TPU runtime-compilation path)")
