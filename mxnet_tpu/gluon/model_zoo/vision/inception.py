"""Inception V3 (reference:
python/mxnet/gluon/model_zoo/vision/inception.py).

Szegedy et al. 2015 — factorized multi-branch conv blocks concatenated on
channels.  Input is 299x299.  Branch containers use HybridConcurrent so
the whole tower lowers into one XLA computation under hybridize().
"""
from __future__ import annotations

from ...block import HybridBlock
from ... import nn

__all__ = ["Inception3", "inception_v3"]


def _make_basic_conv(**kwargs):
    out = nn.HybridSequential(prefix="")
    out.add(nn.Conv2D(use_bias=False, **kwargs))
    out.add(nn.BatchNorm(epsilon=0.001))
    out.add(nn.Activation("relu"))
    return out


def _make_branch(use_pool, *conv_settings):
    out = nn.HybridSequential(prefix="")
    if use_pool == "avg":
        out.add(nn.AvgPool2D(pool_size=3, strides=1, padding=1))
    elif use_pool == "max":
        out.add(nn.MaxPool2D(pool_size=3, strides=2))
    for setting in conv_settings:
        kwargs = {}
        for k, v in zip(("channels", "kernel_size", "strides", "padding"),
                        setting):
            if v is not None:
                kwargs[k] = v
        out.add(_make_basic_conv(**kwargs))
    return out


def _make_A(pool_features, prefix):
    out = nn.HybridConcurrent(axis=1, prefix=prefix)
    with out.name_scope():
        out.add(_make_branch(None, (64, 1, None, None)))
        out.add(_make_branch(None, (48, 1, None, None),
                             (64, 5, None, 2)))
        out.add(_make_branch(None, (64, 1, None, None),
                             (96, 3, None, 1),
                             (96, 3, None, 1)))
        out.add(_make_branch("avg", (pool_features, 1, None, None)))
    return out


def _make_B(prefix):
    out = nn.HybridConcurrent(axis=1, prefix=prefix)
    with out.name_scope():
        out.add(_make_branch(None, (384, 3, 2, None)))
        out.add(_make_branch(None, (64, 1, None, None),
                             (96, 3, None, 1),
                             (96, 3, 2, None)))
        out.add(_make_branch("max"))
    return out


def _make_C(channels_7x7, prefix):
    out = nn.HybridConcurrent(axis=1, prefix=prefix)
    with out.name_scope():
        out.add(_make_branch(None, (192, 1, None, None)))
        out.add(_make_branch(None, (channels_7x7, 1, None, None),
                             (channels_7x7, (1, 7), None, (0, 3)),
                             (192, (7, 1), None, (3, 0))))
        out.add(_make_branch(None, (channels_7x7, 1, None, None),
                             (channels_7x7, (7, 1), None, (3, 0)),
                             (channels_7x7, (1, 7), None, (0, 3)),
                             (channels_7x7, (7, 1), None, (3, 0)),
                             (192, (1, 7), None, (0, 3))))
        out.add(_make_branch("avg", (192, 1, None, None)))
    return out


def _make_D(prefix):
    out = nn.HybridConcurrent(axis=1, prefix=prefix)
    with out.name_scope():
        out.add(_make_branch(None, (192, 1, None, None),
                             (320, 3, 2, None)))
        out.add(_make_branch(None, (192, 1, None, None),
                             (192, (1, 7), None, (0, 3)),
                             (192, (7, 1), None, (3, 0)),
                             (192, 3, 2, None)))
        out.add(_make_branch("max"))
    return out


class _InceptionE(HybridBlock):
    """Block E has nested splits (3x3 branch fans into 1x3 + 3x1)."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.b0 = _make_branch(None, (320, 1, None, None))
            self.b1_stem = _make_branch(None, (384, 1, None, None))
            self.b1_a = _make_branch(None, (384, (1, 3), None, (0, 1)))
            self.b1_b = _make_branch(None, (384, (3, 1), None, (1, 0)))
            self.b2_stem = _make_branch(None, (448, 1, None, None),
                                        (384, 3, None, 1))
            self.b2_a = _make_branch(None, (384, (1, 3), None, (0, 1)))
            self.b2_b = _make_branch(None, (384, (3, 1), None, (1, 0)))
            self.b3 = _make_branch("avg", (192, 1, None, None))

    def hybrid_forward(self, F, x):
        y1 = self.b1_stem(x)
        y2 = self.b2_stem(x)
        return F.concat(self.b0(x), self.b1_a(y1), self.b1_b(y1),
                        self.b2_a(y2), self.b2_b(y2), self.b3(x), dim=1)


class Inception3(HybridBlock):
    def __init__(self, classes=1000, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            self.features.add(_make_basic_conv(channels=32, kernel_size=3,
                                               strides=2))
            self.features.add(_make_basic_conv(channels=32, kernel_size=3))
            self.features.add(_make_basic_conv(channels=64, kernel_size=3,
                                               padding=1))
            self.features.add(nn.MaxPool2D(pool_size=3, strides=2))
            self.features.add(_make_basic_conv(channels=80, kernel_size=1))
            self.features.add(_make_basic_conv(channels=192, kernel_size=3))
            self.features.add(nn.MaxPool2D(pool_size=3, strides=2))
            self.features.add(_make_A(32, "A1_"))
            self.features.add(_make_A(64, "A2_"))
            self.features.add(_make_A(64, "A3_"))
            self.features.add(_make_B("B_"))
            self.features.add(_make_C(128, "C1_"))
            self.features.add(_make_C(160, "C2_"))
            self.features.add(_make_C(160, "C3_"))
            self.features.add(_make_C(192, "C4_"))
            self.features.add(_make_D("D_"))
            self.features.add(_InceptionE(prefix="E1_"))
            self.features.add(_InceptionE(prefix="E2_"))
            self.features.add(nn.AvgPool2D(pool_size=8))
            self.features.add(nn.Dropout(0.5))
            self.output = nn.Dense(classes)

    def hybrid_forward(self, F, x):
        x = self.features(x)
        return self.output(x)


def inception_v3(**kwargs):
    return Inception3(**kwargs)
