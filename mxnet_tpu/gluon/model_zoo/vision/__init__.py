"""model_zoo.vision with the reference's ``get_model`` registry."""
import importlib as _importlib

from ....base import MXNetError

_models = {}
for _modname in ("resnet", "alexnet", "vgg", "mobilenet", "densenet",
                 "squeezenet", "inception"):
    _mod = _importlib.import_module(f".{_modname}", __name__)
    for _name in _mod.__all__:
        _obj = getattr(_mod, _name)
        if callable(_obj) and _name[0].islower() and not \
                _name.startswith("get_"):
            _models[_name] = _obj

# flat exports (function names shadow same-named submodules, as upstream)
from .resnet import *      # noqa: F401,F403,E402
from .vgg import *         # noqa: F401,F403,E402
from .mobilenet import *   # noqa: F401,F403,E402
from .alexnet import *     # noqa: F401,F403,E402
from .densenet import *    # noqa: F401,F403,E402
from .squeezenet import *  # noqa: F401,F403,E402
from .inception import *   # noqa: F401,F403,E402


def get_model(name, **kwargs):
    """Create a model by name (reference: mx.gluon.model_zoo.vision.get_model)."""
    name = name.lower()
    if name not in _models:
        raise MXNetError(f"unknown model {name!r}; available: "
                         f"{sorted(_models)}")
    return _models[name](**kwargs)
