"""Transformer model family: BERT (GluonNLP-style) and seq2seq NMT
(Sockeye-style).

Reference parity: the reference framework itself ships no transformer — the
BASELINE configs #3 (BERT-base pretrain, GluonNLP) and #4 (Sockeye
transformer NMT) are downstream repos built on Gluon/Symbol APIs
(SURVEY.md §1 tail).  This module provides the equivalent model family on
our Gluon, written TPU-first:

- one fused QKV projection per attention block (single MXU matmul),
- parameter names chose so `TP_RULES` (megatron-style tensor parallelism)
  applies by regex: `*qkv_weight` column-parallel, `*proj_weight`
  row-parallel, `*ffn1*` column-, `*ffn2*` row-parallel,
- static shapes throughout (mask arrives as a runtime tensor, never a
  Python branch), so one XLA computation per (batch, seq) bucket —
  the BucketingModule discipline of SURVEY.md §5.7.
"""
from __future__ import annotations

import math
from typing import Optional

from ...base import MXNetError
from ..block import HybridBlock
from ..nn import Dense, Dropout, Embedding, HybridSequential, LayerNorm

__all__ = ["SlidingWindowSelfAttention", "LongformerEncoderCell",
           "LongformerEncoder",
           "MultiHeadAttention", "PositionwiseFFN", "TransformerEncoderCell",
           "TransformerDecoderCell", "TransformerEncoder",
           "TransformerDecoder", "TransformerNMT", "BERTEncoder",
           "BERTModel", "bert_base", "bert_small", "transformer_nmt_base",
           "CausalLMCell", "CausalLM", "causal_lm_small",
           "TP_RULES"]

#: megatron-style tensor-parallel PartitionSpecs for this family — pass to
#: parallel.ShardingRules(TP_RULES)
TP_RULES = [
    (r".*qkv_weight$", ("tp", None)),
    (r".*qkv_bias$", ("tp",)),
    (r".*kv_weight$", ("tp", None)),
    (r".*kv_bias$", ("tp",)),
    (r".*q_weight$", ("tp", None)),
    (r".*q_bias$", ("tp",)),
    (r".*proj_weight$", (None, "tp")),
    (r".*ffn1_weight$", ("tp", None)),
    (r".*ffn1_bias$", ("tp",)),
    (r".*ffn2_weight$", (None, "tp")),
    (r".*word_embed_weight$", ("tp", None)),
]


def _masked_softmax(F, scores, mask):
    """scores (B*H, Sq, Sk); mask same shape, 1=keep, 0=drop (any dtype)."""
    if mask is not None:
        # additive -1e9 mask (pad-and-mask — the XLA-friendly form)
        scores = scores + (F.cast(mask, dtype="float32") - 1.0) * 1e9
    return F.softmax(scores, axis=-1)


class MultiHeadAttention(HybridBlock):
    """Scaled dot-product attention with fused QKV.

    Self-attention: call with (x, mask).  Cross-attention: (x, mask, mem)
    — queries from x, keys/values from mem (one q proj + one fused kv).
    """

    def __init__(self, units, num_heads, dropout=0.0, self_attention=True,
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        if units % num_heads:
            raise MXNetError(f"units {units} not divisible by heads "
                             f"{num_heads}")
        self._units = units
        self._heads = num_heads
        self._self = self_attention
        with self.name_scope():
            if self_attention:
                self.qkv = Dense(3 * units, flatten=False, in_units=units,
                                 prefix="qkv_")
            else:
                self.q_proj = Dense(units, flatten=False, in_units=units,
                                    prefix="q_")
                self.kv = Dense(2 * units, flatten=False, in_units=units,
                                prefix="kv_")
            self.proj = Dense(units, flatten=False, in_units=units,
                              prefix="proj_")
            self.drop = Dropout(dropout) if dropout else None

    def _split_heads(self, F, x, batch, seq):
        # (B, S, U) -> (B*H, S, d)
        x = F.reshape(x, shape=(batch, seq, self._heads,
                                self._units // self._heads))
        x = F.transpose(x, axes=(0, 2, 1, 3))
        return F.reshape(x, shape=(batch * self._heads, seq,
                                   self._units // self._heads))

    def _merge_heads(self, F, x, batch, seq):
        x = F.reshape(x, shape=(batch, self._heads, seq,
                                self._units // self._heads))
        x = F.transpose(x, axes=(0, 2, 1, 3))
        return F.reshape(x, shape=(batch, seq, self._units))

    def hybrid_forward(self, F, x, mask=None, mem=None, valid_len=None):
        """``mask``: arbitrary (B*H, Sq, Sk) attention mask (exact XLA
        softmax path).  ``valid_len``: per-sequence key lengths (B,) or
        (B*H,) — the GluonNLP valid_length idiom; authoritative, so the
        flash kernel can honor it even under jit.  Passing both is
        allowed when they express the SAME prefix mask (the XLA path
        uses ``mask``, flash uses ``valid_len``)."""
        b, sq = x.shape[0], x.shape[1]
        if self._self:
            qkv = self.qkv(x)
            q, k, v = F.split(qkv, num_outputs=3, axis=-1)
            sk = sq
        else:
            if mem is None:
                raise MXNetError("cross-attention needs memory input")
            q = self.q_proj(x)
            kv = self.kv(mem)
            k, v = F.split(kv, num_outputs=2, axis=-1)
            sk = mem.shape[1]
        q = self._split_heads(F, q, b, sq)
        k = self._split_heads(F, k, b, sk)
        v = self._split_heads(F, v, b, sk)
        scale = 1.0 / math.sqrt(self._units // self._heads)
        if self._flash_eligible(F, mask, valid_len):
            # tiled online-softmax Pallas kernel with a chunked-scan
            # custom VJP — differentiable, no (Lq, Lk) score matrix in
            # either direction (kernels/flash_attention.py)
            if valid_len is None:
                out = F.flash_attention(q, k, v, scale=scale)
            else:
                out = F.flash_attention(q, k, v, valid_len, scale=scale)
        else:
            scores = F.batch_dot(q, k, transpose_b=True) * scale
            att = _masked_softmax(F, scores, mask)
            if self.drop is not None:
                att = self.drop(att)
            out = F.batch_dot(att, v)
        return self.proj(self._merge_heads(F, out, b, sq))

    def _flash_eligible(self, F, mask, valid_len) -> bool:
        # Kernel selection policy (auto by default on TPU):
        #   MXNET_ATTENTION_KERNEL=flash  force the Pallas kernel
        #   MXNET_ATTENTION_KERNEL=xla    force the full-softmax XLA path
        #   unset/auto                    flash on the TPU backend when the
        #                                 mask is expressible, XLA otherwise
        # (MXNET_USE_FLASH_ATTENTION=1 is honored as a legacy force-on.)
        # Eligibility regardless of policy: none-mask always works;
        # explicit ``valid_len`` lengths ride the kernel's per-row
        # masking.  An arbitrary (B*H,Sq,Sk) mask WITHOUT lengths falls
        # back to the XLA path — a 2-D mask cannot be proven to be a
        # prefix mask under trace, and collapsing a non-prefix mask to a
        # length silently corrupts attention (caught in round-4 review).
        # The kernel is differentiable (custom VJP over the chunked
        # formulation), so training may ride it too — EXCEPT when this
        # block has attention dropout and dropout is live (train_mode/
        # record), since the flash path has no probs tensor to drop.
        from ...base import get_env
        mode = get_env("MXNET_ATTENTION_KERNEL").lower()
        legacy = get_env("MXNET_USE_FLASH_ATTENTION")
        if legacy == "1":
            mode = "flash"              # legacy force-on
        elif legacy == "0":
            mode = "xla"                # legacy explicit force-off
        if mode in ("xla", "off", "0"):
            return False
        if mask is not None and valid_len is None:
            return False
        if not hasattr(F, "flash_attention"):
            return False
        if self.drop is not None:
            from ... import autograd
            if autograd.is_recording() or autograd.is_training():
                return False
        if mode == "flash":
            return True
        # auto: default to flash only where Mosaic actually compiles — on
        # the TPU backend (eager or under whole-graph jit).  Off-TPU the
        # kernel would run in interpret mode, orders of magnitude slower
        # than XLA's fused softmax.
        import jax
        return jax.default_backend() == "tpu"


class PositionwiseFFN(HybridBlock):
    def __init__(self, units, hidden_size, dropout=0.0, activation="gelu",
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        with self.name_scope():
            self.ffn1 = Dense(hidden_size, flatten=False, in_units=units,
                              prefix="ffn1_")
            self.ffn2 = Dense(units, flatten=False, in_units=hidden_size,
                              prefix="ffn2_")
            self.drop = Dropout(dropout) if dropout else None
        self._act = activation

    def hybrid_forward(self, F, x):
        h = self.ffn1(x)
        if self._act == "gelu":
            h = F.LeakyReLU(h, act_type="gelu")   # exact (erf) gelu op
        else:
            h = F.Activation(h, act_type=self._act)
        if self.drop is not None:
            h = self.drop(h)
        return self.ffn2(h)


class TransformerEncoderCell(HybridBlock):
    """Post-LN encoder layer (BERT/Sockeye convention)."""

    def __init__(self, units, hidden_size, num_heads, dropout=0.0,
                 activation="gelu", prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        with self.name_scope():
            self.attn = MultiHeadAttention(units, num_heads, dropout,
                                           prefix="attn_")
            self.ln1 = LayerNorm(in_channels=units, prefix="ln1_")
            self.ffn = PositionwiseFFN(units, hidden_size, dropout,
                                       activation, prefix="ffn_")
            self.ln2 = LayerNorm(in_channels=units, prefix="ln2_")
            self.drop = Dropout(dropout) if dropout else None

    def hybrid_forward(self, F, x, mask=None, valid_len=None):
        # positional: Block.__call__ forwards *args only (reference Gluon
        # calling convention); mem slot is None for self-attention
        a = self.attn(x, mask, None, valid_len)
        if self.drop is not None:
            a = self.drop(a)
        x = self.ln1(x + a)
        f = self.ffn(x)
        if self.drop is not None:
            f = self.drop(f)
        return self.ln2(x + f)


class TransformerDecoderCell(HybridBlock):
    """Decoder layer: causal self-attention + cross-attention + FFN."""

    def __init__(self, units, hidden_size, num_heads, dropout=0.0,
                 activation="relu", prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        with self.name_scope():
            self.self_attn = MultiHeadAttention(units, num_heads, dropout,
                                                prefix="selfattn_")
            self.ln1 = LayerNorm(in_channels=units, prefix="ln1_")
            self.cross_attn = MultiHeadAttention(
                units, num_heads, dropout, self_attention=False,
                prefix="crossattn_")
            self.ln2 = LayerNorm(in_channels=units, prefix="ln2_")
            self.ffn = PositionwiseFFN(units, hidden_size, dropout,
                                       activation, prefix="ffn_")
            self.ln3 = LayerNorm(in_channels=units, prefix="ln3_")

    def hybrid_forward(self, F, x, causal_mask=None, mem=None,
                       mem_mask=None):
        x = self.ln1(x + self.self_attn(x, causal_mask))
        x = self.ln2(x + self.cross_attn(x, mem_mask, mem))
        return self.ln3(x + self.ffn(x))


def _tie_weight(dense, embed):
    """Share an Embedding's (V, U) weight with a Dense output projection —
    the Dense's own weight parameter is dropped entirely."""
    del dense.params._params[dense.weight.name]
    dense.weight = embed.weight
    dense._reg_params["weight"] = embed.weight


def _positions(F, batch, seq):
    pos = F.arange(seq, dtype="int32")
    return F.broadcast_to(F.reshape(pos, shape=(1, seq)), shape=(batch, seq))


class TransformerEncoder(HybridBlock):
    def __init__(self, num_layers=6, units=512, hidden_size=2048,
                 num_heads=8, max_length=512, dropout=0.0,
                 activation="gelu", prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._units = units
        self._heads = num_heads
        self._max_len = max_length
        with self.name_scope():
            self.pos_embed = Embedding(max_length, units,
                                       prefix="pos_embed_")
            self.cells = HybridSequential(prefix="layers_")
            with self.cells.name_scope():
                for _ in range(num_layers):
                    self.cells.add(TransformerEncoderCell(
                        units, hidden_size, num_heads, dropout, activation))

    def hybrid_forward(self, F, x, mask=None):
        """x: (B, S, units) embedded input.  mask: (B, S) 1=valid, OR a
        1-D (B,) array of per-sequence valid LENGTHS (the GluonNLP
        valid_length idiom) — the length form is authoritative padding
        information, letting the flash-attention path mask by row length
        instead of falling back to the XLA softmax."""
        b, s = x.shape[0], x.shape[1]
        if s > self._max_len:
            raise ValueError(
                f"sequence length {s} exceeds max_length={self._max_len}")
        x = x + self.pos_embed(_positions(F, b, s))
        att_mask = None
        valid_len = None
        if mask is not None:
            if mask.ndim == 1:                     # (B,) valid lengths
                valid_len = mask
                key_mask = F.broadcast_lesser(
                    F.reshape(F.arange(s, dtype="float32"),
                              shape=(1, s)),
                    F.reshape(F.cast(mask, dtype="float32"),
                              shape=(b, 1)))
            else:                                  # (B, S) 0/1 mask
                key_mask = mask
            # (B,S) -> (B,1,1,S) -> (B*H, Sq, Sk)
            att_mask = F.reshape(key_mask, shape=(b, 1, 1, s))
            att_mask = F.broadcast_to(att_mask,
                                      shape=(b, self._heads, s, s))
            att_mask = F.reshape(att_mask, shape=(-1, s, s))
        for cell in self.cells:
            x = cell(x, att_mask, valid_len)
        return x


class TransformerDecoder(HybridBlock):
    def __init__(self, num_layers=6, units=512, hidden_size=2048,
                 num_heads=8, max_length=512, dropout=0.0,
                 activation="relu", prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._units = units
        self._heads = num_heads
        self._max_len = max_length
        with self.name_scope():
            self.pos_embed = Embedding(max_length, units,
                                       prefix="pos_embed_")
            self.cells = HybridSequential(prefix="layers_")
            with self.cells.name_scope():
                for _ in range(num_layers):
                    self.cells.add(TransformerDecoderCell(
                        units, hidden_size, num_heads, dropout, activation))

    def hybrid_forward(self, F, x, mem, mem_mask=None):
        b, s = x.shape[0], x.shape[1]
        if s > self._max_len:
            raise ValueError(
                f"sequence length {s} exceeds max_length={self._max_len}")
        sm = mem.shape[1]
        x = x + self.pos_embed(_positions(F, b, s))
        # causal mask (1,S,S) -> (B*H,S,S)
        pos = F.arange(s, dtype="int32")
        causal = F.broadcast_greater_equal(F.reshape(pos, shape=(s, 1)),
                                           F.reshape(pos, shape=(1, s)))
        causal = F.broadcast_to(F.reshape(causal, shape=(1, s, s)),
                                shape=(b * self._heads, s, s))
        mmask = None
        if mem_mask is not None:
            mmask = F.reshape(mem_mask, shape=(b, 1, 1, sm))
            mmask = F.broadcast_to(mmask,
                                   shape=(b, self._heads, s, sm))
            mmask = F.reshape(mmask, shape=(-1, s, sm))
        for cell in self.cells:
            x = cell(x, causal, mem, mmask)
        return x


class TransformerNMT(HybridBlock):
    """Sockeye-style seq2seq transformer (BASELINE config #4): shared
    source/target vocab embedding, encoder-decoder, tied output proj."""

    def __init__(self, vocab_size, num_layers=6, units=512,
                 hidden_size=2048, num_heads=8, max_length=512,
                 dropout=0.0, tie_weights=True, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._units = units
        with self.name_scope():
            self.word_embed = Embedding(vocab_size, units,
                                        prefix="word_embed_")
            self.encoder = TransformerEncoder(
                num_layers, units, hidden_size, num_heads, max_length,
                dropout, activation="relu", prefix="enc_")
            self.decoder = TransformerDecoder(
                num_layers, units, hidden_size, num_heads, max_length,
                dropout, activation="relu", prefix="dec_")
            self.out_proj = Dense(vocab_size, flatten=False,
                                  in_units=units, use_bias=False,
                                  prefix="out_")
            if tie_weights:
                _tie_weight(self.out_proj, self.word_embed)

    def hybrid_forward(self, F, src, tgt, src_mask=None):
        scale = math.sqrt(self._units)
        mem = self.encoder(self.word_embed(src) * scale, src_mask)
        return self._decode_logits(F, tgt, mem, src_mask)

    # -- inference (the Sockeye translate workflow, config #4) -------------
    def _decode_logits(self, F, tgt, mem, src_mask):
        scale = math.sqrt(self._units)
        dec = self.decoder(self.word_embed(tgt) * scale, mem, src_mask)
        return self.out_proj(dec)

    def translate(self, src, bos: int, eos: int, max_len: int = 50,
                  beam_size: int = 1, alpha: float = 0.6,
                  src_mask=None):
        """Greedy (beam_size=1) or length-normalized beam-search decoding
        (reference workflow: Sockeye's translate CLI over the same
        encoder-decoder; scores use the GNMT length penalty with
        ``alpha``).

        The prefix grows step by step and the decoder re-runs on it —
        per-step jit caches keyed by prefix length keep every step
        compiled (the bucketing discipline of §5.7); the decode-aligned
        flash kernel covers the long-cache regime when enabled.

        Returns (tokens, scores): a list per batch row (EOS stripped)."""
        import numpy as _np

        from ... import ndarray as nd

        scale = math.sqrt(self._units)
        mem = self.encoder(self.word_embed(src) * scale, src_mask)
        b = src.shape[0]
        mem_np_ctx = src.context

        if beam_size <= 1:
            tgt = nd.full((b, 1), bos, ctx=mem_np_ctx)
            finished = _np.zeros((b,), bool)
            logprob = _np.zeros((b,), _np.float64)
            steps = _np.zeros((b,), _np.int64)
            for _ in range(max_len):
                logits = self._decode_logits(nd, tgt, mem, src_mask)
                logp = nd.log_softmax(logits[:, -1, :]).asnumpy()
                nxt = logp.argmax(-1)
                nxt = _np.where(finished, eos, nxt)
                logprob += _np.where(finished, 0.0,
                                     logp[_np.arange(b), nxt])
                steps += (~finished).astype(_np.int64)
                finished |= (nxt == eos)
                tgt = nd.concat(tgt, nd.array(nxt.reshape(b, 1),
                                              ctx=mem_np_ctx), dim=1)
                if finished.all():
                    break
            out = []
            for row in tgt.asnumpy()[:, 1:].astype(int).tolist():
                out.append(row[:row.index(eos)] if eos in row else row)
            # same GNMT length normalization as the beam path, so greedy
            # and beam scores are comparable
            lens = _np.maximum(steps, 1)
            scores = logprob / (((5 + lens) / 6.0) ** alpha)
            return out, [float(s) for s in scores]

        # beam search, one source row at a time (clarity over batching;
        # the per-length jit cache is shared across rows and steps)
        def norm(entry):
            toks, lp, _ = entry
            length = max(len(toks) - 1, 1)
            return lp / (((5 + length) / 6.0) ** alpha)

        results, scores = [], []
        for i in range(b):
            mem_i = mem[i:i + 1]
            mask_i = None if src_mask is None else src_mask[i:i + 1]
            beams = [([bos], 0.0, False)]
            for _ in range(max_len):
                if all(f for _, _, f in beams):
                    break
                cand = []
                for toks, lp, fin in beams:
                    if fin:
                        cand.append((toks, lp, True))
                        continue
                    tgt = nd.array(_np.asarray([toks]), ctx=mem_np_ctx)
                    logits = self._decode_logits(nd, tgt, mem_i, mask_i)
                    logp = nd.log_softmax(logits[0, -1, :]).asnumpy()
                    top = _np.argsort(logp)[-beam_size:]
                    for t in top:
                        cand.append((toks + [int(t)], lp + float(logp[t]),
                                     int(t) == eos))
                cand.sort(key=norm, reverse=True)
                beams = cand[:beam_size]
            best, best_lp, _ = max(beams, key=norm)
            row = best[1:]
            results.append(row[:row.index(eos)] if eos in row else row)
            scores.append(norm((best, best_lp, True)))
        return results, scores


class BERTEncoder(TransformerEncoder):
    """BERT uses the (gelu, post-LN) encoder as-is."""


class BERTModel(HybridBlock):
    """BERT-base-style model (BASELINE config #3): token+segment+position
    embeddings -> encoder -> (MLM decoder over all positions, NSP head
    over [CLS])."""

    def __init__(self, vocab_size=30522, num_layers=12, units=768,
                 hidden_size=3072, num_heads=12, max_length=512,
                 type_vocab_size=2, dropout=0.1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._units = units
        with self.name_scope():
            self.word_embed = Embedding(vocab_size, units,
                                        prefix="word_embed_")
            self.token_type_embed = Embedding(type_vocab_size, units,
                                              prefix="type_embed_")
            self.embed_ln = LayerNorm(in_channels=units, prefix="embed_ln_")
            self.embed_drop = Dropout(dropout) if dropout else None
            self.encoder = BERTEncoder(
                num_layers, units, hidden_size, num_heads, max_length,
                dropout, activation="gelu", prefix="enc_")
            self.pooler = Dense(units, activation="tanh", flatten=False,
                                in_units=units, prefix="pooler_")
            # MLM: transform + decoder tied to the word embedding (BERT
            # convention — decoder keeps its own bias)
            self.mlm_dense = Dense(units, flatten=False, in_units=units,
                                   prefix="mlm_dense_")
            self.mlm_ln = LayerNorm(in_channels=units, prefix="mlm_ln_")
            self.mlm_decoder = Dense(vocab_size, flatten=False,
                                     in_units=units, prefix="mlm_out_")
            _tie_weight(self.mlm_decoder, self.word_embed)
            self.nsp = Dense(2, flatten=False, in_units=units,
                             prefix="nsp_")

    def hybrid_forward(self, F, tokens, token_types, valid_mask=None):
        x = self.word_embed(tokens) + self.token_type_embed(token_types)
        x = self.embed_ln(x)
        if self.embed_drop is not None:
            x = self.embed_drop(x)
        seq = self.encoder(x, valid_mask)                 # (B, S, U)
        h = F.LeakyReLU(self.mlm_dense(seq), act_type="gelu")
        mlm = self.mlm_decoder(self.mlm_ln(h))            # (B, S, V)
        cls = F.squeeze(F.slice_axis(seq, axis=1, begin=0, end=1), axis=1)
        nsp = self.nsp(self.pooler(cls))                  # (B, 2)
        return mlm, nsp


def bert_base(vocab_size=30522, **kwargs):
    return BERTModel(vocab_size=vocab_size, num_layers=12, units=768,
                     hidden_size=3072, num_heads=12, **kwargs)


def bert_small(vocab_size=1000, **kwargs):
    """Tiny config for tests/dryruns."""
    kwargs.setdefault("max_length", 128)
    return BERTModel(vocab_size=vocab_size, num_layers=2, units=64,
                     hidden_size=128, num_heads=4, **kwargs)


def transformer_nmt_base(vocab_size=32000, **kwargs):
    return TransformerNMT(vocab_size, num_layers=6, units=512,
                          hidden_size=2048, num_heads=8, **kwargs)


class SlidingWindowSelfAttention(HybridBlock):
    """Longformer-style banded self-attention over the sliding-window op
    trio (reference family: src/operator/contrib/transformer.cc
    _sldwin_atten_*).

    Memory is O(L·W) per head instead of O(L²): scores, mask, and
    context all live in the (B, L, H, 2w+1) band, so sequence length
    scales linearly — the single-chip long-context complement to the
    ring/sequence-parallel path in ``parallel/ring.py``.  Layout
    follows the reference ops: (B, L, H, D) head tensors, per-head
    dilation, symmetric window of one-sided width ``w``."""

    def __init__(self, units, num_heads, w, dilation=None, dropout=0.0,
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        if units % num_heads:
            raise MXNetError(f"units {units} not divisible by heads "
                             f"{num_heads}")
        self._units = units
        self._heads = num_heads
        self._w = int(w)
        self._dilation = tuple(dilation) if dilation is not None else \
            (1,) * num_heads
        if len(self._dilation) != num_heads:
            raise MXNetError("dilation needs one entry per head")
        with self.name_scope():
            self.qkv = Dense(3 * units, flatten=False, in_units=units,
                             prefix="qkv_")
            self.proj = Dense(units, flatten=False, in_units=units,
                              prefix="proj_")
            self.drop = Dropout(dropout) if dropout else None

    def hybrid_forward(self, F, x, valid_len=None):
        b, l = x.shape[0], x.shape[1]
        d = self._units // self._heads
        qkv = self.qkv(x)
        q, k, v = F.split(qkv, num_outputs=3, axis=-1)
        # (B, L, H, D) — the sldwin op layout
        q = F.reshape(q, shape=(b, l, self._heads, d))
        k = F.reshape(k, shape=(b, l, self._heads, d))
        v = F.reshape(v, shape=(b, l, self._heads, d))
        scale = 1.0 / math.sqrt(d)
        if not hasattr(F, "array"):
            raise MXNetError(
                "SlidingWindowSelfAttention supports the imperative/"
                "hybridize path; compose the _sldwin_atten_* ops "
                "directly for hand-built Symbol graphs")
        import numpy as _np
        dil = F.array(_np.asarray(self._dilation, _np.int32))
        if valid_len is None:
            valid_len = F.full((b,), l)
        s = F._sldwin_atten_score(q, k, dil, w=self._w,
                                  symmetric=True) * scale
        m = F._sldwin_atten_mask_like(s, dil, valid_len, w=self._w,
                                      symmetric=True)
        att = F.softmax(s + (1.0 - m) * -1e9, axis=-1) * m
        if self.drop is not None:
            att = self.drop(att)
        ctx = F._sldwin_atten_context(att, v, dil, w=self._w,
                                      symmetric=True)
        return self.proj(F.reshape(ctx, shape=(b, l, self._units)))


class LongformerEncoderCell(HybridBlock):
    """Post-LN encoder layer with banded self-attention."""

    def __init__(self, units, hidden_size, num_heads, w, dilation=None,
                 dropout=0.0, activation="gelu", prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        with self.name_scope():
            self.attn = SlidingWindowSelfAttention(
                units, num_heads, w, dilation, dropout, prefix="attn_")
            self.ln1 = LayerNorm(in_channels=units, prefix="ln1_")
            self.ffn = PositionwiseFFN(units, hidden_size, dropout,
                                       activation, prefix="ffn_")
            self.ln2 = LayerNorm(in_channels=units, prefix="ln2_")
            self.drop = Dropout(dropout) if dropout else None

    def hybrid_forward(self, F, x, valid_len=None):
        a = self.attn(x, valid_len)
        if self.drop is not None:
            a = self.drop(a)
        x = self.ln1(x + a)
        f = self.ffn(x)
        if self.drop is not None:
            f = self.drop(f)
        return self.ln2(x + f)


class LongformerEncoder(HybridBlock):
    """Token+position embedding over N banded encoder layers — the
    long-sequence encoder family (Longformer): O(L·w) attention admits
    sequence lengths the dense BERT encoder cannot hold."""

    def __init__(self, vocab_size, num_layers=2, units=64,
                 hidden_size=128, num_heads=4, w=32, dilation=None,
                 max_length=4096, dropout=0.0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._units = units
        with self.name_scope():
            self.tok = Embedding(vocab_size, units, prefix="tok_")
            self.pos = Embedding(max_length, units, prefix="pos_")
            self.layers = HybridSequential(prefix="layers_")
            with self.layers.name_scope():
                for _ in range(num_layers):
                    self.layers.add(LongformerEncoderCell(
                        units, hidden_size, num_heads, w, dilation,
                        dropout))
            self.ln = LayerNorm(in_channels=units, prefix="ln_")
        # same cell objects, public iteration order: valid_len must
        # thread through each cell, which Sequential's own __call__
        # cannot do
        self._cells = [c for c in self.layers]

    def hybrid_forward(self, F, tokens, valid_len=None):
        b, l = tokens.shape[0], tokens.shape[1]
        import numpy as _np
        pos_ids = F.array(_np.arange(l, dtype=_np.int64))
        h = self.tok(tokens) + F.reshape(
            self.pos(pos_ids), shape=(1, l, self._units))
        h = self.ln(h)
        for cell in self._cells:
            h = cell(h, valid_len)
        return h


class CausalLMCell(HybridBlock):
    """Pre-factored decoder-only layer: the generation scheduler's
    prefill/decode graphs reach its children (``qkv``/``proj``/``ln1``/
    ``ffn``/``ln2``) directly, so the cell is both a standard post-LN
    causal layer (``hybrid_forward``) and the parameter container for
    :class:`CausalLM`'s paged-attention entries."""

    def __init__(self, units, hidden_size, num_heads, activation="gelu",
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        if units % num_heads:
            raise MXNetError(f"units {units} not divisible by heads "
                             f"{num_heads}")
        self._units = units
        self._heads = num_heads
        with self.name_scope():
            self.qkv = Dense(3 * units, flatten=False, in_units=units,
                             prefix="qkv_")
            self.proj = Dense(units, flatten=False, in_units=units,
                              prefix="proj_")
            self.ln1 = LayerNorm(in_channels=units, prefix="ln1_")
            self.ffn = PositionwiseFFN(units, hidden_size, 0.0,
                                       activation, prefix="ffn_")
            self.ln2 = LayerNorm(in_channels=units, prefix="ln2_")

    def attend(self, F, x, k, v, mask, batch, sq, sk):
        """Post-LN residual layer body around an explicit K/V set —
        shared by the full pass (K/V = the pass's own projections) and
        the decode step (K/V gathered from the block pool)."""
        h = self._heads
        d = self._units // h
        q = F.split(self.qkv(x), num_outputs=3, axis=-1)[0]
        q = F.reshape(F.transpose(
            F.reshape(q, shape=(batch, sq, h, d)),
            axes=(0, 2, 1, 3)), shape=(batch * h, sq, d))
        kh = F.reshape(F.transpose(
            F.reshape(k, shape=(batch, sk, h, d)),
            axes=(0, 2, 1, 3)), shape=(batch * h, sk, d))
        vh = F.reshape(F.transpose(
            F.reshape(v, shape=(batch, sk, h, d)),
            axes=(0, 2, 1, 3)), shape=(batch * h, sk, d))
        scale = 1.0 / math.sqrt(d)
        att = _masked_softmax(F, F.batch_dot(q, kh, transpose_b=True)
                              * scale, mask)
        out = F.batch_dot(att, vh)                 # (B*H, Sq, d)
        out = F.reshape(F.transpose(
            F.reshape(out, shape=(batch, h, sq, d)),
            axes=(0, 2, 1, 3)), shape=(batch, sq, self._units))
        x = self.ln1(x + self.proj(out))
        return self.ln2(x + self.ffn(x))

    def hybrid_forward(self, F, x, mask=None):
        b, s = x.shape[0], x.shape[1]
        kv = F.split(self.qkv(x), num_outputs=3, axis=-1)
        return self.attend(F, x, kv[1], kv[2], mask, b, s, s)


class CausalLM(HybridBlock):
    """Decoder-only LM with a paged-KV generation contract.

    Three compiled entries share one parameter set:

    - ``hybrid_forward(tokens)`` — full causal pass, (B, S) -> (B, S, V)
      logits (training / eval / the whole-sequence serving baseline);
    - ``hybrid_prefill(tokens, seq_len, table, pool)`` — ONE prompt
      (batch 1) padded to a length bucket: causal attention within the
      prompt, every position's K/V scattered into the request's KV
      blocks (``table`` maps position//block -> pool block id), returns
      (last-real-position logits (1, V), updated pool);
    - ``hybrid_decode(tokens, positions, tables, pool)`` — one token
      per running slot: scatter the step's K/V at each slot's current
      position, gather each slot's whole block list back, attend under
      a per-slot length mask, return ((slots, V) logits, updated pool).

    The pool is a single ``(2*num_layers, n_blocks, block, units)``
    array (K rows even, V rows odd).  Block 0 is scratch by convention
    (``serving.kv_cache``): empty slots and table-tail entries point at
    it, and the additive -1e9 mask underflows their attention weight to
    an exact float32 zero — so each slot's output is bitwise-independent
    of every other slot and of pool garbage, which is what makes
    continuous-batched greedy decode bitwise-equal to decoding alone.
    """

    def __init__(self, vocab_size=257, num_layers=2, units=64,
                 hidden_size=128, num_heads=4, max_length=256,
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._units = units
        self._heads = num_heads
        self._layers = num_layers
        self._max_len = max_length
        with self.name_scope():
            self.word_embed = Embedding(vocab_size, units,
                                        prefix="word_embed_")
            self.pos_embed = Embedding(max_length, units,
                                       prefix="pos_embed_")
            self.layers = HybridSequential(prefix="layers_")
            with self.layers.name_scope():
                for _ in range(num_layers):
                    self.layers.add(CausalLMCell(units, hidden_size,
                                                 num_heads))
            self.out_proj = Dense(vocab_size, flatten=False,
                                  in_units=units, use_bias=False,
                                  prefix="out_")
            _tie_weight(self.out_proj, self.word_embed)
        # public iteration order: prefill/decode thread extra state the
        # Sequential __call__ cannot (the LongformerEncoder idiom)
        self._cells = [c for c in self.layers]

    # -- shared pieces ------------------------------------------------
    def init_kv_pool(self, n_blocks, block_size):
        """Zero-initialized pool with this model's layout — what the
        generation scheduler allocates once per server."""
        import numpy as _np
        return _np.zeros((2 * self._layers, int(n_blocks),
                          int(block_size), self._units), _np.float32)

    def _causal(self, F, b, s):
        pos = F.arange(s, dtype="int32")
        causal = F.broadcast_greater_equal(F.reshape(pos, shape=(s, 1)),
                                           F.reshape(pos, shape=(1, s)))
        return F.broadcast_to(F.reshape(causal, shape=(1, s, s)),
                              shape=(b * self._heads, s, s))

    def _block_coords(self, F, positions):
        """position -> (block index within the table, offset in block);
        integer //, % via sub-and-divide (exact for pool-sized ints)."""
        rem = positions % self._bs
        bidx = F.cast((positions - rem) / float(self._bs), dtype="int32")
        return bidx, rem

    def _scatter_kv(self, F, pool, layer, blocks, offsets, k, v, n):
        """Functional write of one layer's K and V rows at
        (block, offset) per entry — positions past a request's
        allocation land in scratch block 0 (masked, finite, ignored)."""
        lk = F.full((n,), 2 * layer, dtype="int32")
        lv = F.full((n,), 2 * layer + 1, dtype="int32")
        pool = F._scatter_set_nd(
            pool, k, F.stack(lk, blocks, offsets, axis=0, num_args=3))
        return F._scatter_set_nd(
            pool, v, F.stack(lv, blocks, offsets, axis=0, num_args=3))

    # -- full pass (training / whole-sequence baseline) ---------------
    def hybrid_forward(self, F, tokens):
        b, s = tokens.shape[0], tokens.shape[1]
        x = self.word_embed(tokens) + self.pos_embed(_positions(F, b, s))
        mask = self._causal(F, b, s)
        for cell in self._cells:
            x = cell(x, mask)
        return self.out_proj(x)

    # -- generation entries (serving.ModelServer.serve_generation) ----
    @property
    def _bs(self):
        return self._pool_block

    def hybrid_prefill(self, F, tokens, seq_len, table, pool):
        """tokens (1, L) int32 padded to a length bucket; seq_len (1,)
        int32; table (1, W) int32 block ids (W = ceil(L/block), tail =
        scratch); pool as in :meth:`init_kv_pool`.  Returns
        ((1, V) logits at the last real position, updated pool)."""
        l = tokens.shape[1]
        bs = pool.shape[2]
        self._pool_block = bs
        x = self.word_embed(tokens) + self.pos_embed(_positions(F, 1, l))
        mask = self._causal(F, 1, l)
        pos = F.arange(l, dtype="int32")
        bidx, rem = self._block_coords(F, pos)
        blocks = F.take(F.reshape(table, shape=(-1,)), bidx, axis=0)
        for i, cell in enumerate(self._cells):
            kv = F.split(cell.qkv(x), num_outputs=3, axis=-1)
            pool = self._scatter_kv(
                F, pool, i, blocks, rem,
                F.reshape(kv[1], shape=(l, self._units)),
                F.reshape(kv[2], shape=(l, self._units)), l)
            x = cell.attend(F, x, kv[1], kv[2], mask, 1, l, l)
        last = F.take(F.reshape(x, shape=(l, self._units)),
                      seq_len - 1, axis=0)              # (1, U)
        return self.out_proj(last), pool

    def hybrid_decode(self, F, tokens, positions, tables, pool):
        """One decode step for the whole running batch: tokens (slots,)
        int32; positions (slots,) int32 (each token's position = the
        sequence length before it); tables (slots, W) int32; pool as in
        :meth:`init_kv_pool`.  Returns ((slots, V) logits, updated
        pool).  Every op is row-independent, so a slot's logits depend
        only on its own token/position/table — the bitwise-equality
        contract continuous batching is tested against."""
        slots = tokens.shape[0]
        w = tables.shape[1]
        bs = pool.shape[2]
        self._pool_block = bs
        s_keys = w * bs
        x = self.word_embed(tokens) + self.pos_embed(positions)
        bidx, rem = self._block_coords(F, positions)
        blocks = F.pick(tables, bidx, axis=-1)          # (slots,)
        # per-slot prefix mask over the gathered key window: key j
        # visible iff j <= position (the new token sees itself)
        keep = F.broadcast_lesser_equal(
            F.reshape(F.arange(s_keys, dtype="int32"), shape=(1, s_keys)),
            F.reshape(positions, shape=(slots, 1)))     # (slots, S)
        mask = F.reshape(F.broadcast_to(
            F.reshape(keep, shape=(slots, 1, 1, s_keys)),
            shape=(slots, self._heads, 1, s_keys)),
            shape=(slots * self._heads, 1, s_keys))
        for i, cell in enumerate(self._cells):
            kv = F.split(cell.qkv(x), num_outputs=3, axis=-1)
            pool = self._scatter_kv(F, pool, i, blocks, rem,
                                    kv[1], kv[2], slots)
            kc = F.reshape(F.take(pool[2 * i], tables, axis=0),
                           shape=(slots, s_keys, self._units))
            vc = F.reshape(F.take(pool[2 * i + 1], tables, axis=0),
                           shape=(slots, s_keys, self._units))
            x3 = F.reshape(x, shape=(slots, 1, self._units))
            x3 = cell.attend(F, x3, kc, vc, mask, slots, 1, s_keys)
            x = F.reshape(x3, shape=(slots, self._units))
        return self.out_proj(x), pool


def causal_lm_small(vocab_size=257, **kwargs):
    """Tiny decoder-only LM for tests/benches — the generation-serving
    counterpart of ``bert_small``."""
    kwargs.setdefault("max_length", 256)
    return CausalLM(vocab_size=vocab_size, num_layers=2, units=64,
                    hidden_size=128, num_heads=4, **kwargs)
