"""gluon.model_zoo (reference: python/mxnet/gluon/model_zoo/; the
transformer family covers the GluonNLP/Sockeye configs the BASELINE names —
those are downstream repos in the reference ecosystem, SURVEY.md §1)."""
from . import vision
from . import transformer
from . import ssd
from . import rcnn
from .vision import get_model
from .transformer import (BERTModel, TransformerNMT, bert_base, bert_small,
                          transformer_nmt_base, TP_RULES)
from .ssd import SSD, SSDMultiBoxLoss, ssd_512_resnet50_v1, ssd_toy
from .rcnn import (FasterRCNN, MaskRCNN, RCNNLoss, faster_rcnn_resnet18_v1,
                   mask_rcnn_resnet18_v1, faster_rcnn_toy, mask_rcnn_toy)
