"""gluon.model_zoo (reference: python/mxnet/gluon/model_zoo/; the
transformer family covers the GluonNLP/Sockeye configs the BASELINE names —
those are downstream repos in the reference ecosystem, SURVEY.md §1)."""
from . import vision
from . import transformer
from .vision import get_model
from .transformer import (BERTModel, TransformerNMT, bert_base, bert_small,
                          transformer_nmt_base, TP_RULES)
