"""gluon.model_zoo (reference: python/mxnet/gluon/model_zoo/; the
transformer family covers the GluonNLP/Sockeye configs the BASELINE names —
those are downstream repos in the reference ecosystem, SURVEY.md §1)."""
from . import vision
from . import transformer
from . import ssd
from .vision import get_model
from .transformer import (BERTModel, TransformerNMT, bert_base, bert_small,
                          transformer_nmt_base, TP_RULES)
from .ssd import SSD, SSDMultiBoxLoss, ssd_512_resnet50_v1, ssd_toy
