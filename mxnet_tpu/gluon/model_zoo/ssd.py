"""SSD single-shot detector (BASELINE config #5).

Reference parity: the GluonCV SSD family is *downstream* of the reference —
built entirely on Gluon + the contrib detection ops (multibox_prior/
target/detection, src/operator/contrib/ — SURVEY.md §2.2).  This module
provides the same model shape on this framework: multi-scale feature
stages, per-scale anchor generators and conv predictors, and the
SSDMultiBoxLoss (cross-entropy with hard-negative mining via
MultiBoxTarget + SmoothL1), all static-shaped for XLA.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

from ...base import MXNetError
from ..block import HybridBlock
from ..loss import Loss
from .. import nn

__all__ = ["SSDAnchorGenerator", "ConvPredictor", "SSD", "SSDMultiBoxLoss",
           "ssd_512_resnet50_v1", "ssd_toy"]


class SSDAnchorGenerator(HybridBlock):
    """Per-scale anchors via MultiBoxPrior (reference: multibox_prior.cc)."""

    def __init__(self, sizes, ratios, clip=True, **kwargs):
        super().__init__(**kwargs)
        self._sizes = tuple(sizes)
        self._ratios = tuple(ratios)
        self._clip = clip

    @property
    def num_anchors(self) -> int:
        return len(self._sizes) + len(self._ratios) - 1

    def hybrid_forward(self, F, x):
        return F.contrib.MultiBoxPrior(x, sizes=self._sizes,
                                       ratios=self._ratios,
                                       clip=self._clip)


class ConvPredictor(HybridBlock):
    """3x3 conv head emitting num_outputs values per anchor position."""

    def __init__(self, num_outputs, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.predictor = nn.Conv2D(num_outputs, 3, 1, 1)

    def hybrid_forward(self, F, x):
        return self.predictor(x)


class SSD(HybridBlock):
    """Multi-scale detector.

    ``stages`` is a list of HybridBlocks applied sequentially; the output
    of EACH stage is a prediction source.  Returns
    (anchors (1,N,4), cls_preds (B,N,classes+1), box_preds (B,N*4)).
    """

    def __init__(self, stages: Sequence[HybridBlock], classes: int,
                 sizes: Sequence[Sequence[float]],
                 ratios: Sequence[Sequence[float]], **kwargs):
        super().__init__(**kwargs)
        if not (len(stages) == len(sizes) == len(ratios)):
            raise MXNetError("stages, sizes, ratios must align per scale")
        self._classes = classes
        with self.name_scope():
            self.stages = nn.HybridSequential(prefix="stages_")
            for s in stages:
                self.stages.add(s)
            self.anchor_generators = []
            self.class_predictors = nn.HybridSequential(prefix="cls_")
            self.box_predictors = nn.HybridSequential(prefix="box_")
            for i, (s, r) in enumerate(zip(sizes, ratios)):
                gen = SSDAnchorGenerator(s, r, prefix=f"anchor{i}_")
                self.anchor_generators.append(gen)
                self.register_child(gen)
                na = gen.num_anchors
                self.class_predictors.add(
                    ConvPredictor(na * (classes + 1)))
                self.box_predictors.add(ConvPredictor(na * 4))

    @property
    def classes(self) -> int:
        return self._classes

    def hybrid_forward(self, F, x):
        anchors, cls_preds, box_preds = [], [], []
        for stage, gen, cp, bp in zip(self.stages,
                                      self.anchor_generators,
                                      self.class_predictors,
                                      self.box_predictors):
            x = stage(x)
            anchors.append(gen(x))
            # (B, A*(C+1), H, W) -> (B, H*W*A, C+1) flattened per anchor
            c = cp(x)
            c = F.transpose(c, axes=(0, 2, 3, 1))
            cls_preds.append(F.reshape(c, shape=(0, -1,
                                                 self._classes + 1)))
            b = bp(x)
            b = F.transpose(b, axes=(0, 2, 3, 1))
            box_preds.append(F.reshape(b, shape=(0, -1)))
        return (F.concat(*anchors, dim=1),
                F.concat(*cls_preds, dim=1),
                F.concat(*box_preds, dim=1))


class SSDMultiBoxLoss(Loss):
    """SmoothL1 loc loss + CE cls loss over MultiBoxTarget outputs
    (the loss GluonCV's SSD trains with)."""

    def __init__(self, negative_mining_ratio=3.0, overlap_threshold=0.5,
                 weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._ratio = negative_mining_ratio
        self._thresh = overlap_threshold

    def __call__(self, anchors, cls_preds, box_preds, labels):
        from ... import ndarray as nd
        # targets (no grad through matching, reference FGradient=None)
        loc_t, loc_m, cls_t = nd.contrib.MultiBoxTarget(
            anchors, labels, nd.transpose(cls_preds, axes=(0, 2, 1)),
            overlap_threshold=self._thresh,
            negative_mining_ratio=self._ratio, ignore_label=-1.0)
        loc_t = nd.stop_gradient(loc_t)
        loc_m = nd.stop_gradient(loc_m)
        cls_t = nd.stop_gradient(cls_t)
        # classification: CE where target >= 0 (−1 = ignored by mining)
        valid = cls_t >= 0.0
        logp = nd.log_softmax(cls_preds, axis=-1)
        cls_loss = -nd.pick(logp, nd.maximum(cls_t, 0.0 * cls_t), axis=-1)
        cls_loss = nd.where(valid, cls_loss, nd.zeros_like(cls_loss))
        # localization: smooth-L1 on matched anchors only
        loc_loss = nd.smooth_l1(box_preds - loc_t, scalar=1.0) * loc_m
        num_pos = nd.maximum(nd.sum(loc_m) / 4.0,
                             nd.ones_like(nd.sum(loc_m)))
        return (nd.sum(cls_loss) + nd.sum(loc_loss)) / num_pos


def _down_block(channels: int) -> nn.HybridSequential:
    """1x1 squeeze + 3x3 stride-2 expand (standard SSD extra layer)."""
    blk = nn.HybridSequential()
    blk.add(nn.Conv2D(channels // 2, 1, 1, 0, use_bias=False),
            nn.BatchNorm(), nn.Activation("relu"),
            nn.Conv2D(channels, 3, 2, 1, use_bias=False),
            nn.BatchNorm(), nn.Activation("relu"))
    return blk


# per-scale anchor config for the 512 variant (GluonCV ssd_512 settings)
_SIZES_512 = [[0.07, 0.1025], [0.15, 0.2121], [0.3, 0.3674],
              [0.45, 0.5196], [0.6, 0.6708], [0.75, 0.8216]]
_RATIOS_512 = [[1, 2, 0.5]] * 2 + [[1, 2, 0.5, 3, 1.0 / 3]] * 4


def ssd_512_resnet50_v1(classes: int = 20, **kwargs) -> SSD:
    """SSD-512 on a ResNet-50 v1 backbone (BASELINE config #5 shape)."""
    from .vision.resnet import resnet50_v1
    base = resnet50_v1()
    feats = list(base.features)       # conv,bn,relu,pool,stage1..4,gap
    # stage outputs: up to stage3 (stride 16) and stage4 (stride 32)
    stage1 = nn.HybridSequential(prefix="base3_")
    for f in feats[:7]:               # through stage3
        stage1.add(f)
    stage2 = nn.HybridSequential(prefix="base4_")
    stage2.add(feats[7])              # stage4
    stages: List[HybridBlock] = [stage1, stage2]
    for _ in range(4):                # 4 extra downsampling scales
        stages.append(_down_block(512))
    return SSD(stages, classes, _SIZES_512, _RATIOS_512, **kwargs)


def ssd_toy(classes: int = 3, **kwargs) -> SSD:
    """Small 3-scale SSD for tests/CI (thumbnail inputs)."""
    s1 = nn.HybridSequential()
    s1.add(nn.Conv2D(16, 3, 2, 1), nn.Activation("relu"),
           nn.Conv2D(32, 3, 2, 1), nn.Activation("relu"))
    s2 = _down_block(64)
    s3 = _down_block(64)
    return SSD([s1, s2, s3], classes,
               sizes=[[0.2, 0.272], [0.37, 0.447], [0.54, 0.619]],
               ratios=[[1, 2, 0.5]] * 3, **kwargs)
