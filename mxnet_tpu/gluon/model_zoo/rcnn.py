"""Faster-RCNN / Mask-RCNN family (BASELINE config #5, second half).

Reference parity: the GluonCV RCNN models are *downstream* of the
reference, built on Gluon + contrib ops — `Proposal`
(src/operator/contrib/proposal.cc), `ROIAlign` (roi_align.cc), and
`box_encode/decode` (bounding_box.cc); SURVEY.md §2.2 contrib row.  This
module provides the same two-stage shape on this framework, static-shaped
end to end for XLA:

  backbone features → RPN head → Proposal (pad-and-mask NMS, fixed
  rpn_post_nms_top_n rois) → ROIAlign → box head (cls + bbox) and, for
  Mask-RCNN, a conv mask head on the same pooled features.

Training uses fixed-size sampled roi batches so every step compiles to
one XLA program; padding rois carry weight 0 in the losses.
"""
from __future__ import annotations

from typing import Optional, Sequence

from ..block import HybridBlock
from ..loss import (Loss, SigmoidBinaryCrossEntropyLoss,
                    SoftmaxCrossEntropyLoss)
from .. import nn

__all__ = ["RPNHead", "FasterRCNN", "MaskRCNN", "RCNNLoss",
           "faster_rcnn_resnet18_v1", "mask_rcnn_resnet18_v1",
           "faster_rcnn_toy", "mask_rcnn_toy"]


class RPNHead(HybridBlock):
    """3x3 conv trunk + 1x1 objectness/bbox heads (per-anchor)."""

    def __init__(self, channels: int, num_anchors: int, **kwargs):
        super().__init__(**kwargs)
        self._na = num_anchors
        with self.name_scope():
            self.conv = nn.Conv2D(channels, 3, 1, 1, activation="relu")
            self.score = nn.Conv2D(num_anchors * 2, 1)
            self.loc = nn.Conv2D(num_anchors * 4, 1)

    def hybrid_forward(self, F, x):
        t = self.conv(x)
        raw = self.score(t)                   # (B, 2A, H, W)
        # softmax over {bg, fg} per anchor so Proposal sees probabilities
        b, _, h, w = raw.shape
        pairs = raw.reshape((b, 2, self._na, h, w))
        prob = F.softmax(pairs, axis=1).reshape((b, 2 * self._na, h, w))
        return prob, self.loc(t)


class FasterRCNN(HybridBlock):
    """Two-stage detector: RPN proposals + ROIAlign + box head.

    Forward returns (cls_pred (B,R,C+1), box_pred (B,R,4), rois (B*R,5),
    rpn_score (B,2A,H,W), rpn_loc (B,4A,H,W)) — everything the training
    loss needs, all static shapes.
    """

    def __init__(self, features: HybridBlock, classes: int,
                 rpn_channels: int = 256, roi_size: int = 7,
                 stride: int = 16, scales=(4.0, 8.0, 16.0),
                 ratios=(0.5, 1.0, 2.0), rpn_post_nms: int = 64,
                 rpn_pre_nms: int = 256, head_hidden: int = 256,
                 img_size: int = 256, **kwargs):
        super().__init__(**kwargs)
        self._classes = classes
        self._stride = stride
        self._scales = tuple(scales)
        self._ratios = tuple(ratios)
        self._post = rpn_post_nms
        self._pre = rpn_pre_nms
        self._roi = roi_size
        self._img = img_size
        na = len(self._scales) * len(self._ratios)
        with self.name_scope():
            self.features = features
            self.rpn = RPNHead(rpn_channels, na)
            self.head = nn.HybridSequential()
            self.head.add(nn.Dense(head_hidden, activation="relu"),
                          nn.Dense(head_hidden, activation="relu"))
            self.cls_pred = nn.Dense(classes + 1)
            self.box_pred = nn.Dense(4)

    @property
    def classes(self) -> int:
        return self._classes

    def _trunk(self, F, x):
        """Shared two-stage trunk; returns (cls, box, rois, rpn_score,
        rpn_loc, pooled)."""
        b = x.shape[0]
        feat = self.features(x)
        rpn_score, rpn_loc = self.rpn(feat)
        im_info = F.full((b, 3), float(self._img)) * \
            F.array([[1.0, 1.0, 1.0 / self._img]])
        rois = F.contrib.Proposal(
            rpn_score, rpn_loc, im_info,
            rpn_pre_nms_top_n=self._pre, rpn_post_nms_top_n=self._post,
            feature_stride=self._stride, scales=self._scales,
            ratios=self._ratios, rpn_min_size=1)
        pooled = F.contrib.ROIAlign(
            feat, rois, pooled_size=(self._roi, self._roi),
            spatial_scale=1.0 / self._stride, sample_ratio=2)
        flat = pooled.reshape((b * self._post, -1))
        h = self.head(flat)
        cls = self.cls_pred(h).reshape((b, self._post, self._classes + 1))
        box = self.box_pred(h).reshape((b, self._post, 4))
        return cls, box, rois, rpn_score, rpn_loc, pooled

    def hybrid_forward(self, F, x):
        return self._trunk(F, x)[:5]


class MaskRCNN(FasterRCNN):
    """Faster-RCNN + per-roi conv mask head (reference downstream:
    GluonCV mask_rcnn; mask head = conv3x3 stack + deconv upsample + 1x1).

    Mask channels are indexed by 0-based FOREGROUND class."""

    def __init__(self, features: HybridBlock, classes: int,
                 mask_channels: int = 64, **kwargs):
        super().__init__(features, classes, **kwargs)
        with self.name_scope():
            self.mask_head = nn.HybridSequential()
            for _ in range(2):
                self.mask_head.add(
                    nn.Conv2D(mask_channels, 3, 1, 1, activation="relu"))
            self.mask_head.add(
                nn.Conv2DTranspose(mask_channels, 2, 2, 0,
                                   activation="relu"),
                nn.Conv2D(classes, 1))

    def hybrid_forward(self, F, x):
        cls, box, rois, rpn_score, rpn_loc, pooled = self._trunk(F, x)
        b = cls.shape[0]
        masks = self.mask_head(pooled)        # (B*R, C, 2*roi, 2*roi)
        masks = masks.reshape((b, self._post, self._classes,
                               2 * self._roi, 2 * self._roi))
        return cls, box, rois, rpn_score, rpn_loc, masks


class RCNNLoss(Loss):
    """Multi-task training loss for the fixed-size roi batch.

    Two stages, matching the reference training recipe:

    - RPN: anchors (recomputed with the exact Proposal-op enumeration,
      ``rpn_anchors``) are matched to ground truth by IoU; objectness BCE
      on positives/negatives, smooth-L1 on positive anchor deltas.
    - RCNN head: each roi is matched to the best gt box (box_iou); rois
      above ``fg_thresh`` become positives with box_encode regression
      targets; padding rois (all-zero) get weight 0.  Adds sigmoid mask
      loss (0-based foreground class channel) when mask logits are
      present.

    ``stride``/``scales``/``ratios`` must match the network's RPN config
    (defaults mirror FasterRCNN's defaults).
    """

    def __init__(self, fg_thresh: float = 0.5, stride: int = 16,
                 scales=(4.0, 8.0, 16.0), ratios=(0.5, 1.0, 2.0),
                 rpn_pos_iou: float = 0.7, rpn_neg_iou: float = 0.3,
                 weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._fg = fg_thresh
        self._stride = stride
        self._scales = tuple(scales)
        self._ratios = tuple(ratios)
        self._rpn_pos = rpn_pos_iou
        self._rpn_neg = rpn_neg_iou
        self._sce = SoftmaxCrossEntropyLoss()
        self._bce = SigmoidBinaryCrossEntropyLoss(from_sigmoid=True)

    @classmethod
    def for_net(cls, net: "FasterRCNN", **kwargs):
        """Build a loss whose anchor config matches ``net``'s RPN."""
        return cls(stride=net._stride, scales=net._scales,
                   ratios=net._ratios, **kwargs)

    def _rpn_losses(self, F, rpn_score, rpn_loc, gt_boxes):
        """Anchor-level objectness BCE + positive-anchor smooth-L1."""
        from ...ndarray.ops_contrib import rpn_anchors
        b = rpn_score.shape[0]
        a2, h, w = rpn_score.shape[1], rpn_score.shape[2], rpn_score.shape[3]
        na = a2 // 2
        n = h * w * na
        anchors = F.array(rpn_anchors(h, w, self._stride, self._scales,
                                      self._ratios), ctx=rpn_score.context)
        # (H,W,A) enumeration — identical to the Proposal op
        fg = F.slice_axis(rpn_score, axis=1, begin=na, end=2 * na)
        fg = fg.transpose((0, 2, 3, 1)).reshape((b, n))
        loc = rpn_loc.reshape((b, na, 4, h, w))
        loc = loc.transpose((0, 3, 4, 1, 2)).reshape((b, n, 4))

        iou = F.contrib.box_iou(
            anchors.reshape((1, n, 4)).broadcast_to((b, n, 4)),
            gt_boxes, format="corner")                   # (B,N,M)
        best_iou = F.max(iou, axis=-1)
        best_gt = F.argmax(iou, axis=-1)
        pos = best_iou > self._rpn_pos
        neg = best_iou < self._rpn_neg
        care = pos | neg
        tgt = F.where(pos, F.ones_like(best_iou), F.zeros_like(best_iou))
        wobj = F.where(care, F.ones_like(best_iou), F.zeros_like(best_iou))
        cls_l = F.mean(self._bce(fg, tgt, wobj))

        samples = F.where(pos, F.ones_like(best_iou),
                          -F.ones_like(best_iou))
        means = F.zeros((4,), ctx=rpn_score.context)
        stds = F.ones((4,), ctx=rpn_score.context)
        abox = anchors.reshape((1, n, 4)).broadcast_to((b, n, 4))
        targets, tmask = F.contrib.box_encode(
            samples, best_gt.astype("float32"), abox, gt_boxes,
            means, stds)
        box_l = F.mean(F.smooth_l1((loc - targets) * tmask, scalar=1.0))
        return cls_l, box_l

    def __call__(self, outs, gt_boxes, gt_classes, gt_masks=None):
        from ... import ndarray as F

        cls, box, rois, rpn_score, rpn_loc = outs[:5]
        masks = outs[5] if len(outs) > 5 else None
        b, r = cls.shape[0], cls.shape[1]
        roi_boxes = rois.reshape((b, r, 5))[:, :, 1:]   # corners

        rpn_cls_l, rpn_box_l = self._rpn_losses(F, rpn_score, rpn_loc,
                                                gt_boxes)

        iou = F.contrib.box_iou(roi_boxes, gt_boxes, format="corner")
        best_iou = F.max(iou, axis=-1)                  # (B,R)
        best_gt = F.argmax(iou, axis=-1)                # (B,R)
        pos = best_iou > self._fg
        live = F.sum(roi_boxes, axis=-1) > 0            # padding rois out

        m = gt_boxes.shape[1]
        sel = F.one_hot(best_gt.astype("int32"), depth=m)  # (B,R,M)

        # class target: matched gt class + 1 for positives, 0 = background
        gtc = F.sum(sel * gt_classes.reshape((b, 1, m)), axis=-1)
        cls_target = F.where(pos, gtc + 1.0,
                             F.zeros_like(gtc)).astype("int32")
        cls_l = self._sce(cls.reshape((-1, cls.shape[-1])),
                          cls_target.reshape((-1,)),
                          F.where(live, F.ones_like(best_iou),
                                  F.zeros_like(best_iou)).reshape((-1, 1)))

        # box regression target (standard RCNN encode, unit std)
        samples = F.where(pos & live, F.ones_like(best_iou),
                          -F.ones_like(best_iou))
        means = F.zeros((4,), ctx=cls.context)
        stds = F.ones((4,), ctx=cls.context)
        targets, tmask = F.contrib.box_encode(
            samples, best_gt.astype("float32"), roi_boxes, gt_boxes,
            means, stds)
        diff = (box - targets) * tmask
        box_l = F.mean(F.smooth_l1(diff, scalar=1.0))

        total = F.mean(cls_l) + box_l + rpn_cls_l + rpn_box_l
        if masks is not None and gt_masks is not None:
            # pooled-resolution mask supervision for positive rois: the
            # 0-based FOREGROUND class channel of the matched class,
            # against the matched gt mask (one-hot contraction keeps
            # shapes static); background rois carry weight 0
            ms = masks.shape
            fg_cls = F.maximum(cls_target.astype("float32") - 1.0,
                               F.zeros_like(best_iou)).astype("int32")
            midx = F.one_hot(fg_cls, depth=ms[2])       # (B,R,C)
            pred = F.sum(masks * midx.reshape((b, r, ms[2], 1, 1)),
                         axis=2)                        # (B,R,h,w)
            gm = F.sum(sel.reshape((b, r, m, 1, 1)) *
                       gt_masks.reshape((b, 1, m) + gt_masks.shape[2:]),
                       axis=2)                          # (B,R,h,w)
            wmask = F.where(pos & live, F.ones_like(best_iou),
                            F.zeros_like(best_iou))
            mask_bce = SigmoidBinaryCrossEntropyLoss()(
                pred.reshape((b * r,) + pred.shape[2:]),
                gm.reshape((b * r,) + gm.shape[2:]),
                wmask.reshape((b * r, 1, 1)))
            total = total + F.mean(mask_bce)
        return total


def _resnet18_features():
    from .vision import resnet18_v1
    net = resnet18_v1()
    feats = nn.HybridSequential()
    # all stages except the global-pool/classifier tail; stride 16 at exit
    for layer in list(net.features._children.values())[:-3]:
        feats.add(layer)
    return feats


def faster_rcnn_resnet18_v1(classes: int = 20, **kwargs) -> FasterRCNN:
    return FasterRCNN(_resnet18_features(), classes, **kwargs)


def mask_rcnn_resnet18_v1(classes: int = 20, **kwargs) -> MaskRCNN:
    return MaskRCNN(_resnet18_features(), classes, **kwargs)


def _toy_features() -> nn.HybridSequential:
    f = nn.HybridSequential()
    for ch in (16, 32, 32, 64):                 # stride 16 at exit
        f.add(nn.Conv2D(ch, 3, 2, 1, activation="relu"))
    return f


def faster_rcnn_toy(classes: int = 3, **kwargs) -> FasterRCNN:
    kwargs.setdefault("rpn_post_nms", 16)
    kwargs.setdefault("rpn_pre_nms", 64)
    kwargs.setdefault("img_size", 64)
    kwargs.setdefault("rpn_channels", 32)
    kwargs.setdefault("head_hidden", 64)
    return FasterRCNN(_toy_features(), classes, **kwargs)


def mask_rcnn_toy(classes: int = 3, **kwargs) -> MaskRCNN:
    kwargs.setdefault("rpn_post_nms", 16)
    kwargs.setdefault("rpn_pre_nms", 64)
    kwargs.setdefault("img_size", 64)
    kwargs.setdefault("rpn_channels", 32)
    kwargs.setdefault("head_hidden", 64)
    kwargs.setdefault("mask_channels", 32)
    return MaskRCNN(_toy_features(), classes, **kwargs)
