"""Gluon losses.

Reference parity: python/mxnet/gluon/loss.py (SURVEY.md §2.5) — same
reduction semantics: per-sample loss vector (mean over all axes except
batch_axis), sample_weight broadcasting via `_apply_weighting`.
"""
from __future__ import annotations

from ..base import MXNetError
from .block import HybridBlock

__all__ = ["Loss", "L2Loss", "L1Loss", "SigmoidBinaryCrossEntropyLoss",
           "SigmoidBCELoss", "SoftmaxCrossEntropyLoss", "SoftmaxCELoss",
           "KLDivLoss", "HuberLoss", "HingeLoss", "SquaredHingeLoss",
           "LogisticLoss", "TripletLoss", "CosineEmbeddingLoss", "CTCLoss",
           "PoissonNLLLoss", "SDMLLoss"]


def _apply_weighting(F, loss, weight=None, sample_weight=None):
    if sample_weight is not None:
        loss = F.broadcast_mul(loss, sample_weight)
    if weight is not None:
        if not isinstance(weight, (int, float)):
            raise MXNetError("weight must be a number")
        loss = loss * weight
    return loss


def _reshape_like(F, x, y):
    return x.reshape(y.shape)


class Loss(HybridBlock):
    def __init__(self, weight, batch_axis, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._weight = weight
        self._batch_axis = batch_axis

    def __repr__(self):
        return (f"{type(self).__name__}(batch_axis={self._batch_axis}, "
                f"w={self._weight})")

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError


class L2Loss(Loss):
    def __init__(self, weight=1.0, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.square(label - pred)
        loss = _apply_weighting(F, loss, self._weight / 2, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


class L1Loss(Loss):
    def __init__(self, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.abs(label - pred)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


class SigmoidBinaryCrossEntropyLoss(Loss):
    def __init__(self, from_sigmoid=False, weight=None, batch_axis=0,
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_sigmoid = from_sigmoid

    def hybrid_forward(self, F, pred, label, sample_weight=None,
                       pos_weight=None):
        label = _reshape_like(F, label, pred)
        if not self._from_sigmoid:
            # numerically stable log-sum-exp form
            loss = F.relu(pred) - pred * label + \
                F.Activation(-F.abs(pred), act_type="softrelu")
            if pos_weight is not None:
                loss = loss + (pos_weight - 1) * label * \
                    (F.relu(-pred) + F.Activation(-F.abs(pred),
                                                  act_type="softrelu"))
        else:
            eps = 1e-12
            if pos_weight is None:
                loss = -(F.log(pred + eps) * label +
                         F.log(1 - pred + eps) * (1 - label))
            else:
                loss = -(F.broadcast_mul(F.log(pred + eps) * label,
                                         pos_weight) +
                         F.log(1 - pred + eps) * (1 - label))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


SigmoidBCELoss = SigmoidBinaryCrossEntropyLoss


class SoftmaxCrossEntropyLoss(Loss):
    def __init__(self, axis=-1, sparse_label=True, from_logits=False,
                 weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._axis = axis
        self._sparse_label = sparse_label
        self._from_logits = from_logits

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        if not self._from_logits:
            pred = F.log_softmax(pred, axis=self._axis)
        if self._sparse_label:
            loss = -F.pick(pred, label, axis=self._axis, keepdims=True)
        else:
            label = _reshape_like(F, label, pred)
            loss = -F.sum(pred * label, axis=self._axis, keepdims=True)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


SoftmaxCELoss = SoftmaxCrossEntropyLoss


class KLDivLoss(Loss):
    def __init__(self, from_logits=True, axis=-1, weight=None, batch_axis=0,
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_logits = from_logits
        self._axis = axis

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        if not self._from_logits:
            pred = F.log_softmax(pred, axis=self._axis)
        loss = label * (F.log(label + 1e-12) - pred)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


class HuberLoss(Loss):
    def __init__(self, rho=1.0, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._rho = rho

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.abs(label - pred)
        loss = F.where(loss > self._rho,
                       loss - 0.5 * self._rho,
                       (0.5 / self._rho) * F.square(loss))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


class HingeLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.relu(self._margin - pred * label)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


class SquaredHingeLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.square(F.relu(self._margin - pred * label))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


class LogisticLoss(Loss):
    def __init__(self, weight=None, batch_axis=0, label_format="signed",
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        if label_format not in ("signed", "binary"):
            raise MXNetError(f"bad label_format {label_format}")
        self._label_format = label_format

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        if self._label_format == "signed":
            label = (label + 1.0) / 2.0
        loss = F.relu(pred) - pred * label + \
            F.Activation(-F.abs(pred), act_type="softrelu")
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


class TripletLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, pred, positive, negative, sample_weight=None):
        positive = _reshape_like(F, positive, pred)
        negative = _reshape_like(F, negative, pred)
        loss = F.sum(F.square(positive - pred) - F.square(negative - pred),
                     axis=self._batch_axis, exclude=True)
        loss = F.relu(loss + self._margin)
        return _apply_weighting(F, loss, self._weight, sample_weight)


class CosineEmbeddingLoss(Loss):
    def __init__(self, weight=None, batch_axis=0, margin=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, input1, input2, label, sample_weight=None):
        input1 = input1.reshape((input1.shape[0], -1)) \
            if input1.ndim > 2 else input1
        input2 = input2.reshape((input2.shape[0], -1)) \
            if input2.ndim > 2 else input2
        dot = F.sum(input1 * input2, axis=-1)
        n1 = F.sqrt(F.sum(F.square(input1), axis=-1) + 1e-12)
        n2 = F.sqrt(F.sum(F.square(input2), axis=-1) + 1e-12)
        cos = dot / (n1 * n2)
        label = label.reshape((-1,))
        pos = 1 - cos
        neg = F.relu(cos - self._margin)
        loss = F.where(label == 1, pos, neg)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return loss


class PoissonNLLLoss(Loss):
    """Poisson negative log likelihood (reference gluon.loss.PoissonNLLLoss):
    ``pred`` is the predicted MEAN (or its log when ``from_logits``);
    optional Stirling approximation adds the target-dependent constant."""

    def __init__(self, weight=None, from_logits=True, batch_axis=0,
                 compute_full=False, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_logits = from_logits
        self._compute_full = compute_full

    def hybrid_forward(self, F, pred, target, sample_weight=None,
                       epsilon=1e-08):
        target = _reshape_like(F, target, pred)
        if self._from_logits:
            loss = F.exp(pred) - target * pred
        else:
            loss = pred - target * F.log(pred + epsilon)
            if self._compute_full:
                # Stirling: t*log(t) - t + 0.5*log(2*pi*t), for t > 1 —
                # the reference applies it in the mean-space branch only
                import math
                stirling = target * F.log(target + epsilon) - target \
                    + 0.5 * F.log(2 * math.pi * (target + epsilon))
                loss = loss + F.where(target > 1.0, stirling,
                                      F.zeros_like(target))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss)


class SDMLLoss(Loss):
    """Smoothed deep metric learning loss (reference
    gluon.loss.SDMLLoss): treats the i-th rows of two batches as the only
    positive pair among 2N candidates and cross-entropies a smoothed
    target against the negated-distance softmax."""

    def __init__(self, smoothing_parameter=0.3, weight=1.0, batch_axis=0,
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._smoothing = smoothing_parameter

    def hybrid_forward(self, F, x1, x2, sample_weight=None):
        n = x1.shape[0]
        # pairwise SQUARED euclidean distances (N, N) — the reference's
        # _compute_distances has no sqrt; the softmax logits are -d²
        d = F.sum(F.square(
            F.expand_dims(x1, axis=1) - F.expand_dims(x2, axis=0)),
            axis=-1)
        logp = F.log_softmax(-d, axis=-1)
        # smoothed one-hot: (1-s) on the diagonal, s/(N-1) elsewhere
        eye = F.one_hot(F.arange(n, dtype="int32"), depth=n)
        smooth = eye * (1.0 - self._smoothing) \
            + (1.0 - eye) * (self._smoothing / max(n - 1, 1))
        loss = -F.sum(smooth * logp, axis=-1)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss)


class CTCLoss(Loss):
    """Connectionist Temporal Classification loss (reference:
    python/mxnet/gluon/loss.py CTCLoss over src/operator/nn/ctc_loss.cc).

    ``pred``: ``(N, T, C)`` for layout 'NTC' (default) or ``(T, N, C)``
    for 'TNC'; the LAST class index ``C-1`` is blank (the reference gluon
    wrapper's ``blank_label='last'`` convention).  ``label``: ``(N, L)``
    padded with ``-1`` unless ``label_lengths`` is given.
    """

    def __init__(self, layout="NTC", label_layout="NT", weight=None,
                 **kwargs):
        if layout not in ("NTC", "TNC"):
            raise MXNetError(f"unsupported CTCLoss layout {layout}")
        if label_layout not in ("NT", "TN"):
            raise MXNetError(f"unsupported label layout {label_layout}")
        batch_axis = label_layout.find("N")
        super().__init__(weight, batch_axis, **kwargs)
        self._layout = layout
        self._label_layout = label_layout

    def hybrid_forward(self, F, pred, label, pred_lengths=None,
                       label_lengths=None, sample_weight=None):
        if self._layout == "NTC":
            pred = F.swapaxes(pred, dim1=0, dim2=1)
        if self._label_layout == "TN":
            label = F.swapaxes(label, dim1=0, dim2=1)
        args = []
        if pred_lengths is not None:
            args.append(pred_lengths)
        if label_lengths is not None:
            args.append(label_lengths)
        loss = F.CTCLoss(pred, label, *args,
                         use_data_lengths=pred_lengths is not None,
                         use_label_lengths=label_lengths is not None,
                         blank_label="last")
        return _apply_weighting(F, loss, self._weight, sample_weight)
