"""Gluon Trainer: applies an optimizer over Parameters with a KVStore seam.

Reference parity: python/mxnet/gluon/trainer.py (SURVEY.md §2.5, §3.2) —
step = allreduce_grads (kvstore push/pull) + update (fused optimizer op per
param).  On a single chip the reduce is a no-op; across in-process devices it
sums replica grads; on a real mesh the sharded path in mxnet_tpu.parallel
(psum over ICI) replaces this loop, matching the north star
(BASELINE.json: kvstore='device' → lax.psum).
"""
from __future__ import annotations

from typing import Dict, List, Optional

from ..base import MXNetError
from .. import optimizer as opt_mod
from .. import kvstore as kv_mod
from .parameter import Parameter, ParameterDict

__all__ = ["Trainer"]


class Trainer:
    def __init__(self, params, optimizer, optimizer_params=None,
                 kvstore="device", compression_params=None,
                 update_on_kvstore=None):
        if isinstance(params, (dict, ParameterDict)):
            params = list(params.values())
        if not isinstance(params, (list, tuple)):
            raise MXNetError("params must be a ParameterDict or list")
        self._all_params = list(params)
        self._params: List[Parameter] = [
            p for p in params if p.grad_req != "null"]
        self._param2idx = {p.name: i for i, p in enumerate(self._params)}
        optimizer_params = optimizer_params or {}
        param_dict = {i: p for i, p in enumerate(self._params)}
        self._optimizer = opt_mod.create(optimizer, param_dict=param_dict,
                                         **optimizer_params)
        self._scale = self._optimizer.rescale_grad
        self._updaters: Dict = {}
        self._kvstore = kv_mod.create(kvstore) if isinstance(kvstore, str) \
            else kvstore
        self._kv_initialized = False
        self._states: Dict = {}

    # -- properties --------------------------------------------------------
    @property
    def learning_rate(self) -> float:
        return self._optimizer.learning_rate

    @property
    def optimizer(self):
        return self._optimizer

    def set_learning_rate(self, lr: float) -> None:
        self._optimizer.set_learning_rate(lr)

    # -- step --------------------------------------------------------------
    def step(self, batch_size: int, ignore_stale_grad: bool = False) -> None:
        """Rescale by 1/batch_size, reduce grads across devices, update."""
        self._optimizer.rescale_grad = self._scale / batch_size
        self.allreduce_grads()
        self.update(batch_size, ignore_stale_grad)

    def allreduce_grads(self) -> None:
        for i, param in enumerate(self._params):
            grads = param.list_grad()
            if len(grads) == 1:
                continue
            reduced = grads[0].copy()
            for g in grads[1:]:
                reduced += g.as_in_context(reduced.context)
            for g in grads:
                reduced.copyto(g)

    def update(self, batch_size: int, ignore_stale_grad: bool = False) -> None:
        self._optimizer.rescale_grad = self._scale / batch_size
        for i, param in enumerate(self._params):
            for ctx, data in param._data.items():
                # reference parity: a 'write'-mode grad untouched by backward
                # since the last step is stale — error unless opted out, in
                # which case the param is skipped (gluon/trainer.py behavior)
                if data._ag is not None and data._ag.grad_req == "write" \
                        and data._ag.fresh:
                    if not ignore_stale_grad:
                        raise MXNetError(
                            f"gradient of Parameter {param.name!r} on {ctx} "
                            f"has not been updated by backward since the "
                            f"last step; set ignore_stale_grad=True to skip "
                            f"such parameters")
                    continue
                key = (i, ctx)
                if key not in self._states:
                    self._states[key] = \
                        self._optimizer.create_state_multi_precision(i, data)
                self._optimizer.update_multi_precision(
                    i, data, data.grad, self._states[key])
                # reset write-mode gradient accumulation for the next batch
                data._ag.fresh = True

    def allreduce_and_update(self, batch_size):
        self.step(batch_size)

    # -- state persistence -------------------------------------------------
    def save_states(self, fname: str) -> None:
        import pickle
        import numpy as _np
        blob = {}
        for (i, ctx), state in self._states.items():
            blob[str(i)] = opt_mod._states_to_np(state)
        with open(fname, "wb") as f:
            pickle.dump({"states": blob,
                         "num_update": self._optimizer.num_update,
                         "index_update_count":
                             dict(self._optimizer._index_update_count)}, f)

    def load_states(self, fname: str) -> None:
        import pickle
        with open(fname, "rb") as f:
            blob = pickle.load(f)
        self._optimizer.num_update = blob.get("num_update", 0)
        # restore per-index counts too, else Adam bias correction restarts
        # at t=1 after resume
        self._optimizer._index_update_count = dict(
            blob.get("index_update_count", {}))
        for i, param in enumerate(self._params):
            if str(i) not in blob["states"]:
                continue
            for ctx in param._data:
                self._states[(i, ctx)] = \
                    opt_mod._states_from_np(blob["states"][str(i)])
