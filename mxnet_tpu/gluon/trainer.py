"""Gluon Trainer: applies an optimizer over Parameters with a KVStore seam.

Reference parity: python/mxnet/gluon/trainer.py (SURVEY.md §2.5, §3.2) —
step = allreduce_grads (kvstore push/pull) + update (fused optimizer op per
param).  On a single chip the reduce is a no-op; across in-process devices it
sums replica grads; on a real mesh the sharded path in mxnet_tpu.parallel
(psum over ICI) replaces this loop, matching the north star
(BASELINE.json: kvstore='device' → lax.psum).
"""
from __future__ import annotations

from typing import Dict, List, Optional

from ..base import MXNetError, get_env, hot_path
from .. import optimizer as opt_mod
from .. import kvstore as kv_mod
from .parameter import Parameter, ParameterDict

__all__ = ["Trainer"]


class Trainer:
    def __init__(self, params, optimizer, optimizer_params=None,
                 kvstore="device", compression_params=None,
                 update_on_kvstore=None):
        if isinstance(params, (dict, ParameterDict)):
            params = list(params.values())
        if not isinstance(params, (list, tuple)):
            raise MXNetError("params must be a ParameterDict or list")
        self._all_params = list(params)
        self._params: List[Parameter] = [
            p for p in params if p.grad_req != "null"]
        self._param2idx = {p.name: i for i, p in enumerate(self._params)}
        optimizer_params = optimizer_params or {}
        param_dict = {i: p for i, p in enumerate(self._params)}
        self._optimizer = opt_mod.create(optimizer, param_dict=param_dict,
                                         **optimizer_params)
        self._scale = self._optimizer.rescale_grad
        self._updaters: Dict = {}
        self._kvstore = kv_mod.create(kvstore) if isinstance(kvstore, str) \
            else kvstore
        if compression_params is not None and self._kvstore is not None:
            if getattr(self._kvstore, "_dist", False):
                self._kvstore.set_gradient_compression(compression_params)
            else:
                import warnings
                warnings.warn(
                    "gradient compression applies to dist_* kvstores only; "
                    "ignored for in-process reduction (ICI collectives)")
        self._kv_initialized = False
        # server-side updates are the dist default (reference behavior);
        # in-process reduction keeps the fused local update path
        self._update_on_kvstore = update_on_kvstore
        self._dist_kv = False
        self._states: Dict = {}
        # param index -> RowSparseNDArray grad stashed by the sparse
        # exchange in allreduce_grads, consumed by update()'s lazy path
        self._sparse_grads: Dict = {}

    # -- properties --------------------------------------------------------
    @property
    def learning_rate(self) -> float:
        return self._optimizer.learning_rate

    @property
    def optimizer(self):
        return self._optimizer

    def set_learning_rate(self, lr: float) -> None:
        self._optimizer.set_learning_rate(lr)

    # -- step --------------------------------------------------------------
    def _init_kvstore(self) -> None:
        """Decide the update path and register params with a dist kvstore.

        Reference parity: Trainer._init_kvstore — with a dist kvstore the
        optimizer runs server-side (update_on_kvstore default True); the
        in-process case keeps the local fused-update path (the real
        multi-device reduce rides mxnet_tpu.parallel's in-graph psum).
        """
        if self._kv_initialized:
            return
        kv = self._kvstore
        self._dist_kv = kv is not None and getattr(kv, "_dist", False)
        if self._update_on_kvstore is None:
            self._update_on_kvstore = self._dist_kv
        if self._update_on_kvstore and kv is None:
            raise MXNetError("update_on_kvstore=True requires a kvstore")
        if self._update_on_kvstore:
            kv.set_optimizer(self._optimizer)
            kv.init(list(range(len(self._params))),
                    [p.list_data()[0] for p in self._params])
        elif self._dist_kv:
            # grads-only reduction through the store: no server optimizer,
            # push/pull sums gradients, the update stays local.  init
            # broadcasts rank 0's weights; pull them back so every worker
            # starts from identical parameters (reference behavior)
            kv.init(list(range(len(self._params))),
                    [p.list_data()[0] for p in self._params])
            for i, p in enumerate(self._params):
                kv.pull(i, out=p.list_data())
        self._kv_initialized = True

    def _stale(self, param) -> bool:
        """True if param's write-mode grad was untouched since last step."""
        return any(d._ag is not None and d._ag.grad_req == "write"
                   and d._ag.fresh for d in param._data.values())

    def step(self, batch_size: int, ignore_stale_grad: bool = False) -> None:
        """Rescale by 1/batch_size, reduce grads across devices, update."""
        self._optimizer.rescale_grad = self._scale / batch_size
        self._init_kvstore()
        if self._update_on_kvstore:
            live = []
            for i, param in enumerate(self._params):
                if self._stale(param):
                    if not ignore_stale_grad:
                        raise MXNetError(
                            f"gradient of Parameter {param.name!r} has not "
                            f"been updated by backward since the last step; "
                            f"set ignore_stale_grad=True to skip such "
                            f"parameters")
                    continue
                live.append((i, param))
            # ONE push call for every live key: the dist kvstore coalesces
            # the whole list into a single DCN sync (kvstore.py
            # _allreduce_batched)
            keys = [i for i, _ in live]
            vals = []
            for _, param in live:
                grads = param.list_grad()
                vals.append(grads if len(grads) > 1 else grads[0])
            if keys:
                self._kvstore.push(keys, vals)
            for i, param in live:
                self._kvstore.pull(i, out=param.list_data())
                for data in param._data.values():
                    if data._ag is not None:
                        data._ag.fresh = True  # reset staleness tracking
            return
        self.allreduce_grads()
        self.update(batch_size, ignore_stale_grad)

    def allreduce_grads(self) -> None:
        """Sum gradients across device replicas and (dist) across workers."""
        self._init_kvstore()
        if self._update_on_kvstore:
            raise MXNetError(
                "allreduce_grads is not applicable when the optimizer runs "
                "on the kvstore (update_on_kvstore=True)")
        push_keys, push_vals = [], []
        sparse_on = bool(get_env("MXTPU_SPARSE_EXCHANGE"))
        for i, param in enumerate(self._params):
            grads = param.list_grad()
            if len(grads) > 1:
                reduced = grads[0].copy()
                for g in grads[1:]:
                    reduced += g.as_in_context(reduced.context)
                for g in grads:
                    reduced.copyto(g)
            if (sparse_on and len(grads) == 1 and
                    getattr(param, "grad_stype", "default")
                    == "row_sparse"):
                # coalesced sparse exchange (the modern ps-lite
                # push/pull): ship only the touched rows, skip the
                # dense store round-trip; update() consumes the stash
                # through the optimizer's lazy row path
                self._sparse_grads[i] = self._exchange_row_sparse(grads[0])
                continue
            if self._dist_kv:
                # cross-worker gradient sum through the store (no server
                # optimizer in this mode; the local fused update applies
                # it).  Local replicas were already reduced above — queue
                # ONE copy per param and push them all as one batched call
                # (one DCN sync), then pull the global sums back.
                push_keys.append(i)
                push_vals.append(grads[0])
        if push_keys:
            self._kvstore.push(push_keys, push_vals)
            for i in push_keys:
                grads = self._params[i].list_grad()
                self._kvstore.pull(i, out=grads if len(grads) > 1
                                   else grads[0])

    @hot_path("step")
    def _exchange_row_sparse(self, grad):
        """Turn one replica-reduced dense gradient into its row-sparse
        form and (multi-worker) exchange it: extract the batch's live
        rows, ``dist.allgather_rows`` the ``(ids, rows)`` slabs, and
        dedup+sum — the wire carries touched rows, not the table."""
        import numpy as np
        import jax.numpy as jnp
        from .. import sparse as sp_mod
        from ..parallel import dist
        g = grad._read()
        idx = jnp.nonzero(jnp.any(g != 0, axis=tuple(range(1, g.ndim))))[0]
        vals = jnp.take(g, idx, axis=0)
        if dist.is_initialized() and dist.num_workers() > 1:
            pairs = dist.allgather_rows(np.asarray(idx), np.asarray(vals))  # mxlint: disable=hidden-host-sync — the exchange IS the host boundary: rows leave the device to ride the DCN
            uids, rows = dist.dedup_sum_rows(pairs)
            idx, vals = jnp.asarray(uids), jnp.asarray(rows)
        return sp_mod.RowSparseNDArray(vals, idx, shape=tuple(g.shape))

    def update(self, batch_size: int, ignore_stale_grad: bool = False) -> None:
        self._optimizer.rescale_grad = self._scale / batch_size
        agg = getattr(self._optimizer, "aggregate_num", 0)
        use_multi = agg > 1 and hasattr(self._optimizer, "update_multi")
        group: List = []   # pending (index, data, grad, state) tuples

        def flush():
            if not group:
                return
            idx, datas, grads, sts = zip(*group)
            self._optimizer.update_multi(list(idx), list(datas),
                                         list(grads), list(sts))
            group.clear()

        for i, param in enumerate(self._params):
            for ctx, data in param._data.items():
                # reference parity: a 'write'-mode grad untouched by backward
                # since the last step is stale — error unless opted out, in
                # which case the param is skipped (gluon/trainer.py behavior)
                if data._ag is not None and data._ag.grad_req == "write" \
                        and data._ag.fresh:
                    if not ignore_stale_grad:
                        raise MXNetError(
                            f"gradient of Parameter {param.name!r} on {ctx} "
                            f"has not been updated by backward since the "
                            f"last step; set ignore_stale_grad=True to skip "
                            f"such parameters")
                    continue
                key = (i, ctx)
                if key not in self._states:
                    self._states[key] = \
                        self._optimizer.create_state_multi_precision(i, data)
                sparse_g = self._sparse_grads.pop(i, None)
                if sparse_g is not None:
                    # stashed row-sparse grad from the coalesced
                    # exchange — always the direct path (the aggregate
                    # group is dense-only), hits the optimizer's lazy
                    # row update
                    self._optimizer.update_multi_precision(
                        i, data, sparse_g, self._states[key])
                elif use_multi and len(param._data) == 1:
                    group.append((i, data, data.grad, self._states[key]))
                    if len(group) >= agg:
                        flush()
                else:
                    self._optimizer.update_multi_precision(
                        i, data, data.grad, self._states[key])
                # reset write-mode gradient accumulation for the next batch
                data._ag.fresh = True
        flush()

    def allreduce_and_update(self, batch_size):
        self.step(batch_size)

    # -- state persistence -------------------------------------------------
    def save_states(self, fname: str) -> None:
        self._init_kvstore()
        if self._update_on_kvstore:
            self._kvstore.save_optimizer_states(fname, dump_optimizer=True)
            return
        import pickle
        import numpy as _np
        blob = {}
        for (i, ctx), state in self._states.items():
            blob[str(i)] = opt_mod._states_to_np(state)
        with open(fname, "wb") as f:
            pickle.dump({"states": blob,
                         "num_update": self._optimizer.num_update,
                         "index_update_count":
                             dict(self._optimizer._index_update_count)}, f)

    def load_states(self, fname: str) -> None:
        self._init_kvstore()
        if self._update_on_kvstore:
            self._kvstore.load_optimizer_states(fname)
            return
        import pickle
        with open(fname, "rb") as f:
            blob = pickle.load(f)
        self._optimizer.num_update = blob.get("num_update", 0)
        # restore per-index counts too, else Adam bias correction restarts
        # at t=1 after resume
        self._optimizer._index_update_count = dict(
            blob.get("index_update_count", {}))
        for i, param in enumerate(self._params):
            if str(i) not in blob["states"]:
                continue
            for ctx in param._data:
                self._states[(i, ctx)] = \
                    opt_mod._states_from_np(blob["states"][str(i)])
