"""Gluon Parameter / ParameterDict.

Reference parity: python/mxnet/gluon/parameter.py (SURVEY.md §2.5) —
deferred initialization on first shape, per-context data replicas,
grad_req/lr_mult/wd_mult, ParameterDict prefix namespacing and save/load.
TPU-native notes: replicas are jax arrays per device; the gradient buffer is
attached through the autograd variable mechanism so hybridized (jit) calls
route cotangents into it.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as _np

from ..base import MXNetError, dtype_np
from ..context import Context, current_context
from .. import initializer as init_mod
from .. import autograd as _autograd
from ..ndarray import NDArray, zeros as nd_zeros, array as nd_array

__all__ = ["Parameter", "Constant", "ParameterDict", "DeferredInitializationError"]


class DeferredInitializationError(MXNetError):
    """Raised when parameter data is requested before shape is known."""


class Parameter:
    """A trainable (or auxiliary) tensor with per-context replicas."""

    def __init__(self, name: str, grad_req: str = "write", shape=None,
                 dtype="float32", lr_mult: float = 1.0, wd_mult: float = 1.0,
                 init=None, allow_deferred_init: bool = False,
                 differentiable: bool = True, stype="default",
                 grad_stype="default"):
        self.name = name
        self._grad_req = grad_req if differentiable else "null"
        if isinstance(shape, int):
            shape = (shape,)
        self.shape = tuple(shape) if shape is not None else None
        self.dtype = dtype_np(dtype)
        self.lr_mult = lr_mult
        self.wd_mult = wd_mult
        self.init = init
        self.allow_deferred_init = allow_deferred_init
        self.stype = stype
        self.grad_stype = grad_stype
        self._data: Optional[Dict[Context, NDArray]] = None
        self._deferred_init = None   # (initializer, ctx_list, default_init)

    # ------------------------------------------------------------------
    @property
    def grad_req(self) -> str:
        return self._grad_req

    @grad_req.setter
    def grad_req(self, req: str) -> None:
        self._grad_req = req
        if self._data is not None and req != "null":
            for arr in self._data.values():
                arr.attach_grad(grad_req=req)

    def _shape_known(self) -> bool:
        return self.shape is not None and all(s > 0 for s in self.shape)

    def _finish_deferred_init(self, inferred_shape=None) -> None:
        if inferred_shape is not None:
            if self.shape is not None:
                merged = tuple(s if s > 0 else i
                               for s, i in zip(self.shape, inferred_shape))
            else:
                merged = tuple(inferred_shape)
            self.shape = merged
        if self._deferred_init is None:
            return
        initializer, ctxs, default_init = self._deferred_init
        if not self._shape_known():
            return
        self._deferred_init = None
        self._init_impl(initializer, ctxs, default_init)

    def _init_impl(self, initializer, ctxs, default_init) -> None:
        data0 = nd_zeros(self.shape, ctx=ctxs[0], dtype=self.dtype)
        explicit = self.init if self.init is not None else None
        chosen = init_mod.create(explicit if explicit is not None
                                 else (initializer if initializer is not None
                                       else default_init))
        if explicit is not None:
            # per-parameter initializer wins outright — bypass the
            # name-suffix dispatch (else e.g. LSTMBias on '*_bias' params
            # would be silently zeroed)
            chosen.init_weight(self.name, data0)
        else:
            chosen(self.name, data0)
        self._data = {}
        for ctx in ctxs:
            arr = data0 if ctx == ctxs[0] else data0.copyto(ctx)
            if self._grad_req != "null":
                arr.attach_grad(grad_req=self._grad_req)
            self._data[ctx] = arr

    def initialize(self, init=None, ctx=None, default_init="uniform",
                   force_reinit: bool = False) -> None:
        if self._data is not None and not force_reinit:
            return
        if ctx is None:
            ctx = [current_context()]
        if isinstance(ctx, Context):
            ctx = [ctx]
        ctx = list(ctx)
        if not self._shape_known():
            if self.allow_deferred_init:
                self._deferred_init = (init, ctx, default_init)
                return
            raise MXNetError(
                f"cannot initialize Parameter {self.name!r}: shape "
                f"{self.shape} unknown; set in_units/in_channels or use "
                f"deferred init")
        self._init_impl(init, ctx, default_init)

    # ------------------------------------------------------------------
    def _check_initialized(self, ctx=None):
        if self._data is None:
            if self._deferred_init is not None:
                raise DeferredInitializationError(
                    f"Parameter {self.name!r} awaits shape inference; run a "
                    f"forward pass first")
            raise MXNetError(
                f"Parameter {self.name!r} has not been initialized; call "
                f".initialize()")
        if ctx is not None and ctx not in self._data:
            raise MXNetError(
                f"Parameter {self.name!r} not initialized on {ctx}; it lives "
                f"on {list(self._data)}")

    def data(self, ctx: Optional[Context] = None) -> NDArray:
        self._check_initialized()
        if ctx is None:
            ctx = next(iter(self._data))
        self._check_initialized(ctx)
        return self._data[ctx]

    def list_data(self) -> List[NDArray]:
        self._check_initialized()
        return list(self._data.values())

    def list_ctx(self) -> List[Context]:
        self._check_initialized()
        return list(self._data.keys())

    def grad(self, ctx: Optional[Context] = None) -> NDArray:
        d = self.data(ctx)
        if d.grad is None:
            raise MXNetError(
                f"Parameter {self.name!r} has grad_req='null'; no gradient")
        return d.grad

    def list_grad(self) -> List[NDArray]:
        self._check_initialized()
        return [d.grad for d in self._data.values()]

    def set_data(self, data) -> None:
        if self._data is None and self._deferred_init is not None:
            # setting data resolves deferred shape (reference behavior)
            self.shape = tuple(data.shape)
            initializer, ctxs, default_init = self._deferred_init
            self._deferred_init = None
            self._init_impl(initializer, ctxs, default_init)
        self._check_initialized()
        src = data if isinstance(data, NDArray) else nd_array(data)
        if tuple(src.shape) != tuple(self.shape):
            raise MXNetError(
                f"cannot set Parameter {self.name!r} of shape {self.shape} "
                f"with data of shape {tuple(src.shape)}")
        for arr in self._data.values():
            src.copyto(arr)

    def zero_grad(self) -> None:
        if self._grad_req == "null" or self._data is None:
            return
        for arr in self._data.values():
            if arr.grad is not None:
                arr.grad._set_data(arr.grad._read() * 0)
                arr._ag.fresh = True

    def reset_ctx(self, ctx) -> None:
        if isinstance(ctx, Context):
            ctx = [ctx]
        self._check_initialized()
        cur = self.data()
        self._data = {}
        for c in ctx:
            arr = cur.copyto(c)
            if self._grad_req != "null":
                arr.attach_grad(grad_req=self._grad_req)
            self._data[c] = arr

    def cast(self, dtype) -> None:
        self.dtype = dtype_np(dtype)
        if self._data is None:
            return
        for ctx, arr in list(self._data.items()):
            new = arr.astype(self.dtype)
            if self._grad_req != "null":
                new.attach_grad(grad_req=self._grad_req)
            self._data[ctx] = new

    def var(self):
        from ..symbol import Symbol
        return Symbol.var(self.name, shape=self.shape)

    def __repr__(self):
        return (f"Parameter {self.name} (shape={self.shape}, "
                f"dtype={self.dtype})")


class Constant(Parameter):
    """Non-trainable constant parameter (reference: gluon.Constant)."""

    def __init__(self, name, value):
        if not isinstance(value, _np.ndarray):
            value = _np.asarray(value, dtype=_np.float32)
        self.value = value

        class _CInit(init_mod.Initializer):
            def __call__(self, _n, arr):
                arr[:] = value

        super().__init__(name, grad_req="null", shape=value.shape,
                         dtype=value.dtype, init=_CInit())


class ParameterDict:
    """Ordered name→Parameter mapping with prefix namespacing."""

    def __init__(self, prefix: str = "", shared: Optional["ParameterDict"] = None):
        self._prefix = prefix
        self._params: Dict[str, Parameter] = {}
        self._shared = shared

    @property
    def prefix(self) -> str:
        return self._prefix

    def items(self):
        return self._params.items()

    def keys(self):
        return self._params.keys()

    def values(self):
        return self._params.values()

    def __iter__(self):
        return iter(self._params)

    def __len__(self):
        return len(self._params)

    def __contains__(self, name):
        return name in self._params

    def __getitem__(self, name) -> Parameter:
        return self._params[name]

    def __repr__(self):
        lines = "\n".join(f"  {p}" for p in self._params.values())
        return f"ParameterDict(prefix={self._prefix!r}\n{lines}\n)"

    def get(self, name: str, **kwargs) -> Parameter:
        """Fetch-or-create ``prefix+name`` (the Block param entry point)."""
        full = self._prefix + name
        if full in self._params:
            param = self._params[full]
            for k, v in kwargs.items():
                if v is None:
                    continue
                if k == "shape" and param.shape is not None:
                    v = tuple(v) if not isinstance(v, int) else (v,)
                    merged = tuple(a if a > 0 else b
                                   for a, b in zip(param.shape, v)) \
                        if len(param.shape) == len(v) else None
                    if merged is None:
                        raise MXNetError(
                            f"shape mismatch for {full}: {param.shape} vs {v}")
                    param.shape = merged
            return param
        if self._shared is not None and full in self._shared:
            param = self._shared[full]
        else:
            param = Parameter(full, **kwargs)
        self._params[full] = param
        return param

    def get_constant(self, name: str, value=None) -> Constant:
        full = self._prefix + name
        if full in self._params:
            return self._params[full]
        if value is None:
            raise MXNetError(f"constant {full!r} not found and no value given")
        c = Constant(full, value)
        self._params[full] = c
        return c

    def update(self, other: "ParameterDict") -> None:
        for k, v in other.items():
            if k in self._params and self._params[k] is not v:
                raise MXNetError(f"duplicate parameter name {k!r}")
            self._params[k] = v

    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False) -> None:
        for p in self._params.values():
            p.initialize(init=init, ctx=ctx, force_reinit=force_reinit)

    def zero_grad(self) -> None:
        for p in self._params.values():
            p.zero_grad()

    def reset_ctx(self, ctx) -> None:
        for p in self._params.values():
            p.reset_ctx(ctx)

    def setattr(self, name, value) -> None:
        for p in self._params.values():
            setattr(p, name, value)

    def save(self, fname: str, strip_prefix: str = "") -> None:
        from ..ndarray import utils as nd_utils
        arg = {}
        for name, p in self._params.items():
            if strip_prefix and name.startswith(strip_prefix):
                name = name[len(strip_prefix):]
            arg[name] = p.data()
        nd_utils.save(fname, arg)

    def load(self, fname: str, ctx=None, allow_missing: bool = False,
             ignore_extra: bool = False, restore_prefix: str = "") -> None:
        from ..ndarray import utils as nd_utils
        loaded = nd_utils.load(fname)
        loaded = {restore_prefix + k: v for k, v in loaded.items()}
        if not allow_missing:
            for name in self._params:
                if name not in loaded:
                    raise MXNetError(f"parameter {name!r} missing from {fname}")
        for name, val in loaded.items():
            if name not in self._params:
                if ignore_extra:
                    continue
                raise MXNetError(f"{fname} has unknown parameter {name!r}")
            self._params[name].set_data(val)
