"""Gluon utilities: multi-device batch splitting, global-norm clipping.

Reference parity: python/mxnet/gluon/utils.py (SURVEY.md §2.3 — the data-
parallel entry point `split_and_load`).
"""
from __future__ import annotations

from typing import List, Sequence

import numpy as _np

from ..base import MXNetError
from ..context import Context
from ..ndarray import NDArray, array as nd_array

__all__ = ["split_data", "split_and_load", "clip_global_norm", "check_sha1",
           "download"]


def split_data(data: NDArray, num_slice: int, batch_axis: int = 0,
               even_split: bool = True) -> List[NDArray]:
    size = data.shape[batch_axis]
    if even_split and size % num_slice != 0:
        raise MXNetError(
            f"data with shape {data.shape} cannot be evenly split into "
            f"{num_slice} slices along axis {batch_axis}; set "
            f"even_split=False")
    step = size // num_slice
    slices = []
    for i in range(num_slice):
        begin = i * step
        end = (i + 1) * step if i < num_slice - 1 else size
        idx = [slice(None)] * data.ndim
        idx[batch_axis] = slice(begin, end)
        slices.append(data[tuple(idx)])
    return slices


def split_and_load(data, ctx_list: Sequence[Context], batch_axis: int = 0,
                   even_split: bool = True) -> List[NDArray]:
    """Split a batch along batch_axis and load each slice onto one context —
    the single-process data-parallel front door (reference §2.3)."""
    if not isinstance(data, NDArray):
        data = nd_array(data, ctx=ctx_list[0])
    if len(ctx_list) == 1:
        return [data.as_in_context(ctx_list[0])]
    slices = split_data(data, len(ctx_list), batch_axis, even_split)
    return [s.as_in_context(ctx) for s, ctx in zip(slices, ctx_list)]


def clip_global_norm(arrays: Sequence[NDArray], max_norm: float,
                     check_isfinite: bool = True) -> float:
    """Rescale arrays in place so the joint L2 norm is at most max_norm."""
    if not arrays:
        raise MXNetError("no arrays to clip")
    # accumulate the squared norms ON DEVICE (one bulked dispatch chain),
    # then read the scalar back once — N arrays cost ONE host sync, not N.
    # Squares accumulate in f32: bf16's 8-bit mantissa would mis-scale
    # the global norm for large tensors
    total_sq = None
    for a in arrays:
        af = a if str(a.dtype) in ("float32", "float64") \
            else a.astype("float32")
        n = (af * af).sum()
        total_sq = n if total_sq is None else total_sq + n
    # single batched readback: the clipped norm is this function's
    # host-facing return value
    # mxlint: disable=hidden-host-sync — one batched readback, was N syncs
    total = float(_np.sqrt(total_sq.asnumpy()))
    if check_isfinite and not _np.isfinite(total):
        import warnings
        warnings.warn("nan or inf in clip_global_norm")
    scale = max_norm / (total + 1e-8)
    if scale < 1.0:
        for a in arrays:
            a *= scale
    return total


def check_sha1(filename: str, sha1_hash: str) -> bool:
    import hashlib
    sha1 = hashlib.sha1()
    with open(filename, "rb") as f:
        while True:
            chunk = f.read(1 << 20)
            if not chunk:
                break
            sha1.update(chunk)
    return sha1.hexdigest() == sha1_hash


def download(url, path=None, overwrite=False, sha1_hash=None,
             retries=5, verify_ssl=True):
    raise MXNetError("this environment has no network egress; place files "
                     "locally and load them directly")
