"""Core Gluon layers: Sequential, Dense, Dropout, norms, Embedding, etc.

Reference parity: python/mxnet/gluon/nn/basic_layers.py (SURVEY.md §2.5).
Parameter names, shapes ((units, in_units) weights), deferred init on first
forward, and layer defaults (BatchNorm eps=1e-5, momentum=0.9) follow the
reference.
"""
from __future__ import annotations

from typing import Optional

from ...base import MXNetError
from ... import autograd as _autograd
from ..block import Block, HybridBlock
from ..parameter import Parameter

__all__ = ["Sequential", "HybridSequential", "Dense", "Dropout", "BatchNorm",
           "InstanceNorm", "LayerNorm", "GroupNorm", "Embedding",
           "RowShardedEmbedding", "Flatten",
           "Lambda", "HybridLambda", "Activation", "LeakyReLU", "PReLU",
           "ELU", "SELU", "GELU", "Swish", "HybridConcurrent", "Identity",
           "ReflectionPad2D"]


def _prod(it):
    n = 1
    for s in it:
        n *= s
    return n


class Sequential(Block):
    """Eager container stacking blocks sequentially."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for b in blocks:
            self.register_child(b)

    def forward(self, x):
        for child in self._children.values():
            x = child(x)
        return x

    def __len__(self):
        return len(self._children)

    def __getitem__(self, i):
        return list(self._children.values())[i]

    def __iter__(self):
        return iter(self._children.values())


class HybridSequential(HybridBlock):
    """Hybridizable container; lowers the whole stack into one jit."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for b in blocks:
            self.register_child(b)

    def hybrid_forward(self, F, x):
        for child in self._children.values():
            x = child(x)
        return x

    def __len__(self):
        return len(self._children)

    def __getitem__(self, i):
        return list(self._children.values())[i]

    def __iter__(self):
        return iter(self._children.values())


class HybridConcurrent(HybridBlock):
    """Run children on the same input, concatenate outputs along ``axis``
    (reference: python/mxnet/gluon/contrib/nn/basic_layers.py
    HybridConcurrent — the Inception/DenseNet branch container)."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def add(self, *blocks):
        for b in blocks:
            self.register_child(b)

    def hybrid_forward(self, F, x):
        outs = [child(x) for child in self._children.values()]
        return F.concat(*outs, dim=self.axis)


class Identity(HybridBlock):
    """Pass-through block (reference: gluon.contrib.nn.Identity)."""

    def hybrid_forward(self, F, x):
        return x


class Dense(HybridBlock):
    """Fully-connected layer: out = act(dot(x, W.T) + b); weight shape
    (units, in_units), MXNet convention."""

    def __init__(self, units, activation=None, use_bias=True, flatten=True,
                 dtype="float32", weight_initializer=None,
                 bias_initializer="zeros", in_units=0, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._units = units
        self._flatten = flatten
        self._act_type = activation
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(units, in_units), dtype=dtype,
                init=weight_initializer, allow_deferred_init=True)
            if use_bias:
                self.bias = self.params.get(
                    "bias", shape=(units,), dtype=dtype,
                    init=bias_initializer, allow_deferred_init=True)

    def infer_shape(self, x, *args):
        in_units = _prod(x.shape[1:]) if self._flatten else x.shape[-1]
        self.weight.shape = (self._units, in_units)

    def hybrid_forward(self, F, x, weight, bias=None):
        if bias is None:
            out = F.FullyConnected(x, weight, num_hidden=self._units,
                                   no_bias=True, flatten=self._flatten)
        else:
            out = F.FullyConnected(x, weight, bias, num_hidden=self._units,
                                   no_bias=False, flatten=self._flatten)
        if self._act_type:
            out = F.Activation(out, act_type=self._act_type)
        return out


class Dropout(HybridBlock):
    def __init__(self, rate, axes=(), prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._rate = rate
        self._axes = axes

    def hybrid_forward(self, F, x):
        return F.Dropout(x, p=self._rate, axes=self._axes)


class BatchNorm(HybridBlock):
    def __init__(self, axis=1, momentum=0.9, epsilon=1e-5, center=True,
                 scale=True, use_global_stats=False,
                 beta_initializer="zeros", gamma_initializer="ones",
                 running_mean_initializer="zeros",
                 running_variance_initializer="ones", in_channels=0,
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._axis = axis
        self._momentum = momentum
        self._epsilon = epsilon
        self._center = center
        self._scale = scale
        self._use_global_stats = use_global_stats
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True,
                grad_req="write" if scale else "null")
            self.beta = self.params.get(
                "beta", shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True,
                grad_req="write" if center else "null")
            self.running_mean = self.params.get(
                "running_mean", shape=(in_channels,),
                init=running_mean_initializer, grad_req="null",
                allow_deferred_init=True)
            self.running_var = self.params.get(
                "running_var", shape=(in_channels,),
                init=running_variance_initializer, grad_req="null",
                allow_deferred_init=True)

    def infer_shape(self, x, *args):
        c = x.shape[self._axis]
        for p in (self.gamma, self.beta, self.running_mean, self.running_var):
            p.shape = (c,)

    def hybrid_forward(self, F, x, gamma, beta, running_mean, running_var):
        training = _autograd.is_training() and not self._use_global_stats
        outs = F.BatchNorm(x, gamma, beta, running_mean, running_var,
                           eps=self._epsilon, momentum=self._momentum,
                           fix_gamma=not self._scale,
                           use_global_stats=self._use_global_stats,
                           axis=self._axis, _training=training)
        out, new_mean, new_var = outs
        if training:
            with _autograd.pause():
                running_mean._set_data(new_mean._read())
                running_var._set_data(new_var._read())
        return out


class InstanceNorm(HybridBlock):
    def __init__(self, axis=1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._epsilon = epsilon
        with self.name_scope():
            self.gamma = self.params.get("gamma", shape=(in_channels,),
                                         init=gamma_initializer,
                                         allow_deferred_init=True)
            self.beta = self.params.get("beta", shape=(in_channels,),
                                        init=beta_initializer,
                                        allow_deferred_init=True)

    def infer_shape(self, x, *args):
        self.gamma.shape = (x.shape[1],)
        self.beta.shape = (x.shape[1],)

    def hybrid_forward(self, F, x, gamma, beta):
        return F.InstanceNorm(x, gamma, beta, eps=self._epsilon)


class GroupNorm(HybridBlock):
    """Group normalization (reference: gluon nn.GroupNorm over
    src/operator/nn/group_norm.cc).  gamma/beta are per-GROUP, shape
    (num_groups,) — the reference convention (torch's GroupNorm is
    per-channel instead; checkpoints are not interchangeable)."""

    def __init__(self, num_groups=1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._num_groups = num_groups
        self._epsilon = epsilon
        with self.name_scope():
            self.gamma = self.params.get("gamma", shape=(num_groups,),
                                         init=gamma_initializer)
            self.beta = self.params.get("beta", shape=(num_groups,),
                                        init=beta_initializer)

    def hybrid_forward(self, F, x, gamma, beta):
        return F.GroupNorm(x, gamma, beta, num_groups=self._num_groups,
                           eps=self._epsilon)


class LayerNorm(HybridBlock):
    def __init__(self, axis=-1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._axis = axis
        self._epsilon = epsilon
        with self.name_scope():
            self.gamma = self.params.get("gamma", shape=(in_channels,),
                                         init=gamma_initializer,
                                         allow_deferred_init=True)
            self.beta = self.params.get("beta", shape=(in_channels,),
                                        init=beta_initializer,
                                        allow_deferred_init=True)

    def infer_shape(self, x, *args):
        c = x.shape[self._axis]
        self.gamma.shape = (c,)
        self.beta.shape = (c,)

    def hybrid_forward(self, F, x, gamma, beta):
        return F.LayerNorm(x, gamma, beta, axis=self._axis,
                           eps=self._epsilon)


class Embedding(HybridBlock):
    """Turns integer ids into dense vectors of ``output_dim``.

    Out-of-range ids are CLIPPED into ``[0, input_dim - 1]`` (the
    reference's ``take`` default and the only mode XLA gathers support
    without a branch) — an id ``>= input_dim`` reads the last row and an
    id ``< 0`` reads row 0, never a wrapped-around row.  Pinned by
    ``test_embedding_clips_out_of_range_ids``.

    With ``sparse_grad=True`` the weight is marked
    ``grad_stype='row_sparse'``: under a ``ShardedTrainer`` step (and
    ``MXTPU_SPARSE_GRAD=1``, the default) its gradient is produced
    in-graph as a ``(values, unique_ids)`` pair via a segment-sum over
    the batch's deduplicated ids, and the optimizer touches only those
    rows — see ``sparse_grad.py``.  Outside a sharded step the flag has
    the reference semantics via the gluon ``Trainer``'s row-sparse
    exchange, or is simply dense.
    """

    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, sparse_grad=False, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._input_dim = input_dim
        self._output_dim = output_dim
        self._sparse_grad = bool(sparse_grad)
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(input_dim, output_dim), dtype=dtype,
                init=weight_initializer,
                grad_stype="row_sparse" if sparse_grad else "default")

    def hybrid_forward(self, F, x, weight):
        if self._sparse_grad and hasattr(x, "_read"):
            from ... import sparse_grad as _sg
            ctx = _sg.trace_ctx()
            if ctx is not None and ctx.wants(self.weight):
                val = ctx.embedding(self.weight, x._read(), weight._read(),
                                    self._input_dim)
                return type(x)(val, ctx=x.context)
        return F.Embedding(x, weight, input_dim=self._input_dim,
                           output_dim=self._output_dim,
                           sparse_grad=self._sparse_grad)


class RowShardedEmbedding(Embedding):
    """An :class:`Embedding` whose table is partitioned along dim 0
    (the vocab axis) across the mesh's ``'dp'`` axis, so a table larger
    than one chip's HBM trains — each data-parallel rank holds
    ``input_dim / dp`` rows, and the forward's gather is a cross-rank
    collective XLA derives from the sharding (no manual all-to-all).

    Only meaningful under a ``ShardedTrainer``: the trainer's sharding
    pass sees the marker and places the weight (and, through
    ``zero_sharding``'s fallback discipline, its optimizer state) with
    dim 0 split over ``'dp'``.  Checkpoints save the logical table and
    re-shard on load over whatever mesh restores it (PR-10 machinery).
    Pairs with dense gradients — a row-sharded table's grad is produced
    and reduce-scattered dense, so ``sparse_grad`` is rejected.
    """

    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, prefix=None, params=None,
                 shard_axis="dp"):
        super().__init__(input_dim, output_dim, dtype=dtype,
                         weight_initializer=weight_initializer,
                         sparse_grad=False, prefix=prefix, params=params)
        self.weight._row_shard_axis = shard_axis


class Flatten(HybridBlock):
    def hybrid_forward(self, F, x):
        return F.flatten(x)


class Lambda(Block):
    def __init__(self, function, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        if isinstance(function, str):
            from ... import ndarray as nd
            function = getattr(nd, function)
        self._fn = function

    def forward(self, *args):
        return self._fn(*args)


class HybridLambda(HybridBlock):
    def __init__(self, function, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._fn_name = function if isinstance(function, str) else None
        self._fn = function

    def hybrid_forward(self, F, *args):
        fn = getattr(F, self._fn_name) if self._fn_name else self._fn
        if self._fn_name is None:
            return fn(F, *args)
        return fn(*args)


class Activation(HybridBlock):
    def __init__(self, activation, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._act_type = activation

    def hybrid_forward(self, F, x):
        return F.Activation(x, act_type=self._act_type)


class LeakyReLU(HybridBlock):
    def __init__(self, alpha, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._alpha = alpha

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="leaky", slope=self._alpha)


class PReLU(HybridBlock):
    def __init__(self, alpha_initializer=None, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        from ... import initializer as init_mod
        with self.name_scope():
            self.alpha = self.params.get(
                "alpha", shape=(1,),
                init=alpha_initializer or init_mod.Constant(0.25))

    def hybrid_forward(self, F, x, alpha):
        return F.LeakyReLU(x, alpha, act_type="prelu")


class ELU(HybridBlock):
    def __init__(self, alpha=1.0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._alpha = alpha

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="elu", slope=self._alpha)


class SELU(HybridBlock):
    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="selu")


class GELU(HybridBlock):
    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="gelu")


class Swish(HybridBlock):
    def __init__(self, beta=1.0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._beta = beta

    def hybrid_forward(self, F, x):
        return x * F.sigmoid(self._beta * x)


class ReflectionPad2D(HybridBlock):
    """Reflection padding on H/W of NCHW input (reference:
    gluon/nn/basic_layers.py ReflectionPad2D over src/operator/pad.cc
    reflect mode)."""

    def __init__(self, padding=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        if isinstance(padding, int):
            padding = (padding,) * 4      # (left, right, top, bottom)
        padding = tuple(padding)
        if len(padding) == 8:
            # reference pad_width form (N..., C..., t, b, l, r)
            t, b, l, r = padding[4:]
            padding = (l, r, t, b)
        if len(padding) != 4:
            raise MXNetError(
                "ReflectionPad2D padding must be an int, a 4-tuple "
                "(left, right, top, bottom), or the reference 8-tuple "
                f"pad_width; got {padding}")
        self._padding = padding

    def hybrid_forward(self, F, x):
        l, r, t, b = self._padding
        return F.pad(x, mode="reflect",
                     pad_width=(0, 0, 0, 0, t, b, l, r))
