"""Vision datasets: MNIST / FashionMNIST / CIFAR10/100 / ImageRecordDataset.

Reference parity: python/mxnet/gluon/data/vision/datasets.py (SURVEY.md
§2.4).  This environment has zero network egress, so the download path is
replaced: each dataset loads from its standard on-disk format if present
under ``root``; otherwise it synthesizes a deterministic class-structured
surrogate of identical shape/dtype (documented loudly) so training code,
tests, and benchmarks run unchanged.
"""
from __future__ import annotations

import gzip
import os
import struct
import warnings

import numpy as _np

from ....base import MXNetError
from ...data.dataset import Dataset, ArrayDataset

__all__ = ["MNIST", "FashionMNIST", "CIFAR10", "CIFAR100",
           "ImageRecordDataset", "ImageFolderDataset"]


def _synth_image_classification(num, shape, num_classes, seed):
    """Deterministic class-structured synthetic data: each class gets a fixed
    random template; samples are noisy copies.  Linearly separable enough for
    convergence smoke tests."""
    rng = _np.random.RandomState(seed)
    templates = rng.uniform(0, 255, (num_classes,) + shape)
    labels = rng.randint(0, num_classes, num)
    noise = rng.normal(0, 32, (num,) + shape)
    data = _np.clip(templates[labels] + noise, 0, 255).astype(_np.uint8)
    return data, labels.astype(_np.int32)


class _DownloadedDataset(Dataset):
    def __init__(self, root, train, transform):
        self._root = os.path.expanduser(root)
        self._train = train
        self._transform = transform
        self._data = None
        self._label = None
        self._get_data()

    def __getitem__(self, idx):
        from ....ndarray import array as nd_array
        x = nd_array(self._data[idx])
        y = self._label[idx]
        if self._transform is not None:
            return self._transform(x, y)
        return x, y

    def __len__(self):
        return len(self._label)


class MNIST(_DownloadedDataset):
    """MNIST; reads idx-ubyte(.gz) files from root when present, else
    synthesizes (no egress)."""

    _files = {
        True: ("train-images-idx3-ubyte", "train-labels-idx1-ubyte"),
        False: ("t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte"),
    }
    _shape = (28, 28, 1)
    _classes = 10
    _seed = 2901

    def __init__(self, root="~/.mxnet/datasets/mnist", train=True,
                 transform=None):
        super().__init__(root, train, transform)

    def _read_idx(self, path_base):
        for ext in ("", ".gz"):
            p = path_base + ext
            if os.path.exists(p):
                op = gzip.open if ext else open
                with op(p, "rb") as f:
                    raw = f.read()
                return raw
        return None

    def _get_data(self):
        imgf, labf = self._files[self._train]
        raw_img = self._read_idx(os.path.join(self._root, imgf))
        raw_lab = self._read_idx(os.path.join(self._root, labf))
        if raw_img is not None and raw_lab is not None:
            magic, num = struct.unpack(">II", raw_lab[:8])
            label = _np.frombuffer(raw_lab, _np.uint8, offset=8)
            magic, num, rows, cols = struct.unpack(">IIII", raw_img[:16])
            data = _np.frombuffer(raw_img, _np.uint8, offset=16).reshape(
                num, rows, cols, 1)
            self._data = data
            self._label = label.astype(_np.int32)
            return
        warnings.warn(
            f"{type(self).__name__}: files not found under {self._root} and "
            f"no network egress; using deterministic synthetic surrogate")
        num = 60000 if self._train else 10000
        seed = self._seed if self._train else self._seed + 1
        self._data, self._label = _synth_image_classification(
            num, self._shape, self._classes, seed)


class FashionMNIST(MNIST):
    _files = {
        True: ("train-images-idx3-ubyte", "train-labels-idx1-ubyte"),
        False: ("t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte"),
    }
    _seed = 2902

    def __init__(self, root="~/.mxnet/datasets/fashion-mnist", train=True,
                 transform=None):
        super().__init__(root, train, transform)


class CIFAR10(_DownloadedDataset):
    _shape = (32, 32, 3)
    _classes = 10
    _seed = 2903

    def __init__(self, root="~/.mxnet/datasets/cifar10", train=True,
                 transform=None):
        super().__init__(root, train, transform)

    def _get_data(self):
        batches = [f"data_batch_{i}" for i in range(1, 6)] if self._train \
            else ["test_batch"]
        base = os.path.join(self._root, "cifar-10-batches-py")
        if os.path.isdir(base):
            import pickle
            datas, labels = [], []
            for b in batches:
                with open(os.path.join(base, b), "rb") as f:
                    d = pickle.load(f, encoding="bytes")
                datas.append(d[b"data"].reshape(-1, 3, 32, 32)
                             .transpose(0, 2, 3, 1))
                labels.extend(d[b"labels"])
            self._data = _np.concatenate(datas)
            self._label = _np.asarray(labels, _np.int32)
            return
        warnings.warn(
            f"{type(self).__name__}: files not found under {self._root} and "
            f"no network egress; using deterministic synthetic surrogate")
        num = 50000 if self._train else 10000
        seed = self._seed if self._train else self._seed + 1
        self._data, self._label = _synth_image_classification(
            num, self._shape, self._classes, seed)


class CIFAR100(CIFAR10):
    _classes = 100
    _seed = 2905

    def __init__(self, root="~/.mxnet/datasets/cifar100", train=True,
                 fine_label=True, transform=None):
        super().__init__(root, train, transform)

    def _get_data(self):
        base = os.path.join(self._root, "cifar-100-python")
        name = "train" if self._train else "test"
        p = os.path.join(base, name)
        if os.path.exists(p):
            import pickle
            with open(p, "rb") as f:
                d = pickle.load(f, encoding="bytes")
            self._data = d[b"data"].reshape(-1, 3, 32, 32).transpose(
                0, 2, 3, 1)
            self._label = _np.asarray(d[b"fine_labels"], _np.int32)
            return
        warnings.warn(
            f"CIFAR100: files not found under {self._root} and no network "
            f"egress; using deterministic synthetic surrogate")
        num = 50000 if self._train else 10000
        seed = self._seed if self._train else self._seed + 1
        self._data, self._label = _synth_image_classification(
            num, self._shape, self._classes, seed)


class ImageRecordDataset(Dataset):
    """Dataset over a RecordIO pack of (header, jpeg/raw image) records
    (reference: mx.gluon.data.vision.ImageRecordDataset over .rec)."""

    def __init__(self, filename, flag=1, transform=None):
        from ....recordio import MXIndexedRecordIO, unpack_img
        idx_file = filename[:-4] + ".idx"
        self._record = MXIndexedRecordIO(idx_file, filename, "r")
        self._flag = flag
        self._transform = transform
        self._unpack = unpack_img

    def __len__(self):
        return len(self._record.keys)

    def __getitem__(self, idx):
        from ....ndarray import array as nd_array
        record = self._record.read_idx(self._record.keys[idx])
        header, img = self._unpack(record, iscolor=self._flag)
        x = nd_array(img)
        y = header.label
        if self._transform is not None:
            return self._transform(x, y)
        return x, y


class ImageFolderDataset(Dataset):
    """A class-per-subfolder image dataset (reference:
    gluon/data/vision/datasets.py ImageFolderDataset): ``root/cat/1.jpg``
    → label = index of sorted folder name.  Decodes via mx.image (PIL
    here, OpenCV in the reference)."""

    def __init__(self, root, flag=1, transform=None):
        import os
        self._root = os.path.expanduser(root)
        self._flag = flag
        self._transform = transform
        self._exts = {".jpg", ".jpeg", ".png", ".bmp"}
        self.synsets = []
        self.items = []
        for folder in sorted(os.listdir(self._root)):
            path = os.path.join(self._root, folder)
            if not os.path.isdir(path):
                continue
            label = len(self.synsets)
            self.synsets.append(folder)
            for fname in sorted(os.listdir(path)):
                if os.path.splitext(fname)[1].lower() in self._exts:
                    self.items.append((os.path.join(path, fname),
                                       label))

    def __len__(self):
        return len(self.items)

    def __getitem__(self, idx):
        from ....image import imread
        path, label = self.items[idx]
        img = imread(path, flag=self._flag)
        if self._transform is not None:
            return self._transform(img, label)
        return img, label
