"""Image transforms (reference: python/mxnet/gluon/data/vision/transforms.py
over src/operator/image/ — SURVEY.md §2.2, §2.4).

Transforms operate on HWC uint8/float NDArrays on the host side of the
pipeline (numpy; cheap, GIL-released) and only ToTensor moves to CHW float.
"""
from __future__ import annotations

import numpy as _np

from ....base import MXNetError
from ....ndarray import NDArray, array as nd_array
from ...block import Block, HybridBlock

__all__ = ["Compose", "Cast", "ToTensor", "Normalize", "Resize", "CropResize",
           "CenterCrop", "RandomResizedCrop", "RandomFlipLeftRight",
           "RandomFlipTopBottom", "RandomBrightness", "RandomContrast",
           "RandomSaturation", "RandomHue", "RandomLighting", "RandomColorJitter"]


def _to_np(x):
    return x.asnumpy() if isinstance(x, NDArray) else _np.asarray(x)


class Compose:
    def __init__(self, transforms):
        self._transforms = transforms

    def __call__(self, x, *args):
        for t in self._transforms:
            x = t(x)
        return (x,) + args if args else x


class Cast:
    def __init__(self, dtype="float32"):
        self._dtype = dtype

    def __call__(self, x):
        return x.astype(self._dtype) if isinstance(x, NDArray) else \
            nd_array(_to_np(x).astype(self._dtype))


class ToTensor:
    """HWC uint8 [0,255] → CHW float32 [0,1] (reference semantics)."""

    def __call__(self, x):
        a = _to_np(x).astype(_np.float32) / 255.0
        if a.ndim == 3:
            a = a.transpose(2, 0, 1)
        elif a.ndim == 4:
            a = a.transpose(0, 3, 1, 2)
        return nd_array(a)


class Normalize:
    def __init__(self, mean=0.0, std=1.0):
        self._mean = _np.asarray(mean, _np.float32)
        self._std = _np.asarray(std, _np.float32)

    def __call__(self, x):
        a = _to_np(x)
        mean = self._mean.reshape(-1, 1, 1) if self._mean.ndim else self._mean
        std = self._std.reshape(-1, 1, 1) if self._std.ndim else self._std
        return nd_array((a - mean) / std)


def _resize_np(a, size):
    """Nearest-neighbor host-side resize (no OpenCV dependency)."""
    h, w = a.shape[:2]
    if isinstance(size, int):
        ow, oh = size, size
    else:
        ow, oh = size
    ys = (_np.arange(oh) * (h / oh)).astype(_np.int64).clip(0, h - 1)
    xs = (_np.arange(ow) * (w / ow)).astype(_np.int64).clip(0, w - 1)
    return a[ys][:, xs]


class Resize:
    def __init__(self, size, keep_ratio=False, interpolation=1):
        self._size = size

    def __call__(self, x):
        return nd_array(_resize_np(_to_np(x), self._size))


class CenterCrop:
    def __init__(self, size, interpolation=1):
        self._size = (size, size) if isinstance(size, int) else size

    def __call__(self, x):
        a = _to_np(x)
        h, w = a.shape[:2]
        cw, ch = self._size
        y0 = max(0, (h - ch) // 2)
        x0 = max(0, (w - cw) // 2)
        return nd_array(a[y0:y0 + ch, x0:x0 + cw])


class CropResize:
    """Crop a fixed box then resize (reference transforms.CropResize:
    x0/y0 upper-left corner, width/height box, optional output size)."""

    def __init__(self, x0, y0, width, height, size=None, interpolation=1):
        self._box = (int(x0), int(y0), int(width), int(height))
        self._size = None if size is None else (
            (size, size) if isinstance(size, int) else tuple(size))

    def __call__(self, x):
        a = _to_np(x)
        x0, y0, w, h = self._box
        H, W = a.shape[:2]
        if x0 < 0 or y0 < 0 or x0 + w > W or y0 + h > H:
            from ....base import MXNetError
            raise MXNetError(
                f"CropResize box (x0={x0}, y0={y0}, w={w}, h={h}) exceeds "
                f"image size (w={W}, h={H})")
        crop = a[y0:y0 + h, x0:x0 + w]
        if self._size is not None:
            crop = _resize_np(crop, self._size)
        return nd_array(crop)


class RandomResizedCrop:
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation=1):
        self._size = size
        self._scale = scale
        self._ratio = ratio

    def __call__(self, x):
        a = _to_np(x)
        h, w = a.shape[:2]
        area = h * w
        for _ in range(10):
            target = area * _np.random.uniform(*self._scale)
            aspect = _np.random.uniform(*self._ratio)
            cw = int(round(_np.sqrt(target * aspect)))
            ch = int(round(_np.sqrt(target / aspect)))
            if cw <= w and ch <= h:
                x0 = _np.random.randint(0, w - cw + 1)
                y0 = _np.random.randint(0, h - ch + 1)
                crop = a[y0:y0 + ch, x0:x0 + cw]
                return nd_array(_resize_np(crop, self._size))
        return nd_array(_resize_np(a, self._size))


class RandomFlipLeftRight:
    def __call__(self, x):
        a = _to_np(x)
        if _np.random.rand() < 0.5:
            a = a[:, ::-1].copy()
        return nd_array(a)


class RandomFlipTopBottom:
    def __call__(self, x):
        a = _to_np(x)
        if _np.random.rand() < 0.5:
            a = a[::-1].copy()
        return nd_array(a)


class _RandomJitter:
    def __init__(self, amount):
        self._amount = amount

    def _factor(self):
        return 1.0 + _np.random.uniform(-self._amount, self._amount)


class RandomBrightness(_RandomJitter):
    def __call__(self, x):
        a = _to_np(x).astype(_np.float32)
        return nd_array(_np.clip(a * self._factor(), 0, 255))


class RandomContrast(_RandomJitter):
    def __call__(self, x):
        a = _to_np(x).astype(_np.float32)
        mean = a.mean()
        return nd_array(_np.clip((a - mean) * self._factor() + mean, 0, 255))


class RandomSaturation(_RandomJitter):
    def __call__(self, x):
        from ....ndarray.ops_image import LUMA
        a = _to_np(x).astype(_np.float32)
        gray = (a * LUMA).sum(axis=-1, keepdims=True)
        f = self._factor()
        return nd_array(_np.clip(a * f + gray * (1 - f), 0, 255))


class RandomHue(_RandomJitter):
    """Hue jitter (reference transforms.RandomHue): rotate RGB around the
    gray axis by a random angle scaled from the jitter amount."""

    def __call__(self, x):
        # one shared YIQ rotation (ops_image.py) — op and transform
        # cannot drift, and f=0 is an exact identity
        from ....ndarray.ops_image import hue_rotation_matrix
        a = _to_np(x).astype(_np.float32)
        f = self._factor() - 1.0            # in [-amount, amount]
        m = hue_rotation_matrix(f)
        return nd_array(_np.clip(a @ m.T, 0, 255))


class RandomLighting:
    def __init__(self, alpha):
        self._alpha = alpha

    def __call__(self, x):
        from ....ndarray.ops_image import (LIGHTING_EIGVAL,
                                           LIGHTING_EIGVEC)
        a = _to_np(x).astype(_np.float32)
        alpha = _np.random.normal(0, self._alpha, 3)
        rgb = LIGHTING_EIGVEC @ (alpha * LIGHTING_EIGVAL)
        return nd_array(_np.clip(a + rgb, 0, 255))


class RandomColorJitter:
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0):
        self._ts = []
        if brightness:
            self._ts.append(RandomBrightness(brightness))
        if contrast:
            self._ts.append(RandomContrast(contrast))
        if saturation:
            self._ts.append(RandomSaturation(saturation))
        if hue:
            self._ts.append(RandomHue(hue))

    def __call__(self, x):
        for t in self._ts:
            x = t(x)
        return x
