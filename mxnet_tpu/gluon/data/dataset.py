"""Datasets (reference parity: python/mxnet/gluon/data/dataset.py)."""
from __future__ import annotations

from ...base import MXNetError

__all__ = ["Dataset", "ArrayDataset", "SimpleDataset", "RecordFileDataset"]


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError

    def transform(self, fn, lazy=True):
        trans = _LazyTransformDataset(self, fn)
        if lazy:
            return trans
        return SimpleDataset([trans[i] for i in range(len(trans))])

    def transform_first(self, fn, lazy=True):
        def first(x, *args):
            if args:
                return (fn(x),) + args
            return fn(x)
        return self.transform(_TransformFirstClosure(fn), lazy)

    def filter(self, fn):
        return SimpleDataset([self[i] for i in range(len(self))
                              if fn(self[i])])

    def take(self, count):
        count = min(count, len(self))
        return SimpleDataset([self[i] for i in range(count)])


class _TransformFirstClosure:
    def __init__(self, fn):
        self._fn = fn

    def __call__(self, x, *args):
        if args:
            return (self._fn(x),) + args
        return self._fn(x)


class _LazyTransformDataset(Dataset):
    def __init__(self, data, fn):
        self._data = data
        self._fn = fn

    def __len__(self):
        return len(self._data)

    def __getitem__(self, idx):
        item = self._data[idx]
        if isinstance(item, tuple):
            return self._fn(*item)
        return self._fn(item)


class SimpleDataset(Dataset):
    def __init__(self, data):
        self._data = data

    def __len__(self):
        return len(self._data)

    def __getitem__(self, idx):
        return self._data[idx]


class ArrayDataset(Dataset):
    """Zips several array-likes into (x, y, ...) samples."""

    def __init__(self, *args):
        if not args:
            raise MXNetError("needs at least one array")
        self._length = len(args[0])
        for i, a in enumerate(args):
            if len(a) != self._length:
                raise MXNetError(f"array {i} has length {len(a)} != "
                                 f"{self._length}")
        self._data = list(args)

    def __len__(self):
        return self._length

    def __getitem__(self, idx):
        if len(self._data) == 1:
            return self._data[0][idx]
        return tuple(d[idx] for d in self._data)


class RecordFileDataset(Dataset):
    """Dataset over a RecordIO file (reference: .rec format, SURVEY.md §2.4)."""

    def __init__(self, filename):
        from ... import recordio
        idx_file = filename[:-4] + ".idx" if filename.endswith(".rec") \
            else filename + ".idx"
        self._record = recordio.MXIndexedRecordIO(idx_file, filename, "r")

    def __len__(self):
        return len(self._record.keys)

    def __getitem__(self, idx):
        return self._record.read_idx(self._record.keys[idx])
