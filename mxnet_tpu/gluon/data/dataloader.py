"""DataLoader: batched iteration with background prefetch.

Reference parity: python/mxnet/gluon/data/dataloader.py (SURVEY.md §2.4) —
multiprocessing workers passing batches through POSIX-shm NDArrays.
TPU-native design: the consumer is one fat chip fed over PCIe, not 8 GPU
queues, so the pipeline is a thread pool (numpy batching releases the GIL in
decode/augment) + a bounded prefetch queue that overlaps host batching with
device steps; batches land on device asynchronously via the NDArray layer.

Failure handling: a worker exception re-raises in the consumer as an
MXNetError naming the worker thread and batch index (never a silent epoch
truncation), transient worker failures are retried ``worker_retries``
times per batch, and a stalled pipeline raises after ``timeout`` seconds
with the stuck worker→batch map instead of blocking forever.  The
``loader_stall`` / ``loader_error`` sites of the deterministic fault plan
(``MXTPU_FAULT_PLAN`` — see mxnet_tpu.faults) exercise both paths on CPU.

Device-input double buffering (``device_prefetch`` /
``MXTPU_DEVICE_PREFETCH``): the prefetch pipeline above ends at the
HOST — every training step still pays the host→device ingestion
transfer on its critical path.  With a depth N > 0 the iterator grows
a device stage: each pulled batch is handed to an (async)
``jax.device_put`` and up to N batches stay resident on device beyond
the one being consumed, so step t's jit consumes an already-resident
batch while batch t+1's transfer overlaps it.  The placement is
pluggable (``set_device_put_fn``): a ``ShardedTrainer.place_batch``
makes the stage sharding-aware for the dp mesh (the ResilientTrainer
wires this for an attached loader).  ``loader.device_put_us`` /
``loader.device_buffer_depth`` measure the stage; the
``DevicePrefetchController`` steers the depth (each slot is a resident
device batch — HBM) via :func:`set_device_prefetch_override`, applied
at the next ``__iter__``.

Data-parallel sharding (elastic fleet): ``num_shards``/``shard_index``
stripe the epoch's batches round-robin across the fleet (batch ``i``
belongs to shard ``i % num_shards`` — the reference's
``num_parts``/``part_index`` idiom, at batch granularity so the batch
size never changes).  ``num_shards="dist"`` resolves BOTH values from
the active process group at each ``__iter__`` — after a fleet re-form
the next epoch automatically re-partitions over the survivors.  The
loader also keeps a **position cursor** (epoch + per-shard batches
consumed + the shard count they were consumed under):
``state_dict()``/``load_state_dict()`` ride the ResilientTrainer
checkpoint payload, and a restore fast-forwards the next epoch to the
equivalent GLOBAL position under the (possibly different) new shard
assignment — skipped batches are never built, their index lists are
simply dropped — so post-re-form resume re-winds the loader instead of
replaying the epoch from batch 0.
"""
from __future__ import annotations

import queue
import threading
import time
from typing import Optional

import numpy as _np

from ...base import MXNetError, get_env
from ...faults import TransientFault, active_plan, retry_call
from ...ndarray import NDArray, array as nd_array
from ...observability.registry import registry as _metrics_registry
from ...observability.trace import span as _span
from .sampler import BatchSampler, RandomSampler, SequentialSampler

# worker failures worth retrying: injected faults and flaky I/O — a broken
# dataset (IndexError, bad shapes) surfaces immediately instead of N times
_RETRYABLE_WORKER_ERRORS = (TransientFault, OSError, TimeoutError,
                            ConnectionError)

__all__ = ["DataLoader", "default_batchify_fn", "default_device_put",
           "set_prefetch_override", "prefetch_override",
           "set_device_prefetch_override", "device_prefetch_override"]

# live prefetch-depth override (the PrefetchController's apply target):
# when set, every DataLoader's next __iter__ uses this depth for its
# prefetch queue and in-flight window instead of its constructor value.
# Process-wide by design — the controller steers the one signal
# (loader.prefetch_depth) all loaders share.
_prefetch_override: Optional[int] = None


def set_prefetch_override(depth: Optional[int]) -> None:
    """Set (or clear, with None) the live prefetch-depth target.  Takes
    effect at each loader's next ``__iter__`` — epoch boundaries, the
    natural reconfiguration point for a pipeline whose queue is sized
    at iterator construction."""
    global _prefetch_override
    _prefetch_override = None if depth is None else max(1, int(depth))


def prefetch_override() -> Optional[int]:
    return _prefetch_override


# live DEVICE-prefetch depth override (the DevicePrefetchController's
# apply target): when set, every DataLoader's next __iter__ uses this
# depth for its device double-buffer stage instead of its constructor /
# knob value.  Process-wide, like the host override above.
_device_prefetch_override: Optional[int] = None


def set_device_prefetch_override(depth: Optional[int]) -> None:
    """Set (or clear, with None) the live device-prefetch depth.
    Takes effect at each loader's next ``__iter__`` — the buffer holds
    live device arrays, so resizing mid-epoch would mean dropping or
    re-transferring batches."""
    global _device_prefetch_override
    _device_prefetch_override = None if depth is None \
        else max(0, int(depth))


def device_prefetch_override() -> Optional[int]:
    return _device_prefetch_override


def default_device_put(batch):
    """Leaf-wise default-device placement: NDArray leaves re-land via
    ``jax.device_put`` (async — the transfer overlaps the consumer),
    numpy leaves become device NDArrays, tuples recurse.  The fallback
    ``put_fn`` when no sharding-aware placer (e.g.
    ``ShardedTrainer.place_batch``) is attached."""
    import jax
    if isinstance(batch, (tuple, list)):
        return tuple(default_device_put(b) for b in batch)
    if isinstance(batch, NDArray):
        return NDArray(jax.device_put(batch._read()), ctx=batch.context)
    return nd_array(batch)


class _WorkerError:
    """Carries a worker exception across the prefetch queue so it re-raises
    in the consumer instead of silently truncating the epoch."""

    def __init__(self, exc: BaseException):
        self.exc = exc


def default_batchify_fn(data):
    """Stack samples into a batch (NDArray or numpy leaves; tuples recurse)."""
    if isinstance(data[0], NDArray):
        import jax.numpy as jnp
        return nd_array(_np.stack([d.asnumpy() for d in data]))
    if isinstance(data[0], tuple):
        transposed = zip(*data)
        return tuple(default_batchify_fn(list(f)) for f in transposed)
    arr = _np.asarray(data)
    if arr.dtype == _np.float64:
        arr = arr.astype(_np.float32)
    return nd_array(arr)


class DataLoader:
    def __init__(self, dataset, batch_size=None, shuffle=False, sampler=None,
                 last_batch=None, batch_sampler=None, batchify_fn=None,
                 num_workers=0, pin_memory=False, prefetch=None,
                 thread_pool=False, timeout=120, worker_retries=0,
                 num_shards=None, shard_index=None,
                 device_prefetch=None, device_put_fn=None):
        self._dataset = dataset
        if num_shards == "dist":
            if shard_index is not None:
                raise MXNetError(
                    "num_shards='dist' resolves shard_index from the "
                    "process group — don't pass both")
        elif num_shards is not None:
            num_shards = int(num_shards)
            shard_index = int(shard_index if shard_index is not None else 0)
            if not 0 <= shard_index < num_shards:
                raise MXNetError(
                    f"shard_index must be in [0, {num_shards}), got "
                    f"{shard_index}")
        elif shard_index is not None:
            raise MXNetError("shard_index requires num_shards")
        self._num_shards = num_shards
        self._shard_index = shard_index
        # position cursor: epoch (1-based once iteration starts),
        # per-shard batches consumed this epoch, the shard count they
        # were consumed under, and the exact GLOBAL base the epoch
        # (re)started from — `global = base + consumed * k` stays exact
        # across repeated re-shards, where reconstructing it from the
        # per-shard count alone would drift by the division remainder
        self._epoch = 0
        self._cursor_batch = 0
        self._cursor_shards = 1
        self._cursor_gbase = 0
        self._cursor_start = 0
        self._pending_state: Optional[dict] = None
        if batch_sampler is None:
            if batch_size is None:
                raise MXNetError("batch_size required when no batch_sampler")
            if sampler is None:
                sampler = RandomSampler(len(dataset)) if shuffle else \
                    SequentialSampler(len(dataset))
            elif shuffle:
                raise MXNetError("shuffle and sampler are exclusive")
            batch_sampler = BatchSampler(sampler, batch_size,
                                         last_batch or "keep")
        self._batch_sampler = batch_sampler
        self._batchify_fn = batchify_fn or default_batchify_fn
        self._num_workers = num_workers
        self._timeout = timeout
        self._worker_retries = max(0, int(worker_retries))
        self._prefetch = max(0, prefetch if prefetch is not None
                             else 2 * num_workers)
        # device double-buffer: None defers to the live override / the
        # MXTPU_DEVICE_PREFETCH knob at each __iter__; 0 = off
        self._device_prefetch = None if device_prefetch is None \
            else max(0, int(device_prefetch))
        self._device_put_fn = device_put_fn
        # `loader.*` observability metrics (process-global; see
        # mxnet_tpu.observability): batches built, per-batch build time,
        # transient worker retries
        reg = _metrics_registry()
        self._c_batches = reg.counter(
            "loader.batches", help="batches built by the DataLoader")
        self._c_retries = reg.counter(
            "loader.worker_retries",
            help="transient worker failures retried")
        self._g_depth = reg.gauge(
            "loader.prefetch_depth",
            help="prefetch queue depth sampled at each batch handoff — "
                 "near-capacity means workers keep ahead of the device; "
                 "near-zero means the pipeline is starving the step")
        self._g_capacity = reg.gauge(
            "loader.prefetch_capacity",
            help="prefetch queue capacity of the most recent __iter__ "
                 "— what the depth gauge can reach; the "
                 "PrefetchController's evidence that an applied target "
                 "is actually live (overrides apply at epoch "
                 "boundaries)")
        self._h_wait = reg.histogram(
            "loader.consume_wait_us",
            help="time the CONSUMER blocked waiting for the next batch "
                 "(the loader-bound share of the step interval; the "
                 "step-trace 'loader' critical-path segment)")
        # pending per-batch attribution the resilience supervisor drains
        # (consume_trace): the loader wait happens BETWEEN steps, so the
        # step trace adopts it retroactively
        self._trace_wait_us = 0.0
        self._trace_wait_end = 0.0
        self._trace_devput_us = 0.0
        self._h_device_put = reg.histogram(
            "loader.device_put_us",
            help="device-prefetch stage: time to DISPATCH one batch's "
                 "device_put (the transfer itself is async and "
                 "overlaps the consumer) — a large value means the "
                 "placement fn is synchronizing")
        self._g_device_depth = reg.gauge(
            "loader.device_buffer_depth",
            help="device-resident batches buffered beyond the one "
                 "being consumed (each slot is HBM); pinned at zero "
                 "with device prefetch on means transfers cannot keep "
                 "ahead of the step")

    def set_device_put_fn(self, fn) -> None:
        """Attach the device-placement callable the device-prefetch
        stage applies to each batch (e.g. a ``ShardedTrainer``'s
        ``place_batch`` for dp-mesh-sharded placement).  None restores
        the leaf-wise default.  Takes effect at the next __iter__."""
        self._device_put_fn = fn

    @property
    def device_put_fn(self):
        return self._device_put_fn

    def _resolve_device_depth(self) -> int:
        """Device-prefetch depth for the NEXT epoch: the live
        controller override wins, then the constructor value, then the
        MXTPU_DEVICE_PREFETCH knob (0 = off)."""
        if _device_prefetch_override is not None:
            return _device_prefetch_override
        if self._device_prefetch is not None:
            return self._device_prefetch
        return max(0, int(get_env("MXTPU_DEVICE_PREFETCH")))

    def _resolve_shard(self):
        """(num_shards, shard_index) for the NEXT epoch.  ``"dist"``
        reads the active process group live, so a fleet re-form is
        picked up at the next ``__iter__`` with no loader surgery."""
        if self._num_shards == "dist":
            from ...parallel import dist
            if dist.is_initialized():
                return dist.num_workers(), dist.rank()
            return 1, 0
        if self._num_shards is None:
            return 1, 0
        return self._num_shards, self._shard_index

    # -- position cursor (checkpoint payload) -------------------------------
    def state_dict(self) -> dict:
        """The loader's position cursor — what the ResilientTrainer
        checkpoint payload carries so resume re-winds instead of
        replaying the epoch.  ``global`` is the exact fleet-wide batch
        position (every shard advances in lockstep with the training
        step); ``batch``/``num_shards`` describe this shard's local
        count, kept for observability."""
        consumed = self._cursor_batch - self._cursor_start
        return {"epoch": self._epoch,
                "batch": self._cursor_batch,
                "num_shards": self._cursor_shards,
                "global": self._cursor_gbase +
                consumed * self._cursor_shards}

    def load_state_dict(self, state: dict) -> None:
        """Restore a :meth:`state_dict` cursor.  Takes effect at the
        next ``__iter__``: the epoch counter is restored and the epoch
        fast-forwards to the saved GLOBAL position re-mapped onto the
        CURRENT shard assignment — index lists are dropped unbuilt, no
        dataset reads.  (Cursors without ``global`` — a pre-PR-9 or
        hand-built dict — fall back to ``batch * num_shards``.)"""
        g = state.get("global")
        if g is None:
            g = int(state.get("batch", 0)) * \
                max(1, int(state.get("num_shards", 1)))
        self._pending_state = {
            "epoch": int(state.get("epoch", 0)),
            "global": int(g)}

    def _epoch_plan(self, num_shards, shard_index, start_batch):
        """(global_index, sample_indices) pairs for THIS shard this
        epoch, skipping the first ``start_batch`` shard-local batches
        without building them."""
        taken = 0
        for i, indices in enumerate(self._batch_sampler):
            if num_shards > 1 and i % num_shards != shard_index:
                continue
            taken += 1
            if taken <= start_batch:
                continue   # fast-forward: the index list is dropped,
                # the samples are never read
            yield i, indices

    def __len__(self):
        n = len(self._batch_sampler)
        k, s = self._resolve_shard()
        if k <= 1:
            return n
        return len(range(s, n, k))

    def _make_batch(self, indices, batch_idx=None):
        # the batch id rides to the chrome-trace timeline as event args
        with _span("loader.batch_build_us",
                   args=None if batch_idx is None
                   else {"batch": batch_idx}):
            samples = [self._dataset[i] for i in indices]
            batch = self._batchify_fn(samples)
        self._c_batches.inc()         # lock-exact: workers race this
        return batch

    def _worker_batch(self, batch_idx, indices, active):
        """Build one batch in a worker thread: fault-plan hooks, bounded
        retry on transient failures, and an error that names this worker
        and batch on final failure."""
        worker = threading.current_thread().name
        active[worker] = batch_idx
        attempts = [0]
        try:
            plan = active_plan()
            if plan is not None:
                stall = plan.scheduled("loader_stall", batch_idx + 1)
                if stall is not None:
                    time.sleep(stall.arg if stall.arg is not None else 30.0)

            def attempt():
                attempts[0] += 1
                if plan is not None:
                    plan.fire("loader_error", batch_idx + 1)
                return self._make_batch(indices, batch_idx)

            def on_retry(attempt_no, exc, delay):
                self._c_retries.inc()

            try:
                return retry_call(attempt, retries=self._worker_retries,
                                  base_delay=0.02, max_delay=1.0,
                                  retry_on=_RETRYABLE_WORKER_ERRORS,
                                  on_retry=on_retry)
            except Exception as exc:
                raise MXNetError(
                    f"DataLoader worker {worker!r} failed on batch "
                    f"{batch_idx} after {attempts[0]} attempt(s): "
                    f"{exc!r}") from exc
        finally:
            active.pop(worker, None)

    def __iter__(self):
        k, s = self._resolve_shard()
        start_batch = 0
        gbase = 0
        if self._pending_state is not None:
            pending = self._pending_state
            self._pending_state = None
            self._epoch = pending["epoch"]
            # global batches [0, G) are consumed fleet-wide; this shard
            # owns global indices ≡ shard_index (mod k), of which
            # [0, G) contains G//k plus one more when the shard's index
            # falls inside the G%k remainder — without that correction,
            # shards below the remainder re-train one already-consumed
            # batch after a re-shard
            gbase = pending["global"]
            start_batch = gbase // k + (1 if gbase % k > s else 0)
        else:
            self._epoch += 1
        self._cursor_shards = k
        self._cursor_gbase = gbase
        self._cursor_start = start_batch
        self._cursor_batch = start_batch
        plan = self._epoch_plan(k, s, start_batch)
        if self._num_workers == 0:
            src = (self._make_batch(indices, bi) for bi, indices in plan)
        else:
            src = self._threaded_iter(plan)
        depth = self._resolve_device_depth()
        if depth > 0:
            src = self._device_stage(src, depth)
        # the position cursor counts batches HANDED TO the consumer —
        # bumped here, at the outermost yield, so device-stage batches
        # still in the buffer (transferred but never trained) are not
        # counted and a checkpoint resume replays them.  The span around
        # each pull is the CONSUMER's wait (how long the training loop
        # starved on input) — recorded as a histogram and banked for the
        # next step's causal trace / critical-path breakdown.
        try:
            while True:
                with _span("loader.consume_wait_us") as sp:
                    try:
                        batch = next(src)
                    except StopIteration:
                        break
                self._trace_wait_us += sp.duration_us
                self._trace_wait_end = sp.t_end
                self._cursor_batch += 1
                yield batch
        finally:
            src.close()

    def consume_trace(self) -> dict:
        """Drain the pending consumer-wait attribution accumulated
        since the last call: ``wait_us`` (time the consumer blocked in
        the loader), ``wait_end`` (``tracing.now()`` timestamp of the
        last wait's end — where a retroactive trace span anchors) and
        ``device_put_us`` (device-prefetch dispatch time nested inside
        that wait).  The ResilientTrainer drains this at each step to
        attribute loader time into the step trace and breakdown."""
        out = {"wait_us": self._trace_wait_us,
               "wait_end": self._trace_wait_end,
               "device_put_us": self._trace_devput_us}
        self._trace_wait_us = 0.0
        self._trace_devput_us = 0.0
        return out

    def _device_stage(self, src, depth: int):
        """Device double buffering: dispatch each pulled host batch to
        the placement fn immediately (``jax.device_put`` is async — the
        transfer proceeds in the background) and keep up to ``depth``
        placed batches in flight beyond the one being yielded, so the
        consumer's step t overlaps batch t+1's host→device transfer
        instead of paying it on the critical path."""
        import collections
        put = self._device_put_fn
        if put is None:
            put = default_device_put
        buf = collections.deque()
        try:
            for item in src:
                # span, not a bare clock pair: the put-dispatch cost
                # rides the unified trace timeline too
                with _span("loader.device_put_us") as dsp:
                    buf.append(put(item))
                self._trace_devput_us += dsp.duration_us
                if len(buf) > depth:
                    self._g_device_depth.set(len(buf) - 1)
                    yield buf.popleft()
            while buf:
                self._g_device_depth.set(len(buf) - 1)
                yield buf.popleft()
        finally:
            close = getattr(src, "close", None)
            if close is not None:
                close()

    def _threaded_iter(self, plan):
        # threaded prefetch pipeline with a bounded in-flight window so a
        # slow consumer never materializes more than window batches.
        # The live override (PrefetchController) wins over the
        # constructor depth, resolved per epoch at iterator build.
        import collections
        from concurrent.futures import ThreadPoolExecutor
        prefetch = _prefetch_override if _prefetch_override is not None \
            else (self._prefetch or 2)
        self._g_capacity.set(prefetch)
        q: "queue.Queue" = queue.Queue(maxsize=prefetch)
        sentinel = object()
        window = self._num_workers + prefetch
        active: dict = {}   # worker thread name -> batch index in progress
        # abandonment flag: an epoch iterator dropped mid-epoch (a
        # `break` at a target step, FleetReformed at a step boundary —
        # a DESIGNED, recurring path under elastic supervision) must
        # release the producer, which would otherwise block forever in
        # q.put with its whole worker pool pinned
        abandoned = threading.Event()

        def hand_over(item) -> bool:
            while not abandoned.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def producer():
            try:
                with ThreadPoolExecutor(self._num_workers) as pool:
                    inflight = collections.deque()
                    for i, idx in plan:
                        if abandoned.is_set():
                            return
                        inflight.append(pool.submit(
                            self._worker_batch, i, idx, active))
                        if len(inflight) >= window:
                            if not hand_over(inflight.popleft().result()):
                                return
                    while inflight:
                        if not hand_over(inflight.popleft().result()):
                            return
            except BaseException as exc:   # surface worker failures
                hand_over(_WorkerError(exc))
            finally:
                # BLOCKING hand-over, not put_nowait: a consumer busy
                # downstream of the queue (e.g. the device-prefetch
                # stage compiling the step on its first batch) can
                # leave the queue momentarily full right as the epoch
                # ends — a dropped sentinel then strands it in q.get
                # until the loader timeout.  hand_over waits for space
                # and still exits promptly on abandonment.
                hand_over(sentinel)

        t = threading.Thread(target=producer, daemon=True)
        t.start()  # mxlint: disable=thread-lifecycle — deliberate abandonment: the producer exits on `abandoned` at every hand-over, but joining would park generator close behind the pool's shutdown(wait=True) for in-flight worker batches
        expected = 0
        try:
            while True:
                try:
                    item = q.get(timeout=self._timeout)
                except queue.Empty:
                    stuck = dict(active)
                    raise MXNetError(
                        f"DataLoader prefetch timed out after "
                        f"{self._timeout}s waiting for batch {expected}"
                        + (f"; stalled workers (worker -> batch): {stuck}"
                           if stuck else "; no worker is active — the "
                           "producer thread may have died")) from None
                if item is sentinel:
                    break
                if isinstance(item, _WorkerError):
                    raise item.exc
                # queue depth AFTER taking our batch: what the consumer
                # would find if it came back immediately (the ROADMAP's
                # prefetch-health gauge; also in flight-recorder records)
                self._g_depth.set(q.qsize())
                yield item
                expected += 1
        finally:
            # runs on normal exhaustion AND on generator close
            # (GeneratorExit from an abandoned for-loop): unblock the
            # producer and drop whatever it already queued
            abandoned.set()
            while True:
                try:
                    q.get_nowait()
                except queue.Empty:
                    break
