"""DataLoader: batched iteration with background prefetch.

Reference parity: python/mxnet/gluon/data/dataloader.py (SURVEY.md §2.4) —
multiprocessing workers passing batches through POSIX-shm NDArrays.
TPU-native design: the consumer is one fat chip fed over PCIe, not 8 GPU
queues, so the pipeline is a thread pool (numpy batching releases the GIL in
decode/augment) + a bounded prefetch queue that overlaps host batching with
device steps; batches land on device asynchronously via the NDArray layer.
"""
from __future__ import annotations

import queue
import threading
from typing import Optional

import numpy as _np

from ...base import MXNetError
from ...ndarray import NDArray, array as nd_array
from .sampler import BatchSampler, RandomSampler, SequentialSampler

__all__ = ["DataLoader", "default_batchify_fn"]


class _WorkerError:
    """Carries a worker exception across the prefetch queue so it re-raises
    in the consumer instead of silently truncating the epoch."""

    def __init__(self, exc: BaseException):
        self.exc = exc


def default_batchify_fn(data):
    """Stack samples into a batch (NDArray or numpy leaves; tuples recurse)."""
    if isinstance(data[0], NDArray):
        import jax.numpy as jnp
        return nd_array(_np.stack([d.asnumpy() for d in data]))
    if isinstance(data[0], tuple):
        transposed = zip(*data)
        return tuple(default_batchify_fn(list(f)) for f in transposed)
    arr = _np.asarray(data)
    if arr.dtype == _np.float64:
        arr = arr.astype(_np.float32)
    return nd_array(arr)


class DataLoader:
    def __init__(self, dataset, batch_size=None, shuffle=False, sampler=None,
                 last_batch=None, batch_sampler=None, batchify_fn=None,
                 num_workers=0, pin_memory=False, prefetch=None,
                 thread_pool=False, timeout=120):
        self._dataset = dataset
        if batch_sampler is None:
            if batch_size is None:
                raise MXNetError("batch_size required when no batch_sampler")
            if sampler is None:
                sampler = RandomSampler(len(dataset)) if shuffle else \
                    SequentialSampler(len(dataset))
            elif shuffle:
                raise MXNetError("shuffle and sampler are exclusive")
            batch_sampler = BatchSampler(sampler, batch_size,
                                         last_batch or "keep")
        self._batch_sampler = batch_sampler
        self._batchify_fn = batchify_fn or default_batchify_fn
        self._num_workers = num_workers
        self._prefetch = max(0, prefetch if prefetch is not None
                             else 2 * num_workers)

    def __len__(self):
        return len(self._batch_sampler)

    def _make_batch(self, indices):
        samples = [self._dataset[i] for i in indices]
        return self._batchify_fn(samples)

    def __iter__(self):
        if self._num_workers == 0:
            for indices in self._batch_sampler:
                yield self._make_batch(indices)
            return
        # threaded prefetch pipeline with a bounded in-flight window so a
        # slow consumer never materializes more than window batches
        import collections
        from concurrent.futures import ThreadPoolExecutor
        q: "queue.Queue" = queue.Queue(maxsize=self._prefetch or 2)
        sentinel = object()
        window = self._num_workers + (self._prefetch or 2)

        def producer():
            try:
                with ThreadPoolExecutor(self._num_workers) as pool:
                    it = iter(self._batch_sampler)
                    inflight = collections.deque()
                    for idx in it:
                        inflight.append(pool.submit(self._make_batch, idx))
                        if len(inflight) >= window:
                            q.put(inflight.popleft().result())
                    while inflight:
                        q.put(inflight.popleft().result())
            except BaseException as exc:   # surface worker failures
                q.put(_WorkerError(exc))
            finally:
                q.put(sentinel)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is sentinel:
                break
            if isinstance(item, _WorkerError):
                raise item.exc
            yield item
