"""gluon.data: datasets, samplers, DataLoader (reference:
python/mxnet/gluon/data/)."""
from .dataset import Dataset, ArrayDataset, SimpleDataset, RecordFileDataset
from .sampler import Sampler, SequentialSampler, RandomSampler, \
    BatchSampler, FilterSampler
from .dataloader import DataLoader
from . import vision
