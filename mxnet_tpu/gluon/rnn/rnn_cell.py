"""Explicit recurrent cells + unrolling.

Reference parity: python/mxnet/gluon/rnn/rnn_cell.py — per-step cells with
``__call__(x_t, states)`` and ``unroll``; Sequential/Dropout/Residual/
Bidirectional wrappers.  The fused layers (rnn_layer.py) are the fast path;
cells exist for custom recurrences and bucketing-era code.
"""
from __future__ import annotations

from ...base import MXNetError
from ..block import HybridBlock

__all__ = ["RecurrentCell", "RNNCell", "LSTMCell", "GRUCell",
           "SequentialRNNCell", "DropoutCell", "ResidualCell",
           "BidirectionalCell"]


class RecurrentCell(HybridBlock):
    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._modified = False

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=None, ctx=None, **kwargs):
        from ... import ndarray as nd
        states = []
        for info in self.state_info(batch_size):
            shape = info["shape"]
            states.append(nd.zeros(shape, ctx=ctx))
        return states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        """Static unroll over `length` steps (reference semantics)."""
        from ... import ndarray as nd
        axis = layout.find("T")
        batch_axis = layout.find("N")
        if isinstance(inputs, (list, tuple)):
            seq = list(inputs)
            batch = seq[0].shape[0]
        else:
            batch = inputs.shape[batch_axis]
            seq = [x.squeeze(axis=axis) for x in
                   nd.split(inputs, num_outputs=length, axis=axis)] \
                if length > 1 else [inputs.squeeze(axis=axis)]
        states = begin_state if begin_state is not None else \
            self.begin_state(batch, ctx=seq[0].context)
        outputs = []
        for t in range(length):
            out, states = self(seq[t], states)
            outputs.append(out)
        if merge_outputs or merge_outputs is None:
            outputs = nd.stack(*outputs, axis=axis)
        if valid_length is not None:
            outputs = nd.SequenceMask(outputs, valid_length,
                                      use_sequence_length=True,
                                      axis=axis, value=0.0)
        return outputs, states

    def forward(self, x, states):
        return super().forward(x, states)


class RNNCell(RecurrentCell):
    def __init__(self, hidden_size, activation="tanh", input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._activation = activation
        with self.name_scope():
            self.i2h_weight = self.params.get(
                "i2h_weight", shape=(hidden_size, input_size),
                init=i2h_weight_initializer, allow_deferred_init=True)
            self.h2h_weight = self.params.get(
                "h2h_weight", shape=(hidden_size, hidden_size),
                init=h2h_weight_initializer, allow_deferred_init=True)
            self.i2h_bias = self.params.get(
                "i2h_bias", shape=(hidden_size,),
                init=i2h_bias_initializer, allow_deferred_init=True)
            self.h2h_bias = self.params.get(
                "h2h_bias", shape=(hidden_size,),
                init=h2h_bias_initializer, allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def infer_shape(self, x, *args):
        self.i2h_weight.shape = (self._hidden_size, x.shape[-1])

    def hybrid_forward(self, F, x, states, i2h_weight, h2h_weight, i2h_bias,
                       h2h_bias):
        h = states[0]
        i2h = F.FullyConnected(x, i2h_weight, i2h_bias,
                               num_hidden=self._hidden_size)
        h2h = F.FullyConnected(h, h2h_weight, h2h_bias,
                               num_hidden=self._hidden_size)
        out = F.Activation(i2h + h2h, act_type=self._activation)
        return out, [out]


class LSTMCell(RecurrentCell):
    def __init__(self, hidden_size, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        with self.name_scope():
            self.i2h_weight = self.params.get(
                "i2h_weight", shape=(4 * hidden_size, input_size),
                init=i2h_weight_initializer, allow_deferred_init=True)
            self.h2h_weight = self.params.get(
                "h2h_weight", shape=(4 * hidden_size, hidden_size),
                init=h2h_weight_initializer, allow_deferred_init=True)
            self.i2h_bias = self.params.get(
                "i2h_bias", shape=(4 * hidden_size,),
                init=i2h_bias_initializer, allow_deferred_init=True)
            self.h2h_bias = self.params.get(
                "h2h_bias", shape=(4 * hidden_size,),
                init=h2h_bias_initializer, allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"},
                {"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def infer_shape(self, x, *args):
        self.i2h_weight.shape = (4 * self._hidden_size, x.shape[-1])

    def hybrid_forward(self, F, x, states, i2h_weight, h2h_weight, i2h_bias,
                       h2h_bias):
        h, c = states
        gates = F.FullyConnected(x, i2h_weight, i2h_bias,
                                 num_hidden=4 * self._hidden_size) + \
            F.FullyConnected(h, h2h_weight, h2h_bias,
                             num_hidden=4 * self._hidden_size)
        parts = F.split(gates, num_outputs=4, axis=1)
        i = F.sigmoid(parts[0])
        f = F.sigmoid(parts[1])
        g = F.tanh(parts[2])
        o = F.sigmoid(parts[3])
        c_new = f * c + i * g
        h_new = o * F.tanh(c_new)
        return h_new, [h_new, c_new]


class GRUCell(RecurrentCell):
    def __init__(self, hidden_size, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        with self.name_scope():
            self.i2h_weight = self.params.get(
                "i2h_weight", shape=(3 * hidden_size, input_size),
                init=i2h_weight_initializer, allow_deferred_init=True)
            self.h2h_weight = self.params.get(
                "h2h_weight", shape=(3 * hidden_size, hidden_size),
                init=h2h_weight_initializer, allow_deferred_init=True)
            self.i2h_bias = self.params.get(
                "i2h_bias", shape=(3 * hidden_size,),
                init=i2h_bias_initializer, allow_deferred_init=True)
            self.h2h_bias = self.params.get(
                "h2h_bias", shape=(3 * hidden_size,),
                init=h2h_bias_initializer, allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def infer_shape(self, x, *args):
        self.i2h_weight.shape = (3 * self._hidden_size, x.shape[-1])

    def hybrid_forward(self, F, x, states, i2h_weight, h2h_weight, i2h_bias,
                       h2h_bias):
        h = states[0]
        gx = F.FullyConnected(x, i2h_weight, i2h_bias,
                              num_hidden=3 * self._hidden_size)
        gh = F.FullyConnected(h, h2h_weight, h2h_bias,
                              num_hidden=3 * self._hidden_size)
        xp = F.split(gx, num_outputs=3, axis=1)
        hp = F.split(gh, num_outputs=3, axis=1)
        r = F.sigmoid(xp[0] + hp[0])
        z = F.sigmoid(xp[1] + hp[1])
        n = F.tanh(xp[2] + r * hp[2])
        out = (1 - z) * n + z * h
        return out, [out]


class SequentialRNNCell(RecurrentCell):
    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._cells = []

    def add(self, cell):
        self.register_child(cell)
        self._cells.append(cell)

    def state_info(self, batch_size=0):
        infos = []
        for c in self._cells:
            infos.extend(c.state_info(batch_size))
        return infos

    def begin_state(self, batch_size=0, **kwargs):
        states = []
        for c in self._cells:
            states.extend(c.begin_state(batch_size, **kwargs))
        return states

    def __call__(self, x, states):
        next_states = []
        pos = 0
        for c in self._cells:
            n = len(c.state_info())
            x, s = c(x, states[pos:pos + n])
            pos += n
            next_states.extend(s)
        return x, next_states

    def forward(self, x, states):
        return self.__call__(x, states)


class DropoutCell(RecurrentCell):
    def __init__(self, rate, axes=(), prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._rate = rate
        self._axes = axes

    def state_info(self, batch_size=0):
        return []

    def hybrid_forward(self, F, x, states):
        if self._rate > 0:
            x = F.Dropout(x, p=self._rate, axes=self._axes)
        return x, states


class ResidualCell(RecurrentCell):
    def __init__(self, base_cell):
        super().__init__()
        self.base_cell = base_cell

    def state_info(self, batch_size=0):
        return self.base_cell.state_info(batch_size)

    def begin_state(self, *a, **kw):
        return self.base_cell.begin_state(*a, **kw)

    def __call__(self, x, states):
        out, states = self.base_cell(x, states)
        return out + x, states

    def forward(self, x, states):
        return self.__call__(x, states)


class BidirectionalCell(RecurrentCell):
    def __init__(self, l_cell, r_cell):
        super().__init__()
        self.l_cell = l_cell
        self.r_cell = r_cell

    def state_info(self, batch_size=0):
        return self.l_cell.state_info(batch_size) + \
            self.r_cell.state_info(batch_size)

    def begin_state(self, *a, **kw):
        return self.l_cell.begin_state(*a, **kw) + \
            self.r_cell.begin_state(*a, **kw)

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        from ... import ndarray as nd
        nl = len(self.l_cell.state_info())
        states = begin_state
        l_states = states[:nl] if states else None
        r_states = states[nl:] if states else None
        l_out, l_states = self.l_cell.unroll(
            length, inputs, l_states, layout, True, valid_length)
        axis = layout.find("T")
        if isinstance(inputs, (list, tuple)):
            rev = list(reversed(inputs))
        else:
            rev = nd.reverse(inputs, axis=axis)
        r_out, r_states = self.r_cell.unroll(
            length, rev, r_states, layout, True, valid_length)
        r_out = nd.reverse(r_out, axis=axis)
        out = nd.concat(l_out, r_out, dim=2)
        return out, l_states + r_states

    def __call__(self, x, states):
        raise MXNetError("BidirectionalCell supports unroll() only")
