"""Fused recurrent layers: RNN / LSTM / GRU.

Reference parity: python/mxnet/gluon/rnn/rnn_layer.py over src/operator/rnn.cc
(cuDNN-fused; SURVEY.md §2.2).  TPU-native: the fused op is a `lax.scan`
whose per-step cell is a pair of MXU matmuls (see ops_nn.py RNN); parameter
layout (per-layer i2h/h2h weight+bias, cuDNN packing order) matches the
reference so checkpoints map 1:1.
"""
from __future__ import annotations

from ...base import MXNetError
from ..block import HybridBlock

__all__ = ["RNN", "LSTM", "GRU"]

_NGATES = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}


class _RNNLayer(HybridBlock):
    def __init__(self, mode, hidden_size, num_layers, layout, dropout,
                 bidirectional, input_size, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        if layout not in ("TNC", "NTC"):
            raise MXNetError(f"layout must be TNC or NTC, got {layout}")
        self._mode = mode
        self._hidden_size = hidden_size
        self._num_layers = num_layers
        self._layout = layout
        self._dropout = dropout
        self._dir = 2 if bidirectional else 1
        self._input_size = input_size
        self._gates = _NGATES[mode]
        ng, ni, nh = self._gates, input_size, hidden_size
        self._ordered_names = []
        with self.name_scope():
            for i in range(num_layers):
                for j in (["l", "r"] if bidirectional else ["l"]):
                    self._register_param(
                        f"{j}{i}_i2h_weight", (ng * nh, ni),
                        i2h_weight_initializer)
                    self._register_param(
                        f"{j}{i}_h2h_weight", (ng * nh, nh),
                        h2h_weight_initializer)
                    self._register_param(
                        f"{j}{i}_i2h_bias", (ng * nh,),
                        i2h_bias_initializer)
                    self._register_param(
                        f"{j}{i}_h2h_bias", (ng * nh,),
                        h2h_bias_initializer)
                ni = nh * self._dir

    def _register_param(self, name, shape, init):
        p = self.params.get(name, shape=shape, init=init,
                            allow_deferred_init=True)
        setattr(self, name, p)
        self._ordered_names.append(name)

    def infer_shape(self, x, *args):
        ndim = x.ndim
        if ndim != 3:
            raise MXNetError(f"rnn input must be 3-d, got {ndim}-d")
        isize = x.shape[2]
        ng, nh = self._gates, self._hidden_size
        ni = isize
        for i in range(self._num_layers):
            for j in (["l", "r"] if self._dir == 2 else ["l"]):
                getattr(self, f"{j}{i}_i2h_weight").shape = (ng * nh, ni)
            ni = nh * self._dir

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=None, ctx=None, **kwargs):
        from ... import ndarray as nd
        ctx = ctx
        shape = (self._num_layers * self._dir, batch_size, self._hidden_size)
        if self._mode == "lstm":
            return [nd.zeros(shape, ctx=ctx), nd.zeros(shape, ctx=ctx)]
        return [nd.zeros(shape, ctx=ctx)]

    def hybrid_forward(self, F, inputs, states=None, **params):
        if self._layout == "NTC":
            inputs = F.swapaxes(inputs, dim1=0, dim2=1)
        batch = inputs.shape[1]
        skip_states = states is None
        if skip_states:
            states = self.begin_state(batch, ctx=getattr(inputs, "context",
                                                         None))
        if not isinstance(states, (list, tuple)):
            states = [states]
        flat = F.concat(*[params[n].reshape((-1,))
                          for n in self._ordered_names], dim=0)
        args = [inputs, flat, states[0]]
        if self._mode == "lstm":
            args.append(states[1])
        outs = F.RNN(*args, state_size=self._hidden_size,
                     num_layers=self._num_layers, mode=self._mode,
                     bidirectional=self._dir == 2, p=self._dropout,
                     state_outputs=True)
        out = outs[0]
        out_states = list(outs[1:])
        if self._layout == "NTC":
            out = F.swapaxes(out, dim1=0, dim2=1)
        if skip_states:
            return out
        return out, out_states

    def __call__(self, inputs, states=None):
        return super().__call__(inputs, states) if states is not None \
            else super().__call__(inputs)


class RNN(_RNNLayer):
    """Multi-layer Elman RNN (relu or tanh)."""

    def __init__(self, hidden_size, num_layers=1, activation="relu",
                 layout="TNC", dropout=0, bidirectional=False, input_size=0,
                 **kwargs):
        super().__init__(f"rnn_{activation}", hidden_size, num_layers,
                         layout, dropout, bidirectional, input_size, **kwargs)


class LSTM(_RNNLayer):
    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0, **kwargs):
        super().__init__("lstm", hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, **kwargs)


class GRU(_RNNLayer):
    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0, **kwargs):
        super().__init__("gru", hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, **kwargs)
