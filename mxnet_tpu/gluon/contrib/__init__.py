"""gluon.contrib (reference: python/mxnet/gluon/contrib) — the
experimental-layer namespace; HybridConcurrent/Identity live in core nn
here but are re-exported under the reference's import path."""
from . import nn  # noqa: F401
from . import cnn  # noqa: F401
from . import rnn  # noqa: F401
from . import estimator  # noqa: F401
