"""gluon.contrib.cnn (reference: python/mxnet/gluon/contrib/cnn/
conv_layers.py) — DeformableConvolution block.

Two parameter sets, as in the reference: a regular convolution computes
the sampling offsets from the input, then the deformable convolution op
(src/operator/contrib/deformable_convolution.cc analog in
ndarray/ops_contrib.py — bilinear-gather im2col + one MXU matmul) applies
the main weights at the offset positions.
"""
from __future__ import annotations

from ..block import HybridBlock
from ..nn.conv_layers import _tuplize

__all__ = ["DeformableConvolution"]


class DeformableConvolution(HybridBlock):
    def __init__(self, channels, kernel_size=(1, 1), strides=(1, 1),
                 padding=(0, 0), dilation=(1, 1), groups=1,
                 num_deformable_group=1, layout="NCHW", use_bias=True,
                 in_channels=0, activation=None, weight_initializer=None,
                 bias_initializer="zeros",
                 offset_weight_initializer="zeros",
                 offset_bias_initializer="zeros", offset_use_bias=True,
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        if layout != "NCHW":
            raise ValueError("DeformableConvolution supports NCHW only")
        kernel_size = _tuplize(kernel_size, 2)
        strides = _tuplize(strides, 2)
        padding = _tuplize(padding, 2)
        dilation = _tuplize(dilation, 2)
        self._channels = channels
        self._in_channels = in_channels
        self._act_type = activation
        offset_channels = 2 * kernel_size[0] * kernel_size[1] * \
            num_deformable_group
        self._offset_channels = offset_channels
        self._kwargs = {
            "kernel": kernel_size, "stride": strides, "pad": padding,
            "dilate": dilation, "num_filter": channels,
            "num_group": groups,
            "num_deformable_group": num_deformable_group,
            "no_bias": not use_bias,
        }
        self._offset_kwargs = {
            "kernel": kernel_size, "stride": strides, "pad": padding,
            "dilate": dilation, "num_filter": offset_channels,
            "num_group": 1, "no_bias": not offset_use_bias,
        }
        cin_g = in_channels // groups if in_channels else 0
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(channels, cin_g) + kernel_size,
                init=weight_initializer, allow_deferred_init=True)
            self.offset_weight = self.params.get(
                "offset_weight",
                shape=(offset_channels, in_channels) + kernel_size,
                init=offset_weight_initializer, allow_deferred_init=True)
            if use_bias:
                self.bias = self.params.get(
                    "bias", shape=(channels,), init=bias_initializer,
                    allow_deferred_init=True)
            else:
                self.bias = None
            if offset_use_bias:
                self.offset_bias = self.params.get(
                    "offset_bias", shape=(offset_channels,),
                    init=offset_bias_initializer, allow_deferred_init=True)
            else:
                self.offset_bias = None

    def infer_shape(self, x, *args):
        cin = x.shape[1]
        groups = self._kwargs["num_group"]
        k = tuple(self._kwargs["kernel"])
        self.weight.shape = (self._channels, cin // groups) + k
        self.offset_weight.shape = (self._offset_channels, cin) + k

    def hybrid_forward(self, F, x, weight, offset_weight, bias=None,
                       offset_bias=None):
        if offset_bias is None:
            offset = F.Convolution(x, offset_weight, **self._offset_kwargs)
        else:
            offset = F.Convolution(x, offset_weight, offset_bias,
                                   **self._offset_kwargs)
        if bias is None:
            out = F.DeformableConvolution(x, offset, weight, **self._kwargs)
        else:
            out = F.DeformableConvolution(x, offset, weight, bias,
                                          **self._kwargs)
        if self._act_type:
            out = F.Activation(out, act_type=self._act_type)
        return out
