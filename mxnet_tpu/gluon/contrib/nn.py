"""gluon.contrib.nn (reference: python/mxnet/gluon/contrib/nn/
basic_layers.py) — re-exports for reference-parity imports:

    from mxnet_tpu.gluon.contrib.nn import HybridConcurrent, Identity
"""
from ..nn import HybridConcurrent, Identity  # noqa: F401

__all__ = ["HybridConcurrent", "Identity"]
