"""gluon.contrib.nn (reference: python/mxnet/gluon/contrib/nn/
basic_layers.py) — re-exports for reference-parity imports:

    from mxnet_tpu.gluon.contrib.nn import HybridConcurrent, Identity
"""
from ..nn import HybridConcurrent, Identity  # noqa: F401
from ..nn import BatchNorm as _BatchNorm

__all__ = ["HybridConcurrent", "Identity", "SyncBatchNorm"]


class SyncBatchNorm(_BatchNorm):
    """Cross-device synchronized BatchNorm (reference:
    python/mxnet/gluon/contrib/nn/basic_layers.py SyncBatchNorm over
    src/operator/contrib/sync_batch_norm.cc).

    TPU-native design: under the whole-step jit with the batch sharded
    over 'dp' (ShardedTrainer), ``jnp.mean`` over the batch axis of a
    sharded tensor IS the global mean — XLA GSPMD inserts the cross-chip
    reduction automatically.  So the plain BatchNorm lowering already has
    SyncBatchNorm semantics there; this subclass exists for API parity
    and accepts (and ignores) the reference's ``num_devices``/``key``
    knobs, which configured the hand-rolled NCCL reduction the compiler
    now owns.
    """

    def __init__(self, in_channels=0, num_devices=None, momentum=0.9,
                 epsilon=1e-5, center=True, scale=True,
                 use_global_stats=False, beta_initializer="zeros",
                 gamma_initializer="ones",
                 running_mean_initializer="zeros",
                 running_variance_initializer="ones", key=None, **kwargs):
        if num_devices is not None:
            import warnings
            warnings.warn(
                "SyncBatchNorm: cross-device stat sync holds under the "
                "sharded whole-step jit (ShardedTrainer); on the "
                "imperative multi-process path stats stay process-local "
                "— num_devices is ignored", stacklevel=2)
        super().__init__(axis=1, momentum=momentum, epsilon=epsilon,
                         center=center, scale=scale,
                         use_global_stats=use_global_stats,
                         beta_initializer=beta_initializer,
                         gamma_initializer=gamma_initializer,
                         running_mean_initializer=running_mean_initializer,
                         running_variance_initializer=(
                             running_variance_initializer),
                         in_channels=in_channels, **kwargs)
