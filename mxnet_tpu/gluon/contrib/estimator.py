"""Gluon Estimator: the high-level fit/evaluate loop with event handlers.

Reference parity: python/mxnet/gluon/contrib/estimator/ (estimator.py +
event_handler.py, 1.6+) — Estimator.fit drives epochs/batches over a
DataIter or DataLoader, updates metrics, and dispatches lifecycle events
(train begin/end, epoch begin/end, batch begin/end) to handler objects;
the stock handlers cover logging, validation, checkpointing, and early
stopping.

TPU-first notes: the step itself is the ordinary autograd-record +
Trainer.step path, so a hybridized net runs whole-graph jit; handlers
run on host between steps (their cost is hidden behind async dispatch
until a metric forces a sync).
"""
from __future__ import annotations

import logging
import time
from typing import List, Optional, Sequence

from ... import autograd as _autograd
from ... import metric as _metric
from ...base import MXNetError
from .. import Trainer as _Trainer
from .. import loss as _gloss

__all__ = ["Estimator", "TrainBegin", "TrainEnd", "EpochBegin",
           "EpochEnd", "BatchBegin", "BatchEnd", "StoppingHandler",
           "MetricHandler", "ValidationHandler", "LoggingHandler",
           "CheckpointHandler", "EarlyStoppingHandler"]


# -- event mixins (reference event_handler.py class names) ------------------

class TrainBegin:
    def train_begin(self, estimator):
        pass


class TrainEnd:
    def train_end(self, estimator):
        pass


class EpochBegin:
    def epoch_begin(self, estimator):
        pass


class EpochEnd:
    def epoch_end(self, estimator):
        pass


class BatchBegin:
    def batch_begin(self, estimator):
        pass


class BatchEnd:
    def batch_end(self, estimator):
        pass


class StoppingHandler(TrainBegin, BatchEnd, EpochEnd):
    """Stop after ``max_epoch`` epochs or ``max_batch`` total batches."""

    def __init__(self, max_epoch=None, max_batch=None):
        self.max_epoch = max_epoch
        self.max_batch = max_batch

    def train_begin(self, estimator):
        if self.max_epoch is not None:
            estimator.max_epoch = self.max_epoch

    def batch_end(self, estimator):
        if self.max_batch is not None and \
                estimator.processed_batches >= self.max_batch:
            estimator.stop_training = True

    def epoch_end(self, estimator):
        if self.max_epoch is not None and \
                estimator.current_epoch + 1 >= self.max_epoch:
            estimator.stop_training = True


class MetricHandler(EpochBegin, BatchEnd):
    """Reset train metrics per epoch; update them per batch."""

    def __init__(self, metrics):
        self.metrics = metrics

    def epoch_begin(self, estimator):
        for m in self.metrics:
            m.reset()

    def batch_end(self, estimator):
        for m in self.metrics:
            if isinstance(m, _metric.Loss):
                # loss metrics consume the batch LOSS, not (label, pred)
                m.update(0, estimator._batch_loss)
            else:
                m.update(estimator._batch_label, estimator._batch_pred)


class ValidationHandler(EpochEnd):
    """Run evaluate() on ``val_data`` every ``epoch_period`` epochs."""

    def __init__(self, val_data, eval_fn, epoch_period=1):
        self.val_data = val_data
        self.eval_fn = eval_fn
        self.epoch_period = epoch_period

    def epoch_end(self, estimator):
        if (estimator.current_epoch + 1) % self.epoch_period == 0:
            self.eval_fn(self.val_data)


class LoggingHandler(TrainBegin, TrainEnd, EpochEnd, BatchEnd):
    """Metric logging: per-epoch by default; ``log_interval=N`` adds a
    line every N batches (the reference's batch mode)."""

    def __init__(self, log_interval="epoch", metrics=None,
                 logger=None):
        if log_interval != "epoch" and (
                not isinstance(log_interval, int) or log_interval <= 0):
            raise MXNetError(
                "log_interval must be 'epoch' or a positive int")
        self.log_interval = log_interval
        self.metrics = metrics
        self.logger = logger or logging.getLogger("mxnet_tpu.estimator")
        self._t0 = None

    def train_begin(self, estimator):
        self._t0 = time.perf_counter()
        self.logger.info("Training begin: %s epochs",
                         estimator.max_epoch)

    def train_end(self, estimator):
        self.logger.info("Training finished in %.1fs",
                         time.perf_counter() - self._t0)

    def _line(self):
        return " ".join(f"{n}={v:.4f}" for n, v in
                        (m.get() for m in self.metrics or []))

    def batch_end(self, estimator):
        if self.log_interval == "epoch":
            return
        if estimator.processed_batches % self.log_interval == 0:
            ms = self.metrics or ([estimator.loss_metric]
                                  + estimator.train_metrics)
            line = " ".join(f"{n}={v:.4f}"
                            for n, v in (m.get() for m in ms))
            self.logger.info("[batch %d] %s",
                             estimator.processed_batches, line)

    def epoch_end(self, estimator):
        parts = []
        for m in (self.metrics or estimator.train_metrics):
            name, val = m.get()
            parts.append(f"{name}={val:.4f}")
        self.logger.info("[epoch %d] %s", estimator.current_epoch,
                         " ".join(parts))


class CheckpointHandler(TrainBegin, EpochEnd):
    """Save parameters (+ trainer states) per epoch; optionally only on
    monitored-metric improvement (``save_best``)."""

    def __init__(self, model_dir, model_prefix="model", monitor=None,
                 mode="min", save_best=False, epoch_period=1):
        import os
        self.model_dir = model_dir
        self.model_prefix = model_prefix
        self.monitor = monitor
        self.save_best = save_best
        self.epoch_period = epoch_period
        if mode not in ("min", "max"):
            raise MXNetError("CheckpointHandler mode must be min|max")
        self.mode = mode
        self.best = None
        os.makedirs(model_dir, exist_ok=True)

    def _better(self, v):
        if self.best is None:
            return True
        return v < self.best if self.mode == "min" else v > self.best

    def epoch_end(self, estimator):
        import os
        if (estimator.current_epoch + 1) % self.epoch_period:
            return
        prefix = os.path.join(self.model_dir, self.model_prefix)
        if self.save_best:
            if self.monitor is None:
                raise MXNetError("save_best requires a monitor metric")
            _, v = self.monitor.get()
            if not self._better(v):
                return
            self.best = v
            estimator.net.save_parameters(f"{prefix}-best.params")
        else:
            estimator.net.save_parameters(
                f"{prefix}-epoch{estimator.current_epoch}.params")
        if estimator.trainer is not None:
            # disk/permission errors must surface; only a trainer without
            # savable state is a legitimate no-op
            if hasattr(estimator.trainer, "save_states"):
                estimator.trainer.save_states(f"{prefix}.states")


class EarlyStoppingHandler(EpochEnd):
    """Stop when the monitored metric fails to improve ``patience``
    consecutive epochs (min_delta slack, reference semantics)."""

    def __init__(self, monitor, mode="min", patience=3, min_delta=0.0):
        if mode not in ("min", "max"):
            raise MXNetError("EarlyStoppingHandler mode must be min|max")
        self.monitor = monitor
        self.mode = mode
        self.patience = patience
        self.min_delta = min_delta
        self.best = None
        self.bad_epochs = 0

    def epoch_end(self, estimator):
        _, v = self.monitor.get()
        improved = self.best is None or (
            v < self.best - self.min_delta if self.mode == "min"
            else v > self.best + self.min_delta)
        if improved:
            self.best = v
            self.bad_epochs = 0
        else:
            self.bad_epochs += 1
            if self.bad_epochs >= self.patience:
                estimator.stop_training = True


class Estimator:
    """fit/evaluate driver (reference estimator.py).

    Parameters: ``net`` (Block), ``loss`` (gluon loss Block),
    ``train_metrics`` (EvalMetric or list), ``trainer`` (built from
    net.collect_params if omitted), ``context`` accepted for signature
    parity."""

    def __init__(self, net, loss, train_metrics=None, trainer=None,
                 context=None):
        del context
        self.net = net
        self.loss = loss
        if not isinstance(loss, _gloss.Loss):
            raise MXNetError("Estimator needs a gluon loss Block")
        if train_metrics is None:
            train_metrics = []
        elif isinstance(train_metrics, _metric.EvalMetric):
            train_metrics = [train_metrics]
        self.train_metrics = list(train_metrics) or [_metric.Accuracy()]
        self.loss_metric = _metric.Loss()
        # validation runs on CLONES so an epoch-end validation pass never
        # resets/overwrites the epoch's training statistics
        self.val_metrics = [type(m)() for m in self.train_metrics]
        self.trainer = trainer or _Trainer(
            net.collect_params(), "adam", {"learning_rate": 1e-3})
        self.stop_training = False
        self.current_epoch = 0
        self.processed_batches = 0
        self.max_epoch = None

    # -- evaluation --------------------------------------------------------
    def evaluate(self, val_data, val_metrics=None):
        metrics = val_metrics or self.val_metrics
        for m in metrics:
            m.reset()
        for batch in val_data:
            x, y = self._split(batch)
            pred = self.net(x)
            for m in metrics:
                m.update(y, pred)
        if hasattr(val_data, "reset"):
            val_data.reset()            # DataIter: rewind for next epoch
        return [m.get() for m in metrics]

    # -- training ----------------------------------------------------------
    def fit(self, train_data, val_data=None, epochs=1,
            event_handlers: Optional[Sequence] = None,
            batch_size: Optional[int] = None):
        handlers = self._default_handlers(val_data,
                                          list(event_handlers or []))
        self.max_epoch = epochs
        self.stop_training = False
        self.processed_batches = 0

        def fire(kind):
            for h in handlers:
                getattr(h, kind)(self) if hasattr(h, kind) else None

        fire("train_begin")
        for epoch in range(epochs):
            self.current_epoch = epoch
            fire("epoch_begin")
            for batch in train_data:
                fire("batch_begin")
                x, y = self._split(batch)
                bs = batch_size or (x.shape[0] if hasattr(x, "shape")
                                    else len(x))
                with _autograd.record():
                    pred = self.net(x)
                    loss = self.loss(pred, y)
                loss.backward()
                self.trainer.step(bs)
                self._batch_pred = pred
                self._batch_label = y
                self._batch_loss = loss
                self.processed_batches += 1
                fire("batch_end")
                if self.stop_training:
                    break
            if hasattr(train_data, "reset"):
                train_data.reset()
            fire("epoch_end")
            if self.stop_training:
                break
        fire("train_end")
        return self

    def _default_handlers(self, val_data, handlers: List):
        has = lambda t: any(isinstance(h, t) for h in handlers)  # noqa
        if not has(MetricHandler):
            handlers.insert(0, MetricHandler(
                [self.loss_metric] + self.train_metrics))
        if val_data is not None and not has(ValidationHandler):
            handlers.append(ValidationHandler(val_data, self.evaluate))
        if not has(StoppingHandler):
            handlers.append(StoppingHandler())
        return handlers

    @staticmethod
    def _split(batch):
        if hasattr(batch, "data"):          # DataBatch
            d = batch.data[0] if isinstance(batch.data, (list, tuple)) \
                else batch.data
            lb = batch.label[0] if isinstance(batch.label, (list, tuple)) \
                else batch.label
            return d, lb
        x, y = batch
        from ...ndarray import NDArray, array
        if not isinstance(x, NDArray):
            x = array(x)
        if not isinstance(y, NDArray):
            y = array(y)
        return x, y
