"""gluon.contrib.rnn — convolutional recurrent cells + variational dropout
(reference: python/mxnet/gluon/contrib/rnn/conv_rnn_cell.py and
rnn_cell.py VariationalDropoutCell).

Conv*Cell replaces the cells' FC gate projections with convolutions over
spatial state maps (h carries (C, H, W)); on TPU each step is still one
fused XLA computation — conv gates ride the MXU exactly like the dense
gates do.
"""
from __future__ import annotations

from ...base import MXNetError
from ..rnn.rnn_cell import RecurrentCell

__all__ = ["ConvRNNCell", "ConvLSTMCell", "ConvGRUCell",
           "VariationalDropoutCell"]


class _BaseConvRNNCell(RecurrentCell):
    def __init__(self, input_shape, hidden_channels, i2h_kernel, h2h_kernel,
                 i2h_pad, activation, prefix=None, params=None,
                 conv_layout="NCHW"):
        super().__init__(prefix=prefix, params=params)
        if conv_layout != "NCHW":
            raise MXNetError("conv cells support NCHW only")
        self._input_shape = tuple(input_shape)        # (C, H, W)
        self._channels = hidden_channels
        self._i2h_kernel = self._t2(i2h_kernel)
        self._h2h_kernel = self._t2(h2h_kernel)
        for k in self._h2h_kernel:
            if k % 2 == 0:
                raise MXNetError("h2h_kernel must be odd (state shape "
                                 "must be preserved)")
        self._i2h_pad = self._t2(i2h_pad)
        self._h2h_pad = tuple(k // 2 for k in self._h2h_kernel)
        self._activation = activation
        cin = self._input_shape[0]
        ng = self._num_gates
        with self.name_scope():
            self.i2h_weight = self.params.get(
                "i2h_weight",
                shape=(ng * hidden_channels, cin) + self._i2h_kernel,
                allow_deferred_init=True)
            self.h2h_weight = self.params.get(
                "h2h_weight",
                shape=(ng * hidden_channels, hidden_channels)
                + self._h2h_kernel,
                allow_deferred_init=True)
            self.i2h_bias = self.params.get(
                "i2h_bias", shape=(ng * hidden_channels,), init="zeros",
                allow_deferred_init=True)
            self.h2h_bias = self.params.get(
                "h2h_bias", shape=(ng * hidden_channels,), init="zeros",
                allow_deferred_init=True)

    @staticmethod
    def _t2(v):
        return (v, v) if isinstance(v, int) else tuple(v)

    @property
    def _num_gates(self):
        raise NotImplementedError

    def _state_shape(self, batch_size):
        _, h, w = self._input_shape
        # i2h stride 1: spatial dims preserved when i2h_pad matches the
        # kernel; the reference computes the conv output size the same way
        oh = h + 2 * self._i2h_pad[0] - self._i2h_kernel[0] + 1
        ow = w + 2 * self._i2h_pad[1] - self._i2h_kernel[1] + 1
        return (batch_size, self._channels, oh, ow)

    def state_info(self, batch_size=0):
        return [{"shape": self._state_shape(batch_size),
                 "__layout__": "NCHW"}]

    def _conv_gates(self, F, x, h, i2h_weight, h2h_weight, i2h_bias,
                    h2h_bias):
        ng = self._num_gates
        i2h = F.Convolution(x, i2h_weight, i2h_bias,
                            kernel=self._i2h_kernel, pad=self._i2h_pad,
                            num_filter=ng * self._channels)
        h2h = F.Convolution(h, h2h_weight, h2h_bias,
                            kernel=self._h2h_kernel, pad=self._h2h_pad,
                            num_filter=ng * self._channels)
        return i2h, h2h


class ConvRNNCell(_BaseConvRNNCell):
    """Vanilla conv recurrence: h' = act(conv(x) + conv(h))."""

    def __init__(self, input_shape, hidden_channels, i2h_kernel=(3, 3),
                 h2h_kernel=(3, 3), i2h_pad=(1, 1), activation="tanh",
                 prefix=None, params=None):
        super().__init__(input_shape, hidden_channels, i2h_kernel,
                         h2h_kernel, i2h_pad, activation, prefix, params)

    @property
    def _num_gates(self):
        return 1

    def hybrid_forward(self, F, x, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h, h2h = self._conv_gates(F, x, states[0], i2h_weight, h2h_weight,
                                    i2h_bias, h2h_bias)
        out = F.Activation(i2h + h2h, act_type=self._activation)
        return out, [out]


class ConvLSTMCell(_BaseConvRNNCell):
    """ConvLSTM (Shi et al. 2015; reference ConvLSTMCell).  Gate order
    i, f, c, o matches the dense LSTMCell."""

    def __init__(self, input_shape, hidden_channels, i2h_kernel=(3, 3),
                 h2h_kernel=(3, 3), i2h_pad=(1, 1), activation="tanh",
                 prefix=None, params=None):
        super().__init__(input_shape, hidden_channels, i2h_kernel,
                         h2h_kernel, i2h_pad, activation, prefix, params)

    @property
    def _num_gates(self):
        return 4

    def state_info(self, batch_size=0):
        s = self._state_shape(batch_size)
        return [{"shape": s, "__layout__": "NCHW"},
                {"shape": s, "__layout__": "NCHW"}]

    def hybrid_forward(self, F, x, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        h, c = states
        i2h, h2h = self._conv_gates(F, x, h, i2h_weight, h2h_weight,
                                    i2h_bias, h2h_bias)
        gates = i2h + h2h
        sl = F.split(gates, num_outputs=4, axis=1)
        in_gate = F.sigmoid(sl[0])
        forget_gate = F.sigmoid(sl[1])
        in_trans = F.Activation(sl[2], act_type=self._activation)
        out_gate = F.sigmoid(sl[3])
        next_c = forget_gate * c + in_gate * in_trans
        next_h = out_gate * F.Activation(next_c,
                                         act_type=self._activation)
        return next_h, [next_h, next_c]


class ConvGRUCell(_BaseConvRNNCell):
    """ConvGRU; gate order r, z, o matches the dense GRUCell."""

    def __init__(self, input_shape, hidden_channels, i2h_kernel=(3, 3),
                 h2h_kernel=(3, 3), i2h_pad=(1, 1), activation="tanh",
                 prefix=None, params=None):
        super().__init__(input_shape, hidden_channels, i2h_kernel,
                         h2h_kernel, i2h_pad, activation, prefix, params)

    @property
    def _num_gates(self):
        return 3

    def hybrid_forward(self, F, x, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        h = states[0]
        i2h, h2h = self._conv_gates(F, x, h, i2h_weight, h2h_weight,
                                    i2h_bias, h2h_bias)
        ii = F.split(i2h, num_outputs=3, axis=1)
        hh = F.split(h2h, num_outputs=3, axis=1)
        reset = F.sigmoid(ii[0] + hh[0])
        update = F.sigmoid(ii[1] + hh[1])
        cand = F.Activation(ii[2] + reset * hh[2],
                            act_type=self._activation)
        next_h = (1.0 - update) * cand + update * h
        return next_h, [next_h]


class VariationalDropoutCell(RecurrentCell):
    """One dropout mask per sequence, reused at every step (Gal &
    Ghahramani 2016; reference VariationalDropoutCell) — applied to the
    base cell's inputs, states, and outputs."""

    def __init__(self, base_cell, drop_inputs=0.0, drop_states=0.0,
                 drop_outputs=0.0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.base_cell = base_cell
        self.drop_inputs = drop_inputs
        self.drop_states = drop_states
        self.drop_outputs = drop_outputs
        self.reset_mask()

    def reset_mask(self):
        self._input_mask = None
        self._state_mask = None
        self._output_mask = None

    def state_info(self, batch_size=0):
        return self.base_cell.state_info(batch_size)

    def begin_state(self, batch_size=0, **kwargs):
        self.reset_mask()
        return self.base_cell.begin_state(batch_size, **kwargs)

    @staticmethod
    def _mask(nd, p, like):
        import numpy as np
        keep = 1.0 - p
        m = (np.random.rand(*like.shape) < keep).astype(np.float32) / keep
        return nd.array(m, ctx=like.context)

    def forward(self, x, states):
        from ... import autograd, ndarray as nd
        training = autograd.is_recording()
        if training and self.drop_inputs:
            if self._input_mask is None:
                self._input_mask = self._mask(nd, self.drop_inputs, x)
            x = x * self._input_mask
        if training and self.drop_states:
            if self._state_mask is None:
                self._state_mask = self._mask(nd, self.drop_states,
                                              states[0])
            states = [s * self._state_mask for s in states[:1]] + \
                list(states[1:])
        out, new_states = self.base_cell(x, states)
        if training and self.drop_outputs:
            if self._output_mask is None:
                self._output_mask = self._mask(nd, self.drop_outputs, out)
            out = out * self._output_mask
        return out, new_states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        self.reset_mask()
        return super().unroll(length, inputs, begin_state, layout,
                              merge_outputs, valid_length)
