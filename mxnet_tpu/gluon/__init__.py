"""Gluon: the imperative/hybrid frontend (reference: python/mxnet/gluon/)."""
from .parameter import Parameter, Constant, ParameterDict, \
    DeferredInitializationError
from .block import Block, CachedGraph, HybridBlock, SymbolBlock
from .trainer import Trainer
from . import nn
from . import loss
from . import utils
from .utils import split_and_load, split_data
from . import rnn
from . import data
from . import model_zoo
from . import contrib  # noqa: F401
