"""Gluon Block / HybridBlock: composable imperative models with a jit path.

Reference parity: python/mxnet/gluon/block.py (SURVEY.md §2.5, §3.3) —
Block (eager), HybridBlock (`hybridize()` → CachedOp), prefix/name scoping,
parameter collection, save/load.

TPU-native design (the survey's designated XLA lowering point, §7):
``hybridize()`` does NOT build an NNVM graph — it traces ``hybrid_forward``
with tracer-backed NDArrays into ONE jitted XLA computation per input
signature (shape/dtype tuple = the cache key, exactly the reference's
CachedOp signature match).  During the trace every descendant Parameter's
``data()`` is substituted by a function input (so weights are runtime
arguments, not baked constants), RNG draws split from a traced key input
(fresh dropout masks per call), and in-place writes to parameters (BatchNorm
running stats) are captured as extra outputs and written back after the call
— the functional translation of the reference's FMutateInputs.  Autograd
records the whole cached call as a single tape node via ``jax.vjp``,
mirroring CachedOp::Backward.
"""
from __future__ import annotations

import re
import threading
import warnings
from typing import Any, Dict, List, Optional, Tuple

from ..base import MXNetError
from ..context import Context, current_context
from .. import autograd as _autograd
from .. import random as _grandom
from ..ndarray import NDArray
from ..ndarray.register import _BoundedCache
from .. import ndarray as nd_mod
from .parameter import Parameter, ParameterDict, DeferredInitializationError

__all__ = ["Block", "HybridBlock", "SymbolBlock", "CachedGraph",
           "name_scope"]

_naming_counter_lock = threading.Lock()
_naming_counters: Dict[str, int] = {}


def _gen_prefix(hint: str) -> str:
    with _naming_counter_lock:
        idx = _naming_counters.get(hint, 0)
        _naming_counters[hint] = idx + 1
    return f"{hint}{idx}_"


class _BlockScope:
    """Prefix scoping: blocks created inside ``with parent.name_scope():``
    get the parent's prefix prepended (reference name manager)."""

    _current = threading.local()

    def __init__(self, block: "Block"):
        self._block = block
        self._counters: Dict[str, int] = {}

    @staticmethod
    def create(prefix: Optional[str], params, hint: str):
        cur = getattr(_BlockScope._current, "value", None)
        if cur is None:
            if prefix is None:
                prefix = _gen_prefix(hint)
            pd = ParameterDict(prefix, params)
            return prefix, pd
        if prefix is None:
            idx = cur._counters.get(hint, 0)
            cur._counters[hint] = idx + 1
            prefix = f"{hint}{idx}_"
        full = cur._block.prefix + prefix
        pd = ParameterDict(full, params if params is not None
                           else cur._block._params._shared)
        return full, pd

    def __enter__(self):
        self._old = getattr(_BlockScope._current, "value", None)
        _BlockScope._current.value = self
        return self

    def __exit__(self, *a):
        _BlockScope._current.value = self._old


class Block:
    """Base building block (reference: gluon.Block)."""

    def __init__(self, prefix: Optional[str] = None, params=None):
        hint = _camel_to_snake(type(self).__name__)
        self._prefix, self._params = _BlockScope.create(prefix, params, hint)
        self._scope = _BlockScope(self)
        self._children: Dict[str, Block] = {}
        self._reg_params: Dict[str, Parameter] = {}
        self._forward_hooks: List = []

    # -- attribute registration -------------------------------------------
    def __setattr__(self, name, value):
        if isinstance(value, Block):
            existing = self.__dict__.get("_children")
            if existing is not None:
                existing[name] = value
        elif isinstance(value, Parameter):
            reg = self.__dict__.get("_reg_params")
            if reg is not None:
                reg[name] = value
        super().__setattr__(name, value)

    @property
    def prefix(self) -> str:
        return self._prefix

    @property
    def name(self) -> str:
        return self._prefix[:-1] if self._prefix.endswith("_") else self._prefix

    @property
    def params(self) -> ParameterDict:
        return self._params

    def name_scope(self) -> _BlockScope:
        return self._scope

    # -- params ------------------------------------------------------------
    def collect_params(self, select: Optional[str] = None) -> ParameterDict:
        ret = ParameterDict(self._params.prefix)
        if select is None:
            ret.update(self._params)
        else:
            pat = re.compile(select)
            for name, p in self._params.items():
                if pat.match(name):
                    ret._params[name] = p
        for child in self._children.values():
            sub = child.collect_params(select)
            for k, v in sub.items():
                ret._params[k] = v
        return ret

    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False) -> None:
        self.collect_params().initialize(init, ctx, verbose, force_reinit)

    def register_child(self, block: "Block", name: Optional[str] = None) -> None:
        self._children[name or str(len(self._children))] = block

    def apply(self, fn) -> "Block":
        for child in self._children.values():
            child.apply(fn)
        fn(self)
        return self

    def cast(self, dtype) -> None:
        for child in self._children.values():
            child.cast(dtype)
        for p in self._params.values():
            p.cast(dtype)

    def hybridize(self, active: bool = True, **kwargs) -> None:
        for child in self._children.values():
            child.hybridize(active, **kwargs)

    # -- persistence ---------------------------------------------------------
    def _collect_params_with_prefix(self, prefix: str = "") -> Dict[str, Parameter]:
        """Structural (attribute-path) parameter names, e.g. ``0.weight`` —
        the reference's save_parameters naming, robust to prefix counters."""
        if prefix:
            prefix += "."
        ret = {prefix + key: p for key, p in self._reg_params.items()}
        for name, child in self._children.items():
            ret.update(child._collect_params_with_prefix(prefix + name))
        return ret

    def save_parameters(self, filename: str, deduplicate: bool = False) -> None:
        from ..ndarray import utils as nd_utils
        params = self._collect_params_with_prefix()
        arrs = {name: p.data() for name, p in params.items()}
        nd_utils.save(filename, arrs)

    def load_parameters(self, filename: str, ctx=None, allow_missing=False,
                        ignore_extra=False, cast_dtype=False,
                        dtype_source="current") -> None:
        from ..ndarray import utils as nd_utils
        loaded = nd_utils.load(filename)
        params = self._collect_params_with_prefix()
        if loaded and params and not any(k in params for k in loaded):
            # fall back: file saved with full prefixed names
            full = self.collect_params()
            loaded = {_strip(k, self.prefix): v for k, v in loaded.items()}
            params = {_strip(k, self.prefix): p for k, p in full.items()}
        for name, arr in loaded.items():
            if name not in params:
                if ignore_extra:
                    continue
                raise MXNetError(f"{filename} contains unknown parameter "
                                 f"{name!r}")
            p = params[name]
            if p._data is None and p._deferred_init is None and ctx is not None:
                p.initialize(ctx=ctx)
            p.set_data(arr)
        if not allow_missing:
            for name in params:
                if name not in loaded:
                    raise MXNetError(f"parameter {name!r} missing from "
                                     f"{filename}")

    save_params = save_parameters
    load_params = load_parameters

    # -- call ----------------------------------------------------------------
    def __call__(self, *args):
        out = self.forward(*args)
        for hook in self._forward_hooks:
            hook(self, args, out)
        return out

    def register_forward_hook(self, hook):
        self._forward_hooks.append(hook)

    def forward(self, *args):
        raise NotImplementedError

    def summary(self, *inputs):
        out = self(*inputs)
        total = sum(int(_prod(p.shape)) for p in self.collect_params().values())
        print(f"{type(self).__name__}: {total} parameters")
        return out

    def __repr__(self):
        lines = [f"{type(self).__name__}("]
        for name, child in self._children.items():
            c = repr(child).replace("\n", "\n  ")
            lines.append(f"  ({name}): {c}")
        lines.append(")")
        return "\n".join(lines)


def _prod(shape):
    n = 1
    for s in shape or ():
        n *= s
    return n


def _strip(name: str, prefix: str) -> str:
    return name[len(prefix):] if name.startswith(prefix) else name


def _camel_to_snake(name: str) -> str:
    return re.sub("([a-z0-9])([A-Z])", r"\1_\2", name).lower()


# ---------------------------------------------------------------------------
# Trace context: Parameter substitution + RNG threading during hybrid trace
# ---------------------------------------------------------------------------

class _TraceCtx:
    _current = threading.local()

    def __init__(self, substitutes: Dict[int, NDArray]):
        self.substitutes = substitutes   # id(Parameter) -> wrapper NDArray

    def __enter__(self):
        self._old = getattr(_TraceCtx._current, "value", None)
        _TraceCtx._current.value = self
        return self

    def __exit__(self, *a):
        _TraceCtx._current.value = self._old

    @staticmethod
    def active() -> Optional["_TraceCtx"]:
        return getattr(_TraceCtx._current, "value", None)


def _param_data_maybe_traced(param: Parameter, ctx) -> NDArray:
    tc = _TraceCtx.active()
    if tc is not None:
        sub = tc.substitutes.get(id(param))
        if sub is not None:
            return sub
    return Parameter.data(param, ctx)


class HybridBlock(Block):
    """A Block whose forward can be lowered to one XLA computation."""

    #: max cached compiled graphs per block (distinct shape/dtype/mode
    #: signatures).  LRU-evicted beyond this — each entry pins a full XLA
    #: executable, so an unbounded dict under shape-diverse inputs (the
    #: recompile storm) was a process-lifetime memory leak.  Raise it for
    #: genuinely many-bucket workloads (BucketingModule-style).
    CACHED_GRAPH_LIMIT = 32

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._active = False
        self._cached_graph = _BoundedCache(self.CACHED_GRAPH_LIMIT)
        self._flags: Dict[str, Any] = {}
        self._recompile_warned = False

    def hybridize(self, active: bool = True, static_alloc: bool = False,
                  static_shape: bool = False, inline_limit: int = 2,
                  **kwargs) -> None:
        self._active = active
        self._flags = dict(static_alloc=static_alloc,
                           static_shape=static_shape, **kwargs)
        self._cached_graph = _BoundedCache(self.CACHED_GRAPH_LIMIT)
        super().hybridize(False, **kwargs)  # children run inside our trace

    def cast(self, dtype):
        self._cached_graph = _BoundedCache(self.CACHED_GRAPH_LIMIT)
        super().cast(dtype)

    def infer_shape(self, *args) -> None:
        """Layer-specific deferred-shape resolution; subclasses with deferred
        params override (Dense/Conv/BatchNorm/...)."""
        raise MXNetError(
            f"{type(self).__name__} has uninitialized parameters with "
            f"unknown shape and no infer_shape; give explicit in_units/"
            f"in_channels")

    # -- forward dispatch --------------------------------------------------
    def forward(self, x, *args):
        from ..symbol import Symbol
        if isinstance(x, Symbol):
            kwargs = {k: p.var() for k, p in self._reg_params.items()}
            from .. import symbol as sym_mod
            return self.hybrid_forward(sym_mod, x, *args, **kwargs)
        if not isinstance(x, NDArray):
            raise MXNetError(f"forward expects NDArray/Symbol, got {type(x)}")
        ctx = x.context
        try:
            params = {k: _param_data_maybe_traced(p, ctx)
                      for k, p in self._reg_params.items()}
        except DeferredInitializationError:
            self._deferred_infer(x, *args)
            params = {k: _param_data_maybe_traced(p, ctx)
                      for k, p in self._reg_params.items()}
        if self._active and _TraceCtx.active() is None:
            try:
                return self._call_cached(x, *args)
            except DeferredInitializationError:
                pass  # first call runs eagerly to settle child deferred shapes
        return self.hybrid_forward(nd_mod, x, *args, **params)

    def _deferred_infer(self, *args) -> None:
        self.infer_shape(*args)
        for p in self._reg_params.values():
            p._finish_deferred_init()

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError

    # -- the CachedOp analog ----------------------------------------------
    def _ordered_params(self, ctx) -> List[Parameter]:
        # warm all deferred inits by the eager path having run already
        return list(self.collect_params().values())

    def _call_cached(self, *inputs):
        ctx = inputs[0].context
        training = _autograd.is_training()
        sig = (tuple((tuple(a.shape), str(a.dtype)) for a in inputs),
               training, ctx)
        entry = self._cached_graph.get(sig)
        if entry is None:
            entry = self._build_cached(inputs, training, ctx)
            evicting = (self._cached_graph.cache_info()["currsize"]
                        >= self.CACHED_GRAPH_LIMIT)
            self._cached_graph.put(sig, entry)
            if evicting and not self._recompile_warned:
                self._recompile_warned = True
                warnings.warn(
                    f"HybridBlock {self.name!r} compiled more than "
                    f"{self.CACHED_GRAPH_LIMIT} distinct input "
                    "signatures; oldest executables are now LRU-"
                    "evicted (recompile storm — consider bucketing "
                    "input shapes or raising CACHED_GRAPH_LIMIT)",
                    RuntimeWarning, stacklevel=3)
        jitted, jitted_vjp, params, meta = entry
        n_outs_cell, write_idx_cell, infer_cell = meta

        pvals = [p.data(ctx)._read() for p in params]
        invals = [a._read() for a in inputs]
        key = _grandom.next_key()

        recording = _autograd.is_recording() and (
            any(p.data(ctx)._ag is not None for p in params) or
            any(getattr(a, "_ag", None) is not None for a in inputs))
        if recording:
            flat, vjp_fn = jitted_vjp(key, *pvals, *invals)
        elif infer_cell[0] is not None:
            # persistent-cache path: the AOT executable deserialized (or
            # compiled once) at build time — same computation, no jit
            # re-trace on a fresh process.  AOT calls are
            # signature-strict; an aval surprise (weak-type drift)
            # degrades permanently to the plain jit path rather than
            # failing the forward.
            try:
                flat = infer_cell[0](key, *pvals, *invals)
            except TypeError:
                infer_cell[0] = None
                flat = jitted(key, *pvals, *invals)
        else:
            flat = jitted(key, *pvals, *invals)

        n_outs = n_outs_cell[0]
        write_idx = write_idx_cell[0]
        outs = [NDArray(v, ctx=ctx) for v in flat[:n_outs]]

        # write back captured aux mutations (running stats)
        if write_idx:
            with _autograd.pause():
                for pos, pi in enumerate(write_idx):
                    params[pi].data(ctx)._set_data(flat[n_outs + pos])

        if recording:
            parents = [None]  # rng key input
            for p in params:
                parents.append(p.data(ctx)._ag)
            for a in inputs:
                parents.append(getattr(a, "_ag", None))
            node = _autograd.TapeNode(
                f"CachedOp[{self.name}]", vjp_fn, parents,
                [(o.shape, o.dtype) for o in outs] +
                [(flat[n_outs + i].shape, flat[n_outs + i].dtype)
                 for i in range(len(write_idx))],
                True)
            # tape sees the flat tuple; only real outs get user cotangents
            for i, o in enumerate(outs):
                o._ag = _autograd.AGInfo(node=node, index=i)
        return outs[0] if n_outs == 1 else tuple(outs)

    def _build_cached(self, inputs, training, ctx):
        import jax
        # ensure deferred params are resolved by one eager run if needed
        params = self._ordered_params(ctx)
        n_outs_cell = [None]
        write_idx_cell = [None]
        block = self
        n_params = len(params)

        def pure_fn(key, *vals):
            pvals = vals[:n_params]
            invals = vals[n_params:]
            wrappers = [NDArray(v, ctx=ctx) for v in pvals]
            win = [NDArray(v, ctx=ctx) for v in invals]
            subs = {id(p): w for p, w in zip(params, wrappers)}
            with _TraceCtx(subs), \
                    _autograd._RecordingScope(False, training), \
                    _KeyScope(key):
                out = block.hybrid_forward_entry(*win)
            outs = list(out) if isinstance(out, (list, tuple)) else [out]
            out_vals = [o._read() for o in outs]
            writes = [(i, w._read()) for i, w in enumerate(wrappers)
                      if w._version > 0]
            n_outs_cell[0] = len(out_vals)
            write_idx_cell[0] = [i for i, _ in writes]
            return tuple(out_vals) + tuple(v for _, v in writes)

        jitted = jax.jit(pure_fn)
        # cached vjp wrapper for the training path: a bare jax.vjp would
        # re-linearize the whole graph in Python EVERY step.  vjp of the
        # JITTED fn (not raw pure_fn) keeps the linearized jaxpr a single
        # pjit eqn, so the returned vjp_fn's transpose also runs as ONE
        # compiled call rather than eager per-primitive dispatch.
        jitted_vjp = jax.jit(lambda *a: jax.vjp(jitted, *a))
        # persistent compile cache (MXTPU_COMPILE_CACHE_DIR): AOT-lower
        # the inference executable now and resolve it through the disk
        # tier, keyed on the lowered StableHLO + backend fingerprint —
        # a fresh process deserializes instead of compiling (the
        # ModelServer cold-start / auto-resume fast path).  Inference
        # only: the training vjp closure's pytree is not a stable
        # serialization target (jax's own persistent cache, pointed at
        # the same dir, covers that jit path instead).
        infer_cell = [None]
        if not training:
            try:
                from ..tuning import compile_cache as _cc
                if _cc.active() is not None:
                    # lower against the CONCRETE values (exact avals,
                    # weak types included — an AOT executable is
                    # signature-strict); the sample key has the same
                    # aval as every _grandom.next_key() draw
                    sample_key = jax.random.PRNGKey(0)
                    vals = [p.data(ctx)._read() for p in params] + \
                           [a._read() for a in inputs]
                    lowered = jitted.lower(sample_key, *vals)
                    infer_cell[0] = _cc.aot_compile(lowered, "graph")
            except Exception:   # noqa: BLE001 — AOT/serialization drift
                infer_cell[0] = None   # degrades to the plain jit path
        return jitted, jitted_vjp, params, (n_outs_cell, write_idx_cell,
                                            infer_cell)

    def hybrid_forward_entry(self, *inputs):
        """Entry used during trace: routes through forward so nested blocks
        participate (their params substitute via the trace context)."""
        ctx = inputs[0].context
        params = {k: _param_data_maybe_traced(p, ctx)
                  for k, p in self._reg_params.items()}
        return self.hybrid_forward(nd_mod, *inputs, **params)

    def cached_graph(self, *inputs, entry: str = "forward"
                     ) -> "CachedGraph":
        """Freeze ONE compiled inference signature into a
        :class:`CachedGraph` — the direct cached-graph entry the serving
        subsystem dispatches through (no autograd bookkeeping, no
        per-call parameter re-read, no aux write-back).

        ``inputs`` is an example batch (NDArrays, or anything
        ``nd.array`` accepts) whose shapes/dtypes define the signature.
        The same per-signature cache ``hybridize()`` fills is reused, so
        a block that already served this signature through ``block(x)``
        hands back the *identical* executable; the call compiles (and
        warms) the graph before returning, so the first real request
        never pays the compile.

        ``entry`` selects the traced method: ``"forward"`` (the default,
        ``hybrid_forward``) or a generation variant the block implements
        — ``"prefill"`` traces ``hybrid_prefill`` (prompt pass: scatters
        K/V into the block pool, returns last-position logits) and
        ``"decode"`` traces ``hybrid_decode`` (one token per running
        slot; the carried state is the KV pool, passed in and returned).
        Non-forward entries compile once per input signature — for
        decode that means once per (slot-count, max-blocks) pair — and
        resolve through the persistent compile cache exactly like the
        forward graph, so a warm process restart skips the XLA compile."""
        if entry != "forward":
            return self._cached_entry_graph(entry, inputs)
        inputs = tuple(a if isinstance(a, NDArray) else nd_mod.array(a)
                       for a in inputs)
        ctx = inputs[0].context
        with _autograd.pause():
            # one eager pass settles every deferred shape (children
            # included) exactly as the hybridize path's first call does
            try:
                self(*inputs)
            except DeferredInitializationError:
                self._deferred_infer(*inputs)
                self(*inputs)
            sig = (tuple((tuple(a.shape), str(a.dtype)) for a in inputs),
                   False, ctx)
            entry = self._cached_graph.get(sig)
            if entry is None:
                entry = self._build_cached(inputs, False, ctx)
                self._cached_graph.put(sig, entry)
            jitted, _jitted_vjp, params, meta = entry
            n_outs_cell, _write_idx_cell, infer_cell = meta
            pvals = [p.data(ctx)._read() for p in params]
            # inference mode disables dropout, so the RNG input is dead:
            # pin one key now and __call__ stays allocation-free and
            # deterministic
            key = _grandom.next_key()
            import jax
            # serve through the persistent-cache AOT executable when one
            # resolved at build time — on a warm restart that skipped
            # the XLA compile entirely (the ModelServer cold-start path)
            entry_fn = infer_cell[0] if infer_cell[0] is not None \
                else jitted
            try:
                flat = entry_fn(key, *pvals,
                                *[a._read() for a in inputs])
            except TypeError:
                if entry_fn is jitted:
                    raise
                infer_cell[0] = None       # aval drift: jit path forever
                entry_fn = jitted
                flat = entry_fn(key, *pvals,
                                *[a._read() for a in inputs])
            jax.block_until_ready(flat)        # compile + warm, here
        return CachedGraph(entry_fn, pvals, key, n_outs_cell[0], ctx,
                           self.name)

    def _cached_entry_graph(self, entry: str, inputs) -> "CachedGraph":
        """Non-forward cached-graph entry (``hybrid_prefill`` /
        ``hybrid_decode``): same trace-compile-warm flow as the forward
        path, keyed separately per entry name so one block can hold its
        prompt buckets and its decode-step signatures side by side."""
        import jax
        method_name = "hybrid_" + entry
        if not callable(getattr(self, method_name, None)):
            raise AttributeError(
                f"{type(self).__name__} has no {method_name}(); a "
                f"generation-servable block implements hybrid_prefill "
                f"and hybrid_decode (see serving.ModelServer docs)")
        inputs = tuple(a if isinstance(a, NDArray) else nd_mod.array(a)
                       for a in inputs)
        ctx = inputs[0].context
        with _autograd.pause():
            sig = (entry,
                   tuple((tuple(a.shape), str(a.dtype)) for a in inputs),
                   False, ctx)
            cached = self._cached_graph.get(sig)
            if cached is None:
                cached = self._build_entry_cached(method_name, inputs, ctx)
                self._cached_graph.put(sig, cached)
            jitted, params, n_outs_cell, infer_cell = cached
            pvals = [p.data(ctx)._read() for p in params]
            # generation graphs are inference-only: dropout is off, the
            # RNG input is dead — pin one key (same discipline as the
            # forward path) so dispatch stays allocation-free
            key = _grandom.next_key()
            entry_fn = infer_cell[0] if infer_cell[0] is not None \
                else jitted
            try:
                flat = entry_fn(key, *pvals,
                                *[a._read() for a in inputs])
            except TypeError:
                if entry_fn is jitted:
                    raise
                infer_cell[0] = None   # aval drift: jit path forever
                entry_fn = jitted
                flat = entry_fn(key, *pvals,
                                *[a._read() for a in inputs])
            jax.block_until_ready(flat)    # compile + warm, here
        return CachedGraph(entry_fn, pvals, key, n_outs_cell[0], ctx,
                           f"{self.name}:{entry}")

    def _build_entry_cached(self, method_name, inputs, ctx):
        """Trace one generation entry into a jitted fn (+ AOT cell).
        Mirrors ``_build_cached`` minus everything inference never
        needs: no vjp, no aux write-back (generation entries thread
        their state — the KV pool — explicitly as an output)."""
        import jax
        params = self._ordered_params(ctx)
        n_outs_cell = [None]
        block = self
        n_params = len(params)
        method = getattr(block, method_name)

        def pure_fn(key, *vals):
            pvals = vals[:n_params]
            invals = vals[n_params:]
            wrappers = [NDArray(v, ctx=ctx) for v in pvals]
            win = [NDArray(v, ctx=ctx) for v in invals]
            subs = {id(p): w for p, w in zip(params, wrappers)}
            with _TraceCtx(subs), \
                    _autograd._RecordingScope(False, False), \
                    _KeyScope(key):
                pkw = {k: _param_data_maybe_traced(p, ctx)
                       for k, p in block._reg_params.items()}
                out = method(nd_mod, *win, **pkw)
            outs = list(out) if isinstance(out, (list, tuple)) else [out]
            out_vals = [o._read() for o in outs]
            n_outs_cell[0] = len(out_vals)
            return tuple(out_vals)

        jitted = jax.jit(pure_fn)
        # persistent compile cache: same disk tier as the forward graph
        # (key = lowered StableHLO + backend fingerprint), so a server
        # restart populates every decode-step signature with
        # deserialization instead of XLA compiles
        infer_cell = [None]
        try:
            from ..tuning import compile_cache as _cc
            if _cc.active() is not None:
                sample_key = jax.random.PRNGKey(0)
                vals = [p.data(ctx)._read() for p in params] + \
                       [a._read() for a in inputs]
                lowered = jitted.lower(sample_key, *vals)
                infer_cell[0] = _cc.aot_compile(lowered, "graph")
        except Exception:   # noqa: BLE001 — AOT/serialization drift
            infer_cell[0] = None   # degrades to the plain jit path
        return jitted, params, n_outs_cell, infer_cell

    def export(self, path: str, epoch: int = 0) -> Tuple[str, str]:
        """Reference parity: save -symbol.json + -%04d.params for the
        SymbolBlock / predict path."""
        from ..symbol import Symbol
        from .. import symbol as sym_mod
        data = Symbol.var("data")
        out = self(data)
        sym_file = f"{path}-symbol.json"
        out.save(sym_file)
        params_file = f"{path}-{epoch:04d}.params"
        from ..ndarray import utils as nd_utils
        arrs = {}
        for name, p in self.collect_params().items():
            arrs[f"arg:{name}"] = p.data()
        nd_utils.save(params_file, arrs)
        return sym_file, params_file


class CachedGraph:
    """Inference-only handle over one compiled cached-graph signature —
    the CachedOp artifact a model server wants (PAPER.md L6a), with
    everything the serving hot path must not pay stripped off:

    - **no autograd bookkeeping** — no vjp build, no TapeNode, no
      parent scan; inference never backprops;
    - **no per-call parameter re-read** — parameter device values were
      snapshotted at freeze time (weights are immutable while serving;
      re-freeze after loading new ones);
    - **no aux write-back** — the graph was traced in inference mode
      (``training=False``) and any residual mutation outputs are
      dropped, never written back: a server must not corrupt running
      stats;
    - **pinned RNG key** — dropout is off in inference mode, so the key
      input is dead; pinning it keeps calls allocation-free and
      bit-deterministic.

    ``raw(*values)`` is the lean entry (numpy/jax values in, tuple of
    jax arrays out — what ``serving.ModelServer`` dispatches per
    batch); ``__call__`` wraps NDArrays for parity with ``block(x)``.
    """

    __slots__ = ("_jitted", "_pvals", "_key", "_n_outs", "_ctx", "name")

    def __init__(self, jitted, pvals, key, n_outs, ctx, name):
        self._jitted = jitted
        self._pvals = tuple(pvals)
        self._key = key
        self._n_outs = n_outs
        self._ctx = ctx
        self.name = name

    @property
    def n_outputs(self) -> int:
        return self._n_outs

    def raw(self, *values):
        """One compiled call: raw array values in (numpy or jax), tuple
        of raw jax arrays out.  No NDArray wrappers, no tape, no sync."""
        flat = self._jitted(self._key, *self._pvals, *values)
        return flat[:self._n_outs]

    def __call__(self, *inputs):
        vals = [a._read() if isinstance(a, NDArray) else a
                for a in inputs]
        outs = [NDArray(v, ctx=self._ctx) for v in self.raw(*vals)]
        return outs[0] if len(outs) == 1 else tuple(outs)


class _KeyScope:
    """Push a traced RNG key for the duration of a hybrid trace so random
    ops draw from a runtime input, not a baked constant."""

    def __init__(self, key):
        self._key = key

    def __enter__(self):
        _grandom.push_key(self._key)
        return self

    def __exit__(self, *a):
        _grandom.pop_key()


class SymbolBlock(Block):
    """Construct a Block from a Symbol graph + params (reference:
    gluon.SymbolBlock.imports for serving exported models)."""

    def __init__(self, outputs, inputs, params=None):
        super().__init__(prefix="symbolblock_", params=None)
        self._outputs = outputs
        self._inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        self._arg_params = params or {}

    @staticmethod
    def imports(symbol_file: str, input_names, param_file: Optional[str] = None,
                ctx=None):
        from ..symbol import load as sym_load
        sym = sym_load(symbol_file)
        params = {}
        if param_file:
            from ..ndarray import utils as nd_utils
            loaded = nd_utils.load(param_file)
            for k, v in loaded.items():
                name = k.split(":", 1)[1] if ":" in k else k
                if ctx is not None:
                    v = v.as_in_context(ctx)
                params[name] = v
        if isinstance(input_names, str):
            input_names = [input_names]
        from ..symbol import Symbol
        inputs = [Symbol.var(n) for n in input_names]
        return SymbolBlock(sym, inputs, params)

    def forward(self, *args):
        feed = {s.name: a for s, a in zip(self._inputs, args)}
        feed.update(self._arg_params)
        return self._outputs.eval_dict(feed)


def name_scope():
    raise MXNetError("use block.name_scope() on a Block instance")
