"""Data iterators: the ``mx.io`` namespace.

Reference parity: python/mxnet/io/io.py (DataIter/DataBatch/DataDesc,
NDArrayIter, PrefetchingIter, ResizeIter) and the C++ iterators in
src/io/ — MNISTIter (iter_mnist.cc), CSVIter (iter_csv.cc), and
ImageRecordIter (iter_image_recordio_2.cc) — see SURVEY.md §2.4.

TPU-native design: the reference's C++ pipeline exists to keep JPEG
decode + augmentation off the training thread; ImageRecordIter uses the
in-tree native C++ core (mxnet_tpu/native/io_core.cc — mmap'd RecordIO +
libjpeg decode + augment on a worker pool, built on demand with g++ and
driven through ctypes, which releases the GIL for the whole batch fill),
falling back to a pool of Python decode threads (PIL releases the GIL in
JPEG decode) when the toolchain is unavailable.  Batches surface as host
numpy first and move to device in one transfer, which is the right shape
for TPU feeding (few large H2D copies, never per-sample).
"""
from __future__ import annotations

import collections
import os
import struct
import threading
import queue as _queue
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import numpy as _np

from .base import MXNetError
from .context import cpu
from .ndarray import NDArray, array as nd_array
from .observability.registry import registry as _metrics_registry
from .observability.trace import span as _span

__all__ = ["DataDesc", "DataBatch", "DataIter", "NDArrayIter", "CSVIter",
           "MNISTIter", "ImageRecordIter", "PrefetchingIter", "ResizeIter",
           "LibSVMIter"]


class DataDesc(collections.namedtuple("DataDesc", ["name", "shape", "dtype",
                                                   "layout"])):
    """Name/shape/dtype/layout of one input stream (reference: io.DataDesc)."""

    def __new__(cls, name, shape, dtype=_np.float32, layout="NCHW"):
        return super().__new__(cls, name, tuple(shape), _np.dtype(dtype),
                               layout)

    @staticmethod
    def get_batch_axis(layout: Optional[str]) -> int:
        return 0 if not layout else layout.find("N")


class DataBatch:
    """One minibatch: data/label lists + padding bookkeeping."""

    def __init__(self, data, label=None, pad=0, index=None,
                 bucket_key=None, provide_data=None, provide_label=None):
        self.data = data if isinstance(data, (list, tuple)) or data is None \
            else [data]
        self.label = label if isinstance(label, (list, tuple)) \
            or label is None else [label]
        self.pad = pad
        self.index = index
        self.bucket_key = bucket_key
        self.provide_data = provide_data
        self.provide_label = provide_label

    def __repr__(self):
        shapes = [getattr(d, "shape", None) for d in (self.data or [])]
        return f"DataBatch: data shapes: {shapes} pad: {self.pad}"


class DataIter:
    """Base iterator (reference: io.DataIter).  Subclasses implement
    ``next()`` raising StopIteration, plus ``reset()``."""

    def __init__(self, batch_size: int = 0):
        self.batch_size = batch_size

    def __iter__(self):
        self.reset()
        return self

    def __next__(self):
        return self.next()

    def reset(self) -> None:
        pass

    def next(self) -> DataBatch:
        if self.iter_next():
            return DataBatch(self.getdata(), self.getlabel(),
                             pad=self.getpad(), index=self.getindex())
        raise StopIteration

    # low-level protocol (only used if next() is not overridden)
    def iter_next(self) -> bool:
        return False

    def getdata(self):
        return None

    def getlabel(self):
        return None

    def getindex(self):
        return None

    def getpad(self):
        return 0


def _init_data(data, allow_empty: bool, default_name: str):
    """Normalize data into an ordered list of (name, numpy array)."""
    if data is None:
        if not allow_empty:
            raise MXNetError("data cannot be None")
        return []
    if isinstance(data, (_np.ndarray, NDArray)):
        data = [data]
    if isinstance(data, (list, tuple)):
        if len(data) == 1:
            data = {default_name: data[0]}
        else:
            data = {f"_{i}_{default_name}": d for i, d in enumerate(data)}
    out = []
    for k, v in data.items():
        if isinstance(v, NDArray):
            v = v.asnumpy()
        out.append((k, _np.asarray(v)))
    return out


class NDArrayIter(DataIter):
    """Iterate over in-memory arrays (reference: io.NDArrayIter), with
    shuffle and ``last_batch_handle`` in {'pad', 'discard', 'roll_over'}."""

    def __init__(self, data, label=None, batch_size: int = 1,
                 shuffle: bool = False, last_batch_handle: str = "pad",
                 data_name: str = "data", label_name: str = "softmax_label"):
        super().__init__(batch_size)
        self.data = _init_data(data, False, data_name)
        self.label = _init_data(label, True, label_name)
        self.num_data = self.data[0][1].shape[0]
        for _, arr in self.data + self.label:
            if arr.shape[0] != self.num_data:
                raise MXNetError("all data/label arrays must share axis 0")
        if last_batch_handle not in ("pad", "discard", "roll_over"):
            raise MXNetError(f"bad last_batch_handle {last_batch_handle!r}")
        self.shuffle = shuffle
        self.last_batch_handle = last_batch_handle
        self.idx = _np.arange(self.num_data)
        # seeded from the GLOBAL numpy stream so mx.random.seed()/the test
        # harness's per-test seeding controls shuffle order (reference
        # parity: the C++ iterators draw from the seeded global RNG)
        self._rng = _np.random.default_rng(
            _np.random.randint(0, 2 ** 31))
        self.cursor = -batch_size
        self._carry = _np.empty(0, dtype=_np.int64)
        self._epoch_idx = self.idx
        self.reset()

    @property
    def provide_data(self) -> List[DataDesc]:
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
                for k, v in self.data]

    @property
    def provide_label(self) -> List[DataDesc]:
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
                for k, v in self.label]

    def reset(self) -> None:
        if self.shuffle:
            self._rng.shuffle(self.idx)
        # roll_over: the partial tail of the previous epoch is served first
        # (reference NDArrayIter roll_over semantics — no sample skipped,
        # no sample duplicated)
        if self._carry.size:
            self._epoch_idx = _np.concatenate([self._carry, self.idx])
            self._carry = _np.empty(0, dtype=_np.int64)
        else:
            self._epoch_idx = self.idx
        self.cursor = -self.batch_size

    def iter_next(self) -> bool:
        self.cursor += self.batch_size
        n = len(self._epoch_idx)
        if self.last_batch_handle in ("discard", "roll_over"):
            return self.cursor + self.batch_size <= n
        return self.cursor < n

    def next(self) -> DataBatch:
        if not self.iter_next():
            if self.last_batch_handle == "roll_over":
                self._carry = self._epoch_idx[self.cursor:].astype(
                    _np.int64)
            raise StopIteration
        data = [self._slice(arr) for _, arr in self.data]
        label = [self._slice(arr) for _, arr in self.label]
        pad = self.getpad()
        return DataBatch([nd_array(d, ctx=cpu()) for d in data],
                         [nd_array(l, ctx=cpu()) for l in label],
                         pad=pad, index=None,
                         provide_data=self.provide_data,
                         provide_label=self.provide_label)

    def _slice(self, arr: _np.ndarray) -> _np.ndarray:
        n = len(self._epoch_idx)
        start = max(self.cursor, 0)
        end = self.cursor + self.batch_size
        sel = self._epoch_idx[start:min(end, n)]
        out = arr[sel]
        if out.shape[0] < self.batch_size:
            # pad by wrapping to the front (reference 'pad' semantics)
            extra = arr[self._epoch_idx[:self.batch_size - out.shape[0]]]
            out = _np.concatenate([out, extra], axis=0)
        return out

    def getpad(self) -> int:
        if self.last_batch_handle == "pad" and \
                self.cursor + self.batch_size > len(self._epoch_idx):
            return self.cursor + self.batch_size - len(self._epoch_idx)
        return 0


class CSVIter(DataIter):
    """Iterate rows of CSV file(s) (reference: src/io/iter_csv.cc).

    ``data_csv``/``label_csv`` paths; ``data_shape`` is the per-example
    shape the flat row reshapes to."""

    def __init__(self, data_csv: str, data_shape: Sequence[int],
                 label_csv: Optional[str] = None,
                 label_shape: Sequence[int] = (1,), batch_size: int = 1,
                 round_batch: bool = True, dtype=_np.float32, **kwargs):
        super().__init__(batch_size)
        data = _np.loadtxt(data_csv, delimiter=",", dtype=dtype, ndmin=2)
        data = data.reshape((-1,) + tuple(data_shape))
        if label_csv is not None:
            label = _np.loadtxt(label_csv, delimiter=",", dtype=dtype,
                                ndmin=2).reshape((-1,) + tuple(label_shape))
        else:
            label = _np.zeros((data.shape[0],) + tuple(label_shape),
                              dtype=dtype)
        self._inner = NDArrayIter(
            {"data": data}, {"label": label}, batch_size,
            last_batch_handle="pad" if round_batch else "discard",
            label_name="label")

    @property
    def provide_data(self):
        return self._inner.provide_data

    @property
    def provide_label(self):
        return self._inner.provide_label

    def reset(self):
        self._inner.reset()

    def next(self):
        return self._inner.next()


class LibSVMIter(DataIter):
    """Iterate libsvm-format sparse rows (reference: src/io/iter_libsvm.cc).

    Rows are materialized CSR-style; batches surface as CSRNDArray."""

    def __init__(self, data_libsvm: str, data_shape: Sequence[int],
                 label_libsvm: Optional[str] = None, batch_size: int = 1,
                 round_batch: bool = True, **kwargs):
        super().__init__(batch_size)
        self._num_col = int(_np.prod(data_shape))
        labels, indptr, indices, values = [], [0], [], []
        with open(data_libsvm) as f:
            for line in f:
                parts = line.strip().split()
                if not parts:
                    continue
                labels.append(float(parts[0]))
                for tok in parts[1:]:
                    i, v = tok.split(":")
                    indices.append(int(i))
                    values.append(float(v))
                indptr.append(len(indices))
        self._labels = _np.asarray(labels, dtype=_np.float32)
        self._indptr = _np.asarray(indptr, dtype=_np.int64)
        self._indices = _np.asarray(indices, dtype=_np.int64)
        self._values = _np.asarray(values, dtype=_np.float32)
        self._round_batch = round_batch
        self.num_data = len(labels)
        self.cursor = -batch_size

    @property
    def provide_data(self):
        return [DataDesc("data", (self.batch_size, self._num_col))]

    @property
    def provide_label(self):
        return [DataDesc("label", (self.batch_size,))]

    def reset(self):
        self.cursor = -self.batch_size

    def _row(self, i: int) -> _np.ndarray:
        out = _np.zeros(self._num_col, dtype=_np.float32)
        lo, hi = self._indptr[i], self._indptr[i + 1]
        out[self._indices[lo:hi]] = self._values[lo:hi]
        return out

    def next(self) -> DataBatch:
        self.cursor += self.batch_size
        if self.cursor >= self.num_data:
            raise StopIteration
        sel = [(self.cursor + k) % self.num_data
               for k in range(self.batch_size)]
        pad = max(0, self.cursor + self.batch_size - self.num_data)
        if pad and not self._round_batch:
            raise StopIteration
        dense = _np.stack([self._row(i) for i in sel])
        try:
            from .sparse import csr_matrix
            data = csr_matrix(dense)
        except ImportError:                      # sparse not built yet
            data = nd_array(dense, ctx=cpu())
        return DataBatch([data],
                         [nd_array(self._labels[sel], ctx=cpu())], pad=pad,
                         provide_data=self.provide_data,
                         provide_label=self.provide_label)


class MNISTIter(DataIter):
    """Read the idx-ubyte MNIST files (reference: src/io/iter_mnist.cc)."""

    def __init__(self, image: str, label: str, batch_size: int = 128,
                 shuffle: bool = True, flat: bool = False,
                 silent: bool = True, seed: int = 0, **kwargs):
        super().__init__(batch_size)
        imgs = self._read_idx(image)
        labs = self._read_idx(label)
        imgs = imgs.astype(_np.float32) / 255.0
        if flat:
            imgs = imgs.reshape(imgs.shape[0], -1)
        else:
            imgs = imgs.reshape(imgs.shape[0], 1, imgs.shape[1],
                                imgs.shape[2])
        self._inner = NDArrayIter({"data": imgs},
                                  {"softmax_label":
                                   labs.astype(_np.float32)},
                                  batch_size, shuffle=shuffle)

    @staticmethod
    def _read_idx(path: str) -> _np.ndarray:
        import gzip
        opener = gzip.open if path.endswith(".gz") else open
        with opener(path, "rb") as f:
            zero, dt, ndim = struct.unpack(">HBB", f.read(4))
            shape = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
            return _np.frombuffer(f.read(), dtype=_np.uint8).reshape(shape)

    @property
    def provide_data(self):
        return self._inner.provide_data

    @property
    def provide_label(self):
        return self._inner.provide_label

    def reset(self):
        self._inner.reset()

    def next(self):
        return self._inner.next()


# ---------------------------------------------------------------------------
# ImageRecordIter: threaded decode+augment over .rec shards
# ---------------------------------------------------------------------------

class ImageRecordIter(DataIter):
    """Threaded JPEG decode + augment + batch over a RecordIO file.

    Reference parity: src/io/iter_image_recordio_2.cc +
    image_aug_default.cc — the pipeline behind the ResNet/ImageNet example.
    Same knobs (``data_shape``, ``rand_crop``, ``rand_mirror``,
    ``mean_r/g/b``, ``std_r/g/b``, ``resize``, ``part_index/num_parts`` for
    distributed sharding, ``preprocess_threads``, ``prefetch_buffer``);
    decode runs on a thread pool (PIL drops the GIL in JPEG decode) and
    finished batches queue into a bounded prefetch buffer.
    """

    def __init__(self, path_imgrec: str, data_shape: Sequence[int],
                 batch_size: int, path_imgidx: Optional[str] = None,
                 shuffle: bool = False, rand_crop: bool = False,
                 rand_mirror: bool = False, resize: int = -1,
                 mean_r: float = 0.0, mean_g: float = 0.0,
                 mean_b: float = 0.0, std_r: float = 1.0,
                 std_g: float = 1.0, std_b: float = 1.0,
                 part_index: int = 0, num_parts: int = 1,
                 preprocess_threads: int = 4, prefetch_buffer: int = 4,
                 label_width: int = 1, round_batch: bool = True,
                 seed: int = 0, use_native: Optional[bool] = None,
                 scaled_decode: bool = True, **kwargs):
        super().__init__(batch_size)
        # native path only: DCT-domain scaled JPEG decode with a 2x
        # oversampling margin — visually equivalent, ~2-4x less decode
        # work per image; pass False for bit-exact full decode (the
        # native-vs-Python parity tests do)
        self.scaled_decode = scaled_decode
        self.data_shape = tuple(data_shape)
        if len(self.data_shape) != 3:
            raise MXNetError("data_shape must be (C, H, W)")
        self.path_imgrec = path_imgrec
        self.shuffle = shuffle
        self.rand_crop = rand_crop
        self.rand_mirror = rand_mirror
        self.resize = resize
        self.mean = _np.array([mean_r, mean_g, mean_b],
                              dtype=_np.float32).reshape(3, 1, 1)
        self.std = _np.array([std_r, std_g, std_b],
                             dtype=_np.float32).reshape(3, 1, 1)
        self.label_width = label_width
        self._round_batch = round_batch
        self.n_threads = max(1, preprocess_threads)
        self.prefetch = max(1, prefetch_buffer)
        self._rng = _np.random.default_rng(seed)
        self._native = None
        self._native_lib = None
        if use_native is not False:
            try:
                self._init_native(path_imgrec, path_imgidx, seed,
                                  part_index, num_parts)
            except MXNetError:
                if use_native:           # explicitly requested: surface it
                    raise
        if self._native is None:
            # pure-Python path: index the record file once
            self._offsets = self._scan_offsets(path_imgrec, path_imgidx)
            # distributed shard (reference: part_index/num_parts)
            shard = len(self._offsets) // num_parts
            lo = part_index * shard
            hi = len(self._offsets) if part_index == num_parts - 1 \
                else lo + shard
            self._offsets = self._offsets[lo:hi]
            self._order = _np.arange(len(self._offsets))
        self._stop = threading.Event()
        self._pool: List[threading.Thread] = []
        self._out: Optional[_queue.Queue] = None
        self.reset()

    def _init_native(self, path_imgrec, path_imgidx, seed,
                     part_index, num_parts) -> None:
        import ctypes
        from . import native
        lib = native.load_io()
        c, h, w = self.data_shape
        mean = (ctypes.c_float * 3)(*self.mean.ravel())
        std = (ctypes.c_float * 3)(*self.std.ravel())
        err = ctypes.create_string_buffer(512)
        # DCT-scaled decode floor: ONLY the resize-shorter target may
        # drive it — that stage renormalizes scale, so a reduced-res
        # decode is visually equivalent.  Without a resize stage a
        # scaled decode would widen the crop's field of view (the crop
        # window would cover 2-4x the source area), silently changing
        # the training data; hint stays 0 (exact decode) then.
        hint = 0
        if getattr(self, "scaled_decode", False) and self.resize > 0:
            hint = self.resize
        handle = lib.MXTPUIOCreate(
            path_imgrec.encode(), (path_imgidx or "").encode(),
            self.batch_size, c, h, w, self.resize,
            int(self.rand_crop), int(self.rand_mirror), int(self.shuffle),
            int(self._round_batch), seed, mean, std, self.label_width,
            part_index, num_parts, self.n_threads, hint, err, len(err))
        if not handle:
            raise MXNetError(
                f"native ImageRecordIter: {err.value.decode()}")
        self._native_lib = lib
        self._native = handle

    @staticmethod
    def _scan_offsets(path: str, idx_path: Optional[str]) -> List[int]:
        if idx_path and os.path.isfile(idx_path):
            offs = []
            with open(idx_path) as f:
                for line in f:
                    parts = line.strip().split("\t")
                    if len(parts) >= 2:
                        offs.append(int(parts[1]))
            return offs
        offs = []
        magic = struct.Struct("<II")
        with open(path, "rb") as f:
            pos = 0
            while True:
                head = f.read(8)
                if len(head) < 8:
                    break
                _, lrec = magic.unpack(head)
                length = lrec & ((1 << 29) - 1)
                offs.append(pos)
                skip = length + (4 - length % 4) % 4
                f.seek(skip, 1)
                pos += 8 + skip
        return offs

    @property
    def provide_data(self):
        return [DataDesc("data", (self.batch_size,) + self.data_shape)]

    @property
    def provide_label(self):
        shape = (self.batch_size,) if self.label_width == 1 \
            else (self.batch_size, self.label_width)
        return [DataDesc("label", shape)]

    # -- pipeline ----------------------------------------------------------
    def reset(self) -> None:
        self._shutdown()
        self._stop = threading.Event()
        self._out = _queue.Queue(maxsize=self.prefetch)
        if self._native is not None:
            self._native_lib.MXTPUIOReset(self._native)
            n_batches = int(
                self._native_lib.MXTPUIONumBatches(self._native))
            target = self._run_native
        else:
            if self.shuffle:
                self._rng.shuffle(self._order)
            n_batches = len(self._order) // self.batch_size
            tail = len(self._order) % self.batch_size
            if self._round_batch and tail:
                n_batches += 1      # final wrap-padded batch (pad set)
            target = self._run_pipeline
        self._n_batches = n_batches
        self._consumed = 0
        feeder = threading.Thread(target=target,
                                  args=(self._stop, self._out, n_batches),
                                  daemon=True)
        feeder.start()
        self._pool = [feeder]

    def _run_native(self, stop: threading.Event, out: _queue.Queue,
                    n_batches: int) -> None:
        """Feeder loop over the C++ core: the ctypes call releases the GIL
        for the whole batch fill, so decode overlaps training fully."""
        import ctypes
        try:
            lib, handle = self._native_lib, self._native
            c, h, w = self.data_shape
            fp = ctypes.POINTER(ctypes.c_float)
            for _ in range(n_batches):
                if stop.is_set():
                    return
                data = _np.empty((self.batch_size, c, h, w),
                                 dtype=_np.float32)
                label = _np.empty((self.batch_size, self.label_width),
                                  dtype=_np.float32)
                pad = lib.MXTPUIONext(
                    handle, data.ctypes.data_as(fp),
                    label.ctypes.data_as(fp))
                if pad < 0:
                    msg = lib.MXTPUIOLastError(handle).decode() \
                        if pad == -2 else "early epoch end"
                    raise MXNetError(f"native iter: {msg}")
                if self.label_width == 1:
                    label = label.reshape(self.batch_size)
                while not stop.is_set():
                    try:
                        out.put((data, label, pad), timeout=0.1)
                        break
                    except _queue.Full:
                        continue
        except BaseException as e:          # surface in next(), don't hang
            while not stop.is_set():
                try:
                    out.put(("__error__", e), timeout=0.1)
                    return
                except _queue.Full:
                    continue

    def _shutdown(self) -> None:
        if self._pool:
            self._stop.set()
            # drain so producers unblock
            try:
                while True:
                    self._out.get_nowait()
            except (_queue.Empty, AttributeError):
                pass
            for t in self._pool:
                if self._native is not None:
                    # the feeder may be inside MXTPUIONext with the GIL
                    # released; Reset/Destroy on a handle another thread
                    # is mutating is a use-after-free — join for real
                    while t.is_alive():
                        t.join(timeout=5)
                else:
                    t.join(timeout=5)
            self._pool = []

    def _run_pipeline(self, stop: threading.Event, out: _queue.Queue,
                      n_batches: int) -> None:
        try:
            self._run_pipeline_inner(stop, out, n_batches)
        except BaseException as e:          # surface in next(), don't hang
            while not stop.is_set():
                try:
                    out.put(("__error__", e), timeout=0.1)
                    return
                except _queue.Full:
                    continue

    def _run_pipeline_inner(self, stop: threading.Event, out: _queue.Queue,
                            n_batches: int) -> None:
        order = self._order
        bs = self.batch_size
        with open(self.path_imgrec, "rb") as f:
            from concurrent.futures import ThreadPoolExecutor
            with ThreadPoolExecutor(self.n_threads) as pool:
                for b in range(n_batches):
                    if stop.is_set():
                        return
                    sel = order[b * bs:(b + 1) * bs]
                    pad = bs - len(sel)
                    if pad:                  # round_batch: wrap to the front
                        sel = _np.concatenate([sel, order[:pad]])
                    raws = []
                    for i in sel:
                        f.seek(self._offsets[i])
                        head = f.read(8)
                        _, lrec = struct.unpack("<II", head)
                        raws.append(f.read(lrec & ((1 << 29) - 1)))
                    seeds = self._rng.integers(0, 2 ** 31, size=len(raws))
                    samples = list(pool.map(self._decode_one, raws, seeds))
                    data = _np.stack([s[0] for s in samples])
                    label = _np.stack([s[1] for s in samples])
                    if self.label_width == 1:
                        label = label.reshape(bs)
                    while not stop.is_set():
                        try:
                            out.put((data, label, pad), timeout=0.1)
                            break
                        except _queue.Full:
                            continue

    def _decode_one(self, raw: bytes, seed: int):
        from .recordio import unpack_img
        header, img = unpack_img(raw)
        rng = _np.random.default_rng(seed)
        c, h, w = self.data_shape
        if img.ndim == 2:
            img = _np.stack([img] * 3, axis=-1)
        if self.resize > 0:
            img = _resize_shorter(img, self.resize)
        img = self._crop(img, h, w, rng)
        if self.rand_mirror and rng.random() < 0.5:
            img = img[:, ::-1]
        chw = img.astype(_np.float32).transpose(2, 0, 1)[:c]
        chw = (chw - self.mean) / self.std
        label = _np.atleast_1d(_np.asarray(header.label,
                                           dtype=_np.float32))
        if label.size < self.label_width:
            label = _np.pad(label, (0, self.label_width - label.size))
        return chw, label[:self.label_width]

    def _crop(self, img: _np.ndarray, h: int, w: int, rng) -> _np.ndarray:
        ih, iw = img.shape[:2]
        if ih < h or iw < w:
            img = _resize_shorter(img, max(h, w))
            ih, iw = img.shape[:2]
        if self.rand_crop:
            top = int(rng.integers(0, ih - h + 1))
            left = int(rng.integers(0, iw - w + 1))
        else:
            top, left = (ih - h) // 2, (iw - w) // 2
        return img[top:top + h, left:left + w]

    def next(self) -> DataBatch:
        if self._consumed >= self._n_batches:
            raise StopIteration
        # consumer-side wait = prefetch-health signal: near-zero means
        # decode keeps ahead of training; large means the pipeline is the
        # bottleneck (more preprocess_threads / deeper prefetch_buffer)
        with _span("io.record_batch_wait_us"):
            item = self._out.get()
        _metrics_registry().counter("io.record_batches").inc()
        if isinstance(item[0], str) and item[0] == "__error__":
            raise MXNetError(
                f"ImageRecordIter pipeline failed: {item[1]!r}") \
                from item[1]
        data, label, pad = item
        self._consumed += 1
        return DataBatch([nd_array(data, ctx=cpu())],
                         [nd_array(label, ctx=cpu())], pad=pad,
                         provide_data=self.provide_data,
                         provide_label=self.provide_label)

    def __del__(self):
        try:
            self._shutdown()
            if self._native is not None:
                self._native_lib.MXTPUIODestroy(self._native)
                self._native = None
        except Exception:
            pass


def _resize_shorter(img: _np.ndarray, size: int) -> _np.ndarray:
    from PIL import Image
    h, w = img.shape[:2]
    if h < w:
        nh, nw = size, max(1, int(round(w * size / h)))
    else:
        nh, nw = max(1, int(round(h * size / w))), size
    return _np.asarray(Image.fromarray(img).resize((nw, nh),
                                                   Image.BILINEAR))


class ResizeIter(DataIter):
    """Resize another iterator to a fixed number of batches per epoch
    (reference: io.ResizeIter)."""

    def __init__(self, data_iter: DataIter, size: int,
                 reset_internal: bool = True):
        super().__init__(data_iter.batch_size)
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0

    @property
    def provide_data(self):
        return self.data_iter.provide_data

    @property
    def provide_label(self):
        return self.data_iter.provide_label

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def next(self) -> DataBatch:
        if self.cur >= self.size:
            raise StopIteration
        try:
            batch = self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            batch = self.data_iter.next()
        self.cur += 1
        return batch


class PrefetchingIter(DataIter):
    """Run the wrapped iterator(s) on a background thread
    (reference: io.PrefetchingIter)."""

    def __init__(self, iters, rename_data=None, rename_label=None):
        if not isinstance(iters, (list, tuple)):
            iters = [iters]
        if len(iters) != 1:
            raise MXNetError("multi-iter prefetch not supported")
        super().__init__(iters[0].batch_size)
        self.iter = iters[0]
        self._queue: _queue.Queue = _queue.Queue(maxsize=2)
        self._thread: Optional[threading.Thread] = None
        self._start()

    @property
    def provide_data(self):
        return self.iter.provide_data

    @property
    def provide_label(self):
        return self.iter.provide_label

    def _start(self):
        def run():
            try:
                for batch in self.iter:
                    self._queue.put(batch)
            finally:
                self._queue.put(None)
        self._thread = threading.Thread(target=run, daemon=True)
        self._thread.start()

    def reset(self):
        if self._thread is not None:
            while self._queue.get() is not None:   # drain to epoch end
                pass
            self._thread.join()
        self._start()

    def next(self) -> DataBatch:
        batch = self._queue.get()
        if batch is None:
            self._thread.join()
            self._thread = None
            raise StopIteration
        return batch
