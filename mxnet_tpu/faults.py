"""Failure-handling primitives shared across the stack.

Reference role: the reference's fault story lived in ps-lite (server-side
replication, van retries — SURVEY.md §2.3); the TPU-native stack replaces
the parameter server entirely, so resilience moves into the training
supervisor (`parallel/resilience.py`) and the host-side plumbing here:
bounded retry with exponential backoff + jitter, wall-clock deadlines, and
a **deterministic fault-injection plan** so every recovery path is
exercisable on CPU in tier-1 tests.

Fault-plan grammar (env var ``MXTPU_FAULT_PLAN`` or :class:`FaultPlan`):

    plan  := entry (';' entry)*
    entry := kind '@' index ['x' count] [':' arg]

``kind`` names an instrumented site (an open set — current sites:
``step_error``, ``nan``, ``ckpt_fail``, ``loader_stall``,
``loader_error``, plus the **host-level** kinds below), ``index`` is the
1-based step / save / batch counter at that site, ``xN`` repeats the
entry for N consecutive indices, and ``arg`` is an optional float
payload (e.g. stall seconds).  Each entry fires exactly once and is
then consumed — a retried step therefore sees the fault on the first
attempt only, which is what makes injected faults *transient*.

Host-level kinds (the elastic-fleet fault surface; each process reads
its OWN plan, so the targeted rank is simply the process whose plan
carries the entry):

- ``host_loss@N`` — the process hard-exits at supervisor step N:
  SIGKILL to itself by default (indistinguishable from a machine
  loss — no flush, no atexit), or ``:code`` to ``os._exit(code)``
  instead.  The survivors' membership layer must detect the expired
  lease and re-form.
- ``heartbeat_stall@N[:secs]`` — the lease publisher freezes at step N
  (for ``secs`` seconds, or forever without an arg) while the process
  KEEPS STEPPING: the false-death/split-brain case.  Peers reap the
  silent lease and re-form with a bumped fencing generation; when this
  process notices the fence it must exit (:class:`~mxnet_tpu.parallel.
  membership.HostFenced`), never rejoin.

Example::

    MXTPU_FAULT_PLAN="step_error@3;nan@5;ckpt_fail@2;loader_stall@4:1.5"

makes training step 3 raise :class:`TransientFault`, poisons the inputs
of step 5 with NaN, breaks the 2nd checkpoint write, and stalls the
dataloader worker building batch 4 for 1.5 s.
"""
from __future__ import annotations

import random as _pyrandom
import re
import threading
import time
from typing import Callable, List, NamedTuple, Optional, Tuple, Type

from .base import MXNetError, get_env

__all__ = ["TransientFault", "DeadlineExceeded", "retry_call", "Deadline",
           "call_with_deadline", "FaultSpec", "FaultPlan", "active_plan",
           "set_fault_plan"]

FAULT_PLAN_ENV = "MXTPU_FAULT_PLAN"


class TransientFault(MXNetError):
    """A failure that is expected to succeed on retry (injected faults,
    flaky I/O, a coordinator that has not come up yet)."""


class DeadlineExceeded(MXNetError):
    """A wall-clock deadline expired before the wrapped work finished."""


# -- retry / deadline utilities ---------------------------------------------

_c_retries = None


def _retry_counter():
    """Process-wide `faults.retries` counter, created on first retry.
    Lazy so this module (imported at package init, before the
    observability package) never races the import order."""
    global _c_retries
    if _c_retries is None:
        from .observability.registry import registry
        _c_retries = registry().counter("faults.retries")
    return _c_retries


def retry_call(fn: Callable, *args,
               retries: int = 3,
               base_delay: float = 0.05,
               max_delay: float = 2.0,
               jitter: float = 0.25,
               retry_on: Tuple[Type[BaseException], ...] = (TransientFault,),
               on_retry: Optional[Callable] = None,
               deadline: Optional["Deadline"] = None,
               sleep: Callable[[float], None] = time.sleep,
               **kwargs):
    """Call ``fn(*args, **kwargs)``, retrying ``retry_on`` failures with
    exponential backoff (``base_delay * 2**attempt``, capped at
    ``max_delay``) plus up to ``jitter`` fractional random spread so
    co-failing workers don't stampede in lock-step.

    ``on_retry(attempt, exc, delay)`` is invoked before each sleep;
    ``deadline`` (a :class:`Deadline`) turns remaining retries off once it
    expires.  The final failure re-raises the original exception.
    """
    if retries < 0:
        raise MXNetError(f"retry_call: retries must be >= 0, got {retries}")
    attempt = 0
    while True:
        try:
            return fn(*args, **kwargs)
        except retry_on as exc:
            attempt += 1
            if attempt > retries or (deadline is not None and
                                     deadline.expired):
                raise
            _retry_counter().inc()   # every retry anywhere in the stack
            delay = min(max_delay, base_delay * (2.0 ** (attempt - 1)))
            if jitter:
                delay *= 1.0 + jitter * _pyrandom.random()
            if deadline is not None:
                delay = min(delay, max(0.0, deadline.remaining()))
            if on_retry is not None:
                on_retry(attempt, exc, delay)
            sleep(delay)


class Deadline:
    """A wall-clock budget shared across a sequence of operations."""

    def __init__(self, timeout: float):
        self.timeout = float(timeout)
        self._start = time.monotonic()

    def remaining(self) -> float:
        return self.timeout - (time.monotonic() - self._start)

    @property
    def expired(self) -> bool:
        return self.remaining() <= 0.0

    def check(self, what: str = "operation") -> None:
        if self.expired:
            raise DeadlineExceeded(
                f"{what} exceeded its {self.timeout:.1f}s deadline")


def call_with_deadline(fn: Callable, timeout: float, *args, **kwargs):
    """Run ``fn`` in a worker thread and give up after ``timeout`` seconds
    with :class:`DeadlineExceeded`.  The abandoned thread is a daemon and
    keeps running to completion — use this only around idempotent,
    side-effect-light calls (connects, metadata reads), never around
    mutation of shared state.
    """
    box: List = []

    def _run():
        try:
            box.append(("ok", fn(*args, **kwargs)))
        except BaseException as exc:   # noqa: BLE001 — re-raised below
            box.append(("err", exc))

    t = threading.Thread(target=_run, daemon=True,
                         name="mxtpu-deadline-worker")
    t.start()
    t.join(timeout)
    if not box:
        raise DeadlineExceeded(
            f"{getattr(fn, '__name__', fn)!r} did not finish within "
            f"{timeout:.1f}s")
    tag, val = box[0]
    if tag == "err":
        raise val
    return val


# -- deterministic fault injection ------------------------------------------

class FaultSpec(NamedTuple):
    kind: str
    index: int
    arg: Optional[float]


_ENTRY_RE = re.compile(
    r"^(?P<kind>[a-z][a-z0-9_]*)@(?P<idx>\d+)"
    r"(?:x(?P<count>\d+))?(?::(?P<arg>[-+0-9.eE]+))?$")


class FaultPlan:
    """A deterministic schedule of injected faults.

    Instrumented sites call :meth:`scheduled(kind, index)` with their
    1-based counter; a matching entry is consumed (fires once) and
    returned as a :class:`FaultSpec`, else ``None``.  Thread-safe — the
    dataloader consults the plan from worker threads.
    """

    def __init__(self, spec: str = ""):
        self._lock = threading.Lock()
        self._entries: List[FaultSpec] = []
        spec = (spec or "").strip()
        if not spec:
            return
        for raw in re.split(r"[;,]", spec):
            raw = raw.strip()
            if not raw:
                continue
            m = _ENTRY_RE.match(raw)
            if not m:
                raise MXNetError(
                    f"bad {FAULT_PLAN_ENV} entry {raw!r}: expected "
                    f"'kind@index[xcount][:arg]' "
                    f"(e.g. 'nan@5', 'step_error@3x2', 'loader_stall@4:1.5')")
            kind = m.group("kind")
            idx = int(m.group("idx"))
            count = int(m.group("count") or 1)
            arg = float(m.group("arg")) if m.group("arg") is not None \
                else None
            for k in range(count):
                self._entries.append(FaultSpec(kind, idx + k, arg))

    @classmethod
    def from_env(cls) -> "FaultPlan":
        return cls(get_env(FAULT_PLAN_ENV))

    @property
    def empty(self) -> bool:
        with self._lock:
            return not self._entries

    def pending(self) -> List[FaultSpec]:
        """Entries not yet fired (diagnostics / test assertions)."""
        with self._lock:
            return list(self._entries)

    def scheduled(self, kind: str, index: int) -> Optional[FaultSpec]:
        """Consume and return the fault scheduled for (kind, index), if
        any.  Multiple entries at the same site fire one per call — that
        is how 'fail N consecutive attempts' is expressed."""
        with self._lock:
            for i, e in enumerate(self._entries):
                if e.kind == kind and e.index == index:
                    return self._entries.pop(i)
            return None

    def fire(self, kind: str, index: int) -> Optional[FaultSpec]:
        """Like :meth:`scheduled`, but raises :class:`TransientFault` when
        a fault is due — for sites whose failure mode IS an exception."""
        spec = self.scheduled(kind, index)
        if spec is not None:
            raise TransientFault(
                f"injected fault {kind}@{index} (MXTPU_FAULT_PLAN)")
        return None

    def __repr__(self) -> str:
        with self._lock:
            return f"FaultPlan({self._entries!r})"


_active_lock = threading.Lock()
_active: Optional[FaultPlan] = None
_active_loaded = False


def active_plan() -> Optional[FaultPlan]:
    """The process-global plan: explicitly set via :func:`set_fault_plan`,
    else lazily parsed from ``MXTPU_FAULT_PLAN`` (once — consumed entries
    must stay consumed), else ``None``."""
    global _active, _active_loaded
    with _active_lock:
        if not _active_loaded:
            _active_loaded = True
            spec = get_env(FAULT_PLAN_ENV).strip()
            if spec:
                _active = FaultPlan(spec)
        return _active


def set_fault_plan(plan) -> None:
    """Install (or clear, with ``None``) the process-global fault plan.
    Accepts a :class:`FaultPlan` or a grammar string."""
    global _active, _active_loaded
    if isinstance(plan, str):
        plan = FaultPlan(plan)
    if plan is not None and not isinstance(plan, FaultPlan):
        raise MXNetError(f"set_fault_plan: expected FaultPlan, str or None, "
                         f"got {type(plan).__name__}")
    with _active_lock:
        _active = plan
        _active_loaded = True
