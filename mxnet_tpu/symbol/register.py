"""Symbol frontends generated from the shared op registry.

Reference role: python/mxnet/symbol/register.py — same generated-wrapper
trick as the ndarray namespace, from the same registry, so ``mx.sym.X`` and
``mx.nd.X`` stay in lockstep (SURVEY.md §2.5).  Includes the reference's
auto-variable behavior: tensor inputs not supplied are created as variables
named ``{op_name}_{input}`` (how ``mx.sym.Convolution(data=d, ...)`` grows
its weight/bias).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

from ..base import MXNetError
from .symbol import Symbol, _Node, _auto_name

# tensor-input declarations for ops whose missing inputs auto-create
# variables (name → (input names, aux flags))
_OP_INPUTS: Dict[str, List[str]] = {
    "FullyConnected": ["data", "weight", "bias"],
    "Convolution": ["data", "weight", "bias"],
    "Deconvolution": ["data", "weight", "bias"],
    "BatchNorm": ["data", "gamma", "beta", "moving_mean", "moving_var"],
    "BatchNorm_v1": ["data", "gamma", "beta", "moving_mean",
                     "moving_var"],
    "LayerNorm": ["data", "gamma", "beta"],
    "InstanceNorm": ["data", "gamma", "beta"],
    "Embedding": ["data", "weight"],
    "LeakyReLU": ["data", "gamma"],
    "RNN": ["data", "parameters", "state", "state_cell"],
    "SoftmaxOutput": ["data", "label"],
}
_OP_AUX = {"BatchNorm": ("moving_mean", "moving_var"),
           "BatchNorm_v1": ("moving_mean", "moving_var")}

# ops whose trailing inputs are optional depending on params
def _needed_inputs(opname: str, kwargs: Dict[str, Any]) -> List[str]:
    names = _OP_INPUTS[opname]
    if opname in ("FullyConnected", "Convolution", "Deconvolution") and \
            kwargs.get("no_bias"):
        names = names[:2]
    if opname == "LeakyReLU" and kwargs.get("act_type", "leaky") != "prelu":
        names = names[:1]
    if opname == "RNN" and kwargs.get("mode") != "lstm":
        names = names[:3]
    return names


def _num_outputs(opname: str, kwargs: Dict[str, Any],
                 n_inputs: int = 1) -> int:
    if opname == "meshgrid":
        return n_inputs                  # one grid per input coordinate
    if opname in ("BatchNorm", "BatchNorm_v1"):
        return 3
    if opname in ("split", "SliceChannel"):
        return int(kwargs.get("num_outputs", 1))
    if opname == "_contrib_hawkesll":
        return 2
    if opname == "split_v2":
        if kwargs.get("sections"):
            return int(kwargs["sections"])
        from ..ndarray.ops_misc import normalize_split_indices
        return len(normalize_split_indices(kwargs.get("indices", ()))) + 1
    if opname == "RNN":
        return 3 if kwargs.get("mode") == "lstm" else 2
    if opname == "topk" and kwargs.get("ret_typ") == "both":
        return 2
    if opname in ("linalg_gelqf", "linalg_slogdet", "linalg_syevd"):
        return 2
    if opname == "_sample_multinomial" and kwargs.get("get_prob"):
        return 2
    if opname == "Custom":
        from ..operator import _custom_registry
        prop_cls = _custom_registry.get(kwargs.get("op_type"))
        if prop_cls is not None:
            # strip op machinery AND __key__-style scoped metadata
            # (AttrScope stamps are node attrs, never prop kwargs)
            user = {k: v for k, v in kwargs.items()
                    if k not in ("op_type", "_training")
                    and not (k.startswith("__") and k.endswith("__"))}
            return len(prop_cls(**user).list_outputs())
        return 1
    if opname == "LayerNorm" and kwargs.get("output_mean_var"):
        return 3
    if opname == "_foreach":
        return int(kwargs.get("n_outs", 1)) + len(kwargs.get("state_names", ()))
    if opname == "_while_loop":
        return int(kwargs.get("n_outs", 1)) + len(kwargs.get("loop_names", ()))
    if opname == "_cond":
        return int(kwargs.get("n_outs", 1))
    return 1


def apply_op(opname: str, args: List[Symbol], kwargs: Dict[str, Any],
             name: Optional[str] = None) -> Symbol:
    from ..ndarray.register import get_op
    op = get_op(opname)          # validates registration
    canonical = op.name
    # split tensor kwargs from attribute kwargs
    tensor_kwargs = {k: v for k, v in kwargs.items()
                     if isinstance(v, Symbol)}
    attrs = {k: v for k, v in kwargs.items()
             if not isinstance(v, Symbol) and k not in ("name",)}
    # scoped defaults: active NameManager names the node, active AttrScope
    # stamps its attrs (reference name.py/attribute.py behavior)
    from .. import attribute as _attribute
    from .. import name as _name
    node_name = _name.current().get(name or kwargs.get("name"),
                                    canonical.lower().lstrip("_"))
    attrs = _attribute.current().get(attrs)
    attrs.pop("name", None)

    inputs: List = []
    if canonical in _OP_INPUTS:
        needed = _needed_inputs(canonical, attrs)
        pos = list(args)
        for in_name in needed:
            if pos:
                sym = pos.pop(0)
            elif in_name in tensor_kwargs:
                sym = tensor_kwargs.pop(in_name)
            else:
                aux = canonical in _OP_AUX and in_name in _OP_AUX[canonical]
                sym = Symbol.var(f"{node_name}_{in_name}",
                                 **({"__aux__": True} if aux else {}))
            inputs.append(sym)
    else:
        inputs = list(args) + list(tensor_kwargs.values())
    head_refs = []
    for s in inputs:
        if not isinstance(s, Symbol):
            raise MXNetError(f"symbol op {opname} got non-symbol input "
                             f"{type(s)}")
        if len(s._heads) != 1:
            raise MXNetError("cannot feed a grouped symbol as one input")
        head_refs.append(s._heads[0])

    node = _Node(canonical, node_name, attrs, head_refs,
                 _num_outputs(canonical, attrs, len(head_refs)))
    return Symbol([(node, i) for i in range(node.num_outputs)]) \
        if node.num_outputs > 1 else Symbol([(node, 0)])


def _make_sym_frontend(opname: str):
    def frontend(*args, **kwargs):
        name = kwargs.pop("name", None)
        # same positional-parameter convention as the nd wrappers
        from ..ndarray.register import get_op, split_positional_params
        inputs, kwargs = split_positional_params(get_op(opname), args,
                                                 kwargs)
        return apply_op(opname, inputs, kwargs, name=name)
    frontend.__name__ = opname
    return frontend


def _attach_frontends(module) -> None:
    from ..ndarray.register import _registry
    for name, op in list(_registry.items()):
        if not hasattr(module, name):
            setattr(module, name, _make_sym_frontend(name))
