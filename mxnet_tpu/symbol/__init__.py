"""``mx.sym``: lazy graph composition over the shared op registry.

Reference role: NNVM Symbol + python/mxnet/symbol/ (SURVEY.md §2.1 L4, §2.5)
— compose a DAG of op nodes, auto-creating variables for unbound tensor
inputs; infer shapes; bind into an Executor; save/load JSON.

TPU-native design: the Symbol is a plain-Python DAG whose *lowering* is a
pure JAX function composed from the same makers that power `mx.nd.*` — so
`simple_bind` is a `jax.jit` (the reference's GraphExecutor memory planning
is XLA buffer assignment), and shape inference is `jax.eval_shape` (the
reference's InferShape pass).  JSON layout mirrors the reference's
(nodes/arg_nodes/heads) so exported graphs are inspectable the same way.
"""
import sys as _sys

from .symbol import Symbol, var, Variable, Group, load, load_json
from .register import _attach_frontends

_attach_frontends(_sys.modules[__name__])

from . import contrib  # noqa: E402,F401  (after frontends exist)
from . import random   # noqa: E402,F401  (sampling-node frontends)

# fluent method surface, kept in lockstep with NDArray's (the generated
# method lists live in ndarray/__init__.py)
from ..ndarray import _attach_symbol_fluent as _asf  # noqa: E402

_asf()
