"""``mx.sym.random``: sampling ops as graph nodes.

Reference role: python/mxnet/symbol/random.py — the symbol frontends over
src/operator/random/sample_op.cc, so random draws can live INSIDE a
composed graph (noise layers, VAE reparameterization, symbolic dropout
experiments).  Each call creates a ``_random_*`` / ``_sample_*`` node; at
execution the symbol runner splits the executor's per-forward base key
across all sampling nodes (symbol.py ``compile``), so every ``forward``
draws fresh values — under one jit compilation, because the key is an
argument of the compiled function.
"""
from __future__ import annotations

from typing import Any, Dict, Optional

from .register import apply_op
from .symbol import Symbol

__all__ = ["uniform", "normal", "randint", "exponential", "gamma",
           "poisson", "negative_binomial", "generalized_negative_binomial",
           "multinomial", "shuffle"]


def _attrs(shape, dtype, **params) -> Dict[str, Any]:
    from ..ndarray.ops_random import _canon_shape
    attrs = dict(params)
    attrs["shape"] = _canon_shape(shape)   # shared None->(1,) rule
    if dtype is not None:
        attrs["dtype"] = dtype
    return attrs


def _scalar_or_sample(scalar_op: str, sample_op: str, params, shape, dtype,
                      names, name: Optional[str]):
    """Reference dispatch rule (symbol/random.py _random_helper): all-scalar
    parameters go to the ``_random_*`` op; Symbol parameters go to the
    per-element ``_sample_*`` op."""
    if any(isinstance(p, Symbol) for p in params):
        attrs = dict(_attrs(shape, dtype))
        if shape is None:
            attrs.pop("shape")
        return apply_op(sample_op, list(params), attrs, name=name)
    attrs = _attrs(shape, dtype, **dict(zip(names, map(float, params))))
    return apply_op(scalar_op, [], attrs, name=name)


def uniform(low=0.0, high=1.0, shape=None, dtype=None, name=None, **kwargs):
    return _scalar_or_sample("_random_uniform", "_sample_uniform",
                             [low, high], shape, dtype, ("low", "high"),
                             name)


def normal(loc=0.0, scale=1.0, shape=None, dtype=None, name=None, **kwargs):
    return _scalar_or_sample("_random_normal", "_sample_normal",
                             [loc, scale], shape, dtype, ("loc", "scale"),
                             name)


def gamma(alpha=1.0, beta=1.0, shape=None, dtype=None, name=None, **kwargs):
    return _scalar_or_sample("_random_gamma", "_sample_gamma",
                             [alpha, beta], shape, dtype, ("alpha", "beta"),
                             name)


def exponential(scale=1.0, shape=None, dtype=None, name=None, **kwargs):
    # parameterized by SCALE (mean), matching the reference frontend and
    # mx.nd.random.exponential; the per-element _sample_exponential op
    # takes a RATE, so a Symbol scale is inverted in-graph
    if isinstance(scale, Symbol):
        attrs = dict(_attrs(shape, dtype))
        if shape is None:
            attrs.pop("shape")
        return apply_op("_sample_exponential", [1.0 / scale], attrs,
                        name=name)
    return apply_op("_random_exponential", [],
                    _attrs(shape, dtype, scale=float(scale)), name=name)


def poisson(lam=1.0, shape=None, dtype=None, name=None, **kwargs):
    return _scalar_or_sample("_random_poisson", "_sample_poisson",
                             [lam], shape, dtype, ("lam",), name)


def negative_binomial(k=1, p=1.0, shape=None, dtype=None, name=None,
                      **kwargs):
    return _scalar_or_sample("_random_negative_binomial",
                             "_sample_negative_binomial",
                             [k, p], shape, dtype, ("k", "p"), name)


def generalized_negative_binomial(mu=1.0, alpha=1.0, shape=None, dtype=None,
                                  name=None, **kwargs):
    return _scalar_or_sample("_random_generalized_negative_binomial",
                             "_sample_generalized_negative_binomial",
                             [mu, alpha], shape, dtype, ("mu", "alpha"),
                             name)


def randint(low, high, shape=None, dtype="int32", name=None, **kwargs):
    return apply_op("_random_randint", [],
                    _attrs(shape, dtype, low=int(low), high=int(high)),
                    name=name)


def multinomial(data, shape=None, get_prob=False, dtype="int32", name=None,
                **kwargs):
    attrs: Dict[str, Any] = {"get_prob": bool(get_prob), "dtype": dtype}
    if shape is not None:
        attrs["shape"] = shape if isinstance(shape, int) else tuple(shape)
    return apply_op("_sample_multinomial", [data], attrs, name=name)


def shuffle(data, name=None, **kwargs):
    return apply_op("_shuffle", [data], {}, name=name)
