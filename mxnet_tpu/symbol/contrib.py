"""``mx.sym.contrib``: symbol frontends for the _contrib_* ops
(reference: python/mxnet/symbol/contrib.py)."""
from __future__ import annotations

import sys as _sys

from ..ndarray.register import _registry
from .register import _make_sym_frontend

_PREFIX = "_contrib_"
_mod = _sys.modules[__name__]

for _name in list(_registry):
    if _name.startswith(_PREFIX):
        setattr(_mod, _name[len(_PREFIX):], _make_sym_frontend(_name))
