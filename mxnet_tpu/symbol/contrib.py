"""``mx.sym.contrib``: symbol frontends for the _contrib_* ops plus the
control-flow constructors (reference: python/mxnet/symbol/contrib.py —
foreach/while_loop/cond trace the user's body into a subgraph and emit one
_foreach/_while_loop/_cond node; SURVEY.md §2.2).

The tracing protocol mirrors the reference: body callables receive fresh
placeholder Variables, the composed result becomes the subgraph attribute,
and every outer Symbol the body captured (weights, constants) is detected
as a free variable and wired in as an explicit op input — so
``simple_bind`` binds them and backward yields their gradients.
"""
from __future__ import annotations

import itertools as _itertools
import sys as _sys

from ..base import MXNetError
from ..ndarray.register import _registry
from ..ndarray.ops_control_flow import SubgraphAttr
from .register import _make_sym_frontend, apply_op
from .symbol import Group, Symbol

_PREFIX = "_contrib_"
_mod = _sys.modules[__name__]

for _name in list(_registry):
    if _name.startswith(_PREFIX):
        setattr(_mod, _name[len(_PREFIX):], _make_sym_frontend(_name))


_uid = _itertools.count()


def _as_list(x):
    return ([x], True) if isinstance(x, Symbol) else (list(x), False)


def _free_vars(inner, placeholder_names):
    """Var nodes the traced subgraph references beyond its placeholders —
    these are shared _Node objects with the outer graph, so wrapping them
    links the control-flow node into the caller's graph."""
    syms, names = [], []
    for node in inner._topo():
        if node.is_var and node.name not in placeholder_names:
            syms.append(Symbol([(node, 0)]))
            names.append(node.name)
    return syms, names


def foreach(body, data, init_states, name="foreach"):
    """Scan ``body(x_t, states) -> (out_t, new_states)`` over axis 0 of
    ``data`` inside the graph (reference sym.contrib.foreach ≡ lax.scan).
    Returns ``(outputs, final_states)`` with the caller's nesting shape."""
    tag = f"_{name}{next(_uid)}"
    data_list, data_single = _as_list(data)
    state_list, state_single = _as_list(init_states)
    data_ph = [Symbol.var(f"{tag}_data{i}") for i in range(len(data_list))]
    state_ph = [Symbol.var(f"{tag}_state{i}") for i in range(len(state_list))]
    outs, new_states = body(data_ph[0] if data_single else data_ph,
                            state_ph[0] if state_single else state_ph)
    outs_list, out_single = _as_list(outs)
    new_state_list, _ = _as_list(new_states)
    if len(new_state_list) != len(state_list):
        raise MXNetError("foreach body returned %d states, expected %d"
                         % (len(new_state_list), len(state_list)))
    inner = Group(outs_list + new_state_list)
    ph_names = [s.name for s in data_ph + state_ph]
    free_syms, free_names = _free_vars(inner, set(ph_names))
    res = apply_op("_foreach", data_list + state_list + free_syms, {
        "subgraph": SubgraphAttr(inner),
        "data_names": tuple(s.name for s in data_ph),
        "state_names": tuple(s.name for s in state_ph),
        "free_names": tuple(free_names),
        "n_outs": len(outs_list)}, name=name)
    heads = list(res)
    o = heads[:len(outs_list)]
    st = heads[len(outs_list):]
    return (o[0] if out_single else o), (st[0] if state_single else st)


def while_loop(cond, func, loop_vars, max_iterations=None,
               name="while_loop"):
    """Bounded in-graph while loop (reference sym.contrib.while_loop).
    ``cond(*loop_vars)`` must yield a scalar; ``func(*loop_vars)`` yields
    ``(step_output, new_loop_vars)``.  Outputs are buffered to
    ``max_iterations`` rows (static shapes); rows past the exit step are
    zeros.  Reverse-mode differentiable — see ops_control_flow.py."""
    if max_iterations is None:
        raise MXNetError("while_loop requires max_iterations (static shapes)")
    tag = f"_{name}{next(_uid)}"
    lv_list, lv_single = _as_list(loop_vars)
    lv_ph = [Symbol.var(f"{tag}_loop{i}") for i in range(len(lv_list))]
    cond_out = cond(*lv_ph)
    outs, new_lv = func(*lv_ph)
    outs_list, out_single = _as_list(outs)
    new_lv_list, _ = _as_list(new_lv)
    if len(new_lv_list) != len(lv_list):
        raise MXNetError("while_loop func returned %d loop_vars, expected %d"
                         % (len(new_lv_list), len(lv_list)))
    inner_body = Group(outs_list + new_lv_list)
    ph_names = set(s.name for s in lv_ph)
    free_syms, free_names = _free_vars(Group([cond_out] + outs_list
                                             + new_lv_list), ph_names)
    res = apply_op("_while_loop", lv_list + free_syms, {
        "cond_subgraph": SubgraphAttr(cond_out),
        "body_subgraph": SubgraphAttr(inner_body),
        "loop_names": tuple(s.name for s in lv_ph),
        "free_names": tuple(free_names),
        "n_outs": len(outs_list),
        "max_iterations": int(max_iterations)}, name=name)
    heads = list(res)
    o = heads[:len(outs_list)]
    st = heads[len(outs_list):]
    return (o[0] if out_single else o), (st[0] if lv_single else st)


def cond(pred, then_func, else_func, name="cond"):
    """In-graph conditional (reference sym.contrib.cond ≡ lax.cond): both
    branches are traced once; outputs must agree in count (and, as XLA
    requires, in shape/dtype)."""
    then_out, then_single = _as_list(then_func())
    else_out, _ = _as_list(else_func())
    if len(then_out) != len(else_out):
        raise MXNetError("cond branches disagree: %d vs %d outputs"
                         % (len(then_out), len(else_out)))
    free_syms, free_names = _free_vars(Group(then_out + else_out), set())
    res = apply_op("_cond", [pred] + free_syms, {
        "then_subgraph": SubgraphAttr(Group(then_out)),
        "else_subgraph": SubgraphAttr(Group(else_out)),
        "free_names": tuple(free_names),
        "n_outs": len(then_out)}, name=name)
    heads = list(res)
    return heads[0] if then_single else heads
