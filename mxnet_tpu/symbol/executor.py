"""Executor: bound symbolic graph with forward/backward.

Reference role: src/executor/graph_executor.cc + python/mxnet/executor.py
(SURVEY.md §2.1 L6b, §3.4) — ahead-of-time bound computation with argument/
gradient/aux arrays.  TPU-native: bind = jit the composed graph function
(XLA does the memory planning the reference's PlanMemory pass did); backward
holds the `jax.vjp` residuals from the last is_train forward.
"""
from __future__ import annotations

from typing import Dict, List, Optional

from ..base import MXNetError
from ..ndarray import NDArray

__all__ = ["Executor"]


class Executor:
    def __init__(self, symbol, ctx, arg_arrays: List[NDArray],
                 grad_arrays: Optional[List[NDArray]], grad_req: str,
                 aux_arrays: List[NDArray]):
        self._symbol = symbol
        self._ctx = ctx
        self.arg_arrays = arg_arrays
        self.grad_arrays = grad_arrays or [None] * len(arg_arrays)
        self.aux_arrays = aux_arrays
        self._grad_req = grad_req
        self._arg_names = symbol.list_arguments()
        self._aux_names = symbol.list_auxiliary_states()
        self.outputs: List[NDArray] = []
        self._vjp_fn = None
        self._run_cache: Dict[bool, object] = {}
        self._n_args = len(arg_arrays)

    # -- dict views --------------------------------------------------------
    @property
    def arg_dict(self) -> Dict[str, NDArray]:
        return dict(zip(self._arg_names, self.arg_arrays))

    @property
    def grad_dict(self) -> Dict[str, NDArray]:
        return dict(zip(self._arg_names, self.grad_arrays))

    @property
    def aux_dict(self) -> Dict[str, NDArray]:
        return dict(zip(self._aux_names, self.aux_arrays))

    @property
    def output_dict(self) -> Dict[str, NDArray]:
        return dict(zip(self._symbol.list_outputs(), self.outputs))

    # -- execution ---------------------------------------------------------
    def _get_run(self, training: bool):
        import jax
        cached = self._run_cache.get(training)
        if cached is None:
            run = self._symbol.compile(training=training)
            names = self._arg_names + self._aux_names
            needs_rng = run.needs_rng

            def flat(*vals):
                feed = dict(zip(names, vals))
                if needs_rng:
                    # base key rides as the LAST argument so the arg/aux
                    # cotangent slice in backward() stays positional
                    feed["__rng_key__"] = vals[len(names)]
                return tuple(run(feed))
            cached = (jax.jit(flat), needs_rng)
            self._run_cache[training] = cached
        return cached

    def forward(self, is_train: bool = False, **kwargs) -> List[NDArray]:
        import jax
        for k, v in kwargs.items():
            if k not in self._arg_names:
                raise MXNetError(f"unknown input {k!r}")
            self.arg_dict[k]._set_data(
                v._read() if isinstance(v, NDArray) else v)
        dev = self._ctx.device
        # pin every operand to this executor's device: args may have been
        # copied in from another context (multi-device executor groups)
        vals = [jax.device_put(a._read(), dev) for a in self.arg_arrays] + \
            [jax.device_put(a._read(), dev) for a in self.aux_arrays]
        fn, needs_rng = self._get_run(is_train)
        if needs_rng:
            from .. import random as _grandom
            vals = vals + [jax.device_put(_grandom.next_key(), dev)]
        if is_train and self._grad_req != "null":
            outs, self._vjp_fn = jax.vjp(fn, *vals)
        else:
            outs = fn(*vals)
        self.outputs = [NDArray(v, ctx=self._ctx) for v in outs]
        return self.outputs

    def backward(self, out_grads=None, retain_graph=False) -> None:
        import jax.numpy as jnp
        if self._vjp_fn is None:
            raise MXNetError("backward requires a prior forward(is_train=True)")
        if out_grads is None:
            cts = tuple(jnp.ones(o.shape, o.dtype) for o in self.outputs)
        else:
            if isinstance(out_grads, NDArray):
                out_grads = [out_grads]
            cts = tuple(g._read() if isinstance(g, NDArray) else g
                        for g in out_grads)
            if len(cts) < len(self.outputs):
                cts = cts + tuple(jnp.zeros(o.shape, o.dtype)
                                  for o in self.outputs[len(cts):])
        in_cts = self._vjp_fn(cts)
        if not retain_graph:
            self._vjp_fn = None
        for i, g in enumerate(in_cts[:self._n_args]):
            tgt = self.grad_arrays[i]
            if tgt is None or self._grad_req == "null":
                continue
            if self._grad_req == "add":
                tgt._set_data(tgt._read() + g)
            else:
                tgt._set_data(g)

    def copy_params_from(self, arg_params: Dict[str, NDArray],
                         aux_params: Optional[Dict[str, NDArray]] = None,
                         allow_extra_params: bool = False) -> None:
        for name, arr in (arg_params or {}).items():
            if name in self.arg_dict:
                arr.copyto(self.arg_dict[name])
            elif not allow_extra_params:
                raise MXNetError(f"unknown argument {name!r}")
        for name, arr in (aux_params or {}).items():
            if name in self.aux_dict:
                arr.copyto(self.aux_dict[name])
            elif not allow_extra_params:
                raise MXNetError(f"unknown aux state {name!r}")

    def reshape(self, partial_shaping=False, allow_up_sizing=False,
                **kwargs):
        # a new bind at the new shapes; jit handles the rest
        from ..ndarray import zeros as nd_zeros
        arg_shapes, _, aux_shapes = self._symbol.infer_shape(**kwargs)
        args = [nd_zeros(s, ctx=self._ctx) for s in arg_shapes]
        aux = [nd_zeros(s, ctx=self._ctx) for s in aux_shapes]
        grads = [nd_zeros(s, ctx=self._ctx) for s in arg_shapes] \
            if self._grad_req != "null" else None
        return Executor(self._symbol, self._ctx, args, grads,
                        self._grad_req, aux)
