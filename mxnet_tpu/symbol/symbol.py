"""Symbol graph core (see package docstring)."""
from __future__ import annotations

import json
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as _np

from ..base import MXNetError
from ..context import Context, current_context

__all__ = ["Symbol", "var", "Variable", "Group", "load", "load_json"]

_name_lock = threading.Lock()
_name_counters: Dict[str, int] = {}


def _op_kwargs(attrs):
    """Node attrs minus scoped user attributes: ``__key__``-style entries
    (AttrScope stamps, __shape__/__dtype__, ...) are metadata, never op
    parameters."""
    return {k: v for k, v in attrs.items()
            if not (k.startswith("__") and k.endswith("__"))}


def _auto_name(hint: str) -> str:
    with _name_lock:
        idx = _name_counters.get(hint, 0)
        _name_counters[hint] = idx + 1
    return f"{hint}{idx}"


class _Node:
    """One op application (or variable) in the graph."""

    __slots__ = ("op", "name", "attrs", "inputs", "num_outputs")

    def __init__(self, op: Optional[str], name: str, attrs: Dict[str, Any],
                 inputs: List[Tuple["_Node", int]], num_outputs: int = 1):
        self.op = op          # None for variables
        self.name = name
        self.attrs = attrs
        self.inputs = inputs
        self.num_outputs = num_outputs

    @property
    def is_var(self) -> bool:
        return self.op is None


class Symbol:
    """An ordered list of (node, output_index) heads."""

    def __init__(self, heads: List[Tuple[_Node, int]]):
        self._heads = heads

    # -- construction ------------------------------------------------------
    @staticmethod
    def var(name: str, shape=None, dtype=None, **kwargs) -> "Symbol":
        from .. import attribute as _attribute
        attrs = dict(_attribute.current().get())
        if shape is not None:
            attrs["__shape__"] = tuple(shape)
        if dtype is not None:
            # canonical name ("float16"), not str(np.float16)'s repr
            attrs["__dtype__"] = _np.dtype(dtype).name
        attrs.update({k: v for k, v in kwargs.items() if v is not None})
        return Symbol([(_Node(None, name, attrs, []), 0)])

    # -- protocol ----------------------------------------------------------
    @property
    def name(self) -> str:
        node, idx = self._heads[0]
        if node.num_outputs > 1:
            return f"{node.name}_output{idx}"
        return node.name

    def __len__(self):
        return len(self._heads)

    def __getitem__(self, idx):
        if isinstance(idx, str):
            names = self.list_outputs()
            idx = names.index(idx)
        return Symbol([self._heads[idx]])

    def __iter__(self):
        return (Symbol([h]) for h in self._heads)

    def __repr__(self):
        return f"<Symbol {self.name}>"

    # arithmetic builds graph nodes through the sym frontends
    def _binop(self, opname, other, reverse=False):
        from .register import apply_op
        if isinstance(other, Symbol):
            args = (other, self) if reverse else (self, other)
            return apply_op(opname, list(args), {})
        scal = {"scalar": float(other)}
        # one broadcast-op -> scalar-op mapping for both frontends
        from ..ndarray.register import _SCALAR_MAP
        fwd, rev = _SCALAR_MAP[opname]
        return apply_op(rev if reverse else fwd, [self], scal)

    def __add__(self, o):
        return self._binop("broadcast_add", o)

    def __radd__(self, o):
        return self._binop("broadcast_add", o, True)

    def __sub__(self, o):
        return self._binop("broadcast_sub", o)

    def __rsub__(self, o):
        return self._binop("broadcast_sub", o, True)

    def __mul__(self, o):
        return self._binop("broadcast_mul", o)

    def __rmul__(self, o):
        return self._binop("broadcast_mul", o, True)

    def __truediv__(self, o):
        return self._binop("broadcast_div", o)

    def __rtruediv__(self, o):
        return self._binop("broadcast_div", o, True)

    def __pow__(self, o):
        return self._binop("broadcast_power", o)

    # comparisons build graph nodes like the arithmetic dunders; __eq__ is
    # deliberately NOT overridden (Symbols must stay identity-hashable for
    # graph bookkeeping — use sym.broadcast_equal explicitly)
    def __gt__(self, o):
        return self._binop("broadcast_greater", o)

    def __ge__(self, o):
        return self._binop("broadcast_greater_equal", o)

    def __lt__(self, o):
        return self._binop("broadcast_lesser", o)

    def __le__(self, o):
        return self._binop("broadcast_lesser_equal", o)

    def __ne__(self, o):
        if isinstance(o, Symbol) or isinstance(o, (int, float)):
            return self._binop("broadcast_not_equal", o)
        return NotImplemented

    def __neg__(self):
        from .register import apply_op
        return apply_op("negative", [self], {})

    # -- graph walks -------------------------------------------------------
    def _topo(self) -> List[_Node]:
        order, seen = [], set()

        def visit(node: _Node):
            if id(node) in seen:
                return
            seen.add(id(node))
            for parent, _ in node.inputs:
                visit(parent)
            order.append(node)

        for node, _ in self._heads:
            visit(node)
        return order

    def list_arguments(self) -> List[str]:
        return [n.name for n in self._topo()
                if n.is_var and not n.attrs.get("__aux__")]

    def list_auxiliary_states(self) -> List[str]:
        return [n.name for n in self._topo()
                if n.is_var and n.attrs.get("__aux__")]

    def list_inputs(self) -> List[str]:
        return [n.name for n in self._topo() if n.is_var]

    def list_outputs(self) -> List[str]:
        outs = []
        for node, idx in self._heads:
            if node.num_outputs > 1:
                outs.append(f"{node.name}_output{idx}")
            else:
                outs.append(f"{node.name}_output")
        return outs

    def get_internals(self) -> "Symbol":
        heads = []
        for node in self._topo():
            for i in range(node.num_outputs):
                heads.append((node, i))
        return Symbol(heads)

    def attr(self, key):
        attrs = self._heads[0][0].attrs
        v = attrs.get(key)
        if v is None and not key.startswith("__"):
            v = attrs.get(f"__{key}__")   # AttrScope-stamped user attr
        return v

    def attr_dict(self):
        """name → attrs for every node carrying attrs (reference
        Symbol.attr_dict; init_params reads per-variable ``__init__``)."""
        out = {}
        for node in self._topo():
            if node.attrs:
                out[node.name] = dict(node.attrs)
        return out

    # -- lowering to a JAX function ---------------------------------------
    def compile(self, training: bool = False):
        """Return fn(feed: dict name→jax value) → list of output values.

        If the graph contains sampling ops (``Operator.needs_rng`` — Dropout,
        the ``_random_*``/``_sample_*`` families), the feed must carry a base
        PRNG key under ``"__rng_key__"``; the runner splits one subkey per
        sampling node, so a single fresh key per forward gives every node an
        independent draw (and, under jit, fresh randomness per call with no
        recompilation — the key is an argument, not a constant).  The
        returned function advertises this via its ``needs_rng`` attribute."""
        from ..ndarray.register import get_op

        order = self._topo()

        # only ACTIVE sampling nodes demand a key (node_takes_key is THE
        # shared predicate): a pure-inference executor of a Dropout model
        # or an rng-free foreach must not advance the global stream,
        # keeping seed(n); predict(); draw() reproducible
        from ..ndarray.register import _SUBGRAPH_OPS, node_takes_key
        rng_ids = [id(n) for n in order
                   if not n.is_var
                   and node_takes_key(n.op, n.attrs, training)]

        def run(feed: Dict[str, Any]) -> List[Any]:
            keymap: Dict[int, Any] = {}
            if rng_ids:
                import jax.random as jr
                base = feed.get("__rng_key__")
                if base is None:
                    raise MXNetError(
                        "graph contains sampling ops; feed must carry a "
                        "'__rng_key__' base key")
                keymap = dict(zip(rng_ids, jr.split(base, len(rng_ids))))
            vals: Dict[int, Any] = {}
            for node in order:
                if node.is_var:
                    if node.name not in feed:
                        raise MXNetError(
                            f"symbol input {node.name!r} missing from feed; "
                            f"have {sorted(feed)}")
                    vals[id(node)] = (feed[node.name],)
                    continue
                op = get_op(node.op)
                kwargs = _op_kwargs(node.attrs)
                if node.op in ("BatchNorm", "BatchNorm_v1", "Custom",
                               "_foreach", "_while_loop", "_cond"):
                    # train/eval-sensitive ops (BatchNorm statistics;
                    # subgraph bodies may hold Dropout/BatchNorm of their
                    # own) follow the executor's mode
                    kwargs.setdefault("_training", training)
                if op.needs_rng and id(node) not in keymap and \
                        node.op not in _SUBGRAPH_OPS:
                    # a sampling node node_takes_key() excluded from the
                    # key split (inference-gated Dropout) executes as
                    # identity — DERIVED from the shared predicate, so the
                    # gate cannot drift from the key-feed decision
                    vals[id(node)] = (vals[id(node.inputs[0][0])]
                                      [node.inputs[0][1]],)
                    continue
                extra = _scalar_extra(node.op, kwargs)
                fn = op.get_fn(kwargs)
                ins = [vals[id(p)][i] for p, i in node.inputs] + extra
                if id(node) in keymap:
                    ins.append(keymap[id(node)])
                out = fn(*ins)
                vals[id(node)] = out if isinstance(out, tuple) else (out,)
            return [vals[id(n)][i] for n, i in self._heads]

        run.needs_rng = bool(rng_ids)
        return run

    def eval_dict(self, feed: Dict[str, Any]):
        """Evaluate with a name→NDArray feed; returns NDArray(s)."""
        from ..ndarray import NDArray
        ctx = None
        jfeed = {}
        for k, v in feed.items():
            if isinstance(v, NDArray):
                jfeed[k] = v._read()
                ctx = ctx or v.context
            else:
                jfeed[k] = v
        run = self.compile()
        if run.needs_rng:
            from .. import random as _grandom
            jfeed["__rng_key__"] = _grandom.next_key()
        outs = [NDArray(v, ctx=ctx or current_context())
                for v in run(jfeed)]
        return outs[0] if len(outs) == 1 else outs

    def eval(self, ctx=None, **kwargs):
        out = self.eval_dict(kwargs)
        return out if isinstance(out, list) else [out]

    # -- shape/type inference ---------------------------------------------
    def infer_shape(self, *args, **kwargs):
        import jax
        arg_names = self.list_arguments()
        aux_names = self.list_auxiliary_states()
        shapes: Dict[str, Tuple[int, ...]] = {}
        if args:
            for n, s in zip(arg_names, args):
                if s is not None:
                    shapes[n] = tuple(s)
        shapes.update({k: tuple(v) for k, v in kwargs.items()})
        # iterative local inference via eval_shape with placeholder dtypes
        known = dict(shapes)
        for n in self._topo():
            if n.is_var and n.name not in known:
                declared = n.attrs.get("__shape__")
                if declared:
                    known[n.name] = tuple(declared)
        missing = [n for n in self.list_inputs() if n not in known]
        if missing:
            inferred = _infer_missing(self, known, missing)
            known.update(inferred)
        try:
            feed = {name: jax.ShapeDtypeStruct(tuple(known[name]),
                                               _np.float32)
                    for name in self.list_inputs()}
            run = self.compile()
            if run.needs_rng:
                feed["__rng_key__"] = _key_struct()
            outs = jax.eval_shape(lambda f: run(f), feed)
            out_shapes = [tuple(o.shape) for o in outs]
        except KeyError as e:
            raise MXNetError(f"cannot infer shapes; unknown input {e}")
        arg_shapes = [tuple(known[n]) for n in arg_names]
        aux_shapes = [tuple(known[n]) for n in aux_names]
        return arg_shapes, out_shapes, aux_shapes

    def infer_shape_partial(self, *args, **kwargs):
        try:
            return self.infer_shape(*args, **kwargs)
        except MXNetError:
            return None, None, None

    def infer_type(self, *args, **kwargs):
        """Forward dtype-propagation pass (the reference's FInferType
        fixed-point in miniature — nnvm infer_shape_type pass): seed
        variable dtypes from positional/keyword hints or ``__dtype__``
        attrs (default fp32), then walk the topo order with per-op
        rules (Cast-family fixes the dtype, comparisons/indices follow
        MXNet's fp32-out convention, everything else promotes)."""
        order = self._topo()
        var_nodes = [n for n in order if n.is_var]
        arg_names = [n.name for n in var_nodes
                     if not n.attrs.get("__aux__")]
        aux_names = [n.name for n in var_nodes if n.attrs.get("__aux__")]
        seeded: Dict[str, _np.dtype] = {}
        for n, t in zip(arg_names, args):
            if t is not None:
                seeded[n] = _np.dtype(t)
        all_inputs = {n.name for n in var_nodes}
        for k, v in kwargs.items():
            if k not in all_inputs:
                raise MXNetError(
                    f"infer_type got unknown argument {k!r}; inputs are "
                    f"{sorted(all_inputs)}")
            if v is not None:
                seeded[k] = _np.dtype(v)

        def parse_dt(v, default="float32"):
            try:
                return _np.dtype(v)
            except TypeError:
                return _np.dtype(default)

        # MXNet conventions: arg-index ops emit fp32; shape/size arrays
        # are int32 (matching the registered jnp.int32 lowerings)
        FIXED = {"argmax": _np.dtype(_np.float32),
                 "argmin": _np.dtype(_np.float32),
                 "one_hot": _np.dtype(_np.float32),
                 "shape_array": _np.dtype(_np.int32),
                 "size_array": _np.dtype(_np.int32)}
        dtypes: Dict[Tuple[int, int], _np.dtype] = {}
        name_to_dt: Dict[str, _np.dtype] = {}
        for node in order:
            if node.is_var:
                dt = seeded.get(node.name)
                if dt is None:
                    declared = node.attrs.get("__dtype__")
                    dt = parse_dt(declared) if declared \
                        else _np.dtype(_np.float32)
                dtypes[(id(node), 0)] = dt
                name_to_dt[node.name] = dt
                continue
            in_dts = [dtypes[(id(p), i)] for p, i in node.inputs]
            if node.op in ("Cast", "cast", "amp_cast") or \
                    (node.op in ("one_hot", "argsort") and
                     node.attrs.get("dtype")):
                out_dt = parse_dt(node.attrs.get("dtype", "float32"))
            elif node.op in FIXED:
                out_dt = FIXED[node.op]
            elif node.op == "argsort":
                out_dt = _np.dtype(_np.float32)
            elif in_dts:
                if len(in_dts) == 1:
                    out_dt = in_dts[0]
                else:
                    # MXNet (and the jax backend under x64-disabled)
                    # never widens int+float to fp64: the FLOAT
                    # operands decide; mixed floats take the widest
                    floats = [d for d in in_dts if d.kind == "f"]
                    out_dt = _np.result_type(*(floats or in_dts))
            else:
                out_dt = _np.dtype(_np.float32)
            for i in range(node.num_outputs):
                dtypes[(id(node), i)] = out_dt

        arg_types = [name_to_dt[n] for n in arg_names]
        aux_types = [name_to_dt[n] for n in aux_names]
        out_types = [dtypes[(id(n), i)] for n, i in self._heads]
        return arg_types, out_types, aux_types

    # -- binding -----------------------------------------------------------
    def simple_bind(self, ctx=None, grad_req="write", type_dict=None,
                    **kwargs):
        from .executor import Executor
        from ..ndarray import zeros as nd_zeros
        ctx = ctx or current_context()
        arg_shapes, _, aux_shapes = self.infer_shape(**kwargs)
        args = [nd_zeros(s, ctx=ctx) for s in arg_shapes]
        aux = [nd_zeros(s, ctx=ctx) for s in aux_shapes]
        grad_arrays = None
        if grad_req != "null":
            grad_arrays = [nd_zeros(s, ctx=ctx) for s in arg_shapes]
        return Executor(self, ctx, args, grad_arrays, grad_req, aux)

    def bind(self, ctx=None, args=None, args_grad=None, grad_req="write",
             aux_states=None, group2ctx=None, shared_exec=None):
        from .executor import Executor
        ctx = ctx or current_context()
        arg_names = self.list_arguments()
        if isinstance(args, dict):
            args = [args[n] for n in arg_names]
        if isinstance(args_grad, dict):
            args_grad = [args_grad.get(n) for n in arg_names]
        if isinstance(aux_states, dict):
            aux_states = [aux_states[n]
                          for n in self.list_auxiliary_states()]
        return Executor(self, ctx, list(args),
                        list(args_grad) if args_grad else None, grad_req,
                        list(aux_states) if aux_states else [])

    # -- serialization -----------------------------------------------------
    def tojson(self) -> str:
        order = self._topo()
        nid = {id(n): i for i, n in enumerate(order)}
        nodes = []
        for n in order:
            nodes.append({
                "op": n.op if n.op else "null",
                "name": n.name,
                "attrs": {k: _attr_str(v) for k, v in n.attrs.items()},
                "inputs": [[nid[id(p)], i, 0] for p, i in n.inputs],
            })
        arg_nodes = [i for i, n in enumerate(order) if n.is_var]
        heads = [[nid[id(n)], i, 0] for n, i in self._heads]
        return json.dumps({"nodes": nodes, "arg_nodes": arg_nodes,
                           "node_row_ptr": [], "heads": heads,
                           "attrs": {"mxnet_version": ["int", 10500]}},
                          indent=2)

    def save(self, fname: str) -> None:
        with open(fname, "w") as f:
            f.write(self.tojson())

    def __deepcopy__(self, memo):
        return load_json(self.tojson())


_KEY_STRUCT = None


def _key_struct():
    """ShapeDtypeStruct of a PRNG key, for abstract (eval_shape) runs.
    Computed once — the struct is invariant and building a real key per
    call would waste a device computation on every shape inference."""
    global _KEY_STRUCT
    if _KEY_STRUCT is None:
        import jax
        import jax.random as jr
        k = jr.PRNGKey(0)
        _KEY_STRUCT = jax.ShapeDtypeStruct(k.shape, k.dtype)
    return _KEY_STRUCT


def _scalar_extra(opname: str, kwargs: Dict[str, Any]) -> list:
    """The *_scalar op family takes the scalar as a 0-d array input (one
    compile per shape, not per constant — see ops_elemwise); in symbol
    graphs it is stored as a node attr, so pop it into an input here."""
    if opname.endswith("_scalar") and "scalar" in kwargs:
        import jax.numpy as jnp
        return [jnp.asarray(kwargs.pop("scalar"))]
    return []


def _attr_str(v) -> str:
    if isinstance(v, str):
        return v
    return json.dumps(v) if isinstance(v, (list, tuple, dict)) else str(v)


def _parse_attr(s: str):
    if not isinstance(s, str):
        return s
    low = s.strip()
    if low in ("True", "true"):
        return True
    if low in ("False", "false"):
        return False
    try:
        return json.loads(low)
    except (ValueError, TypeError):
        return s


def _infer_missing(sym: Symbol, known: Dict[str, Tuple[int, ...]],
                   missing: List[str]) -> Dict[str, Tuple[int, ...]]:
    """Forward-walk inferring parameter shapes for common layer ops from the
    data shapes (the role of the reference's fixed-point InferShape pass)."""
    from ..ndarray.register import get_op
    import jax
    out: Dict[str, Tuple[int, ...]] = {}
    shapes: Dict[Tuple[int, int], Tuple[int, ...]] = {}
    for node in sym._topo():
        if node.is_var:
            name = node.name
            if name in known:
                shapes[(id(node), 0)] = tuple(known[name])
            continue
        in_shapes = []
        unknown_inputs = []
        for p, i in node.inputs:
            s = shapes.get((id(p), i))
            in_shapes.append(s)
            # see through dtype casts (AMP-converted graphs wrap params in
            # amp_cast): the shape rule applies to the underlying variable
            while p is not None and p.op in ("amp_cast", "cast", "Cast") \
                    and len(p.inputs) == 1:
                p = p.inputs[0][0]
            if s is None and p is not None and p.is_var:
                unknown_inputs.append((p, len(in_shapes) - 1))
        if unknown_inputs:
            _infer_node_params(node, in_shapes, unknown_inputs, out)
            for p, pos in unknown_inputs:
                if p.name in out:
                    shapes[(id(p), 0)] = out[p.name]
                    in_shapes[pos] = out[p.name]
        if any(s is None for s in in_shapes):
            continue
        op = get_op(node.op)
        kwargs = _op_kwargs(node.attrs)
        if node.op in ("BatchNorm", "BatchNorm_v1"):
            kwargs.setdefault("_training", False)
        try:
            extra = _scalar_extra(node.op, kwargs)
            # match the maker fn's ARITY exactly: non-subgraph sampling
            # fns always take a key (the runner may skip them, but when
            # called they expect it); the control-flow trio pops a key
            # only when a subgraph samples (op_takes_key)
            if op.needs_rng:
                from ..ndarray.register import _SUBGRAPH_OPS, op_takes_key
                if node.op not in _SUBGRAPH_OPS or op_takes_key(op, kwargs):
                    extra = extra + [_key_struct()]
            fn = op.get_fn(kwargs)
            outs = jax.eval_shape(
                fn, *[jax.ShapeDtypeStruct(s, _np.float32)
                      for s in in_shapes], *extra)
            outs = outs if isinstance(outs, tuple) else (outs,)
            for i, o in enumerate(outs):
                shapes[(id(node), i)] = tuple(o.shape)
        except Exception:
            continue
    return out


def _infer_node_params(node: _Node, in_shapes, unknown, out) -> None:
    """Parameter-shape rules for the common layers (weight/bias/γ/β...)."""
    a = node.attrs
    data = in_shapes[0]
    if data is None:
        return
    if node.op == "FullyConnected":
        nh = int(a.get("num_hidden"))
        flat = a.get("flatten", True)
        in_units = 1
        if flat:
            for s in data[1:]:
                in_units *= s
        else:
            in_units = data[-1]
        for p, pos in unknown:
            if pos == 1:
                out[p.name] = (nh, in_units)
            elif pos == 2:
                out[p.name] = (nh,)
    elif node.op in ("Convolution", "Deconvolution"):
        nf = int(a.get("num_filter"))
        k = tuple(a.get("kernel"))
        ng = int(a.get("num_group", 1))
        cin = data[1]
        for p, pos in unknown:
            if pos == 1:
                if node.op == "Convolution":
                    out[p.name] = (nf, cin // ng) + k
                else:
                    out[p.name] = (cin, nf // ng) + k
            elif pos == 2:
                out[p.name] = (nf,)
    elif node.op in ("BatchNorm", "BatchNorm_v1", "LayerNorm",
                     "InstanceNorm"):
        axis = int(a.get("axis",
                         1 if node.op.startswith("BatchNorm") else -1))
        c = data[axis % len(data)]
        for p, pos in unknown:
            out[p.name] = (c,)
    elif node.op == "Embedding":
        for p, pos in unknown:
            if pos == 1:
                out[p.name] = (int(a.get("input_dim")),
                               int(a.get("output_dim")))
    elif node.op == "RNN":
        # packed cuDNN-layout parameter vector + zero states derived from
        # data (T, N, I) and the op attrs — lets FusedRNNCell bind without
        # a declared input_size.  Malformed graphs degrade to
        # shape-unknown (the pre-existing contract), never crash here.
        from ..base import rnn_packed_param_count
        mode = a.get("mode", "lstm")
        if len(data) != 3 or a.get("state_size") is None or \
                mode not in ("lstm", "gru", "rnn_tanh", "rnn_relu"):
            return
        T, N, I = data
        H = int(a.get("state_size"))
        nl = int(a.get("num_layers", 1))
        ndir = 2 if a.get("bidirectional") else 1
        total = rnn_packed_param_count(mode, I, H, nl,
                                       bool(a.get("bidirectional")))
        for p, pos in unknown:
            if pos == 1:
                out[p.name] = (total,)
            elif pos in (2, 3):
                out[p.name] = (ndir * nl, N, H)


def var(name: str, **kwargs) -> Symbol:
    return Symbol.var(name, **kwargs)


Variable = var


def Group(symbols: Sequence[Symbol]) -> Symbol:
    heads = []
    for s in symbols:
        heads.extend(s._heads)
    return Symbol(heads)


_SUBGRAPH_ATTRS = ("subgraph", "cond_subgraph", "body_subgraph",
                   "then_subgraph", "else_subgraph")


def load_json(json_str: str) -> Symbol:
    data = json.loads(json_str)
    nodes: List[_Node] = []
    for nd_ in data["nodes"]:
        attrs = {k: _parse_attr(v) for k, v in nd_.get("attrs", {}).items()}
        if nd_["op"] in ("_foreach", "_while_loop", "_cond"):
            # subgraph attrs serialized as embedded graph JSON — rebuild
            # the Symbol wrapper (reference: subgraph deserialization in
            # nnvm::Graph LoadJSON)
            from ..ndarray.ops_control_flow import SubgraphAttr
            for key in _SUBGRAPH_ATTRS:
                if isinstance(attrs.get(key), dict):
                    attrs[key] = SubgraphAttr(
                        load_json(json.dumps(attrs[key])))
        inputs = [(nodes[i], oi) for i, oi, _ in nd_.get("inputs", [])]
        op = None if nd_["op"] == "null" else nd_["op"]
        num_out = 1
        node = _Node(op, nd_["name"], attrs, inputs, num_out)
        nodes.append(node)
    # fix num_outputs from max referenced index
    for nd_, node in zip(data["nodes"], nodes):
        for i, oi, _ in nd_.get("inputs", []):
            nodes[i].num_outputs = max(nodes[i].num_outputs, oi + 1)
    for ref in data["heads"]:
        nodes[ref[0]].num_outputs = max(nodes[ref[0]].num_outputs,
                                        ref[1] + 1)
    heads = [(nodes[i], oi) for i, oi, *_ in data["heads"]]
    return Symbol(heads)


def load(fname: str) -> Symbol:
    with open(fname) as f:
        return load_json(f.read())
