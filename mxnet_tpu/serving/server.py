"""ModelServer: continuous-batching inference over CachedOp graphs.

The executable a model server wants already exists in this stack:
``hybridize()``'s compiled-graph artifact (PAPER.md L6a — the CachedOp
analog).  This module wraps it in the serving loop the north star's
"millions of users" traffic shape needs:

    submit() -> AdmissionQueue (bounded, 429 past depth)
             -> batcher thread: shape-bucketed batch assembly
                (padding-length buckets, the BERT bench idiom)
             -> dispatch workers: ONE CachedGraph.raw call per bucket,
                batch formation overlapping device execution
             -> per-request results, metrics, flight-recorder records

Observability is wired from day one: ``serving.request_us`` (per-request
end-to-end latency histogram), ``serving.queue_depth`` (gauge),
``serving.dispatch_us`` (per-batch device-call histogram), and the
batch-formation-efficiency counters ``serving.tokens_real`` /
``serving.tokens_padded`` — all through the process-global registry, so
the Prometheus endpoint and JSONL writer see the serving path with zero
extra plumbing.  Every completed request also lands in the flight
recorder's per-request ring, dumped on crash alongside step records.

Knobs (all through ``base.register_env``): ``MXTPU_SERVING_MAX_BATCH``,
``MXTPU_SERVING_QUEUE_DEPTH``, ``MXTPU_SERVING_DEADLINE_MS``,
``MXTPU_SERVING_WORKERS``, ``MXTPU_SERVING_BATCH_WINDOW_US``.
"""
from __future__ import annotations

import itertools
import queue as _queue
import signal
import threading
import time
from typing import Dict, Optional, Sequence, Tuple

import numpy as _np

from ..base import MXNetError, get_env, hot_path, jax_compute_dtype
from ..ndarray import NDArray, array as nd_array
from ..observability import tracing as _tracing
from ..observability.flight import recorder as _flight_recorder
from ..observability.registry import registry
from ..observability.sampler import maybe_start_from_env as \
    _maybe_start_sampler
from ..observability.watchdog import touchpoint as _touchpoint
from .batcher import (AdmissionQueue, Batcher, DeadlineExceeded,
                      GenRequest, Request, RequestCancelled, ServerClosed,
                      ServerOverloaded)
from .buckets import Bucketer, NoBucketError
from .kv_cache import BlockKVCache

__all__ = ["ModelServer", "GenerationServer"]

MAX_BATCH_ENV = "MXTPU_SERVING_MAX_BATCH"
QUEUE_DEPTH_ENV = "MXTPU_SERVING_QUEUE_DEPTH"
DEADLINE_MS_ENV = "MXTPU_SERVING_DEADLINE_MS"
WORKERS_ENV = "MXTPU_SERVING_WORKERS"
BATCH_WINDOW_US_ENV = "MXTPU_SERVING_BATCH_WINDOW_US"
KV_BLOCK_ENV = "MXTPU_SERVING_KV_BLOCK"
KV_BLOCKS_ENV = "MXTPU_SERVING_KV_BLOCKS"
DECODE_SLOTS_ENV = "MXTPU_SERVING_DECODE_SLOTS"
PREFILL_MODE_ENV = "MXTPU_SERVING_PREFILL_MODE"
MAX_NEW_ENV = "MXTPU_SERVING_MAX_NEW_TOKENS"


def _live_window_s() -> float:
    """The knob-governed batch window, re-read before every batch pop
    (the BatchWindowController's live adaptation seam)."""
    return float(get_env(BATCH_WINDOW_US_ENV)) / 1e6


def _key_str(key: Tuple) -> str:
    """Compact human-readable bucket tag for records/debugging:
    ``32:int32|32:int32`` — dtype included, so two buckets differing
    only in dtype stay distinguishable in postmortems."""
    parts = []
    for shape, dt in key:
        parts.append(("x".join(str(s) for s in shape) or "scalar")
                     + ":" + str(dt))
    return "|".join(parts)


def _freeze_generic(block, examples):
    """Compile a non-Hybrid block (e.g. a SymbolBlock from the export
    seam) into one jitted inference callable with the CachedGraph.raw
    contract: raw values in, tuple of raw jax arrays out.  Parameters
    are baked as constants — fine for serving, where weights are
    immutable."""
    import jax

    from .. import autograd as _autograd

    ctx = examples[0].context

    def fn(*vals):
        ins = [NDArray(v, ctx=ctx) for v in vals]
        with _autograd.pause():
            out = block(*ins)
        outs = out if isinstance(out, (list, tuple)) else (out,)
        return tuple(o._read() for o in outs)

    jitted = jax.jit(fn)
    jax.block_until_ready(jitted(*[e._read() for e in examples]))
    return jitted


class ModelServer:
    """Continuous-batching inference server over one model.

    ``block`` is a :class:`~mxnet_tpu.gluon.HybridBlock` (served through
    the direct cached-graph entry — no autograd bookkeeping) or any
    Block (e.g. a ``SymbolBlock`` imported from the ``export()`` seam —
    see :meth:`from_exported`), serving host-side numpy results.

    Requests are single samples WITHOUT the batch dimension; the server
    assembles them into padded, bucketed batches and runs one compiled
    call per bucket.  ``submit`` is non-blocking and returns a
    :class:`~mxnet_tpu.serving.batcher.Request` future; ``infer`` is the
    blocking convenience wrapper.

    Lifecycle: ``start()`` spawns the batcher + N dispatch workers;
    ``stop(drain=True)`` (or context-manager exit, or SIGTERM via
    :meth:`install_sigterm`) closes admission, drains every queued
    request, and joins the threads.
    """

    def __init__(self, block, *, max_batch: Optional[int] = None,
                 queue_depth: Optional[int] = None,
                 deadline_ms: Optional[float] = None,
                 workers: Optional[int] = None,
                 length_buckets: Optional[Sequence[int]] = None,
                 pad_axis: int = 0,
                 batch_buckets: Optional[Sequence[int]] = None,
                 batch_window_us: Optional[float] = None,
                 unpad_outputs: bool = True,
                 flight=None):
        self._block = block
        self.unpad_outputs = unpad_outputs
        self.max_batch = int(get_env(MAX_BATCH_ENV) if max_batch is None
                             else max_batch)
        self.queue_depth = int(get_env(QUEUE_DEPTH_ENV)
                               if queue_depth is None else queue_depth)
        self.deadline_ms = float(get_env(DEADLINE_MS_ENV)
                                 if deadline_ms is None else deadline_ms)
        self.workers = max(1, int(get_env(WORKERS_ENV)
                                  if workers is None else workers))
        # explicit argument = frozen window; knob-governed (the default)
        # = read LIVE per batch, so the BatchWindowController (or an
        # operator export) retunes a running server
        if batch_window_us is None:
            window_s = _live_window_s
        else:
            window_s = float(batch_window_us) / 1e6
        self._bucketer = Bucketer(self.max_batch,
                                  length_buckets=length_buckets,
                                  pad_axis=pad_axis,
                                  batch_buckets=batch_buckets)
        reg = registry()
        self._g_depth = reg.gauge(
            "serving.queue_depth",
            help="admission-queue depth (requests waiting for assembly)")
        self._h_request = reg.histogram(
            "serving.request_us",
            help="per-request end-to-end latency (enqueue to done)")
        self._h_dispatch = reg.histogram(
            "serving.dispatch_us",
            help="per-batch compiled-call wall time")
        self._c_requests = reg.counter(
            "serving.requests", help="requests admitted")
        self._c_done = reg.counter(
            "serving.requests_done", help="requests completed ok")
        self._c_rej_429 = reg.counter(
            "serving.rejected_429",
            help="requests rejected at admission (queue full)")
        self._c_rej_deadline = reg.counter(
            "serving.rejected_deadline",
            help="requests rejected at assembly (deadline expired)")
        self._c_batches = reg.counter(
            "serving.batches", help="batched compiled calls dispatched")
        self._c_real = reg.counter(
            "serving.tokens_real",
            help="real (unpadded) elements served — batch-efficiency "
                 "numerator")
        self._c_padded = reg.counter(
            "serving.tokens_padded",
            help="padded sequence positions dispatched within occupied "
                 "batch slots (length-bucket waste)")
        self._c_slots_padded = reg.counter(
            "serving.slots_padded",
            help="empty batch slots dispatched (batch-bucket waste), "
                 "counted in slots — kept apart from tokens_padded so "
                 "sequence-padding efficiency is not polluted by "
                 "batch-pad")
        self._flight = _flight_recorder() if flight is None else flight
        self._admission = AdmissionQueue(self.queue_depth,
                                         gauge=self._g_depth)
        self._out: _queue.Queue = _queue.Queue(
            maxsize=max(2, 2 * self.workers))
        self._batcher = Batcher(self._admission, self._bucketer,
                                self._out, self.max_batch,
                                window_s, self._expire,
                                on_error=self._fail)
        self._graphs: Dict[Tuple, object] = {}
        self._compile_lock = threading.Lock()
        self._lifecycle_lock = threading.Lock()
        self._threads = []
        self._started = False
        self._stopped = False
        self._drain_down = False
        self._rid = itertools.count()
        self._prev_sigterm = None
        # progress heartbeat for the watchdog: one bump per worker-loop
        # iteration (idle pops included — a healthy-idle server keeps
        # beating; only a wedged dispatch goes silent), thresholded on
        # the dispatch histogram's recent p99
        self._tp_dispatch = _touchpoint("serving.dispatch",
                                        hist="serving.dispatch_us")
        _maybe_start_sampler()

    # -- construction helpers ----------------------------------------------
    @classmethod
    def from_exported(cls, symbol_file: str, input_names,
                      param_file: Optional[str] = None, ctx=None, **kw
                      ) -> "ModelServer":
        """Serve an exported symbol/params pair (the
        ``examples/serve_c_api.md`` export seam): loads via
        ``SymbolBlock.imports`` and serves through one jitted graph."""
        from ..gluon.block import SymbolBlock
        blk = SymbolBlock.imports(symbol_file, input_names, param_file,
                                  ctx=ctx)
        return cls(blk, **kw)

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "ModelServer":
        with self._lifecycle_lock:
            if self._started:
                return self
            if self._stopped:
                raise ServerClosed("server already stopped")
            self._batcher.start()
            for i in range(self.workers):
                t = threading.Thread(target=self._worker_loop,
                                     name=f"mxtpu-serving-worker-{i}",
                                     daemon=True)
                t.start()
                self._threads.append(t)
            self._started = True
        return self

    def stop(self, drain: bool = True, timeout: Optional[float] = None
             ) -> None:
        """Shut down: close admission (further submits raise
        ServerClosed), then either drain every queued request through
        the normal path (``drain=True``) or fail them immediately."""
        with self._lifecycle_lock:
            if self._stopped:
                return
            self._stopped = True
            self._admission.close()
            if not drain:
                for r in self._admission.shed():
                    self._finish(r, error=ServerClosed(
                        "server stopped without draining"))
            if self._started:
                self._batcher.join(timeout)
                if self._batcher.is_alive():
                    # timed-out join: the batcher may still be putting
                    # batches — sentinels would race AHEAD of them and
                    # strand their requests.  Flag the workers down
                    # instead; they drain whatever still arrives and
                    # exit on an idle tick.
                    self._drain_down = True
                else:
                    for _ in self._threads:
                        try:
                            self._out.put(None, timeout=1.0)
                        except _queue.Full:   # a wedged worker: flag
                            self._drain_down = True
                            break
                for t in self._threads:
                    t.join(timeout)
            else:
                # never started: nothing will drain the queue — shed
                for r in self._admission.shed():
                    self._finish(r, error=ServerClosed(
                        "server stopped before start"))
            self._g_depth.set(0)

    def __enter__(self) -> "ModelServer":
        return self.start()

    def __exit__(self, exc_type, exc, tb) -> None:
        self.stop(drain=exc_type is None)

    def install_sigterm(self) -> None:
        """Chain a SIGTERM handler that drains and stops the server
        (the k8s/preemption graceful-shutdown contract), then calls the
        previous handler.  The drain runs on its OWN (non-daemon)
        thread: the signal may have interrupted a frame on this very
        thread holding the locks stop() needs, so blocking inside the
        handler would deadlock — the handler returns immediately, the
        interrupted frame resumes and releases its locks, and the drain
        thread keeps the process alive until shutdown completes."""
        prev = signal.getsignal(signal.SIGTERM)
        self._prev_sigterm = prev

        def drain_then_chain(signum, frame):
            self.stop(drain=True)
            if callable(prev) and prev not in (signal.SIG_IGN,
                                               signal.SIG_DFL):
                prev(signum, frame)

        def handler(signum, frame):
            threading.Thread(target=drain_then_chain,
                             args=(signum, frame),
                             name="mxtpu-serving-sigterm-drain",
                             daemon=False).start()

        signal.signal(signal.SIGTERM, handler)

    def uninstall_sigterm(self) -> None:
        if self._prev_sigterm is not None:
            signal.signal(signal.SIGTERM, self._prev_sigterm)
            self._prev_sigterm = None

    # -- client surface ------------------------------------------------------
    def submit(self, *inputs, deadline_ms: Optional[float] = None
               ) -> Request:
        """Enqueue one sample (inputs WITHOUT the batch dim); returns a
        Request future.  Raises :class:`ServerOverloaded` when the
        admission queue is full, :class:`ServerClosed` after stop, and
        :class:`NoBucketError` when no shape bucket fits."""
        arrs = []
        for x in inputs:
            a = x.asnumpy() if isinstance(x, NDArray) else _np.asarray(x)  # mxlint: disable=hidden-host-sync — request ingestion: client samples become host buffers at the serving boundary
            cd = jax_compute_dtype(a.dtype)
            if a.dtype != cd:
                a = a.astype(cd)
            arrs.append(a)
        key = self._bucketer.sample_key(arrs)
        ms = self.deadline_ms if deadline_ms is None else float(deadline_ms)
        deadline = (time.monotonic() + ms / 1e3) if ms > 0 else None
        req = Request(next(self._rid), tuple(arrs), key, deadline)
        # causal tracing root: ONE trace per request, head-sampled here
        # at admission (explicit lifecycle — finished in _finish on a
        # worker thread; the Request object carries the context across
        # the queue hops)
        req.trace = _tracing.tracer().begin(
            "serving.request", activate=False,
            args={"rid": req.rid, "bucket": _key_str(key)})
        try:
            self._admission.submit(req)
        except BaseException as exc:
            # admission refused ownership: the request never enters the
            # pipeline, so nobody downstream will ever finish this span.
            # Span hygiene FIRST, metrics after — the close must not
            # depend on anything else in the handler succeeding.
            if req.trace is not None:
                req.trace.annotate(error=type(exc).__name__)
                req.trace.finish()
            if isinstance(exc, ServerOverloaded):
                self._c_rej_429.inc()
            raise
        self._c_requests.inc()
        return req

    def infer(self, *inputs, timeout: Optional[float] = None,
              deadline_ms: Optional[float] = None):
        """Blocking convenience: submit + wait; returns host numpy
        output(s)."""
        return self.submit(*inputs, deadline_ms=deadline_ms
                           ).result(timeout)

    def warmup(self, *samples) -> int:
        """Precompile every (shape bucket, batch bucket) signature the
        given example samples imply, so no live request pays a compile.
        Each sample is one request's input tuple (or a single array).
        Returns the number of executables now resident."""
        for sample in samples:
            sample = sample if isinstance(sample, (tuple, list)) \
                else (sample,)
            # canonicalize dtypes exactly as submit() does, or the
            # warmed signatures can never match live requests
            arrs = []
            for a in sample:
                a = _np.asarray(a)
                cd = jax_compute_dtype(a.dtype)
                arrs.append(a.astype(cd) if a.dtype != cd else a)
            key = self._bucketer.sample_key(arrs)
            for bsz in self._bucketer.batch_buckets:
                self._graph_for(key, bsz)
        return len(self._graphs)

    def stats(self) -> dict:
        """Serving-side registry view plus the derived
        sequence-padding-efficiency ratio (real positions over positions
        dispatched in occupied slots — empty batch slots are reported
        separately as ``slots_padded``, not folded into the ratio)."""
        real, padded = self._c_real.n, self._c_padded.n
        return {
            "requests": self._c_requests.n,
            "done": self._c_done.n,
            "rejected_429": self._c_rej_429.n,
            "rejected_deadline": self._c_rej_deadline.n,
            "batches": self._c_batches.n,
            "queue_depth": self._g_depth.value,
            "tokens_real": real,
            "tokens_padded": padded,
            "slots_padded": self._c_slots_padded.n,
            "batch_efficiency": round(real / (real + padded), 4)
            if real + padded else 0.0,
            "executables": len(self._graphs),
        }

    # -- compiled-graph resolution (cold path) -------------------------------
    def _build_graph(self, block, key: Tuple, batch: int):
        """Compile ``block``'s executable for one (shape bucket, batch
        bucket) signature — lock-free, so :meth:`swap_block` can stage a
        full replacement graph set while live traffic keeps hitting the
        current one."""
        examples = [nd_array(_np.zeros((batch,) + tuple(shape),
                                       dtype=dt))
                    for shape, dt in key]
        from ..gluon.block import HybridBlock
        if isinstance(block, HybridBlock):
            g = block.cached_graph(*examples).raw
        else:
            g = _freeze_generic(block, examples)
        # one throwaway dispatch with HOST (numpy) arguments — the
        # argument types live batches arrive with.  The build above
        # warmed the executable against device-committed example
        # arrays; jax keys the lowering on argument sharding, so
        # without this the FIRST live batch would pay a second
        # lowering+compile (measured: ~600ms on the transformer)
        import jax as _jax
        _jax.block_until_ready(g(
            *[_np.zeros((batch,) + tuple(shape), dtype=dt)
              for shape, dt in key]))
        return g

    def _graph_for(self, key: Tuple, batch: int):
        """The executable for one (shape bucket, batch bucket): built on
        first use (``warmup()`` prebuilds), then a dict hit forever."""
        gk = (key, batch)
        g = self._graphs.get(gk)
        if g is not None:
            return g
        with self._compile_lock:
            g = self._graphs.get(gk)
            if g is not None:
                return g
            g = self._build_graph(self._block, key, batch)
            self._graphs[gk] = g
            return g

    # -- blue/green weight swap ----------------------------------------------
    def swap_block(self, new_block) -> int:
        """Rolling blue/green swap: compile ``new_block`` (the green
        side — typically the same architecture with new parameters) for
        EVERY signature the current graph set serves, all outside the
        lock while live traffic keeps dispatching on the old
        executables, then flip the block and the whole graph dict
        atomically.  In-flight batches hold a reference to the old
        executable and complete on it — zero requests drop.  With
        ``MXTPU_COMPILE_CACHE_DIR`` set the green compiles deserialize
        from the persistent cache (same architecture = same lowering).
        Returns the number of executables in the new set."""
        staged: Dict[Tuple, object] = {}
        for gk in list(self._graphs.keys()):
            staged[gk] = self._build_graph(new_block, gk[0], gk[1])
        with self._compile_lock:
            # a signature first compiled while we staged: build it for
            # the green side too (rare — the race window is one compile)
            for gk in list(self._graphs.keys()):
                if gk not in staged:
                    staged[gk] = self._build_graph(new_block, gk[0],
                                                   gk[1])
            self._block = new_block
            self._graphs = staged
        return len(staged)

    # -- dispatch-worker scaling (SloController seam) ------------------------
    def set_workers(self, n: int) -> int:
        """Retarget the dispatch-worker count on a RUNNING server (the
        :class:`~mxnet_tpu.tuning.controllers.SloController`'s scaling
        surface).  Growth spawns workers immediately; shrink retires
        one worker per sentinel, after any batches already queued ahead
        of it — requests are never dropped by a shrink.  Returns the
        new target."""
        n = max(1, int(n))
        with self._lifecycle_lock:
            if self._stopped:
                return self.workers
            delta = n - self.workers
            if delta == 0:
                return n
            if not self._started:
                self.workers = n
                return n
            if delta > 0:
                for _ in range(delta):
                    t = threading.Thread(
                        target=self._worker_loop,
                        name=f"mxtpu-serving-worker-{len(self._threads)}",
                        daemon=True)
                    t.start()
                    self._threads.append(t)
            else:
                for _ in range(-delta):
                    try:
                        self._out.put(None, timeout=1.0)
                    except _queue.Full:
                        # a wedged dispatch queue: scaling DOWN under
                        # that much pressure is wrong anyway — keep the
                        # workers we failed to retire
                        n += 1
            self.workers = n
            return n

    # -- dispatch (hot path) -------------------------------------------------
    def _worker_loop(self) -> None:
        tp = self._tp_dispatch
        while True:
            tp.beat()
            try:
                batch = self._out.get(timeout=0.25)
            except _queue.Empty:
                if self._drain_down:
                    break
                continue
            if batch is None:
                break
            try:
                graph = self._graph_for(batch.key, batch.batch)
                self._dispatch_batch(graph, batch)
            except Exception as e:  # a failed batch fails ITS requests,
                for req in batch.requests:      # never the server
                    if not req.done():
                        self._finish(req, error=e)

    @hot_path("dispatch")
    def _dispatch_batch(self, graph, batch) -> None:
        """Serving dispatch entry point: ONE compiled call for the whole
        bucket, one batched device→host transfer, then per-request
        fan-out."""
        # dispatch span: child of the batch's assembly span (tracing
        # off = batch.trace is None = no tracer touch on this hot root)
        sp = None if batch.trace is None else _tracing.tracer().begin(
            "serving.dispatch", parent=batch.trace, activate=False,
            args={"batch": batch.batch, "bucket": _key_str(batch.key)})
        rb = None
        try:
            t0 = time.monotonic()
            for req in batch.requests:
                req.t_dispatch = t0
            flat = graph(*batch.arrays)
            rb = None if sp is None else _tracing.tracer().begin(
                "serving.readback", parent=sp, activate=False)
            # response materialization: ONE batched device→host transfer
            # per BATCH (results are host values by contract), not per
            # request
            outs = [_np.asarray(v) for v in flat]  # mxlint: disable=hidden-host-sync,hot-path-purity — batched response readback, one transfer (and one buffer) per batch
        except BaseException as exc:
            # a failed batch must still record its dispatch span — the
            # postmortem trace of exactly the batch that died
            if sp is not None:
                sp.annotate(error=type(exc).__name__)
                if rb is not None:
                    rb.finish()
                sp.finish()
            raise
        if rb is not None:
            rb.finish()
            sp.finish()
        # inc(), not .n bumps: N workers finish batches concurrently and
        # the direct-bump idiom is reserved for single-threaded hot loops
        self._h_dispatch.observe((time.monotonic() - t0) * 1e6)
        self._c_batches.inc()
        self._c_real.inc(batch.real)
        self._c_padded.inc(batch.tokens_padded)
        self._c_slots_padded.inc(batch.slots_padded)
        for i, req in enumerate(batch.requests):
            req.batch_size = batch.batch
            row = self._unpad_row(tuple(o[i] for o in outs), req)
            self._finish(req, result=row[0] if len(row) == 1 else row)

    def _unpad_row(self, row, req: Request):
        """Undo length-bucket padding on a request's outputs: slice axis
        ``pad_axis`` (per-sample) back to the request's real length when
        its size equals the padded bucket — a per-position output like
        BERT's MLM logits trims; a pooled output with a different
        ``pad_axis`` extent passes through.  A pooled dim that
        COINCIDES with a bucket size (e.g. a 64-wide embedding under a
        64-token bucket) is indistinguishable from a length axis —
        construct with ``unpad_outputs=False`` and slice client-side
        for such models.  The padded positions' VALUES remain a model
        contract: a sequence model that attends everywhere must take a
        mask/valid-length input (pass it as part of the request) — the
        server cannot invent one."""
        bkt = self._bucketer
        if not bkt.length_buckets or not self.unpad_outputs:
            return row
        ax = bkt.pad_axis
        padded = req.key[0][0][ax]
        real = req.inputs[0].shape[ax]
        if real == padded:
            return row
        out = []
        for o in row:
            if o.ndim > ax and o.shape[ax] == padded:
                sl = [slice(None)] * o.ndim
                sl[ax] = slice(0, real)
                o = o[tuple(sl)]
            out.append(o)
        return tuple(out)

    def _finish(self, req: Request, result=None, error=None) -> None:
        """Complete one request: latency histogram, counters, flight
        record, wake the client."""
        req.t_done = time.monotonic()
        req._result = result
        req._error = error
        dur_us = (req.t_done - req.t_enqueue) * 1e6
        trace_id = None
        if req.trace is not None:
            trace_id = req.trace.trace_id
            if error is not None:
                req.trace.annotate(error=type(error).__name__)
            req.trace.finish()
        if error is None:
            # the explicit trace_id puts the exemplar on THIS request's
            # trace (no contextvar crosses the worker-thread hop)
            self._h_request.observe(dur_us, trace_id=trace_id)
            self._c_done.inc()
        self._flight.record_request(
            request_id=req.rid,
            enqueue=round(req.t_enqueue, 6),
            assemble=round(req.t_assemble, 6),
            dispatch=round(req.t_dispatch, 6),
            done=round(req.t_done, 6),
            bucket=_key_str(req.key),
            batch_size=req.batch_size,
            us=round(dur_us, 1),
            # causal cross-reference: a crash dump's request ring points
            # into the span ring / JSONL stream
            trace_id=trace_id,
            ok=error is None)
        req._event.set()

    def _expire(self, req: Request) -> None:
        self._c_rej_deadline.inc()
        self._finish(req, error=DeadlineExceeded(
            f"request {req.rid} spent its deadline queued (429-style); "
            f"the server is over capacity — back off"))

    def _fail(self, req: Request, error: BaseException) -> None:
        """Assembly-failure path: same accounting as every other
        completion (flight record, timestamps), just with an error."""
        self._finish(req, error=error)


class GenerationServer:
    """ModelServer's generation mode: an **iteration-level** (token-level
    continuous-batching) decode scheduler over a paged KV cache.

    The whole-sequence :class:`ModelServer` batches one compiled call
    per request set — fine for one-shot inference, but an autoregressive
    decode loop batched that way strands the chip on the longest request
    in every batch.  Here the schedulable unit is ONE DECODE STEP:

    - ``submit_generate(prompt)`` enqueues a generation (bounded queue,
      429 past the depth — same backpressure contract as ``submit``);
    - admission into the *running batch* gates on **KV block
      availability** (a worst-case reservation against the
      :class:`~mxnet_tpu.serving.kv_cache.BlockKVCache` pool), not just
      queue depth — an admitted request can never exhaust the pool
      mid-decode;
    - each admitted prompt runs ONE compiled **prefill** (batch 1,
      padded to a length bucket — the existing bucketing discipline)
      that scatters prompt K/V into the request's blocks and yields the
      first token (TTFT is measured exactly here);
    - every iteration dispatches ONE compiled **decode step** over all
      running slots (signature = (slot-count, max-blocks), compiled
      once, persistent-cache warm); finished requests leave their slot
      and queued prefills join at the very next iteration — no request
      ever waits for another's tail.

    ``MXTPU_SERVING_PREFILL_MODE`` picks the prefill interleave:
    ``"interleave"`` admits at most one prefill per decode iteration
    (smooth decode cadence for running requests), ``"step"`` prefills
    every admissible queued request before the next decode step (fastest
    burst drain).  Read live per iteration; the bench measures both.

    The model contract is three compiled entries sharing one parameter
    set (see ``gluon.model_zoo.transformer.CausalLM``):
    ``hybrid_forward`` (whole-sequence baseline), ``hybrid_prefill`` and
    ``hybrid_decode`` (paged), plus ``init_kv_pool``.  Greedy decode
    here is bitwise-reproducible per request regardless of batch
    composition: every decode-step op is row-independent and the
    additive mask underflows foreign/garbage keys to exact zero weight.
    """

    def __init__(self, block, *, slots: Optional[int] = None,
                 kv_block: Optional[int] = None,
                 kv_blocks: Optional[int] = None,
                 queue_depth: Optional[int] = None,
                 deadline_ms: Optional[float] = None,
                 max_new_tokens: Optional[int] = None,
                 prompt_buckets: Sequence[int] = (16, 32, 64),
                 eos: Optional[int] = None,
                 flight=None):
        for need in ("hybrid_prefill", "hybrid_decode", "init_kv_pool"):
            if not callable(getattr(block, need, None)):
                raise MXNetError(
                    f"generation serving needs a block with {need}() — "
                    f"see gluon.model_zoo.transformer.CausalLM")
        self._block = block
        self._slots = max(1, int(get_env(DECODE_SLOTS_ENV)
                                 if slots is None else slots))
        self._target_slots = self._slots
        self.queue_depth = int(get_env(QUEUE_DEPTH_ENV)
                               if queue_depth is None else queue_depth)
        self.deadline_ms = float(get_env(DEADLINE_MS_ENV)
                                 if deadline_ms is None else deadline_ms)
        self.max_new_cap = max(1, int(get_env(MAX_NEW_ENV)
                                      if max_new_tokens is None
                                      else max_new_tokens))
        self.eos = eos
        self._buckets = tuple(sorted(set(int(b) for b in prompt_buckets)))
        self._kv = BlockKVCache(kv_blocks, kv_block)
        # decode table width: worst-case blocks for the largest prompt
        # bucket plus the generation cap — ONE decode signature per
        # slot count
        bs = self._kv.block_size
        self._max_blocks = -(-(self._buckets[-1] + self.max_new_cap) // bs)
        self._pool = block.init_kv_pool(self._kv.n_blocks, bs)
        self._tables: Dict[int, object] = {}
        reg = registry()
        self._g_depth = reg.gauge(
            "serving.queue_depth",
            help="admission-queue depth (requests waiting for assembly)")
        self._h_request = reg.histogram(
            "serving.request_us",
            help="per-request end-to-end latency (enqueue to done)")
        self._h_ttft = reg.histogram(
            "serving.ttft_us",
            help="time to first token: generation enqueue to the "
                 "prefill's first emitted token")
        self._h_step = reg.histogram(
            "serving.decode_step_us",
            help="one iteration-level decode step: compiled call + "
                 "batched logits readback over all running slots")
        self._c_requests = reg.counter(
            "serving.requests", help="requests admitted")
        self._c_done = reg.counter(
            "serving.requests_done", help="requests completed ok")
        self._c_rej_429 = reg.counter(
            "serving.rejected_429",
            help="requests rejected at admission (queue full)")
        self._c_rej_deadline = reg.counter(
            "serving.rejected_deadline",
            help="requests rejected at assembly (deadline expired)")
        self._c_tokens = reg.counter(
            "serving.tokens_generated",
            help="tokens emitted by the generation scheduler (prefill "
                 "first-tokens included)")
        self._c_steps = reg.counter(
            "serving.decode_steps", help="decode iterations dispatched")
        self._flight = _flight_recorder() if flight is None else flight
        self._queue = []
        self._running = [None] * self._slots
        self._lock = threading.Condition()
        self._prefill_graphs: Dict[int, object] = {}
        self._decode_graphs: Dict[int, object] = {}
        # per-slot-count reusable decode-step assembly buffers (tokens,
        # positions, tables), built with the graph so the per-step hot
        # path allocates nothing
        self._step_bufs: Dict[int, tuple] = {}
        self._compile_lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._closed = False
        self._abort = False
        self._rid = itertools.count()
        self._prev_sigterm = None
        # progress heartbeat for the watchdog: bumped every scheduler
        # iteration AND inside the idle condition-wait, thresholded on
        # the decode-step histogram's recent p99 — a wedged decode
        # dispatch goes silent, a merely-idle scheduler never does
        self._tp_decode = _touchpoint("serving.decode",
                                      hist="serving.decode_step_us")
        _maybe_start_sampler()

    # -- lifecycle ----------------------------------------------------
    def start(self) -> "GenerationServer":
        with self._lock:
            if self._thread is not None:
                return self
            if self._closed:
                raise ServerClosed("server already stopped")
            self._thread = threading.Thread(
                target=self._run, name="mxtpu-serving-decode-scheduler",
                daemon=True)
            self._thread.start()
        return self

    def stop(self, drain: bool = True,
             timeout: Optional[float] = None) -> None:
        """Close admission; ``drain=True`` finishes every queued and
        running generation through the normal path, else they fail with
        ServerClosed (their KV blocks released either way)."""
        with self._lock:
            if self._closed and self._thread is None:
                return
            self._closed = True
            if not drain:
                self._abort = True
            self._lock.notify_all()
            t = self._thread
        if t is not None:
            t.join(timeout)
        if t is None or not t.is_alive():
            # never started (or fully joined): fail whatever remains
            with self._lock:
                shed, self._queue = self._queue, []
                run = [r for r in self._running if r is not None]
                self._running = [None] * self._slots
            for r in shed + run:
                self._finish_gen(r, error=ServerClosed(
                    "server stopped" if t is not None
                    else "server stopped before start"))
            self._g_depth.set(0)
        with self._lock:
            self._thread = None

    def __enter__(self) -> "GenerationServer":
        return self.start()

    def __exit__(self, exc_type, exc, tb) -> None:
        self.stop(drain=exc_type is None)

    def install_sigterm(self) -> None:
        """SIGTERM-drain parity with :meth:`ModelServer.install_sigterm`
        (the k8s/preemption graceful-shutdown contract): chain a handler
        that drains and stops the scheduler, then calls the previous
        handler.  The drain runs on its OWN non-daemon thread — the
        signal may have interrupted a frame holding the scheduler lock,
        so the handler itself never blocks in signal context; the
        non-daemon drain thread keeps the process alive until every
        queued and running generation has finished and released its KV
        blocks."""
        prev = signal.getsignal(signal.SIGTERM)
        self._prev_sigterm = prev

        def drain_then_chain(signum, frame):
            self.stop(drain=True)
            if callable(prev) and prev not in (signal.SIG_IGN,
                                               signal.SIG_DFL):
                prev(signum, frame)

        def handler(signum, frame):
            threading.Thread(target=drain_then_chain,
                             args=(signum, frame),
                             name="mxtpu-serving-gen-sigterm-drain",
                             daemon=False).start()

        signal.signal(signal.SIGTERM, handler)

    def uninstall_sigterm(self) -> None:
        if self._prev_sigterm is not None:
            signal.signal(signal.SIGTERM, self._prev_sigterm)
            self._prev_sigterm = None

    # -- client surface -----------------------------------------------
    def submit_generate(self, prompt, max_new_tokens: Optional[int] = None,
                        deadline_ms: Optional[float] = None,
                        eos: Optional[int] = None) -> GenRequest:
        """Enqueue one generation: ``prompt`` is a 1-D sequence of token
        ids; returns a :class:`GenRequest` future whose ``result()`` is
        the greedy-decoded token ids (EOS included when hit).  Raises
        :class:`ServerOverloaded` past the queue depth (429),
        :class:`NoBucketError` when the prompt fits no length bucket or
        the request could never fit the KV pool, and ``MXNetError`` past
        the server's ``max_new_tokens`` cap (the cap sizes the compiled
        decode signature's block table)."""
        arr = _np.ascontiguousarray(_np.asarray(prompt).ravel(),
                                    dtype=_np.int32)  # mxlint: disable=hidden-host-sync — request ingestion at the serving boundary
        plen = int(arr.shape[0])
        if plen < 1:
            raise MXNetError("empty prompt")
        self._bucket_for(plen)          # raises NoBucketError past max
        mnt = self.max_new_cap if max_new_tokens is None \
            else int(max_new_tokens)
        if mnt < 1:
            raise MXNetError("max_new_tokens must be >= 1")
        if mnt > self.max_new_cap:
            raise MXNetError(
                f"max_new_tokens {mnt} exceeds the server cap "
                f"{self.max_new_cap} (the cap sizes the decode "
                f"signature; construct the server with a larger "
                f"max_new_tokens)")
        if not self._kv.fits(plen, mnt):
            raise NoBucketError(
                f"prompt of {plen} + {mnt} new tokens needs "
                f"{self._kv.blocks_needed(plen, mnt)} KV blocks; the "
                f"pool holds {self._kv.capacity}")
        ms = self.deadline_ms if deadline_ms is None else float(deadline_ms)
        deadline = (time.monotonic() + ms / 1e3) if ms > 0 else None
        req = GenRequest(next(self._rid), arr, mnt, deadline,
                         self.eos if eos is None else eos)
        req.trace = _tracing.tracer().begin(
            "serving.generate", activate=False,
            args={"rid": req.rid, "prompt": plen, "max_new": mnt})
        try:
            with self._lock:
                if self._closed:
                    raise ServerClosed("server is shut down")
                if len(self._queue) >= self.queue_depth:
                    self._c_rej_429.inc()
                    raise ServerOverloaded(
                        f"admission queue full ({self.queue_depth} deep)"
                        f" — retry with backoff (429)")
                self._queue.append(req)
                self._g_depth.set(len(self._queue))
                self._lock.notify_all()
        except BaseException as exc:
            # rejected before entering the pipeline: nobody downstream
            # holds the span, so close it here or it leaks open forever
            if req.trace is not None:
                req.trace.annotate(error=type(exc).__name__)
                req.trace.finish()
            raise
        self._c_requests.inc()
        return req

    def generate(self, prompt, timeout: Optional[float] = None, **kw):
        """Blocking convenience: submit + wait; returns the generated
        token ids."""
        return self.submit_generate(prompt, **kw).result(timeout)

    def cancel(self, req: GenRequest) -> bool:
        """Cancel an in-flight generation (the stream-disconnect path):
        a still-queued request is failed immediately; a running one is
        marked and leaves the batch at the next iteration boundary —
        either way :meth:`_finish_gen` releases its KV blocks, so a
        client hanging up mid-stream returns the pool to zero.  Returns
        False when the request had already completed."""
        with self._lock:
            if req.done():
                return False
            queued = req in self._queue
            if queued:
                self._queue.remove(req)
                self._g_depth.set(len(self._queue))
            else:
                # running (or mid-admission): the scheduler owns it —
                # flag it and let the iteration edge retire it
                req.cancelled = True
        if queued:
            self._finish_gen(req, error=RequestCancelled(
                f"generation {req.rid} cancelled while queued"))
        return True

    def warmup(self) -> int:
        """Precompile the decode-step signature and every prompt-bucket
        prefill, so no live generation pays a compile.  On a warm
        process with ``MXTPU_COMPILE_CACHE_DIR`` set this deserializes
        instead of compiling (compiles==0).  Returns the number of
        executables resident."""
        self._decode_graph(self._slots)
        for b in self._buckets:
            self._prefill_graph(b)
        return len(self._prefill_graphs) + len(self._decode_graphs)

    def stats(self) -> dict:
        with self._lock:
            occupied = sum(1 for r in self._running if r is not None)
            depth = len(self._queue)
        return {
            "requests": self._c_requests.n,
            "done": self._c_done.n,
            "rejected_429": self._c_rej_429.n,
            "rejected_deadline": self._c_rej_deadline.n,
            "queue_depth": depth,
            "slots": self._slots,
            "slots_occupied": occupied,
            "tokens_generated": self._c_tokens.n,
            "decode_steps": self._c_steps.n,
            "kv_blocks_used": self._kv.used(),
            "kv_blocks_total": self._kv.capacity,
            "executables": len(self._prefill_graphs) +
            len(self._decode_graphs),
        }

    # -- slot-count control (DecodeSlotController seam) ----------------
    @property
    def decode_slots(self) -> int:
        return self._slots

    def set_decode_slots(self, n: int) -> None:
        """Retarget the running-batch slot count.  Takes effect between
        iterations: growth immediately, shrink once occupancy allows —
        running requests are never evicted.  A new slot count is a new
        compiled decode signature (the recompile the
        DecodeSlotController's bracketing stop economizes); previously
        used counts stay cached."""
        with self._lock:
            self._target_slots = max(1, int(n))
            self._lock.notify_all()

    # -- compiled-graph resolution (cold path) -------------------------
    def _bucket_for(self, plen: int) -> int:
        for b in self._buckets:
            if b >= plen:
                return b
        raise NoBucketError(
            f"prompt length {plen} exceeds the largest prompt bucket "
            f"{self._buckets[-1]}")

    def _prefill_graph(self, bucket: int):
        g = self._prefill_graphs.get(bucket)
        if g is not None:
            return g
        with self._compile_lock:
            g = self._prefill_graphs.get(bucket)
            if g is None:
                bs = self._kv.block_size
                w = -(-bucket // bs)
                g = self._block.cached_graph(
                    _np.zeros((1, bucket), _np.int32),
                    _np.zeros((1,), _np.int32),
                    _np.zeros((1, w), _np.int32),
                    self._pool, entry="prefill")
                self._prewarm_locked(
                    g, _np.zeros((1, bucket), _np.int32),
                    _np.ones((1,), _np.int32),
                    _np.zeros((1, w), _np.int32))
                self._prefill_graphs[bucket] = g
            return g

    def _decode_graph(self, slots: int):
        g = self._decode_graphs.get(slots)
        if g is not None:
            return g
        with self._compile_lock:
            g = self._decode_graphs.get(slots)
            if g is None:
                g = self._block.cached_graph(
                    _np.zeros((slots,), _np.int32),
                    _np.zeros((slots,), _np.int32),
                    _np.zeros((slots, self._max_blocks), _np.int32),
                    self._pool, entry="decode")
                self._prewarm_locked(
                    g, _np.zeros((slots,), _np.int32),
                    _np.zeros((slots,), _np.int32),
                    _np.zeros((slots, self._max_blocks), _np.int32))
                self._step_bufs[slots] = (
                    _np.zeros((slots,), _np.int32),
                    _np.zeros((slots,), _np.int32),
                    _np.zeros((slots, self._max_blocks), _np.int32))
                self._decode_graphs[slots] = g
            return g

    def _prewarm_locked(self, graph, *host_args) -> None:
        """Two throwaway ``raw`` dispatches with HOST (numpy) argument
        types.  The cached-graph build warms the executable against
        device-committed example arrays, but jax keys the lowering on
        argument sharding — without this the FIRST live call would pay
        a second lowering+compile (~700ms on the transformer).  Called
        twice because the first flips ``self._pool`` from its initial
        host array to the committed pool the graph returns, which is a
        third signature; the second call IS steady state.  All-zero
        block tables route the dummy KV writes into the scratch block,
        which no real table row references."""
        for _ in range(2):
            logits, pool = graph.raw(*host_args, self._pool)
            self._pool = pool
        _np.asarray(logits)  # mxlint: disable=hidden-host-sync — cold-path warmup barrier, not a live request

    # -- the scheduler loop --------------------------------------------
    def _run(self) -> None:
        tp = self._tp_decode
        while True:
            tp.beat()
            with self._lock:
                while (not self._queue
                       and not any(r is not None for r in self._running)
                       and not self._closed):
                    self._lock.wait(0.1)
                    tp.beat()   # healthy-idle keeps the heartbeat alive
                if self._abort:
                    shed, self._queue = self._queue, []
                    run = [r for r in self._running if r is not None]
                    self._running = [None] * self._slots
                    self._g_depth.set(0)
                else:
                    self._retarget_slots_locked()
                    admit, expired = self._admit_locked()
            if self._abort:
                for r in shed + run:
                    self._finish_gen(r, error=ServerClosed(
                        "server stopped without draining"))
                return
            for r in expired:
                self._expire_gen(r)
            # graph/bucket resolution OUTSIDE the hot per-step root:
            # first use compiles under the lock; after warmup these are
            # dict hits
            for req in admit:
                bucket = self._bucket_for(len(req.prompt))
                self._prefill(self._prefill_graph(bucket), req, bucket)
            occupied = any(r is not None for r in self._running)
            if occupied:
                self._decode_step(self._decode_graph(self._slots))
            elif not admit and not expired:
                if self._closed:
                    with self._lock:
                        idle = not self._queue and not any(
                            r is not None for r in self._running)
                    if idle:
                        return
                else:
                    # nothing flowed (e.g. pool exhausted by an earlier
                    # admission wave): don't spin the condition hot
                    time.sleep(0.002)

    def _retarget_slots_locked(self) -> None:
        tgt = self._target_slots
        if tgt == self._slots:
            return
        occ = [r for r in self._running if r is not None]
        if tgt < self._slots and len(occ) > tgt:
            return          # shrink waits for occupancy, never evicts
        self._running = occ + [None] * (tgt - len(occ))
        self._slots = tgt

    def _admit_locked(self):
        """Sweep deadline-expired queued requests, then pop the FIFO
        head while (a) a slot is open, (b) the KV pool honors the
        worst-case block reservation, and (c) the live prefill-mode
        budget allows — ``interleave`` admits at most one per decode
        iteration, ``step`` fills every open slot."""
        now = time.monotonic()
        expired = [r for r in self._queue
                   if r.deadline is not None and r.deadline < now]
        if expired:
            self._queue = [r for r in self._queue if r not in expired]
        free = sum(1 for r in self._running if r is None)
        mode = str(get_env(PREFILL_MODE_ENV)).lower()
        budget = free if mode == "step" else min(free, 1)
        admit = []
        while budget > 0 and self._queue:
            head = self._queue[0]
            table = self._kv.reserve(head.rid, len(head.prompt),
                                     head.max_new_tokens)
            if table is None:
                break           # blocks exhausted: FIFO holds the line
            self._tables[head.rid] = table
            self._queue.pop(0)
            admit.append(head)
            budget -= 1
        self._g_depth.set(len(self._queue))
        return admit, expired

    # -- dispatch (hot path) -------------------------------------------
    @hot_path("dispatch")
    def _prefill(self, graph, req: GenRequest, bucket: int) -> None:
        """One prompt prefill (batch 1, padded to ``bucket``): scatters
        prompt K/V into the request's reserved blocks, emits the first
        token (the TTFT measurement point), and seats the request in a
        running-batch slot."""
        sp = None if req.trace is None else _tracing.tracer().begin(
            "serving.prefill", parent=req.trace, activate=False,
            args={"bucket": bucket})
        plen = len(req.prompt)
        try:
            # the prep is fallible too (ensure() asserts pool-table
            # agreement) — it must fail the request AND close the span,
            # exactly like a compiled-call failure
            table = self._kv.ensure(req.rid, plen)
            bs = self._kv.block_size
            toks = _np.zeros((1, bucket), _np.int32)  # mxlint: disable=hot-path-purity — per-prefill pad buffer, amortized over the prompt
            toks[0, :plen] = req.prompt
            tb = _np.asarray([table.padded(-(-bucket // bs))], _np.int32)  # mxlint: disable=hot-path-purity — per-prefill block-table row, amortized over the prompt
            req.t_prefill = time.monotonic()
            logits, pool = graph.raw(
                toks, _np.asarray([plen], _np.int32), tb, self._pool)  # mxlint: disable=hot-path-purity — per-prefill scalar wrap, amortized over the prompt
            self._pool = pool  # mxlint: disable=lock-discipline — scheduler-thread-owned; the lock-held writes happen in pre-start warmup
            tok = int(_np.asarray(logits)[0].argmax())  # mxlint: disable=hidden-host-sync,hot-path-purity — first-token readback: TTFT is measured on host arrival
        except BaseException as exc:
            if sp is not None:
                sp.annotate(error=type(exc).__name__)
                sp.finish()
            self._finish_gen(req, error=exc
                             if isinstance(exc, Exception) else
                             MXNetError(str(exc)))
            if not isinstance(exc, Exception):
                raise
            return
        req.t_first = time.monotonic()
        # close the span at the TTFT point: it measures the prefill
        # (prep + compiled call + first-token readback), and closing
        # before the fan-out bookkeeping means a failure there can no
        # longer strand it open
        if sp is not None:
            sp.finish()
        trace_id = None if req.trace is None else req.trace.trace_id
        self._h_ttft.observe((req.t_first - req.t_enqueue) * 1e6,
                             trace_id=trace_id)
        req.push_token(tok)
        req.pos = plen          # the new token decodes at position plen
        self._c_tokens.inc()
        if req.cancelled:
            self._finish_gen(req, error=RequestCancelled(
                f"generation {req.rid} cancelled mid-stream"))
            return
        if (req.eos is not None and tok == req.eos) \
                or len(req.tokens) >= req.max_new_tokens:
            self._finish_gen(req)
            return
        with self._lock:
            slot = self._running.index(None)
            self._running[slot] = req

    @hot_path("dispatch")
    def _decode_step(self, graph) -> None:
        """ONE iteration of the decode scheduler: a single compiled call
        advances every running slot by one token, then one batched
        logits readback fans results out — finished requests free their
        slot (and KV blocks) before the next iteration's admissions."""
        occupied = [(i, r) for i, r in enumerate(self._running)
                    if r is not None]
        sp = None
        for _, r in occupied:
            if r.trace is not None:
                sp = _tracing.tracer().begin(
                    "serving.decode_step", parent=r.trace,
                    activate=False,
                    args={"occupied": len(occupied),
                          "slots": self._slots})
                for _, o in occupied:
                    if o.trace is not None and o is not r:
                        sp.link(o.trace)
                break
        try:
            # reused per-slot-count assembly buffers (built with the
            # graph); zeroed every step so empty slots and table tails
            # land in the scratch block, never a live request's blocks.
            # Assembly is inside the try: a failed ensure() must fail
            # the batch AND close the step span like a compiled-call
            # failure would
            tokens, positions, tables = self._step_bufs[self._slots]
            tokens.fill(0)
            positions.fill(0)
            tables.fill(0)
            for i, r in occupied:
                # lazy block growth: back the write position; infallible
                # under the admission-time reservation
                table = self._kv.ensure(r.rid, r.pos + 1)
                tokens[i] = r.tokens[-1]
                positions[i] = r.pos
                tables[i, :] = table.padded(self._max_blocks)
            t0 = time.monotonic()
            logits, pool = graph.raw(tokens, positions, tables,
                                     self._pool)
            self._pool = pool  # mxlint: disable=lock-discipline — scheduler-thread-owned; the lock-held writes happen in pre-start warmup
            lg = _np.asarray(logits)  # mxlint: disable=hidden-host-sync,hot-path-purity — ONE batched logits readback per decode step (results are host tokens by contract)
        except BaseException as exc:
            if sp is not None:
                sp.annotate(error=type(exc).__name__)
                sp.finish()
            with self._lock:
                for i, _ in occupied:
                    self._running[i] = None
            for _, r in occupied:
                self._finish_gen(r, error=exc
                                 if isinstance(exc, Exception) else
                                 MXNetError(str(exc)))
            if not isinstance(exc, Exception):
                raise
            return
        trace_id = None if sp is None else sp.trace_id
        # the step span measures the compiled call + batched readback;
        # close it before the fan-out so a failure in per-request
        # bookkeeping can no longer strand it open
        if sp is not None:
            sp.finish()
        self._h_step.observe((time.monotonic() - t0) * 1e6,
                             trace_id=trace_id)
        self._c_steps.inc()
        finished = []
        for i, r in occupied:
            tok = int(lg[i].argmax())  # mxlint: disable=hidden-host-sync — lg is already host memory; this argmax is numpy, not a device round-trip
            r.push_token(tok)
            r.pos += 1
            self._c_tokens.inc()
            if (r.cancelled or (r.eos is not None and tok == r.eos)
                    or len(r.tokens) >= r.max_new_tokens):
                finished.append((i, r))
        if finished:
            with self._lock:
                for i, _ in finished:
                    self._running[i] = None
            for _, r in finished:
                self._finish_gen(r, error=RequestCancelled(
                    f"generation {r.rid} cancelled mid-stream")
                    if r.cancelled else None)

    # -- completion paths ----------------------------------------------
    def _finish_gen(self, req: GenRequest, error=None) -> None:
        """Every generation exit path lands here — finish, deadline,
        abort, dispatch failure — so KV blocks (and the unused tail of
        the reservation) can never leak."""
        self._kv.release(req.rid)
        with self._lock:
            self._tables.pop(req.rid, None)
        req.t_done = time.monotonic()
        req._error = error
        dur_us = (req.t_done - req.t_enqueue) * 1e6
        trace_id = None
        if req.trace is not None:
            trace_id = req.trace.trace_id
            if error is not None:
                req.trace.annotate(error=type(error).__name__)
            req.trace.annotate(tokens=len(req.tokens))
            req.trace.finish()
        if error is None:
            self._h_request.observe(dur_us, trace_id=trace_id)
            self._c_done.inc()
        self._flight.record_request(
            request_id=req.rid,
            enqueue=round(req.t_enqueue, 6),
            assemble=round(req.t_prefill, 6),
            dispatch=round(req.t_first, 6),
            done=round(req.t_done, 6),
            bucket=f"gen:{len(req.prompt)}+{len(req.tokens)}",
            batch_size=self._slots,
            us=round(dur_us, 1),
            trace_id=trace_id,
            ok=error is None)
        req._event.set()
        req._wake_stream()

    def _expire_gen(self, req: GenRequest) -> None:
        self._c_rej_deadline.inc()
        self._finish_gen(req, error=DeadlineExceeded(
            f"generation {req.rid} spent its deadline queued "
            f"(429-style); the server is over capacity — back off"))
