"""ModelServer: continuous-batching inference over CachedOp graphs.

The executable a model server wants already exists in this stack:
``hybridize()``'s compiled-graph artifact (PAPER.md L6a — the CachedOp
analog).  This module wraps it in the serving loop the north star's
"millions of users" traffic shape needs:

    submit() -> AdmissionQueue (bounded, 429 past depth)
             -> batcher thread: shape-bucketed batch assembly
                (padding-length buckets, the BERT bench idiom)
             -> dispatch workers: ONE CachedGraph.raw call per bucket,
                batch formation overlapping device execution
             -> per-request results, metrics, flight-recorder records

Observability is wired from day one: ``serving.request_us`` (per-request
end-to-end latency histogram), ``serving.queue_depth`` (gauge),
``serving.dispatch_us`` (per-batch device-call histogram), and the
batch-formation-efficiency counters ``serving.tokens_real`` /
``serving.tokens_padded`` — all through the process-global registry, so
the Prometheus endpoint and JSONL writer see the serving path with zero
extra plumbing.  Every completed request also lands in the flight
recorder's per-request ring, dumped on crash alongside step records.

Knobs (all through ``base.register_env``): ``MXTPU_SERVING_MAX_BATCH``,
``MXTPU_SERVING_QUEUE_DEPTH``, ``MXTPU_SERVING_DEADLINE_MS``,
``MXTPU_SERVING_WORKERS``, ``MXTPU_SERVING_BATCH_WINDOW_US``.
"""
from __future__ import annotations

import itertools
import queue as _queue
import signal
import threading
import time
from typing import Dict, Optional, Sequence, Tuple

import numpy as _np

from ..base import MXNetError, get_env, hot_path, jax_compute_dtype
from ..ndarray import NDArray, array as nd_array
from ..observability import tracing as _tracing
from ..observability.flight import recorder as _flight_recorder
from ..observability.registry import registry
from .batcher import (AdmissionQueue, Batcher, DeadlineExceeded, Request,
                      ServerClosed, ServerOverloaded)
from .buckets import Bucketer

__all__ = ["ModelServer"]

MAX_BATCH_ENV = "MXTPU_SERVING_MAX_BATCH"
QUEUE_DEPTH_ENV = "MXTPU_SERVING_QUEUE_DEPTH"
DEADLINE_MS_ENV = "MXTPU_SERVING_DEADLINE_MS"
WORKERS_ENV = "MXTPU_SERVING_WORKERS"
BATCH_WINDOW_US_ENV = "MXTPU_SERVING_BATCH_WINDOW_US"


def _live_window_s() -> float:
    """The knob-governed batch window, re-read before every batch pop
    (the BatchWindowController's live adaptation seam)."""
    return float(get_env(BATCH_WINDOW_US_ENV)) / 1e6


def _key_str(key: Tuple) -> str:
    """Compact human-readable bucket tag for records/debugging:
    ``32:int32|32:int32`` — dtype included, so two buckets differing
    only in dtype stay distinguishable in postmortems."""
    parts = []
    for shape, dt in key:
        parts.append(("x".join(str(s) for s in shape) or "scalar")
                     + ":" + str(dt))
    return "|".join(parts)


def _freeze_generic(block, examples):
    """Compile a non-Hybrid block (e.g. a SymbolBlock from the export
    seam) into one jitted inference callable with the CachedGraph.raw
    contract: raw values in, tuple of raw jax arrays out.  Parameters
    are baked as constants — fine for serving, where weights are
    immutable."""
    import jax

    from .. import autograd as _autograd

    ctx = examples[0].context

    def fn(*vals):
        ins = [NDArray(v, ctx=ctx) for v in vals]
        with _autograd.pause():
            out = block(*ins)
        outs = out if isinstance(out, (list, tuple)) else (out,)
        return tuple(o._read() for o in outs)

    jitted = jax.jit(fn)
    jax.block_until_ready(jitted(*[e._read() for e in examples]))
    return jitted


class ModelServer:
    """Continuous-batching inference server over one model.

    ``block`` is a :class:`~mxnet_tpu.gluon.HybridBlock` (served through
    the direct cached-graph entry — no autograd bookkeeping) or any
    Block (e.g. a ``SymbolBlock`` imported from the ``export()`` seam —
    see :meth:`from_exported`), serving host-side numpy results.

    Requests are single samples WITHOUT the batch dimension; the server
    assembles them into padded, bucketed batches and runs one compiled
    call per bucket.  ``submit`` is non-blocking and returns a
    :class:`~mxnet_tpu.serving.batcher.Request` future; ``infer`` is the
    blocking convenience wrapper.

    Lifecycle: ``start()`` spawns the batcher + N dispatch workers;
    ``stop(drain=True)`` (or context-manager exit, or SIGTERM via
    :meth:`install_sigterm`) closes admission, drains every queued
    request, and joins the threads.
    """

    def __init__(self, block, *, max_batch: Optional[int] = None,
                 queue_depth: Optional[int] = None,
                 deadline_ms: Optional[float] = None,
                 workers: Optional[int] = None,
                 length_buckets: Optional[Sequence[int]] = None,
                 pad_axis: int = 0,
                 batch_buckets: Optional[Sequence[int]] = None,
                 batch_window_us: Optional[float] = None,
                 unpad_outputs: bool = True,
                 flight=None):
        self._block = block
        self.unpad_outputs = unpad_outputs
        self.max_batch = int(get_env(MAX_BATCH_ENV) if max_batch is None
                             else max_batch)
        self.queue_depth = int(get_env(QUEUE_DEPTH_ENV)
                               if queue_depth is None else queue_depth)
        self.deadline_ms = float(get_env(DEADLINE_MS_ENV)
                                 if deadline_ms is None else deadline_ms)
        self.workers = max(1, int(get_env(WORKERS_ENV)
                                  if workers is None else workers))
        # explicit argument = frozen window; knob-governed (the default)
        # = read LIVE per batch, so the BatchWindowController (or an
        # operator export) retunes a running server
        if batch_window_us is None:
            window_s = _live_window_s
        else:
            window_s = float(batch_window_us) / 1e6
        self._bucketer = Bucketer(self.max_batch,
                                  length_buckets=length_buckets,
                                  pad_axis=pad_axis,
                                  batch_buckets=batch_buckets)
        reg = registry()
        self._g_depth = reg.gauge(
            "serving.queue_depth",
            help="admission-queue depth (requests waiting for assembly)")
        self._h_request = reg.histogram(
            "serving.request_us",
            help="per-request end-to-end latency (enqueue to done)")
        self._h_dispatch = reg.histogram(
            "serving.dispatch_us",
            help="per-batch compiled-call wall time")
        self._c_requests = reg.counter(
            "serving.requests", help="requests admitted")
        self._c_done = reg.counter(
            "serving.requests_done", help="requests completed ok")
        self._c_rej_429 = reg.counter(
            "serving.rejected_429",
            help="requests rejected at admission (queue full)")
        self._c_rej_deadline = reg.counter(
            "serving.rejected_deadline",
            help="requests rejected at assembly (deadline expired)")
        self._c_batches = reg.counter(
            "serving.batches", help="batched compiled calls dispatched")
        self._c_real = reg.counter(
            "serving.tokens_real",
            help="real (unpadded) elements served — batch-efficiency "
                 "numerator")
        self._c_padded = reg.counter(
            "serving.tokens_padded",
            help="padded elements dispatched — batch-efficiency "
                 "denominator")
        self._flight = _flight_recorder() if flight is None else flight
        self._admission = AdmissionQueue(self.queue_depth,
                                         gauge=self._g_depth)
        self._out: _queue.Queue = _queue.Queue(
            maxsize=max(2, 2 * self.workers))
        self._batcher = Batcher(self._admission, self._bucketer,
                                self._out, self.max_batch,
                                window_s, self._expire,
                                on_error=self._fail)
        self._graphs: Dict[Tuple, object] = {}
        self._compile_lock = threading.Lock()
        self._lifecycle_lock = threading.Lock()
        self._threads = []
        self._started = False
        self._stopped = False
        self._drain_down = False
        self._rid = itertools.count()
        self._prev_sigterm = None

    # -- construction helpers ----------------------------------------------
    @classmethod
    def from_exported(cls, symbol_file: str, input_names,
                      param_file: Optional[str] = None, ctx=None, **kw
                      ) -> "ModelServer":
        """Serve an exported symbol/params pair (the
        ``examples/serve_c_api.md`` export seam): loads via
        ``SymbolBlock.imports`` and serves through one jitted graph."""
        from ..gluon.block import SymbolBlock
        blk = SymbolBlock.imports(symbol_file, input_names, param_file,
                                  ctx=ctx)
        return cls(blk, **kw)

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "ModelServer":
        with self._lifecycle_lock:
            if self._started:
                return self
            if self._stopped:
                raise ServerClosed("server already stopped")
            self._batcher.start()
            for i in range(self.workers):
                t = threading.Thread(target=self._worker_loop,
                                     name=f"mxtpu-serving-worker-{i}",
                                     daemon=True)
                t.start()
                self._threads.append(t)
            self._started = True
        return self

    def stop(self, drain: bool = True, timeout: Optional[float] = None
             ) -> None:
        """Shut down: close admission (further submits raise
        ServerClosed), then either drain every queued request through
        the normal path (``drain=True``) or fail them immediately."""
        with self._lifecycle_lock:
            if self._stopped:
                return
            self._stopped = True
            self._admission.close()
            if not drain:
                for r in self._admission.shed():
                    self._finish(r, error=ServerClosed(
                        "server stopped without draining"))
            if self._started:
                self._batcher.join(timeout)
                if self._batcher.is_alive():
                    # timed-out join: the batcher may still be putting
                    # batches — sentinels would race AHEAD of them and
                    # strand their requests.  Flag the workers down
                    # instead; they drain whatever still arrives and
                    # exit on an idle tick.
                    self._drain_down = True
                else:
                    for _ in self._threads:
                        try:
                            self._out.put(None, timeout=1.0)
                        except _queue.Full:   # a wedged worker: flag
                            self._drain_down = True
                            break
                for t in self._threads:
                    t.join(timeout)
            else:
                # never started: nothing will drain the queue — shed
                for r in self._admission.shed():
                    self._finish(r, error=ServerClosed(
                        "server stopped before start"))
            self._g_depth.set(0)

    def __enter__(self) -> "ModelServer":
        return self.start()

    def __exit__(self, exc_type, exc, tb) -> None:
        self.stop(drain=exc_type is None)

    def install_sigterm(self) -> None:
        """Chain a SIGTERM handler that drains and stops the server
        (the k8s/preemption graceful-shutdown contract), then calls the
        previous handler.  The drain runs on its OWN (non-daemon)
        thread: the signal may have interrupted a frame on this very
        thread holding the locks stop() needs, so blocking inside the
        handler would deadlock — the handler returns immediately, the
        interrupted frame resumes and releases its locks, and the drain
        thread keeps the process alive until shutdown completes."""
        prev = signal.getsignal(signal.SIGTERM)
        self._prev_sigterm = prev

        def drain_then_chain(signum, frame):
            self.stop(drain=True)
            if callable(prev) and prev not in (signal.SIG_IGN,
                                               signal.SIG_DFL):
                prev(signum, frame)

        def handler(signum, frame):
            threading.Thread(target=drain_then_chain,
                             args=(signum, frame),
                             name="mxtpu-serving-sigterm-drain",
                             daemon=False).start()

        signal.signal(signal.SIGTERM, handler)

    def uninstall_sigterm(self) -> None:
        if self._prev_sigterm is not None:
            signal.signal(signal.SIGTERM, self._prev_sigterm)
            self._prev_sigterm = None

    # -- client surface ------------------------------------------------------
    def submit(self, *inputs, deadline_ms: Optional[float] = None
               ) -> Request:
        """Enqueue one sample (inputs WITHOUT the batch dim); returns a
        Request future.  Raises :class:`ServerOverloaded` when the
        admission queue is full, :class:`ServerClosed` after stop, and
        :class:`NoBucketError` when no shape bucket fits."""
        arrs = []
        for x in inputs:
            a = x.asnumpy() if isinstance(x, NDArray) else _np.asarray(x)  # mxlint: disable=hidden-host-sync — request ingestion: client samples become host buffers at the serving boundary
            cd = jax_compute_dtype(a.dtype)
            if a.dtype != cd:
                a = a.astype(cd)
            arrs.append(a)
        key = self._bucketer.sample_key(arrs)
        ms = self.deadline_ms if deadline_ms is None else float(deadline_ms)
        deadline = (time.monotonic() + ms / 1e3) if ms > 0 else None
        req = Request(next(self._rid), tuple(arrs), key, deadline)
        # causal tracing root: ONE trace per request, head-sampled here
        # at admission (explicit lifecycle — finished in _finish on a
        # worker thread; the Request object carries the context across
        # the queue hops)
        req.trace = _tracing.tracer().begin(
            "serving.request", activate=False,
            args={"rid": req.rid, "bucket": _key_str(key)})
        try:
            self._admission.submit(req)
        except ServerOverloaded:
            self._c_rej_429.inc()
            raise
        self._c_requests.inc()
        return req

    def infer(self, *inputs, timeout: Optional[float] = None,
              deadline_ms: Optional[float] = None):
        """Blocking convenience: submit + wait; returns host numpy
        output(s)."""
        return self.submit(*inputs, deadline_ms=deadline_ms
                           ).result(timeout)

    def warmup(self, *samples) -> int:
        """Precompile every (shape bucket, batch bucket) signature the
        given example samples imply, so no live request pays a compile.
        Each sample is one request's input tuple (or a single array).
        Returns the number of executables now resident."""
        for sample in samples:
            sample = sample if isinstance(sample, (tuple, list)) \
                else (sample,)
            # canonicalize dtypes exactly as submit() does, or the
            # warmed signatures can never match live requests
            arrs = []
            for a in sample:
                a = _np.asarray(a)
                cd = jax_compute_dtype(a.dtype)
                arrs.append(a.astype(cd) if a.dtype != cd else a)
            key = self._bucketer.sample_key(arrs)
            for bsz in self._bucketer.batch_buckets:
                self._graph_for(key, bsz)
        return len(self._graphs)

    def stats(self) -> dict:
        """Serving-side registry view plus the derived
        batch-formation-efficiency ratio."""
        real, padded = self._c_real.n, self._c_padded.n
        return {
            "requests": self._c_requests.n,
            "done": self._c_done.n,
            "rejected_429": self._c_rej_429.n,
            "rejected_deadline": self._c_rej_deadline.n,
            "batches": self._c_batches.n,
            "queue_depth": self._g_depth.value,
            "tokens_real": real,
            "tokens_padded": padded,
            "batch_efficiency": round(real / padded, 4) if padded else 0.0,
            "executables": len(self._graphs),
        }

    # -- compiled-graph resolution (cold path) -------------------------------
    def _graph_for(self, key: Tuple, batch: int):
        """The executable for one (shape bucket, batch bucket): built on
        first use (``warmup()`` prebuilds), then a dict hit forever."""
        gk = (key, batch)
        g = self._graphs.get(gk)
        if g is not None:
            return g
        with self._compile_lock:
            g = self._graphs.get(gk)
            if g is not None:
                return g
            examples = [nd_array(_np.zeros((batch,) + tuple(shape),
                                           dtype=dt))
                        for shape, dt in key]
            from ..gluon.block import HybridBlock
            if isinstance(self._block, HybridBlock):
                g = self._block.cached_graph(*examples).raw
            else:
                g = _freeze_generic(self._block, examples)
            self._graphs[gk] = g
            return g

    # -- dispatch (hot path) -------------------------------------------------
    def _worker_loop(self) -> None:
        while True:
            try:
                batch = self._out.get(timeout=0.25)
            except _queue.Empty:
                if self._drain_down:
                    break
                continue
            if batch is None:
                break
            try:
                graph = self._graph_for(batch.key, batch.batch)
                self._dispatch_batch(graph, batch)
            except Exception as e:  # a failed batch fails ITS requests,
                for req in batch.requests:      # never the server
                    if not req.done():
                        self._finish(req, error=e)

    @hot_path("dispatch")
    def _dispatch_batch(self, graph, batch) -> None:
        """Serving dispatch entry point: ONE compiled call for the whole
        bucket, one batched device→host transfer, then per-request
        fan-out."""
        # dispatch span: child of the batch's assembly span (tracing
        # off = batch.trace is None = no tracer touch on this hot root)
        sp = None if batch.trace is None else _tracing.tracer().begin(
            "serving.dispatch", parent=batch.trace, activate=False,
            args={"batch": batch.batch, "bucket": _key_str(batch.key)})
        rb = None
        try:
            t0 = time.monotonic()
            for req in batch.requests:
                req.t_dispatch = t0
            flat = graph(*batch.arrays)
            rb = None if sp is None else _tracing.tracer().begin(
                "serving.readback", parent=sp, activate=False)
            # response materialization: ONE batched device→host transfer
            # per BATCH (results are host values by contract), not per
            # request
            outs = [_np.asarray(v) for v in flat]  # mxlint: disable=hidden-host-sync,hot-path-purity — batched response readback, one transfer (and one buffer) per batch
        except BaseException as exc:
            # a failed batch must still record its dispatch span — the
            # postmortem trace of exactly the batch that died
            if sp is not None:
                sp.annotate(error=type(exc).__name__)
                if rb is not None:
                    rb.finish()
                sp.finish()
            raise
        if rb is not None:
            rb.finish()
            sp.finish()
        # inc(), not .n bumps: N workers finish batches concurrently and
        # the direct-bump idiom is reserved for single-threaded hot loops
        self._h_dispatch.observe((time.monotonic() - t0) * 1e6)
        self._c_batches.inc()
        self._c_real.inc(batch.real)
        self._c_padded.inc(batch.padded)
        for i, req in enumerate(batch.requests):
            req.batch_size = batch.batch
            row = self._unpad_row(tuple(o[i] for o in outs), req)
            self._finish(req, result=row[0] if len(row) == 1 else row)

    def _unpad_row(self, row, req: Request):
        """Undo length-bucket padding on a request's outputs: slice axis
        ``pad_axis`` (per-sample) back to the request's real length when
        its size equals the padded bucket — a per-position output like
        BERT's MLM logits trims; a pooled output with a different
        ``pad_axis`` extent passes through.  A pooled dim that
        COINCIDES with a bucket size (e.g. a 64-wide embedding under a
        64-token bucket) is indistinguishable from a length axis —
        construct with ``unpad_outputs=False`` and slice client-side
        for such models.  The padded positions' VALUES remain a model
        contract: a sequence model that attends everywhere must take a
        mask/valid-length input (pass it as part of the request) — the
        server cannot invent one."""
        bkt = self._bucketer
        if not bkt.length_buckets or not self.unpad_outputs:
            return row
        ax = bkt.pad_axis
        padded = req.key[0][0][ax]
        real = req.inputs[0].shape[ax]
        if real == padded:
            return row
        out = []
        for o in row:
            if o.ndim > ax and o.shape[ax] == padded:
                sl = [slice(None)] * o.ndim
                sl[ax] = slice(0, real)
                o = o[tuple(sl)]
            out.append(o)
        return tuple(out)

    def _finish(self, req: Request, result=None, error=None) -> None:
        """Complete one request: latency histogram, counters, flight
        record, wake the client."""
        req.t_done = time.monotonic()
        req._result = result
        req._error = error
        dur_us = (req.t_done - req.t_enqueue) * 1e6
        trace_id = None
        if req.trace is not None:
            trace_id = req.trace.trace_id
            if error is not None:
                req.trace.annotate(error=type(error).__name__)
            req.trace.finish()
        if error is None:
            # the explicit trace_id puts the exemplar on THIS request's
            # trace (no contextvar crosses the worker-thread hop)
            self._h_request.observe(dur_us, trace_id=trace_id)
            self._c_done.inc()
        self._flight.record_request(
            request_id=req.rid,
            enqueue=round(req.t_enqueue, 6),
            assemble=round(req.t_assemble, 6),
            dispatch=round(req.t_dispatch, 6),
            done=round(req.t_done, 6),
            bucket=_key_str(req.key),
            batch_size=req.batch_size,
            us=round(dur_us, 1),
            # causal cross-reference: a crash dump's request ring points
            # into the span ring / JSONL stream
            trace_id=trace_id,
            ok=error is None)
        req._event.set()

    def _expire(self, req: Request) -> None:
        self._c_rej_deadline.inc()
        self._finish(req, error=DeadlineExceeded(
            f"request {req.rid} spent its deadline queued (429-style); "
            f"the server is over capacity — back off"))

    def _fail(self, req: Request, error: BaseException) -> None:
        """Assembly-failure path: same accounting as every other
        completion (flight record, timestamps), just with an error."""
        self._finish(req, error=error)
