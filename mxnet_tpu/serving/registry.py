"""Model registry: N named servers behind one admission layer.

One process, one frontend, many models — the multi-tenancy shape the
north star's "millions of users" traffic actually arrives in.  The
registry owns the name → server binding and everything that hangs off
it:

- **per-model metrics** — every entry gets its own serving spine under
  ``serving.model.<name>.*`` (request latency, admits, 429s, sheds,
  TTFT for generation models).  The registry has no label concept, so
  the model label is carried in the metric NAME and re-rendered as a
  real ``model="<name>"`` Prometheus label by the exporter
  (:func:`mxnet_tpu.observability.export.prometheus_text`) — dashboards
  group per model, the in-process registry stays label-free.
- **priorities + load shedding** — each model carries an integer
  priority (higher = more important).  The registry holds one *shed
  level*; a request for a model whose priority is below it is rejected
  at the door with :class:`ServerOverloaded` (HTTP 429) before touching
  the model's own admission queue.  The
  :class:`~mxnet_tpu.tuning.controllers.SloController` raises the level
  lowest-priority-first when the priority model's p99 blows its SLO,
  and lowers it when the tail recovers.
- **lifecycle** — ``load()`` starts (and optionally warms) a server;
  with ``MXTPU_COMPILE_CACHE_DIR`` set the warmup deserializes from the
  persistent compile cache, so loading a model into a warm process
  costs no XLA compile.  ``unload()`` drains and removes.  ``swap()``
  is the rolling blue/green weight swap: the green block compiles for
  every live signature while traffic keeps hitting blue, then flips
  atomically (:meth:`ModelServer.swap_block`) — zero dropped requests.

Knobs: ``MXTPU_FRONTEND_PRIORITY`` (default model priority),
``MXTPU_FRONTEND_SLO_MS`` (default per-model p99 SLO budget; 0 = none).
"""
from __future__ import annotations

import re
import threading
import time
from typing import Dict, List, Optional

from ..base import get_env
from ..observability.registry import registry as _metrics
from .batcher import ServerOverloaded, ServingError
from .server import GenerationServer, ModelServer

__all__ = ["ModelEntry", "ModelRegistry", "UnknownModel",
           "MODEL_METRIC_PREFIX"]

PRIORITY_ENV = "MXTPU_FRONTEND_PRIORITY"
SLO_MS_ENV = "MXTPU_FRONTEND_SLO_MS"

#: metric-name namespace the exporter re-renders as a ``model=`` label
MODEL_METRIC_PREFIX = "serving.model."

_NAME_OK = re.compile(r"^[A-Za-z0-9_.\-]{1,64}$")


class UnknownModel(ServingError):
    """Request for a model name the registry does not hold (404)."""


def _metric_component(name: str) -> str:
    """Model name → one dotted-metric-name component (``[a-z0-9_]+``):
    lowercased, every other character folded to ``_``."""
    comp = re.sub(r"[^a-z0-9_]", "_", name.lower())
    return comp or "_"


class ModelEntry:
    """One registered model: the server, its admission policy, and its
    per-model metric spine (socket-to-socket latency — the server's own
    ``serving.request_us`` measures enqueue-to-done, this measures what
    the CLIENT saw, which is what the SLO is written against)."""

    def __init__(self, name: str, server, *, priority: int,
                 slo_ms: float):
        self.name = name
        self.server = server
        self.kind = ("generate" if isinstance(server, GenerationServer)
                     else "predict")
        self.priority = int(priority)
        self.slo_ms = float(slo_ms)
        self.status = "loading"
        self.loaded_at = time.time()
        self.swaps = 0
        m = MODEL_METRIC_PREFIX + _metric_component(name)
        reg = _metrics()
        self.h_request = reg.histogram(
            m + ".request_us",
            help=f"model {name}: socket-to-socket request latency "
                 f"(the SLO signal)")
        self.c_requests = reg.counter(
            m + ".requests", help=f"model {name}: requests admitted")
        self.c_done = reg.counter(
            m + ".requests_done",
            help=f"model {name}: requests completed ok")
        self.c_rejected = reg.counter(
            m + ".rejected_429",
            help=f"model {name}: requests rejected by the model's own "
                 f"admission queue (backpressure 429)")
        self.c_shed = reg.counter(
            m + ".shed",
            help=f"model {name}: requests shed by the registry's "
                 f"priority gate (SLO-protective 429)")
        if self.kind == "generate":
            self.h_ttft = reg.histogram(
                m + ".ttft_us",
                help=f"model {name}: socket-measured time to first "
                     f"streamed token")
        else:
            self.h_ttft = None

    def describe(self) -> dict:
        d = {
            "name": self.name,
            "kind": self.kind,
            "status": self.status,
            "priority": self.priority,
            "slo_ms": self.slo_ms,
            "swaps": self.swaps,
            "stats": self.server.stats(),
        }
        return d


class ModelRegistry:
    """Named :class:`ModelServer`/:class:`GenerationServer` instances
    behind one priority-aware admission gate (see module docstring)."""

    def __init__(self):
        self._models: Dict[str, ModelEntry] = {}
        self._lock = threading.Lock()
        self._shed_level = 0
        self._g_shed = _metrics().gauge(
            "serving.shed_priority",
            help="registry shed level: requests for models with "
                 "priority BELOW this are 429'd at the door (0 = "
                 "nothing shed)")
        self._g_shed.set(0)
        self._g_models = _metrics().gauge(
            "serving.models_loaded", help="models resident in the "
                                          "registry")
        self._g_models.set(0)

    # -- lifecycle -----------------------------------------------------
    def load(self, name: str, server, *, priority: Optional[int] = None,
             slo_ms: Optional[float] = None, start: bool = True,
             warm=None) -> ModelEntry:
        """Register (and by default start) a server under ``name``.

        ``warm`` prebuilds executables before the model goes ready:
        for a :class:`ModelServer` pass example sample tuples
        (forwarded to :meth:`ModelServer.warmup`); for a
        :class:`GenerationServer` pass True.  On a warm process with
        ``MXTPU_COMPILE_CACHE_DIR`` set this deserializes instead of
        compiling — the warm-start load path."""
        if not _NAME_OK.match(name or ""):
            raise ServingError(
                f"model name {name!r} must match {_NAME_OK.pattern}")
        if priority is None:
            priority = int(get_env(PRIORITY_ENV))
        if slo_ms is None:
            slo_ms = float(get_env(SLO_MS_ENV))
        entry = ModelEntry(name, server, priority=priority,
                           slo_ms=slo_ms)
        with self._lock:
            if name in self._models:
                raise ServingError(
                    f"model {name!r} is already loaded (swap() replaces "
                    f"weights; unload() first to replace the server)")
            self._models[name] = entry
            self._g_models.set(len(self._models))
        try:
            if start:
                server.start()
            if warm is not None:
                if entry.kind == "generate":
                    server.warmup()
                elif warm is not True:
                    server.warmup(*warm)
            entry.status = "ready"
        except BaseException:
            with self._lock:
                self._models.pop(name, None)
                self._g_models.set(len(self._models))
            raise
        return entry

    def unload(self, name: str, drain: bool = True,
               timeout: Optional[float] = None) -> None:
        """Drain (or shed, ``drain=False``) and remove one model."""
        with self._lock:
            entry = self._models.pop(name, None)
            self._g_models.set(len(self._models))
        if entry is None:
            raise UnknownModel(f"no model named {name!r}")
        entry.status = "unloading"
        entry.server.stop(drain=drain, timeout=timeout)
        entry.status = "unloaded"

    def stop_all(self, drain: bool = True,
                 timeout: Optional[float] = None) -> None:
        """Graceful-shutdown sweep: drain every resident server (the
        frontend's SIGTERM path fans out here)."""
        with self._lock:
            entries = list(self._models.values())
        for e in entries:
            e.status = "unloading"
            e.server.stop(drain=drain, timeout=timeout)
            e.status = "unloaded"

    # -- lookup --------------------------------------------------------
    def get(self, name: str) -> ModelEntry:
        with self._lock:
            entry = self._models.get(name)
        if entry is None:
            raise UnknownModel(f"no model named {name!r}")
        return entry

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._models)

    def entries(self) -> List[ModelEntry]:
        with self._lock:
            return [self._models[n] for n in sorted(self._models)]

    def describe(self) -> dict:
        return {"models": [e.describe() for e in self.entries()],
                "shed_level": self.shed_level}

    def ready(self) -> bool:
        """Readiness: at least one model, all of them ready."""
        entries = self.entries()
        return bool(entries) and all(e.status == "ready"
                                     for e in entries)

    # -- the priority admission gate -----------------------------------
    @property
    def shed_level(self) -> int:
        return self._shed_level

    def set_shed_level(self, level: int) -> None:
        """Requests for models with ``priority < level`` are 429'd at
        the door (the SloController's shedding actuator).  0 sheds
        nothing."""
        self._shed_level = max(0, int(level))
        self._g_shed.set(self._shed_level)

    def priorities(self) -> List[int]:
        """Distinct priorities resident, ascending (the SloController's
        shed ladder)."""
        return sorted({e.priority for e in self.entries()})

    def admit(self, entry: ModelEntry) -> None:
        """The registry-level gate, called before the model's own
        admission queue: shed low-priority work while the level is
        raised."""
        if entry.priority < self._shed_level:
            entry.c_shed.inc()
            raise ServerOverloaded(
                f"model {entry.name!r} (priority {entry.priority}) shed "
                f"at level {self._shed_level} — the host is protecting "
                f"higher-priority SLOs; retry with backoff (429)")

    # -- blue/green ----------------------------------------------------
    def swap(self, name: str, new_block) -> int:
        """Rolling blue/green weight swap on a predict model (see
        :meth:`ModelServer.swap_block`).  Traffic keeps flowing on the
        old executables for the whole compile; the flip is atomic and
        drops nothing.  Returns the executable count of the new set."""
        entry = self.get(name)
        if entry.kind != "predict":
            raise ServingError(
                "blue/green swap is a ModelServer operation; reload "
                "generation models via unload()+load()")
        entry.status = "swapping"
        try:
            n = entry.server.swap_block(new_block)
            entry.swaps += 1
        finally:
            entry.status = "ready"
        return n
