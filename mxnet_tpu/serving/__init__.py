"""Serving subsystem: continuous-batching inference over CachedOp graphs.

Everything before this package optimized *training*; the north star says
"serve heavy traffic from millions of users".  This package is the
inference path: a :class:`ModelServer` that loads a hybridized
``HybridBlock`` (served through the direct cached-graph entry,
``HybridBlock.cached_graph`` — no autograd bookkeeping) or an exported
symbol/params pair (``ModelServer.from_exported``, the
``examples/serve_c_api.md`` seam), and runs **continuous/dynamic
batching**:

- :mod:`.batcher` — bounded admission queue (submits past the depth are
  rejected with :class:`ServerOverloaded`, the 429 analog; requests that
  out-wait their deadline are rejected with :class:`DeadlineExceeded`),
  plus the batcher thread and the dispatch handoff queue, so batch
  formation overlaps device execution;
- :mod:`.buckets` — shape-bucketed batch assembly: padding-length
  buckets (the BERT bench's padding machinery) x power-of-two batch
  buckets, one compiled executable per signature, with
  real/padded-element accounting for the batch-efficiency metric;
- :mod:`.server` — the :class:`ModelServer` lifecycle (start / graceful
  drain on ``stop()`` and SIGTERM), per-request metrics
  (``serving.request_us``, ``serving.queue_depth``, ``serving.tokens_*``)
  and flight-recorder request records;
- :mod:`.kv_cache` — the block-managed (paged) KV cache backing
  generation: fixed-size token blocks handed out from a free list, a
  worst-case reservation at admission, released the moment a request
  leaves (finish, deadline, or shed) — the ``serving.kv_blocks_used``
  gauge is the occupancy signal;
- :class:`GenerationServer` (in :mod:`.server`) — **token-level
  continuous batching** for autoregressive decode: an iteration-level
  scheduler where the schedulable unit is one decode step, finished
  requests exit the running batch every iteration, and queued prefills
  join open slots immediately (``MXTPU_SERVING_PREFILL_MODE`` picks
  interleaved vs batch-first prefill);
- :mod:`.registry` + :mod:`.frontend` — the production front door: a
  :class:`ModelRegistry` holding N named servers with priorities and
  per-model SLOs behind one admission gate, and the stdlib
  :class:`HttpFrontend` speaking JSON predict / SSE token streaming /
  W3C ``traceparent`` over it (``POST /v1/models/<name>/predict``,
  ``.../generate``, ``GET /v1/models``, ``/healthz``, ``/readyz``).

Quick start::

    from mxnet_tpu.serving import ModelServer
    net.hybridize()
    with ModelServer(net, max_batch=16) as srv:
        y = srv.infer(x)            # x: ONE sample, no batch dim

Generation::

    from mxnet_tpu.serving import GenerationServer
    lm = causal_lm_small(); ...
    with GenerationServer(lm, slots=4) as srv:
        ids = srv.generate(prompt_ids)      # greedy token ids

Knobs: ``MXTPU_SERVING_MAX_BATCH``, ``MXTPU_SERVING_QUEUE_DEPTH``,
``MXTPU_SERVING_DEADLINE_MS``, ``MXTPU_SERVING_WORKERS``,
``MXTPU_SERVING_BATCH_WINDOW_US``, ``MXTPU_SERVING_KV_BLOCK``,
``MXTPU_SERVING_KV_BLOCKS``, ``MXTPU_SERVING_DECODE_SLOTS``,
``MXTPU_SERVING_PREFILL_MODE``, ``MXTPU_SERVING_MAX_NEW_TOKENS``,
``MXTPU_FRONTEND_PORT``, ``MXTPU_FRONTEND_PRIORITY``,
``MXTPU_FRONTEND_SLO_MS`` (see the README knob table).
"""
from __future__ import annotations

from .batcher import (AdmissionQueue, Batcher, DeadlineExceeded,
                      GenRequest, Request, RequestCancelled, ServerClosed,
                      ServerOverloaded, ServingError)
from .buckets import Bucketer, NoBucketError
from .frontend import HttpFrontend
from .kv_cache import BlockKVCache, BlockTable, SCRATCH_BLOCK
from .registry import ModelEntry, ModelRegistry, UnknownModel
from .server import GenerationServer, ModelServer

__all__ = ["ModelServer", "GenerationServer", "Bucketer", "Request",
           "GenRequest", "AdmissionQueue", "Batcher", "BlockKVCache",
           "BlockTable", "SCRATCH_BLOCK", "ServingError", "ServerClosed",
           "ServerOverloaded", "DeadlineExceeded", "RequestCancelled",
           "NoBucketError", "HttpFrontend", "ModelRegistry", "ModelEntry",
           "UnknownModel"]
