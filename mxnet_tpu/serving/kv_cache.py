"""Block-managed KV cache for the generation scheduler.

The whole-sequence batcher (``serving.batcher``) pads every request to a
length bucket and strands cache memory on the longest member of each
batch.  This module is the paging generalization: KV memory is
pre-allocated once as a pool of fixed-size *token blocks*
(``MXTPU_SERVING_KV_BLOCK`` positions per block,
``MXTPU_SERVING_KV_BLOCKS`` blocks total) and a free-list allocator
hands blocks to requests as their sequences grow, returning them the
moment a request finishes — including the deadline/429 rejection paths.

Two accounting layers keep the pool leak-proof:

* **Reservation** — admission to the running batch reserves the
  *worst case* block count (``ceil((prompt + max_new_tokens)/block)``)
  so a request, once decoding, can never exhaust the pool mid-flight.
* **Allocation** — physical blocks are assigned lazily, only when the
  sequence actually crosses a block boundary, so short generations give
  their unused reservation back at release.

Block 0 is reserved scratch: empty decode slots and unwritten
block-table tail entries point at it, so compiled graphs always gather
and scatter in-bounds.  Garbage read from scratch is masked to an exact
additive zero by the attention mask, keeping per-request outputs
bitwise independent of pool contents.

Occupancy (allocated blocks) is exported as the ``serving.kv_blocks_used``
gauge; it must return to zero after the server drains.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional

from ..base import get_env
from ..observability.registry import registry

__all__ = ["BlockTable", "BlockKVCache"]

#: reserved scratch block — never allocated, every table tail points here.
SCRATCH_BLOCK = 0


class BlockTable:
    """Per-request view of the pool: the ordered block ids backing one
    sequence.  Grown by :meth:`BlockKVCache.ensure`, read by the decode
    graph as a fixed-width int32 row (tail padded with the scratch id).
    """

    __slots__ = ("blocks", "reserved", "seq_len")

    def __init__(self, reserved: int):
        self.blocks: List[int] = []
        self.reserved = reserved
        self.seq_len = 0

    def padded(self, width: int) -> List[int]:
        """Fixed-width row for the compiled decode graph."""
        row = self.blocks[:width]
        return row + [SCRATCH_BLOCK] * (width - len(row))


class BlockKVCache:
    """Free-list allocator over a fixed pool of KV token blocks.

    Thread-safe; the scheduler thread allocates/frees while admission
    (caller threads) queries :meth:`can_reserve`.
    """

    def __init__(self, n_blocks: Optional[int] = None,
                 block_size: Optional[int] = None):
        self.block_size = int(block_size if block_size is not None
                              else get_env("MXTPU_SERVING_KV_BLOCK"))
        n = int(n_blocks if n_blocks is not None
                else get_env("MXTPU_SERVING_KV_BLOCKS"))
        if self.block_size < 1:
            raise ValueError("KV block size must be >= 1")
        if n < 2:
            raise ValueError("KV pool needs >= 2 blocks (block 0 is scratch)")
        self.n_blocks = n
        # block 0 is scratch and never enters the free list.
        self._free: List[int] = list(range(n - 1, 0, -1))
        self._reserved = 0          # blocks promised to admitted requests
        self._tables: Dict[int, BlockTable] = {}
        self._lock = threading.Lock()
        self._g_used = registry().gauge(
            "serving.kv_blocks_used",
            "KV-cache blocks currently allocated to live generations")
        self._g_used.set(0)

    # -- capacity ----------------------------------------------------
    @property
    def capacity(self) -> int:
        """Usable blocks (pool minus the scratch block)."""
        return self.n_blocks - 1

    def blocks_needed(self, prompt_len: int, max_new_tokens: int) -> int:
        """Worst-case block count for a request (reservation unit)."""
        total = prompt_len + max_new_tokens
        return -(-total // self.block_size)

    def can_reserve(self, n: int) -> bool:
        with self._lock:
            return self._reserved + n <= self.capacity

    def fits(self, prompt_len: int, max_new_tokens: int) -> bool:
        """Could this request EVER be admitted (empty pool)?  Requests
        failing this are rejected at submit, not queued forever."""
        return self.blocks_needed(prompt_len, max_new_tokens) <= self.capacity

    # -- allocation --------------------------------------------------
    def reserve(self, req_id: int, prompt_len: int,
                max_new_tokens: int) -> Optional[BlockTable]:
        """Admit a request: reserve its worst-case block count and hand
        back its (empty) block table.  Returns None when the pool cannot
        currently honor the reservation."""
        need = self.blocks_needed(prompt_len, max_new_tokens)
        with self._lock:
            if self._reserved + need > self.capacity:
                return None
            self._reserved += need
            table = BlockTable(need)
            self._tables[req_id] = table
            return table

    def ensure(self, req_id: int, seq_len: int) -> BlockTable:
        """Grow a request's table so positions ``[0, seq_len)`` are
        physically backed.  Lazy: blocks come off the free list only as
        the sequence crosses block boundaries.  The reservation makes
        this infallible for admitted requests."""
        with self._lock:
            table = self._tables[req_id]
            need = -(-seq_len // self.block_size)
            if need > table.reserved:
                raise RuntimeError(
                    "request %d grew past its reservation (%d > %d blocks)"
                    % (req_id, need, table.reserved))
            while len(table.blocks) < need:
                table.blocks.append(self._free.pop())
            table.seq_len = seq_len
            self._g_used.set(self.n_blocks - 1 - len(self._free))
            return table

    def release(self, req_id: int) -> None:
        """Return a request's blocks AND its unused reservation.  Called
        on every exit path: finish, deadline, 429, server close."""
        with self._lock:
            table = self._tables.pop(req_id, None)
            if table is None:
                return
            self._free.extend(reversed(table.blocks))
            table.blocks = []
            self._reserved -= table.reserved
            self._g_used.set(self.n_blocks - 1 - len(self._free))

    # -- introspection -----------------------------------------------
    def used(self) -> int:
        with self._lock:
            return self.n_blocks - 1 - len(self._free)

    def reserved(self) -> int:
        with self._lock:
            return self._reserved
