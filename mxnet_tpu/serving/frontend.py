"""HTTP frontend: the wire protocol over the model registry.

The stdlib-``http.server`` front door (the ``MXTPU_METRICS_PORT``
precedent — zero new dependencies) that turns "a server object in a
process" into "a service": N named models behind one port, speaking
JSON for one-shot inference and Server-Sent Events for token
streaming.

Wire surface::

    GET  /healthz                      liveness (the process is up)
    GET  /readyz                       readiness (models loaded+warm,
                                       not draining) — 503 otherwise
    GET  /v1/models                    registry listing + live stats
    POST /v1/models/<name>/predict     {"inputs": [[...], ...]} ->
                                       {"outputs": [...]} over
                                       submit()/result()
    POST /v1/models/<name>/generate    {"prompt": [ids]} -> SSE stream,
                                       one `data:` event per token,
                                       terminated by `event: done`

Contracts the tests pin down:

- **bitwise parity** — a predict response carries exactly the floats
  ``submit()`` would have returned (JSON round-trips repr-precision);
- **streaming** — tokens flush per decode iteration (TCP_NODELAY, one
  ``flush()`` per event), so socket TTFT tracks in-process TTFT; a
  client hanging up mid-stream cancels the generation at the next
  iteration edge and its KV blocks return to the pool;
- **trace stitching** — a W3C ``traceparent`` request header becomes
  the parent of the request's ``serving.request``/``serving.generate``
  root (one trace from the caller's socket to the decode step); the
  response echoes the request root's traceparent back;
- **admission** — the registry's priority gate runs before the model's
  own admission queue; both reject as HTTP 429 with a JSON body naming
  the reason;
- **graceful shutdown** — ``stop()`` (or SIGTERM via
  :meth:`HttpFrontend.install_sigterm`) closes the listener, then
  drains every registered server — the GenerationServer drain included,
  so KV occupancy is zero when the process exits.

Knobs: ``MXTPU_FRONTEND_PORT`` (the deployment opt-in),
``MXTPU_FRONTEND_SLO_MS``, ``MXTPU_FRONTEND_PRIORITY``.
"""
from __future__ import annotations

import json
import signal
import socket as _socket
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import numpy as _np

from ..base import get_env, hot_path
from ..observability import tracing as _tracing
from ..observability.export import debug_route as _debug_route
from .batcher import (DeadlineExceeded, RequestCancelled, ServerClosed,
                      ServerOverloaded, ServingError)
from .buckets import NoBucketError
from .registry import ModelRegistry, UnknownModel

__all__ = ["HttpFrontend", "FRONTEND_PORT_ENV"]

FRONTEND_PORT_ENV = "MXTPU_FRONTEND_PORT"

#: HTTP status for each serving-error shape (the wire contract)
_STATUS = (
    (UnknownModel, 404),
    (ServerOverloaded, 429),
    (DeadlineExceeded, 504),
    (RequestCancelled, 499),      # nginx's "client closed request"
    (ServerClosed, 503),
    (NoBucketError, 400),
)


def _status_for(exc: BaseException) -> int:
    for etype, code in _STATUS:
        if isinstance(exc, etype):
            return code
    if isinstance(exc, TimeoutError):
        return 504
    return 400 if isinstance(exc, (ValueError, KeyError, TypeError)) \
        else 500


class _Handler(BaseHTTPRequestHandler):
    server_version = "mxtpu-frontend"
    #: HTTP/1.1: keep-alive for the JSON endpoints (Content-Length
    #: delimited); SSE responses opt out per-response via
    #: ``Connection: close`` (close-delimited stream)
    protocol_version = "HTTP/1.1"

    # -- plumbing ------------------------------------------------------
    @property
    def _fe(self) -> "HttpFrontend":
        return self.server.frontend

    def log_message(self, fmt, *args):   # no stderr chatter per request
        pass

    def _read_json(self) -> dict:
        n = int(self.headers.get("Content-Length") or 0)
        if n <= 0:
            return {}
        body = self.rfile.read(n)
        payload = json.loads(body)
        if not isinstance(payload, dict):
            raise ValueError("request body must be a JSON object")
        return payload

    def _drain_body(self) -> None:
        """Discard the unread request body WITHOUT parsing it, so a
        rejection issued before ingestion (shed 429, unknown model)
        stays cheap under a retry storm while the keep-alive stream
        keeps its framing."""
        n = int(self.headers.get("Content-Length") or 0)
        while n > 0:
            chunk = self.rfile.read(min(n, 1 << 16))
            if not chunk:
                break
            n -= len(chunk)

    def _send_json(self, code: int, obj: dict,
                   extra_headers=()) -> None:
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in extra_headers:
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _send_error_json(self, exc: BaseException) -> None:
        code = _status_for(exc)
        self._send_json(code, {"error": type(exc).__name__,
                               "detail": str(exc), "status": code})

    def _remote_ctx(self):
        """The caller's W3C trace context, if the header carries one."""
        return _tracing.parse_traceparent(
            self.headers.get("traceparent"))

    # -- GET -----------------------------------------------------------
    def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler contract
        path, _, query = self.path.partition("?")
        dbg = _debug_route(path, query)
        if dbg is not None:
            # the shared /debug/* surface (observability.export) —
            # knob-gated, pre-encoded (status, content-type, body)
            status, ctype, body = dbg
            self.send_response(status)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        elif path == "/healthz":
            self._send_json(200, {"ok": True})
        elif path == "/readyz":
            fe = self._fe
            if fe.draining:
                self._send_json(503, {"ready": False,
                                      "reason": "draining"})
            elif not fe.registry.ready():
                self._send_json(503, {"ready": False,
                                      "reason": "models not ready"})
            else:
                self._send_json(200, {"ready": True})
        elif path == "/v1/models":
            self._send_json(200, self._fe.registry.describe())
        else:
            self._send_json(404, {"error": "NotFound", "status": 404,
                                  "detail": "try /v1/models, /healthz, "
                                            "/readyz, /debug"})

    # -- POST ----------------------------------------------------------
    def do_POST(self):  # noqa: N802 — BaseHTTPRequestHandler contract
        path = self.path.partition("?")[0]
        parts = [p for p in path.split("/") if p]
        if len(parts) == 4 and parts[0] == "v1" and \
                parts[1] == "models" and \
                parts[3] in ("predict", "generate"):
            name, verb = parts[2], parts[3]
            try:
                # admission BEFORE ingestion: a shed (429) or unknown
                # model must not pay the JSON parse — the door has to
                # stay cheap exactly when the SloController is
                # turning traffic away
                try:
                    entry = self._fe.registry.get(name)
                    self._fe.registry.admit(entry)
                except Exception:
                    self._drain_body()
                    raise
                payload = self._read_json()
                if verb == "predict":
                    if entry.kind != "predict":
                        raise ValueError(
                            f"model {name!r} is a generation model — "
                            f"POST .../generate")
                    self._predict(entry, payload)
                else:
                    if entry.kind != "generate":
                        raise ValueError(
                            f"model {name!r} is a predict model — "
                            f"POST .../predict")
                    self._generate(entry, payload)
            except (BrokenPipeError, ConnectionResetError):
                return                # client gone; nothing to answer
            except Exception as e:    # noqa: BLE001 — wire boundary:
                self._send_error_json(e)   # every failure is a status
        else:
            self._send_json(404, {"error": "NotFound", "status": 404,
                                  "detail": "POST /v1/models/<name>/"
                                            "predict|generate"})

    # -- predict -------------------------------------------------------
    def _predict(self, entry, payload: dict) -> None:
        t0 = time.monotonic()
        raw = payload["inputs"] if "inputs" in payload \
            else [payload["input"]]
        dtypes = payload.get("dtypes")
        arrays = []
        for i, v in enumerate(raw):
            dt = dtypes[i] if dtypes else payload.get("dtype")
            arrays.append(_np.asarray(v, dtype=dt) if dt
                          else _np.asarray(v))
        entry.c_requests.inc()
        # the remote context (when given) parents the request root the
        # server opens at submit — one trace from the caller's socket
        # to the dispatch span
        with _tracing.activate(self._remote_ctx()):
            req = entry.server.submit(
                *arrays, deadline_ms=payload.get("deadline_ms"))
        try:
            result = req.result(
                timeout=float(payload.get("timeout_s", 60.0)))
        except ServingError:
            raise
        rows = result if isinstance(result, tuple) else (result,)
        dur_us = (time.monotonic() - t0) * 1e6
        trace_id = None if req.trace is None else req.trace.trace_id
        entry.h_request.observe(dur_us, trace_id=trace_id)
        entry.c_done.inc()
        headers = []
        if req.trace is not None:
            headers.append(("traceparent", req.trace.traceparent))
        self._finish_predict(entry, req, rows, dur_us, headers)

    @hot_path("dispatch")
    def _finish_predict(self, entry, req, rows, dur_us,
                        headers) -> None:
        """Response serialization — the frontend's per-request hot
        tail: one JSON body, one socket write."""
        body = {"model": entry.name, "rid": req.rid,
                "outputs": [r.tolist() for r in rows],
                "shapes": [list(r.shape) for r in rows],
                "us": round(dur_us, 1)}
        self._send_json(200, body, extra_headers=headers)

    # -- generate (SSE) ------------------------------------------------
    def _generate(self, entry, payload: dict) -> None:
        t0 = time.monotonic()
        prompt = payload["prompt"]
        kw = {}
        if payload.get("max_new_tokens") is not None:
            kw["max_new_tokens"] = int(payload["max_new_tokens"])
        if payload.get("deadline_ms") is not None:
            kw["deadline_ms"] = float(payload["deadline_ms"])
        if payload.get("eos") is not None:
            kw["eos"] = int(payload["eos"])
        entry.c_requests.inc()
        with _tracing.activate(self._remote_ctx()):
            req = entry.server.submit_generate(prompt, **kw)
        # SSE: close-delimited stream (no Content-Length), flushed per
        # token.  TCP_NODELAY so each event leaves the host now — the
        # socket-measured TTFT contract depends on it.
        try:
            self.connection.setsockopt(_socket.IPPROTO_TCP,
                                       _socket.TCP_NODELAY, 1)
        except OSError:
            pass
        self.send_response(200)
        self.send_header("Content-Type", "text/event-stream")
        self.send_header("Cache-Control", "no-cache")
        if req.trace is not None:
            self.send_header("traceparent", req.trace.traceparent)
        self.send_header("Connection", "close")
        self.close_connection = True
        self.end_headers()
        timeout = float(payload.get("timeout_s", 60.0))
        n = 0
        try:
            for tok in req.stream(timeout=timeout):
                if n == 0:
                    trace_id = None if req.trace is None \
                        else req.trace.trace_id
                    entry.h_ttft.observe(
                        (time.monotonic() - t0) * 1e6,
                        trace_id=trace_id)
                self._write_event(
                    f'data: {{"token": {int(tok)}, "index": {n}}}\n\n')
                n += 1
        except (BrokenPipeError, ConnectionResetError, OSError):
            # the client hung up mid-stream: cancel so the scheduler
            # retires the slot and the KV blocks return to the pool
            entry.server.cancel(req)
            return
        except ServingError as e:
            # stream already started — the error rides the stream
            try:
                self._write_event(
                    "event: error\ndata: "
                    + json.dumps({"error": type(e).__name__,
                                  "detail": str(e),
                                  "status": _status_for(e)}) + "\n\n")
            except OSError:
                pass
            return
        dur_us = (time.monotonic() - t0) * 1e6
        trace_id = None if req.trace is None else req.trace.trace_id
        entry.h_request.observe(dur_us, trace_id=trace_id)
        entry.c_done.inc()
        self._write_event(
            "event: done\ndata: "
            + json.dumps({"model": entry.name, "rid": req.rid,
                          "tokens": req.tokens, "n": n,
                          "us": round(dur_us, 1)}) + "\n\n")

    @hot_path("dispatch")
    def _write_event(self, event: str) -> None:
        """One SSE event onto the wire — the frontend's per-token hot
        path: encode, write, flush (TCP_NODELAY set at stream start, so
        the flush IS the send)."""
        self.wfile.write(event.encode())
        self.wfile.flush()


class _Server(ThreadingHTTPServer):
    daemon_threads = True
    #: a handler thread blocked in result()/stream() must not outlive
    #: a stuck client forever
    allow_reuse_address = True


class HttpFrontend:
    """The production front door: one listener over a
    :class:`~mxnet_tpu.serving.registry.ModelRegistry`.

    ``port=0`` binds an ephemeral port (tests) — the bound port is
    ``frontend.port``.  ``stop(drain=True)`` closes the listener, then
    drains every registered server (the graceful-shutdown contract);
    :meth:`install_sigterm` wires that to SIGTERM the same way the
    servers themselves do — the handler never blocks in signal
    context."""

    def __init__(self, registry: Optional[ModelRegistry] = None,
                 port: Optional[int] = None, addr: str = "0.0.0.0",
                 start: bool = False):
        if port is None:
            knob = str(get_env(FRONTEND_PORT_ENV)).strip()
            port = int(knob) if knob else 0
        self.registry = registry if registry is not None \
            else ModelRegistry()
        self._httpd = _Server((addr, int(port)), _Handler)
        self._httpd.frontend = self
        self._thread: Optional[threading.Thread] = None
        self._prev_sigterm = None
        self.draining = False
        if start:
            self.start()

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    # -- lifecycle -----------------------------------------------------
    def start(self) -> "HttpFrontend":
        if self._thread is not None:
            return self
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            kwargs={"poll_interval": 0.25},
            daemon=True, name="mxtpu-frontend")
        self._thread.start()
        return self

    def stop(self, drain: bool = True,
             timeout: Optional[float] = None) -> None:
        """Graceful shutdown: stop accepting, then drain (or shed) every
        registered server.  In-flight handler threads holding request
        futures complete on the servers' own drain path."""
        self.draining = True
        if self._thread is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._thread.join(timeout=5)
            self._thread = None
        self.registry.stop_all(drain=drain, timeout=timeout)

    def __enter__(self) -> "HttpFrontend":
        return self.start()

    def __exit__(self, exc_type, exc, tb) -> None:
        self.stop(drain=exc_type is None)

    def install_sigterm(self) -> None:
        """Chain a SIGTERM handler that gracefully stops the frontend
        (listener down, every model drained — the k8s preStop
        contract).  Same discipline as the servers' own installers: the
        handler spawns a non-daemon drain thread and returns
        immediately, never blocking in signal context."""
        # the manual stack-dump signal (SIGQUIT by default) rides along
        # wherever the drain handler is wired: a wedged drain is exactly
        # when an operator wants kill -QUIT introspection
        from ..observability.watchdog import install_stack_signal
        install_stack_signal()
        prev = signal.getsignal(signal.SIGTERM)
        self._prev_sigterm = prev

        def drain_then_chain(signum, frame):
            self.stop(drain=True)
            if callable(prev) and prev not in (signal.SIG_IGN,
                                               signal.SIG_DFL):
                prev(signum, frame)

        def handler(signum, frame):
            threading.Thread(target=drain_then_chain,
                             args=(signum, frame),
                             name="mxtpu-frontend-sigterm-drain",
                             daemon=False).start()

        signal.signal(signal.SIGTERM, handler)

    def uninstall_sigterm(self) -> None:
        if self._prev_sigterm is not None:
            signal.signal(signal.SIGTERM, self._prev_sigterm)
            self._prev_sigterm = None
