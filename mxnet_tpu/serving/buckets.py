"""Shape-bucketed batch assembly for the serving subsystem.

A compiled CachedOp executable is pinned to ONE input signature
(shapes + dtypes), so a server that dispatched every request shape
as-is would recompile constantly — the recompile storm
``HybridBlock.CACHED_GRAPH_LIMIT`` warns about.  The classic fix (the
reference's BucketingModule economics, and this repo's NMT bench row)
is *bucketing*: pad variable dimensions up to a small fixed menu of
sizes so the whole workload funnels through a handful of executables.

Two bucket axes compose here:

- **batch buckets** — powers of two up to ``max_batch`` (a partial
  batch of 3 dispatches as a padded batch of 4), so batch assembly
  never introduces new signatures;
- **length buckets** — optional per-sample padding of ``pad_axis`` to
  the smallest configured length that fits (the BERT bench's
  valid-length padding idiom, PERF.md round 4): a 20-token request
  joins the 32-token bucket.

Padding is real work the chip does for nothing, so the assembler
reports it — with the two pad axes kept SEPARATE, because they waste
differently:

- ``serving.tokens_padded`` — padded *sequence positions* inside
  occupied batch slots (a 20-token request in a 32-token bucket wastes
  12 positions): the length-bucket cost;
- ``serving.slots_padded`` — *empty batch slots* (3 requests dispatched
  as a padded batch of 4 waste one whole slot): the batch-bucket cost.

``serving.tokens_real`` stays the numerator.  Conflating the two (as
one "padded elements" denominator) polluted the sequence-padding
efficiency number with batch-pad, which matters once the generation
scheduler reports per-token decode efficiency.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as _np

from ..base import MXNetError, hot_path

__all__ = ["Bucketer", "NoBucketError"]


class NoBucketError(MXNetError):
    """The request's shape fits no configured bucket (e.g. a sequence
    longer than the largest length bucket) — a client error, rejected
    at submission."""


def _pow2_buckets(max_batch: int) -> Tuple[int, ...]:
    out = []
    b = 1
    while b < max_batch:
        out.append(b)
        b *= 2
    out.append(max_batch)
    return tuple(out)


class Bucketer:
    """Maps request samples to (shape-bucket, batch-bucket) signatures
    and assembles padded batches.

    A *sample* is a tuple of per-request input arrays WITHOUT the batch
    dimension (the server stacks them).  With ``length_buckets`` set,
    every input whose ``pad_axis`` dimension equals the first input's
    length is padded (zeros) up to the smallest bucket that fits;
    inputs without that dimension pass through fixed-shape.
    """

    def __init__(self, max_batch: int = 8,
                 length_buckets: Optional[Sequence[int]] = None,
                 pad_axis: int = 0,
                 batch_buckets: Optional[Sequence[int]] = None):
        if max_batch < 1:
            raise MXNetError(f"max_batch must be >= 1, got {max_batch}")
        self.max_batch = int(max_batch)
        self.pad_axis = int(pad_axis)
        self.length_buckets = tuple(sorted(set(int(b) for b in
                                               length_buckets))) \
            if length_buckets else ()
        if batch_buckets:
            bb = tuple(sorted(set(int(b) for b in batch_buckets)))
            if bb[-1] != self.max_batch:
                raise MXNetError(
                    f"largest batch bucket {bb[-1]} must equal "
                    f"max_batch {self.max_batch}")
            self.batch_buckets = bb
        else:
            self.batch_buckets = _pow2_buckets(self.max_batch)

    # -- bucket selection ---------------------------------------------------
    def batch_bucket(self, n: int) -> int:
        """Smallest batch bucket that holds ``n`` requests."""
        for b in self.batch_buckets:
            if b >= n:
                return b
        return self.batch_buckets[-1]

    def length_bucket(self, length: int) -> int:
        """Smallest length bucket >= ``length`` (raises NoBucketError
        past the largest)."""
        for b in self.length_buckets:
            if b >= length:
                return b
        raise NoBucketError(
            f"sample length {length} exceeds the largest length bucket "
            f"{self.length_buckets[-1]}")

    def sample_key(self, inputs: Sequence[_np.ndarray]) -> Tuple:
        """The shape-bucket key for one sample: a tuple of (padded
        per-sample shape, dtype name) per input.  Requests sharing a key
        batch together and share one executable per batch bucket."""
        if not inputs:
            raise MXNetError("empty request")
        if not self.length_buckets:
            return tuple((tuple(a.shape), str(a.dtype)) for a in inputs)
        ax = self.pad_axis
        lead = inputs[0]
        if lead.ndim <= ax:
            raise NoBucketError(
                f"pad_axis {ax} out of range for sample shape "
                f"{tuple(lead.shape)}")
        length = lead.shape[ax]
        bucket = self.length_bucket(length)
        key = []
        for a in inputs:
            shape = list(a.shape)
            if a.ndim > ax and a.shape[ax] == length:
                shape[ax] = bucket
            key.append((tuple(shape), str(a.dtype)))
        return tuple(key)

    # -- assembly -----------------------------------------------------------
    @hot_path("dispatch")
    def assemble(self, requests
                 ) -> Tuple[List[_np.ndarray], int, int, int, int]:
        """Pad-and-stack one bucket's requests into batch arrays.

        Returns ``(arrays, batch_bucket, real_elements, slots_padded,
        tokens_padded)``: element counts are over the first input.
        ``slots_padded`` is the count of EMPTY batch slots (batch-bucket
        rounding); ``tokens_padded`` is the padded sequence positions
        within OCCUPIED slots (length-bucket rounding) — two different
        wastes, counted apart.  Runs once per BATCH on the batcher
        thread; the pad buffers are per-batch allocations amortized over
        every request in them.
        """
        n = len(requests)
        bsz = self.batch_bucket(n)
        key = requests[0].key
        arrays: List[_np.ndarray] = []
        for j, (pshape, dt) in enumerate(key):
            # per-BATCH pad buffer (not per-op, not per-request): the one
            # allocation continuous batching exists to amortize
            buf = _np.zeros((bsz,) + tuple(pshape), dtype=dt)  # mxlint: disable=hot-path-purity — per-batch pad buffer, amortized over the batch
            for i, req in enumerate(requests):
                a = req.inputs[j]
                buf[(i,) + tuple(slice(0, s) for s in a.shape)] = a
            arrays.append(buf)
        real = sum(int(req.inputs[0].size) for req in requests)
        slot_elems = 1
        for s in key[0][0]:
            slot_elems *= int(s)
        slots_padded = bsz - n
        tokens_padded = n * slot_elems - real
        return arrays, bsz, real, slots_padded, tokens_padded
