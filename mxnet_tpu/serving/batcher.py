"""Admission queue + continuous-batching pump for the serving subsystem.

The request path, end to end::

    client -> submit() -> AdmissionQueue -> [batcher thread] -> _Batch
           -> dispatch queue -> [worker threads] -> one CachedOp call
           -> split rows -> Request.result()

Three flow-control behaviors, all measured:

- **backpressure**: the admission queue is depth-bounded; a submit
  past the bound fails immediately with :class:`ServerOverloaded`
  (the HTTP-429 analog) instead of growing an unbounded backlog —
  shedding load at the door is what keeps p99 finite under overload;
- **deadlines**: a request still queued when its deadline expires is
  rejected at batch assembly with :class:`DeadlineExceeded` — the chip
  never spends a batch slot computing an answer nobody is waiting for;
- **continuous batching**: ONE batcher thread drains the queue
  head-of-line by shape bucket, waits up to a short window for the
  bucket to fill, and hands assembled batches to a bounded dispatch
  queue that N workers drain — so the next batch forms WHILE the
  current one executes on device, and dispatch-queue pressure
  propagates back to admission.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional, Tuple

from ..base import MXNetError, hot_path

__all__ = ["Request", "GenRequest", "AdmissionQueue", "Batcher",
           "ServingError", "ServerClosed", "ServerOverloaded",
           "DeadlineExceeded", "RequestCancelled"]


class ServingError(MXNetError):
    """Base class for serving-path request failures."""


class ServerClosed(ServingError):
    """Submit after shutdown (or a request shed by a non-draining
    stop)."""


class ServerOverloaded(ServingError):
    """Admission queue full — the 429: retry later, ideally with
    backoff."""


class DeadlineExceeded(ServingError):
    """The request's deadline expired while it was still queued."""


class RequestCancelled(ServingError):
    """The client walked away (stream disconnect / explicit cancel);
    the server dropped the request at the next iteration boundary and
    released its resources."""


class Request:
    """One in-flight inference request: inputs, lifecycle timestamps
    (the flight-recorder record), and a one-shot completion event.

    ``trace`` is the request's causal-tracing root span (None when
    tracing is off or head sampling dropped it): opened at submit,
    finished at completion, and the parent every batch the request
    rides links back to — the contextvar cannot cross the
    submit→batcher→worker thread hops, so the request object IS the
    context carrier on this path."""

    __slots__ = ("rid", "inputs", "key", "deadline", "batch_size",
                 "t_enqueue", "t_assemble", "t_dispatch", "t_done",
                 "trace", "_event", "_result", "_error")

    def __init__(self, rid: int, inputs: Tuple, key: Tuple,
                 deadline: Optional[float]):
        self.rid = rid
        self.inputs = inputs
        self.key = key
        self.deadline = deadline        # monotonic seconds, None = none
        self.batch_size = 0
        self.t_enqueue = time.monotonic()
        self.t_assemble = 0.0
        self.t_dispatch = 0.0
        self.t_done = 0.0
        self.trace = None
        self._event = threading.Event()
        self._result = None
        self._error: Optional[BaseException] = None

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None):
        """Block until the request completes; returns the output array
        (tuple of arrays for multi-output models) or raises the
        request's error."""
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"request {self.rid} not completed within {timeout}s")
        if self._error is not None:
            raise self._error
        return self._result


class GenRequest:
    """One in-flight *generation* request for the iteration-level decode
    scheduler (``ModelServer``'s generation mode): prompt in, greedy
    token ids out, one token per decode step.

    Lifecycle timestamps split time-to-first-token from total latency:
    ``t_first`` is stamped when the prefill's logits yield token one
    (the ``serving.ttft_us`` histogram); ``t_done`` when the request
    leaves the running batch.  ``trace`` is the causal-tracing root
    opened at submit (None when tracing is off/sampled out) — the
    request object carries it across the submit→scheduler thread hop,
    and every decode step the request rides links back to it.

    Tokens are published through :meth:`push_token` (scheduler side)
    and consumed either whole (:meth:`result`) or incrementally
    (:meth:`stream` — the SSE frontend's per-token seam).  The
    publisher never blocks: the token list grows under a condition the
    consumer waits on, so a slow stream reader stalls only its own
    socket, never the decode loop."""

    __slots__ = ("rid", "prompt", "max_new_tokens", "deadline", "eos",
                 "tokens", "trace", "t_enqueue", "t_prefill", "t_first",
                 "t_done", "pos", "cancelled", "_tcond", "_event",
                 "_error")

    def __init__(self, rid: int, prompt, max_new_tokens: int,
                 deadline: Optional[float], eos: Optional[int]):
        self.rid = rid
        self.prompt = prompt                # 1-D int32 numpy token ids
        self.max_new_tokens = max_new_tokens
        self.deadline = deadline            # monotonic seconds, None = none
        self.eos = eos                      # stop token id, None = run to cap
        self.tokens: List[int] = []         # generated ids (EOS included)
        self.trace = None
        self.t_enqueue = time.monotonic()
        self.t_prefill = 0.0
        self.t_first = 0.0
        self.t_done = 0.0
        self.pos = 0          # position of the NEXT token to decode
        self.cancelled = False  # set by cancel(); honored by the
        #                         scheduler at the next iteration edge
        self._tcond = threading.Condition()
        self._event = threading.Event()
        self._error: Optional[BaseException] = None

    def done(self) -> bool:
        return self._event.is_set()

    def push_token(self, tok: int) -> None:
        """Scheduler side: publish one generated token and wake any
        stream consumer.  Non-blocking by construction."""
        with self._tcond:
            self.tokens.append(tok)
            self._tcond.notify_all()

    def _wake_stream(self) -> None:
        """Completion side: wake stream consumers blocked past the last
        token (called after the done event is set)."""
        with self._tcond:
            self._tcond.notify_all()

    def stream(self, timeout: Optional[float] = None):
        """Incremental consumer: yield token ids as the scheduler emits
        them, ending when the generation finishes (the streaming twin
        of :meth:`result`).  ``timeout`` bounds each WAIT for the next
        token, not the whole generation.  The request's error
        (deadline, shed, cancel) is raised after every already-emitted
        token has been yielded."""
        i = 0
        while True:
            with self._tcond:
                while i >= len(self.tokens) and not self._event.is_set():
                    if not self._tcond.wait(timeout):
                        raise TimeoutError(
                            f"generation {self.rid}: no token within "
                            f"{timeout}s")
                fresh = self.tokens[i:]
                finished = self._event.is_set()
            for tok in fresh:
                i += 1
                yield tok
            if finished and i >= len(self.tokens):
                if self._error is not None:
                    raise self._error
                return

    def result(self, timeout: Optional[float] = None) -> List[int]:
        """Block until generation finishes; returns the generated token
        ids (EOS included when hit) or raises the request's error."""
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"generation {self.rid} not completed within {timeout}s")
        if self._error is not None:
            raise self._error
        return self.tokens


class AdmissionQueue:
    """Bounded FIFO with shape-bucket-aware batch pops.

    ``submit`` is the backpressure point (raises past ``depth``);
    ``pop_bucket`` is the batcher's side: take the head request's
    bucket, collect up to ``max_batch`` peers, waiting at most
    ``window_s`` for the bucket to fill.  Expired requests are swept
    out and returned separately so the caller can fail them.
    """

    def __init__(self, depth: int, gauge=None):
        self.depth = int(depth)
        self._q: List[Request] = []
        self._cond = threading.Condition()
        self._closed = False
        self._gauge = gauge

    def __len__(self) -> int:
        return len(self._q)

    @property
    def closed(self) -> bool:
        return self._closed

    def _set_gauge_locked(self) -> None:
        if self._gauge is not None:
            self._gauge.set(len(self._q))

    def submit(self, req: Request) -> None:
        with self._cond:
            if self._closed:
                raise ServerClosed("server is shut down")
            if len(self._q) >= self.depth:
                raise ServerOverloaded(
                    f"admission queue full ({self.depth} deep) — "
                    f"retry with backoff (429)")
            self._q.append(req)
            self._set_gauge_locked()
            self._cond.notify_all()

    def close(self) -> None:
        """No further submits; pending requests stay for draining."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    def shed(self) -> List[Request]:
        """Drop every queued request (non-draining stop); returns them
        so the caller can fail them with ServerClosed."""
        with self._cond:
            dropped, self._q = self._q, []
            self._set_gauge_locked()
            self._cond.notify_all()
            return dropped

    def pop_bucket(self, max_batch: int, window_s: float
                   ) -> Optional[Tuple[List[Request], List[Request]]]:
        """Next assembled-batch worth of requests: ``(batch, expired)``,
        or ``None`` when the queue is closed and fully drained."""
        with self._cond:
            while True:
                now = time.monotonic()
                expired = [r for r in self._q
                           if r.deadline is not None and r.deadline < now]
                if expired:
                    self._q = [r for r in self._q if r not in expired]
                    self._set_gauge_locked()
                if self._q:
                    break
                if expired:
                    # deliver the expirations NOW — waiting for fresh
                    # traffic would strand their waiters
                    return [], expired
                if self._closed:
                    return None
                self._cond.wait()
            head_key = self._q[0].key
            t_limit = None
            while True:
                take = [r for r in self._q if r.key == head_key]
                if len(take) >= max_batch or self._closed or window_s <= 0:
                    break
                now = time.monotonic()
                if t_limit is None:
                    t_limit = now + window_s
                remaining = t_limit - now
                if remaining <= 0:
                    break
                self._cond.wait(remaining)
            take = take[:max_batch]
            taken = set(id(r) for r in take)
            self._q = [r for r in self._q if id(r) not in taken]
            self._set_gauge_locked()
            return take, expired


class _Batch:
    """One assembled, padded batch headed for a single compiled call.
    ``trace`` carries the assembly span across the dispatch-queue hop
    (the dispatch span's parent); None when no member request is
    traced.  Padding is carried split: ``slots_padded`` (empty batch
    slots) vs ``tokens_padded`` (padded sequence positions in occupied
    slots)."""

    __slots__ = ("key", "batch", "arrays", "requests", "real",
                 "slots_padded", "tokens_padded", "trace")

    def __init__(self, key, batch, arrays, requests, real, slots_padded,
                 tokens_padded, trace=None):
        self.key = key
        self.batch = batch
        self.arrays = arrays
        self.requests = requests
        self.real = real
        self.slots_padded = slots_padded
        self.tokens_padded = tokens_padded
        self.trace = trace


class Batcher:
    """The continuous-batching pump: one thread that turns the admission
    queue into a stream of assembled batches on a bounded handoff queue
    (its ``put`` blocking is how dispatch pressure reaches admission)."""

    def __init__(self, admission: AdmissionQueue, bucketer, out_queue,
                 max_batch: int, window_s,
                 on_expired: Callable[[Request], None],
                 on_error: Optional[Callable[[Request, BaseException],
                                             None]] = None):
        self._admission = admission
        self._bucketer = bucketer
        self._out = out_queue
        self._max_batch = max_batch
        # a float is a frozen window; a CALLABLE is re-read before every
        # batch pop — the live-knob mode the BatchWindowController
        # adapts (one get_env per assembled batch: noise next to the
        # window it configures)
        self._window = window_s
        self._on_expired = on_expired
        self._on_error = on_error
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run,
                                        name="mxtpu-serving-batcher",
                                        daemon=True)
        self._thread.start()

    def join(self, timeout: Optional[float] = None) -> None:
        if self._thread is not None:
            self._thread.join(timeout)

    def is_alive(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def _run(self) -> None:
        while True:
            window = self._window() if callable(self._window) \
                else self._window
            popped = self._admission.pop_bucket(self._max_batch, window)
            if popped is None:
                break
            batch_reqs, expired = popped
            for r in expired:
                self._on_expired(r)
            if not batch_reqs:
                continue
            try:
                batch = self._assemble(batch_reqs)
            except Exception as e:   # a poison batch fails ITS requests;
                for r in batch_reqs:     # the pump must keep pumping
                    if self._on_error is not None:
                        self._on_error(r, e)   # uniform accounting path
                    else:
                        r._error = e
                        r._event.set()
                continue
            self._out.put(batch)

    @hot_path("dispatch")
    def _assemble(self, requests: List[Request]) -> _Batch:
        """Batch-assembly entry point (serving hot path): stamp the
        assembly timestamp and pad-and-stack via the bucketer.

        Causal tracing: when any member request carries a trace, the
        assembly gets a span parented on the FIRST traced request and
        LINKED to every other traced member — one batch, many causes;
        the links render as flow arrows from each request's root.
        Tracing off = every ``r.trace`` is None = no tracer touch."""
        sp = parent_req = None
        for r in requests:
            if r.trace is not None:
                from ..observability import tracing as _tracing
                sp = _tracing.tracer().begin(
                    "serving.assemble", parent=r.trace, activate=False)
                parent_req = r
                break
        t = time.monotonic()
        for r in requests:
            r.t_assemble = t
        try:
            arrays, bsz, real, slots_pad, tokens_pad = \
                self._bucketer.assemble(requests)
        except BaseException as exc:
            # a poison batch still records its assembly span (the pump
            # fails these requests and keeps pumping — the trace should
            # show where they died)
            if sp is not None:
                sp.annotate(error=type(exc).__name__)
                sp.finish()
            raise
        if sp is not None:
            for r in requests:
                if r.trace is not None and r is not parent_req:
                    sp.link(r.trace)
            sp.annotate(batch=bsz, real=real, slots_padded=slots_pad,
                        tokens_padded=tokens_pad)
            sp.finish()
        return _Batch(requests[0].key, bsz, arrays, requests, real,
                      slots_pad, tokens_pad, trace=sp)
