"""``python -m mxnet_tpu.kvstore_server`` — the reference's server-process
entrypoint (reference: python/mxnet/kvstore_server.py), kept so cluster
scripts written for the parameter-server launcher run unchanged.

There are no parameter servers in this build: the PS push/pull plane is
replaced by synchronous SPMD collectives (in-graph psum over ICI; one host
allreduce per step over DCN — SURVEY.md §2.3/§5.8, parallel/dist.py).
A process launched with DMLC_ROLE=server or =scheduler therefore has
nothing to serve; it logs that fact and exits 0 so job trackers see a
clean completion instead of a crash.
"""
from __future__ import annotations

import logging
import os
import sys


def _main() -> int:
    role = os.environ.get("DMLC_ROLE", "")
    logging.basicConfig(level=logging.INFO)
    log = logging.getLogger("kvstore_server")
    if role in ("server", "scheduler"):
        log.info(
            "DMLC_ROLE=%s: this build has no parameter servers — gradient "
            "exchange is synchronous collective allreduce (kvstore "
            "dist_sync over jax.distributed). Exiting cleanly; only "
            "worker processes participate.", role)
        return 0
    if role == "worker":
        log.info("DMLC_ROLE=worker: nothing to do in kvstore_server; "
                 "run your training script directly (it joins the "
                 "process group via mxnet_tpu.parallel.dist).")
        return 0
    log.error("kvstore_server: DMLC_ROLE is not set")
    return 1


if __name__ == "__main__":
    sys.exit(_main())
