"""Symbolic RNN cells (reference: python/mxnet/rnn/rnn_cell.py).

Each cell is a Symbol-graph factory: ``cell(inputs, states)`` appends one
step, ``cell.unroll(T, inputs)`` builds the whole sequence.  TPU-native
note: the unrolled graph hits CachedOp/simple_bind as ONE jitted XLA
computation per bucket (SURVEY §5.7), so explicit unrolling costs nothing
at run time; FusedRNNCell lowers to the single fused ``RNN`` op
(lax.scan inside) when the whole sequence is wanted at once.

Gate orders match the reference exactly (i,f,c,o for LSTM; r,z,o for GRU)
so packed weights are interchangeable with fused-op parameters.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

from ..base import MXNetError

__all__ = ["RNNParams", "BaseRNNCell", "RNNCell", "LSTMCell", "GRUCell",
           "FusedRNNCell", "SequentialRNNCell", "BidirectionalCell",
           "DropoutCell", "ResidualCell"]


def _sym():
    from .. import symbol
    return symbol


class RNNParams:
    """Container for a cell's Symbol variables, keyed by name suffix."""

    def __init__(self, prefix: str = ""):
        self._prefix = prefix
        self._params = {}

    def get(self, name: str, **kwargs):
        name = self._prefix + name
        if name not in self._params:
            self._params[name] = _sym().var(name, **kwargs)
        return self._params[name]


class BaseRNNCell:
    """Abstract cell: one step of computation on Symbol inputs."""

    def __init__(self, prefix: str = "", params: Optional[RNNParams] = None):
        if params is None:
            params = RNNParams(prefix)
            self._own_params = True
        else:
            self._own_params = False
        self._prefix = prefix
        self._params = params
        self._modified = False
        self.reset()

    def reset(self) -> None:
        self._init_counter = -1
        self._counter = -1

    @property
    def params(self) -> RNNParams:
        self._own_params = False
        return self._params

    @property
    def state_info(self) -> List[dict]:
        raise NotImplementedError

    @property
    def _gate_names(self) -> Tuple[str, ...]:
        return ()

    def __call__(self, inputs, states):
        raise NotImplementedError

    # -- states ------------------------------------------------------------
    def begin_state(self, func=None, **kwargs) -> List:
        """Zero initial states.  With no batch reference available the
        reference emits shape-(0,·) zeros resolved at bind; here each
        state becomes a variable named ``{prefix}begin_state_N`` that
        simple_bind treats as an auxiliary input (bind zeros), unless the
        caller passes ``batch_ref`` (any (N, ·) Symbol) — then the state
        is synthesized in-graph as broadcast zeros and needs no binding."""
        assert not self._modified, \
            "After applying modifier cells, call begin_state on the base"
        batch_ref = kwargs.pop("batch_ref", None)
        states = []
        for info in self.state_info:
            self._init_counter += 1
            name = f"{self._prefix}begin_state_{self._init_counter}"
            n_hidden = info["shape"][-1]
            if batch_ref is not None:
                sym = _sym()
                col = sym.zeros_like(
                    sym.slice_axis(batch_ref, axis=-1, begin=0, end=1))
                state = sym.broadcast_to(col, shape=(0, n_hidden))
            elif func is not None:
                state = func(name=name, **info)
            else:
                state = _sym().var(name, shape=info["shape"])
            states.append(state)
        return states

    # -- weights (fused-op interchange) ------------------------------------
    def unpack_weights(self, args: dict) -> dict:
        return dict(args)

    def pack_weights(self, args: dict) -> dict:
        return dict(args)

    # -- unroll ------------------------------------------------------------
    def _normalize_inputs(self, length: int, inputs, layout: str):
        sym = _sym()
        if isinstance(inputs, (list, tuple)):
            if len(inputs) != length:
                raise MXNetError(f"unroll: {len(inputs)} inputs for "
                                 f"length {length}")
            return list(inputs)
        axis = layout.find("T")
        if axis not in (0, 1):
            raise MXNetError(f"unsupported layout {layout!r}")
        split = sym.split(inputs, num_outputs=length, axis=axis,
                          squeeze_axis=True)
        if length == 1:
            return [split]
        return [split[i] for i in range(length)]

    def unroll(self, length: int, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        """Unroll the cell ``length`` steps.

        Returns (outputs, states): outputs is a list of per-step Symbols,
        or one stacked Symbol when merge_outputs=True (stacked on the
        layout's T axis)."""
        self.reset()
        seq = self._normalize_inputs(length, inputs, layout)
        if begin_state is None:
            begin_state = self.begin_state(batch_ref=seq[0])
        states = begin_state
        outputs = []
        for t in range(length):
            out, states = self(seq[t], states)
            outputs.append(out)
        if merge_outputs:
            axis = layout.find("T")
            outputs = _sym().stack(*outputs, axis=axis)
        return outputs, states


class RNNCell(BaseRNNCell):
    """Vanilla tanh/relu cell (reference RNNCell)."""

    def __init__(self, num_hidden: int, activation: str = "tanh",
                 prefix: str = "rnn_", params=None):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._activation = activation
        self._iW = self.params.get("i2h_weight")
        self._iB = self.params.get("i2h_bias")
        self._hW = self.params.get("h2h_weight")
        self._hB = self.params.get("h2h_bias")

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ("",)

    def __call__(self, inputs, states):
        self._counter += 1
        sym = _sym()
        name = f"{self._prefix}t{self._counter}_"
        i2h = sym.FullyConnected(data=inputs, weight=self._iW, bias=self._iB,
                                 num_hidden=self._num_hidden,
                                 name=f"{name}i2h")
        h2h = sym.FullyConnected(data=states[0], weight=self._hW,
                                 bias=self._hB,
                                 num_hidden=self._num_hidden,
                                 name=f"{name}h2h")
        output = sym.Activation(i2h + h2h, act_type=self._activation,
                                name=f"{name}out")
        return output, [output]


class LSTMCell(BaseRNNCell):
    """LSTM (reference LSTMCell; gate order i, f, c, o).

    ``forget_bias`` follows the reference convention: applied at
    INITIALIZATION (an ``__init__`` attr on the bias variable consumed by
    Module.init_params), never at runtime — so trained weights stay
    bit-interchangeable with the fused RNN op's packed parameters."""

    def __init__(self, num_hidden: int, prefix: str = "lstm_", params=None,
                 forget_bias: float = 1.0):
        super().__init__(prefix=prefix, params=params)
        import json
        self._num_hidden = num_hidden
        self._iW = self.params.get("i2h_weight")
        self._iB = self.params.get(
            "i2h_bias",
            __init__=json.dumps(["lstmbias",
                                 {"forget_bias": forget_bias}]))
        self._hW = self.params.get("h2h_weight")
        self._hB = self.params.get("h2h_bias")
        self._forget_bias = forget_bias

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"},
                {"shape": (0, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ("_i", "_f", "_c", "_o")

    def __call__(self, inputs, states):
        self._counter += 1
        sym = _sym()
        name = f"{self._prefix}t{self._counter}_"
        i2h = sym.FullyConnected(data=inputs, weight=self._iW, bias=self._iB,
                                 num_hidden=4 * self._num_hidden,
                                 name=f"{name}i2h")
        h2h = sym.FullyConnected(data=states[0], weight=self._hW,
                                 bias=self._hB,
                                 num_hidden=4 * self._num_hidden,
                                 name=f"{name}h2h")
        gates = i2h + h2h
        g = sym.split(gates, num_outputs=4, axis=1,
                      name=f"{name}slice")
        in_gate = sym.Activation(g[0], act_type="sigmoid")
        forget_gate = sym.Activation(g[1], act_type="sigmoid")
        in_transform = sym.Activation(g[2], act_type="tanh")
        out_gate = sym.Activation(g[3], act_type="sigmoid")
        next_c = forget_gate * states[1] + in_gate * in_transform
        next_h = out_gate * sym.Activation(next_c, act_type="tanh")
        return next_h, [next_h, next_c]


class GRUCell(BaseRNNCell):
    """GRU (reference GRUCell; gate order r, z, o)."""

    def __init__(self, num_hidden: int, prefix: str = "gru_", params=None):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._iW = self.params.get("i2h_weight")
        self._iB = self.params.get("i2h_bias")
        self._hW = self.params.get("h2h_weight")
        self._hB = self.params.get("h2h_bias")

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ("_r", "_z", "_o")

    def __call__(self, inputs, states):
        self._counter += 1
        sym = _sym()
        name = f"{self._prefix}t{self._counter}_"
        prev = states[0]
        i2h = sym.FullyConnected(data=inputs, weight=self._iW, bias=self._iB,
                                 num_hidden=3 * self._num_hidden,
                                 name=f"{name}i2h")
        h2h = sym.FullyConnected(data=prev, weight=self._hW, bias=self._hB,
                                 num_hidden=3 * self._num_hidden,
                                 name=f"{name}h2h")
        ii = sym.split(i2h, num_outputs=3, axis=1)
        hh = sym.split(h2h, num_outputs=3, axis=1)
        reset = sym.Activation(ii[0] + hh[0], act_type="sigmoid")
        update = sym.Activation(ii[1] + hh[1], act_type="sigmoid")
        next_h_tmp = sym.Activation(ii[2] + reset * hh[2], act_type="tanh")
        next_h = (1.0 - update) * next_h_tmp + update * prev
        return next_h, [next_h]


class FusedRNNCell(BaseRNNCell):
    """Whole-sequence fused cell over the single ``RNN`` op — the XLA
    lax.scan lowering (reference FusedRNNCell over the cuDNN kernel)."""

    def __init__(self, num_hidden: int, num_layers: int = 1,
                 mode: str = "lstm", bidirectional: bool = False,
                 dropout: float = 0.0, prefix: Optional[str] = None,
                 params=None, forget_bias: float = 1.0,
                 get_next_state: bool = False, input_size: int = 0):
        if prefix is None:
            prefix = f"{mode}_"
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._num_layers = num_layers
        self._mode = mode
        self._bidirectional = bidirectional
        self._dropout = dropout
        self._get_next_state = get_next_state
        self._input_size = input_size
        # shape inference here is forward-only (eval_shape), so the packed
        # parameter length cannot be derived backward from the data shape;
        # with input_size given, the variable carries its exact shape and
        # simple_bind needs nothing else
        kw = {}
        if input_size:
            kw["shape"] = (self._param_count(input_size),)
        self._param = self.params.get("parameters", **kw)

    def _param_count(self, input_size: int) -> int:
        from ..base import rnn_packed_param_count
        return rnn_packed_param_count(self._mode, input_size,
                                      self._num_hidden, self._num_layers,
                                      self._bidirectional)

    @property
    def state_info(self):
        d = 2 if self._bidirectional else 1
        info = [{"shape": (d * self._num_layers, 0, self._num_hidden),
                 "__layout__": "LNC"}]
        if self._mode == "lstm":
            info.append({"shape": (d * self._num_layers, 0,
                                   self._num_hidden),
                         "__layout__": "LNC"})
        return info

    def __call__(self, inputs, states):
        raise MXNetError("FusedRNNCell cannot step; use unroll()")

    def unroll(self, length: int, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        sym = _sym()
        if isinstance(inputs, (list, tuple)):
            axis = layout.find("T")
            inputs = sym.stack(*inputs, axis=axis)
        if layout == "NTC":      # RNN op wants TNC
            inputs = sym.swapaxes(inputs, dim1=0, dim2=1)
        kwargs = {}
        if begin_state is None:
            # in-graph zero states (L*D, N, H), synthesized from the data
            # symbol so simple_bind needs no extra shapes
            ndir = 2 if self._bidirectional else 1
            zcol = sym.zeros_like(sym.slice_axis(
                sym.slice_axis(inputs, axis=0, begin=0, end=1),
                axis=2, begin=0, end=1))               # (1, N, 1)
            zstate = sym.broadcast_to(
                zcol, shape=(ndir * self._num_layers, 0,
                             self._num_hidden))
            begin_state = [zstate] + ([zstate] if self._mode == "lstm"
                                      else [])
        kwargs["state"] = begin_state[0]
        if self._mode == "lstm":
            kwargs["state_cell"] = begin_state[1]
        out = sym.RNN(data=inputs, parameters=self._param,
                      state_size=self._num_hidden,
                      num_layers=self._num_layers, mode=self._mode,
                      bidirectional=self._bidirectional, p=self._dropout,
                      state_outputs=True,
                      name=f"{self._prefix}rnn", **kwargs)
        seq = out[0]
        if layout == "NTC":
            seq = _sym().swapaxes(seq, dim1=0, dim2=1)
        states = [out[1]] + ([out[2]] if self._mode == "lstm" else [])
        if merge_outputs is False:
            sym = _sym()
            t_axis = layout.find("T")
            split = sym.split(seq, num_outputs=length, axis=t_axis,
                              squeeze_axis=True)
            seq = [split] if length == 1 else \
                [split[i] for i in range(length)]
        if not self._get_next_state:
            states = []
        return seq, states

    def _ngates(self) -> int:
        return {"lstm": 4, "gru": 3, "rnn_tanh": 1, "rnn_relu": 1}[
            self._mode]

    def _infer_input_size(self, total: int) -> int:
        """Recover layer-0 input size from the packed parameter length."""
        g, H = self._ngates(), self._num_hidden
        ndir = 2 if self._bidirectional else 1
        rest = 0
        layer_in = H * ndir
        for _ in range(1, self._num_layers):
            rest += ndir * (g * H * layer_in + g * H * H + 2 * g * H)
        first_fixed = ndir * (g * H * H + 2 * g * H)
        num = total - rest - first_fixed
        in_size, rem = divmod(num, ndir * g * H)
        if rem or in_size <= 0:
            raise MXNetError(
                f"packed parameter length {total} does not match "
                f"mode={self._mode} layers={self._num_layers} "
                f"hidden={self._num_hidden}")
        return in_size

    def _slices(self, input_size: int):
        """(cell_prefix, name, shape, offset) for the reference packed
        layout: per layer, per direction: Wx, Wh, bx, bh."""
        g, H = self._ngates(), self._num_hidden
        ndir = 2 if self._bidirectional else 1
        out = []
        offset = 0
        layer_in = input_size
        for layer in range(self._num_layers):
            for d in range(ndir):
                cp = f"{self._prefix}{'lr'[d]}{layer}_"
                for nm, shape in (("i2h_weight", (g * H, layer_in)),
                                  ("h2h_weight", (g * H, H)),
                                  ("i2h_bias", (g * H,)),
                                  ("h2h_bias", (g * H,))):
                    n = 1
                    for s in shape:
                        n *= s
                    out.append((cp, nm, shape, offset))
                    offset += n
            layer_in = H * ndir
        return out, offset

    def unpack_weights(self, args: dict) -> dict:
        """Split the packed ``{prefix}parameters`` vector into the per-cell
        weights ``unfuse()``'s cells expect (reference unpack_weights)."""
        from .. import ndarray as nd
        key = f"{self._prefix}parameters"
        if key not in args:
            return dict(args)
        args = dict(args)
        flat = args.pop(key).asnumpy().reshape(-1)
        slices, total = self._slices(self._infer_input_size(flat.size))
        if total != flat.size:
            raise MXNetError("packed parameter length mismatch")
        for cp, nm, shape, offset in slices:
            n = 1
            for s in shape:
                n *= s
            args[cp + nm] = nd.array(
                flat[offset:offset + n].reshape(shape))
        return args

    def pack_weights(self, args: dict) -> dict:
        """Inverse of unpack_weights: gather per-cell weights back into
        one packed vector."""
        import numpy as _np
        from .. import ndarray as nd
        probe = f"{self._prefix}l0_i2h_weight"
        if probe not in args:
            return dict(args)
        args = dict(args)
        in_size = args[probe].shape[-1]
        slices, total = self._slices(in_size)
        flat = _np.zeros((total,), _np.float32)
        for cp, nm, shape, offset in slices:
            n = 1
            for s in shape:
                n *= s
            flat[offset:offset + n] = \
                args.pop(cp + nm).asnumpy().reshape(-1)
        args[f"{self._prefix}parameters"] = nd.array(flat)
        return args

    def unfuse(self) -> "SequentialRNNCell":
        """Equivalent stack of unfused cells (reference unfuse)."""
        stack = SequentialRNNCell()
        get = {"rnn_relu": lambda p: RNNCell(self._num_hidden,
                                             activation="relu", prefix=p),
               "rnn_tanh": lambda p: RNNCell(self._num_hidden,
                                             activation="tanh", prefix=p),
               "lstm": lambda p: LSTMCell(self._num_hidden, prefix=p),
               "gru": lambda p: GRUCell(self._num_hidden, prefix=p)}[
            self._mode]
        for i in range(self._num_layers):
            if self._bidirectional:
                stack.add(BidirectionalCell(
                    get(f"{self._prefix}l{i}_"),
                    get(f"{self._prefix}r{i}_"),
                    output_prefix=f"{self._prefix}bi_l{i}_"))
            else:
                stack.add(get(f"{self._prefix}l{i}_"))
            if self._dropout > 0 and i != self._num_layers - 1:
                stack.add(DropoutCell(self._dropout,
                                      prefix=f"{self._prefix}_dropout{i}_"))
        return stack


class SequentialRNNCell(BaseRNNCell):
    """Stack of cells applied in order each step."""

    def __init__(self, params=None):
        super().__init__(prefix="", params=params)
        self._cells: List[BaseRNNCell] = []

    def add(self, cell: BaseRNNCell) -> None:
        self._cells.append(cell)

    @property
    def state_info(self):
        return [i for c in self._cells for i in c.state_info]

    def begin_state(self, func=None, **kwargs):
        return [s for c in self._cells
                for s in c.begin_state(func=func, **kwargs)]

    def __call__(self, inputs, states):
        next_states = []
        p = 0
        for cell in self._cells:
            n = len(cell.state_info)
            inputs, st = cell(inputs, states[p:p + n])
            next_states.extend(st)
            p += n
        return inputs, next_states

    def unpack_weights(self, args):
        for c in self._cells:
            args = c.unpack_weights(args)
        return args

    def pack_weights(self, args):
        for c in self._cells:
            args = c.pack_weights(args)
        return args


class DropoutCell(BaseRNNCell):
    """Dropout on the step output (reference DropoutCell)."""

    def __init__(self, dropout: float, prefix: str = "dropout_",
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._dropout = dropout

    @property
    def state_info(self):
        return []

    def __call__(self, inputs, states):
        if self._dropout > 0:
            inputs = _sym().Dropout(inputs, p=self._dropout)
        return inputs, states


class ResidualCell(BaseRNNCell):
    """Adds the step input to the base cell's output (reference
    ResidualCell modifier)."""

    def __init__(self, base_cell: BaseRNNCell):
        super().__init__(prefix=base_cell._prefix, params=base_cell._params)
        self.base_cell = base_cell
        base_cell._modified = True

    @property
    def state_info(self):
        return self.base_cell.state_info

    def begin_state(self, func=None, **kwargs):
        self.base_cell._modified = False
        st = self.base_cell.begin_state(func=func, **kwargs)
        self.base_cell._modified = True
        return st

    def __call__(self, inputs, states):
        out, states = self.base_cell(inputs, states)
        return out + inputs, states


class BidirectionalCell(BaseRNNCell):
    """Runs one cell forward and one backward over the sequence; per-step
    outputs are concatenated (reference BidirectionalCell — unroll only)."""

    def __init__(self, l_cell: BaseRNNCell, r_cell: BaseRNNCell,
                 params=None, output_prefix: str = "bi_"):
        super().__init__(prefix="", params=params)
        self._l_cell = l_cell
        self._r_cell = r_cell
        self._output_prefix = output_prefix

    @property
    def state_info(self):
        return self._l_cell.state_info + self._r_cell.state_info

    def begin_state(self, func=None, **kwargs):
        return (self._l_cell.begin_state(func=func, **kwargs) +
                self._r_cell.begin_state(func=func, **kwargs))

    def __call__(self, inputs, states):
        raise MXNetError("BidirectionalCell cannot step; use unroll()")

    def unroll(self, length: int, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        sym = _sym()
        seq = self._normalize_inputs(length, inputs, layout)
        if begin_state is None:
            begin_state = self.begin_state(batch_ref=seq[0])
        nl = len(self._l_cell.state_info)
        l_out, l_states = self._unroll_one(self._l_cell, seq,
                                           begin_state[:nl])
        r_out, r_states = self._unroll_one(self._r_cell, list(reversed(seq)),
                                           begin_state[nl:])
        r_out = list(reversed(r_out))
        outputs = [sym.concat(lo, ro, dim=1,
                              name=f"{self._output_prefix}t{t}")
                   for t, (lo, ro) in enumerate(zip(l_out, r_out))]
        if merge_outputs:
            outputs = sym.stack(*outputs, axis=layout.find("T"))
        return outputs, l_states + r_states

    @staticmethod
    def _unroll_one(cell, seq, states):
        outs = []
        for x in seq:
            o, states = cell(x, states)
            outs.append(o)
        return outs, states
