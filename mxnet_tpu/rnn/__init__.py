"""mx.rnn — the legacy (pre-gluon) symbolic RNN cell API.

Reference parity: python/mxnet/rnn/ (SURVEY.md §2.5 frontend) — cells
compose Symbol graphs step by step, the BucketingModule consumes
``unroll`` outputs, and BucketSentenceIter feeds variable-length text.
The gluon cells (gluon/rnn) are the imperative/hybrid face; this package
is the Module-era face over the same registry ops.
"""
from .rnn_cell import (BaseRNNCell, RNNCell, LSTMCell, GRUCell,
                       FusedRNNCell, SequentialRNNCell, BidirectionalCell,
                       DropoutCell, ResidualCell, RNNParams)
from .io import BucketSentenceIter, encode_sentences

__all__ = ["BaseRNNCell", "RNNCell", "LSTMCell", "GRUCell", "FusedRNNCell",
           "SequentialRNNCell", "BidirectionalCell", "DropoutCell",
           "ResidualCell", "RNNParams", "BucketSentenceIter",
           "encode_sentences"]
