"""mx.rnn data helpers (reference: python/mxnet/rnn/io.py) —
BucketSentenceIter + encode_sentences, the BucketingModule's canonical
feeder.  Long-context story (SURVEY §5.7): buckets keep jit cache keys
finite; each bucket's padded batch is one static-shape XLA computation.
"""
from __future__ import annotations

import random as _pyrandom
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..base import MXNetError
from ..io import DataBatch, DataDesc, DataIter

__all__ = ["encode_sentences", "BucketSentenceIter"]


def encode_sentences(sentences: Sequence[Sequence[str]],
                     vocab: Optional[Dict[str, int]] = None,
                     invalid_label: int = -1, invalid_key: str = "\n",
                     start_label: int = 0,
                     unknown_token: Optional[str] = None):
    """Map token sequences to id sequences, growing ``vocab`` as needed
    (reference encode_sentences)."""
    idx = start_label
    if vocab is None:
        vocab = {invalid_key: invalid_label}
        new_vocab = True
    else:
        new_vocab = False
        idx = max([v for v in vocab.values() if v != invalid_label],
                  default=start_label - 1) + 1
    res = []
    for sent in sentences:
        coded = []
        for word in sent:
            if word not in vocab:
                if not new_vocab:
                    if unknown_token is None:
                        raise MXNetError(f"unknown token {word!r}")
                    word = unknown_token
                    if word not in vocab:
                        vocab[word] = idx
                        idx += 1
                else:
                    vocab[word] = idx
                    idx += 1
            coded.append(vocab[word])
        res.append(coded)
    return res, vocab


class BucketSentenceIter(DataIter):
    """Pad id-sequences into per-bucket batches (reference
    BucketSentenceIter).  Yields DataBatch with ``bucket_key`` for
    BucketingModule's per-bucket jit cache."""

    def __init__(self, sentences: Sequence[Sequence[int]], batch_size: int,
                 buckets: Optional[Sequence[int]] = None,
                 invalid_label: int = -1, data_name: str = "data",
                 label_name: str = "softmax_label", dtype: str = "float32",
                 layout: str = "NT"):
        super().__init__(batch_size)
        if buckets is None:
            counts = np.bincount([len(s) for s in sentences])
            buckets = [i for i, n in enumerate(counts)
                       if n >= batch_size]
            if not buckets:
                buckets = [max(len(s) for s in sentences)]
        buckets = sorted(buckets)
        self.data = [[] for _ in buckets]
        ndiscard = 0
        for sent in sentences:
            buck = np.searchsorted(buckets, len(sent))
            if buck == len(buckets):
                ndiscard += 1
                continue
            buff = np.full((buckets[buck],), invalid_label, dtype=dtype)
            buff[:len(sent)] = sent
            self.data[buck].append(buff)
        self.data = [np.asarray(x, dtype=dtype) for x in self.data]
        self.buckets = list(buckets)
        self.batch_size = batch_size
        self.invalid_label = invalid_label
        self.data_name = data_name
        self.label_name = label_name
        self.dtype = dtype
        self.layout = layout
        self.major_axis = layout.find("N")
        self.default_bucket_key = max(buckets)
        self.ndiscard = ndiscard

        shape = (batch_size, self.default_bucket_key) \
            if self.major_axis == 0 else (self.default_bucket_key,
                                          batch_size)
        self.provide_data = [DataDesc(data_name, shape, dtype)]
        self.provide_label = [DataDesc(label_name, shape, dtype)]

        self.idx: List[Tuple[int, int]] = []
        for i, buck in enumerate(self.data):
            self.idx.extend([(i, j) for j in
                             range(0, len(buck) - batch_size + 1,
                                   batch_size)])
        self.curr_idx = 0
        self.reset()

    def reset(self) -> None:
        self.curr_idx = 0
        _pyrandom.shuffle(self.idx)
        for buck in self.data:
            np.random.shuffle(buck)

    def next(self) -> DataBatch:
        if self.curr_idx == len(self.idx):
            raise StopIteration
        i, j = self.idx[self.curr_idx]
        self.curr_idx += 1
        from .. import ndarray as nd
        buf = self.data[i][j:j + self.batch_size]
        if self.major_axis == 1:
            data = nd.array(buf.T)
            label_np = np.full_like(buf, self.invalid_label)
            label_np[:, :-1] = buf[:, 1:]
            label = nd.array(label_np.T)
        else:
            data = nd.array(buf)
            label_np = np.full_like(buf, self.invalid_label)
            label_np[:, :-1] = buf[:, 1:]
            label = nd.array(label_np)
        shape = data.shape
        return DataBatch([data], [label], pad=0,
                         bucket_key=self.buckets[i],
                         provide_data=[DataDesc(self.data_name, shape,
                                                self.dtype)],
                         provide_label=[DataDesc(self.label_name, shape,
                                                 self.dtype)])
