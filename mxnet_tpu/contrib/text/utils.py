"""Token counting helpers (reference: contrib/text/utils.py)."""
from __future__ import annotations

import collections
import re
from typing import Optional

__all__ = ["count_tokens_from_str"]


def count_tokens_from_str(source_str: str, token_delim: str = " ",
                          seq_delim: str = "\n", to_lower: bool = False,
                          counter_to_update: Optional[
                              collections.Counter] = None
                          ) -> collections.Counter:
    """Count all tokens in ``source_str``, splitting on ``token_delim``
    and ``seq_delim`` (reference count_tokens_from_str semantics)."""
    source_str = re.sub(f"({re.escape(token_delim)})|"
                        f"({re.escape(seq_delim)})", " ", source_str)
    if to_lower:
        source_str = source_str.lower()
    counter = counter_to_update if counter_to_update is not None \
        else collections.Counter()
    counter.update(source_str.split())
    return counter
