"""mx.contrib.text — text token indexing and embeddings (reference:
python/mxnet/contrib/text/{utils,vocab,embedding}.py, SURVEY.md §2.5 misc
frontend)."""
from . import utils  # noqa: F401
from . import vocab  # noqa: F401
from . import embedding  # noqa: F401
from .vocab import Vocabulary  # noqa: F401
