"""Vocabulary (reference: contrib/text/vocab.py) — frequency-ordered
token↔index maps with unknown/reserved token handling."""
from __future__ import annotations

import collections
from typing import List, Optional, Sequence, Union

from ...base import MXNetError

__all__ = ["Vocabulary"]


class Vocabulary:
    """Indexes tokens by decreasing frequency (ties broken
    lexicographically, the reference's ordering), after the unknown token
    (index 0) and any reserved tokens."""

    def __init__(self, counter: Optional[collections.Counter] = None,
                 most_freq_count: Optional[int] = None, min_freq: int = 1,
                 unknown_token: str = "<unk>",
                 reserved_tokens: Optional[Sequence[str]] = None):
        if min_freq < 1:
            raise MXNetError("min_freq must be >= 1")
        if reserved_tokens is not None:
            if len(set(reserved_tokens)) != len(reserved_tokens) or \
                    unknown_token in reserved_tokens:
                raise MXNetError("reserved_tokens must be unique and must "
                                 "not contain unknown_token")
        self._unknown_token = unknown_token
        self._reserved_tokens = list(reserved_tokens) if reserved_tokens \
            else None
        self._idx_to_token = [unknown_token] + (list(reserved_tokens)
                                                if reserved_tokens else [])
        self._token_to_idx = {t: i for i, t in
                              enumerate(self._idx_to_token)}
        if counter is not None:
            self._index_counter(counter, most_freq_count, min_freq)

    def _index_counter(self, counter, most_freq_count, min_freq):
        pairs = sorted(counter.items(), key=lambda kv: (-kv[1], kv[0]))
        taken = 0
        for token, freq in pairs:
            if freq < min_freq:
                break
            if most_freq_count is not None and taken >= most_freq_count:
                break
            if token not in self._token_to_idx:
                self._token_to_idx[token] = len(self._idx_to_token)
                self._idx_to_token.append(token)
                taken += 1

    # -- protocol ----------------------------------------------------------
    def __len__(self) -> int:
        return len(self._idx_to_token)

    @property
    def token_to_idx(self):
        return self._token_to_idx

    @property
    def idx_to_token(self) -> List[str]:
        return self._idx_to_token

    @property
    def unknown_token(self) -> str:
        return self._unknown_token

    @property
    def reserved_tokens(self):
        return self._reserved_tokens

    def to_indices(self, tokens: Union[str, Sequence[str]]):
        single = isinstance(tokens, str)
        toks = [tokens] if single else tokens
        idx = [self._token_to_idx.get(t, 0) for t in toks]
        return idx[0] if single else idx

    def to_tokens(self, indices: Union[int, Sequence[int]]):
        single = isinstance(indices, int)
        idxs = [indices] if single else indices
        toks = []
        for i in idxs:
            if not 0 <= i < len(self._idx_to_token):
                raise MXNetError(f"token index {i} out of range")
            toks.append(self._idx_to_token[i])
        return toks[0] if single else toks
