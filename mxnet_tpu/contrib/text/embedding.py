"""Token embeddings (reference: contrib/text/embedding.py).

File-based: ``CustomEmbedding`` parses 'token v1 v2 ...' text files, the
registered ``glove``/``fasttext`` classes read the same format from a
local ``pretrained_file_path`` — this environment has zero egress, so the
reference's URL-download path is replaced by an explicit local-file
contract (raised as an error with guidance when the file is absent).
"""
from __future__ import annotations

import io
import os
from typing import Callable, Dict, List, Optional, Sequence, Union

import numpy as _np

from ...base import MXNetError
from .vocab import Vocabulary

__all__ = ["register", "create", "list_embedding_names", "TokenEmbedding",
           "CustomEmbedding", "CompositeEmbedding", "GloVe", "FastText"]

_registry: Dict[str, type] = {}


def register(klass):
    _registry[klass.__name__.lower()] = klass
    return klass


def create(embedding_name: str, **kwargs) -> "TokenEmbedding":
    name = embedding_name.lower()
    if name not in _registry:
        raise MXNetError(f"unknown embedding {embedding_name!r}; "
                         f"registered: {sorted(_registry)}")
    return _registry[name](**kwargs)


def list_embedding_names() -> List[str]:
    return sorted(_registry)


class TokenEmbedding:
    """Token → vector map with unknown-token fallback (reference
    _TokenEmbedding)."""

    def __init__(self, unknown_token: str = "<unk>",
                 init_unknown_vec: Callable = _np.zeros):
        self._unknown_token = unknown_token
        self._init_unknown_vec = init_unknown_vec
        self._idx_to_token: List[str] = [unknown_token]
        self._token_to_idx: Dict[str, int] = {unknown_token: 0}
        self._idx_to_vec: Optional[_np.ndarray] = None

    # -- loading -----------------------------------------------------------
    def _load_embedding_txt(self, path: str, elem_delim: str = " ",
                            encoding: str = "utf8") -> None:
        vecs: List[_np.ndarray] = []
        dim = None
        with io.open(path, "r", encoding=encoding) as f:
            for line_num, line in enumerate(f):
                parts = line.rstrip().split(elem_delim)
                if line_num == 0 and len(parts) == 2 and \
                        parts[0].isdigit() and parts[1].isdigit():
                    continue          # fastText header line: "count dim"
                token, elems = parts[0], parts[1:]
                if not elems:
                    continue
                if dim is None:
                    dim = len(elems)
                elif len(elems) != dim:
                    raise MXNetError(
                        f"{path}:{line_num}: inconsistent vector length")
                if token in self._token_to_idx:
                    continue
                self._token_to_idx[token] = len(self._idx_to_token)
                self._idx_to_token.append(token)
                vecs.append(_np.asarray(elems, dtype=_np.float32))
        if dim is None:
            raise MXNetError(f"no vectors found in {path}")
        unk = self._init_unknown_vec((dim,)).astype(_np.float32)
        self._idx_to_vec = _np.vstack([unk] + vecs)

    # -- access ------------------------------------------------------------
    def __len__(self) -> int:
        return len(self._idx_to_token)

    @property
    def vec_len(self) -> int:
        return 0 if self._idx_to_vec is None else self._idx_to_vec.shape[1]

    @property
    def token_to_idx(self):
        return self._token_to_idx

    @property
    def idx_to_token(self):
        return self._idx_to_token

    @property
    def idx_to_vec(self):
        from ... import ndarray as nd
        return nd.array(self._idx_to_vec)

    def get_vecs_by_tokens(self, tokens: Union[str, Sequence[str]],
                           lower_case_backup: bool = False):
        from ... import ndarray as nd
        single = isinstance(tokens, str)
        toks = [tokens] if single else list(tokens)
        rows = []
        for t in toks:
            i = self._token_to_idx.get(t)
            if i is None and lower_case_backup:
                i = self._token_to_idx.get(t.lower())
            rows.append(self._idx_to_vec[i if i is not None else 0])
        out = _np.stack(rows)
        return nd.array(out[0]) if single else nd.array(out)

    def update_token_vectors(self, tokens: Union[str, Sequence[str]],
                             new_vectors) -> None:
        toks = [tokens] if isinstance(tokens, str) else list(tokens)
        vecs = new_vectors.asnumpy() if hasattr(new_vectors, "asnumpy") \
            else _np.asarray(new_vectors)
        vecs = vecs.reshape(len(toks), -1)
        # resolve every index BEFORE writing: an unknown token must not
        # leave the table half-mutated
        idxs = []
        for t in toks:
            if t not in self._token_to_idx:
                raise MXNetError(f"token {t!r} is not in the embedding")
            idxs.append(self._token_to_idx[t])
        for i, v in zip(idxs, vecs):
            self._idx_to_vec[i] = v


class CustomEmbedding(TokenEmbedding):
    """'token v1 v2 …' text-file embedding (reference CustomEmbedding)."""

    def __init__(self, pretrained_file_path: str, elem_delim: str = " ",
                 encoding: str = "utf8", **kwargs):
        super().__init__(**kwargs)
        self._load_embedding_txt(pretrained_file_path, elem_delim, encoding)


class _PretrainedFileEmbedding(TokenEmbedding):
    def __init__(self, pretrained_file_name: str = "",
                 embedding_root: str = "", pretrained_file_path: str = "",
                 **kwargs):
        super().__init__(**kwargs)
        path = pretrained_file_path or (
            os.path.join(embedding_root, pretrained_file_name)
            if pretrained_file_name else "")
        if not path or not os.path.exists(path):
            raise MXNetError(
                f"{type(self).__name__}: pretrained file not found at "
                f"{path!r}. This environment cannot download embeddings; "
                "pass pretrained_file_path= pointing at a local "
                "'token v1 v2 ...' text file.")
        self._load_embedding_txt(path)


@register
class GloVe(_PretrainedFileEmbedding):
    """GloVe vectors from a local file (reference GloVe; download replaced
    by the local-file contract)."""


@register
class FastText(_PretrainedFileEmbedding):
    """fastText vectors from a local .vec file (header line skipped)."""


class CompositeEmbedding(TokenEmbedding):
    """Concatenate several embeddings, indexed by one Vocabulary
    (reference CompositeEmbedding)."""

    def __init__(self, vocabulary: Vocabulary,
                 token_embeddings: Union[TokenEmbedding,
                                         Sequence[TokenEmbedding]]):
        super().__init__(unknown_token=vocabulary.unknown_token)
        if isinstance(token_embeddings, TokenEmbedding):
            token_embeddings = [token_embeddings]
        self._vocab = vocabulary
        self._idx_to_token = list(vocabulary.idx_to_token)
        self._token_to_idx = dict(vocabulary.token_to_idx)
        blocks = []
        for emb in token_embeddings:
            vecs = emb.get_vecs_by_tokens(self._idx_to_token).asnumpy()
            blocks.append(vecs)
        self._idx_to_vec = _np.concatenate(blocks, axis=1)

    @property
    def vocabulary(self) -> Vocabulary:
        return self._vocab
