"""``mx.contrib``: experimental / extension namespaces (reference:
python/mxnet/contrib/) — amp (mixed precision), quantization (int8
post-training), onnx (import/export).
"""
from . import amp  # noqa: F401
from . import quantization  # noqa: F401
from . import onnx  # noqa: F401
from . import text  # noqa: F401
