"""``mx.contrib``: experimental / extension namespaces (reference:
python/mxnet/contrib/).  Holds amp (mixed precision) and the detection op
frontends used by the GluonCV-style models.
"""
from . import amp  # noqa: F401
