"""INT8 post-training quantization frontend.

Reference parity: python/mxnet/contrib/quantization.py `quantize_model` +
the calibration machinery (src/operator/quantization/calibrate.cc minmax
mode; SURVEY.md §2.2 quantization row).  The reference rewrites a Symbol
graph; the Gluon-era analog here rewrites a Block tree in place:

    net = ...               # trained fp32 HybridBlock
    qnet = quantize_net(net, calib_data=[batch1, batch2])
    y = qnet(x)             # Dense/Conv2D now run int8 on the MXU

Per-tensor symmetric int8 everywhere (the reference's int8 flow).
Calibration is minmax over the provided batches; layers without
calibration quantize activations dynamically per batch.
"""
from __future__ import annotations

from typing import Dict, Iterable, Optional, Sequence

from ..base import MXNetError
from ..gluon import nn
from ..gluon.block import HybridBlock

__all__ = ["QuantizedDense", "QuantizedConv2D", "quantize_net"]


def _quantize_weight(w):
    """fp32 NDArray -> (int8 NDArray, min, max NDArrays), symmetric."""
    import numpy as np
    from .. import ndarray as F
    a = w.asnumpy()
    mx = float(np.max(np.abs(a))) or 1e-8
    q = np.clip(np.round(a / (mx / 127.0)), -127, 127).astype(np.int8)
    ctx = w.context
    return (F.array(q, ctx=ctx, dtype="int8"),
            F.array(np.float32(-mx), ctx=ctx),
            F.array(np.float32(mx), ctx=ctx))


class _QuantizedBase(HybridBlock):
    """Shared int8 wrapper state: quantized weight + ranges + float bias."""

    def __init__(self, weight, bias, act_type, calib_range, **kwargs):
        super().__init__(**kwargs)
        self._qw, self._wmin, self._wmax = _quantize_weight(weight)
        self._fbias = bias.data() if bias is not None else None
        self._act_type = act_type        # activation name string or None
        self._calib = calib_range        # (min, max) floats or None

    def _quantize_input(self, F, x):
        if self._calib is not None:
            return F.quantize_v2(x, min_calib_range=float(self._calib[0]),
                                 max_calib_range=float(self._calib[1]))
        return F.quantize_v2(x)


class QuantizedDense(_QuantizedBase):
    """int8 Dense: quantize input -> int8 matmul on the MXU (int32
    accumulate) -> dequantize -> float bias/activation."""

    def __init__(self, dense: nn.Dense, calib_range=None, **kwargs):
        super().__init__(dense.weight.data(),
                         getattr(dense, "bias", None),
                         dense._act_type, calib_range, **kwargs)
        self._units = dense._units
        self._flatten = dense._flatten

    def hybrid_forward(self, F, x):
        q, mn, mx = self._quantize_input(F, x)
        out32, omn, omx = F.quantized_fully_connected(
            q, self._qw, mn, mx, self._wmin, self._wmax,
            num_hidden=self._units, no_bias=True, flatten=self._flatten)
        y = F.dequantize(out32, omn, omx)
        if self._fbias is not None:
            y = y + self._fbias
        if self._act_type:
            y = F.Activation(y, act_type=self._act_type)
        return y


class QuantizedConv2D(_QuantizedBase):
    """int8 Conv2D via the MXU integer conv path."""

    def __init__(self, conv: nn.Conv2D, calib_range=None, **kwargs):
        super().__init__(conv.weight.data(),
                         getattr(conv, "bias", None),
                         conv._act_type, calib_range, **kwargs)
        self._kernel = conv._kwargs["kernel"]
        self._stride = conv._kwargs["stride"]
        self._pad = conv._kwargs["pad"]
        self._dilate = conv._kwargs.get("dilate", (1, 1))
        self._groups = conv._kwargs.get("num_group", 1)
        self._channels = conv._channels

    def hybrid_forward(self, F, x):
        q, mn, mx = self._quantize_input(F, x)
        out32, omn, omx = F.quantized_conv(
            q, self._qw, mn, mx, self._wmin, self._wmax,
            kernel=self._kernel, stride=self._stride, pad=self._pad,
            dilate=self._dilate, num_filter=self._channels,
            num_group=self._groups, no_bias=True)
        y = F.dequantize(out32, omn, omx)
        if self._fbias is not None:
            y = y + self._fbias.reshape((1, -1, 1, 1))
        if self._act_type:
            y = F.Activation(y, act_type=self._act_type)
        return y


def _collect_ranges(net: HybridBlock, calib_data: Iterable,
                    targets) -> Dict[int, tuple]:
    """minmax calibration: run the fp32 net over the batches, recording
    each target layer's input range (reference calib_mode='naive')."""
    ranges: Dict[int, list] = {}
    hooks = []

    def make_hook(block):
        def hook(blk, args, out):
            import numpy as np
            x = args[0].asnumpy()
            lo, hi = float(np.min(x)), float(np.max(x))
            cur = ranges.get(id(blk))
            if cur is None:
                ranges[id(blk)] = [lo, hi]
            else:
                cur[0] = min(cur[0], lo)
                cur[1] = max(cur[1], hi)
        return hook

    def attach(block):
        for child in block._children.values():
            if isinstance(child, targets):
                child.register_forward_hook(make_hook(child))
                hooks.append(child)
            else:
                attach(child)
    attach(net)
    for batch in calib_data:
        net(batch)
    for blk in hooks:
        blk._forward_hooks.clear()
    return {k: tuple(v) for k, v in ranges.items()}


def quantize_net(net: HybridBlock, calib_data: Optional[Iterable] = None,
                 exclude_layers: Sequence[str] = (),
                 quantize_conv: bool = True) -> HybridBlock:
    """Rewrite ``net`` in place: Dense (and optionally Conv2D) layers
    become int8 blocks.  Returns ``net``.

    With ``calib_data`` (an iterable of input batches), activation ranges
    are calibrated minmax-style and frozen; without it, activations are
    quantized dynamically per batch (slower, range-exact).
    """
    targets = (nn.Dense, nn.Conv2D) if quantize_conv else (nn.Dense,)
    ranges: Dict[int, tuple] = {}
    if calib_data is not None:
        ranges = _collect_ranges(net, calib_data, targets)

    def swap(block):
        for name, child in list(block._children.items()):
            if name in exclude_layers:
                continue
            if isinstance(child, nn.Dense):
                q = QuantizedDense(child, ranges.get(id(child)))
            elif quantize_conv and isinstance(child, nn.Conv2D):
                q = QuantizedConv2D(child, ranges.get(id(child)))
            else:
                swap(child)
                continue
            block._children[name] = q
            if getattr(block, name, None) is child:
                object.__setattr__(block, name, q)
        return block
    return swap(net)
