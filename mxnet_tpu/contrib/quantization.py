"""INT8 post-training quantization frontend.

Reference parity: python/mxnet/contrib/quantization.py `quantize_model` +
the calibration machinery (src/operator/quantization/calibrate.cc minmax
mode; SURVEY.md §2.2 quantization row).  The reference rewrites a Symbol
graph; the Gluon-era analog here rewrites a Block tree in place:

    net = ...               # trained fp32 HybridBlock
    qnet = quantize_net(net, calib_data=[batch1, batch2])
    y = qnet(x)             # Dense/Conv2D now run int8 on the MXU

Per-tensor symmetric int8 everywhere (the reference's int8 flow).
Calibration over the provided batches is minmax (reference 'naive') or
KL-optimal entropy thresholding (reference 'entropy',
calibrate.cc-style); layers without calibration quantize activations
dynamically per batch.
"""
from __future__ import annotations

from typing import Dict, Iterable, Optional, Sequence

from ..base import MXNetError
from ..gluon import nn
from ..gluon.block import HybridBlock

__all__ = ["QuantizedDense", "QuantizedConv2D", "quantize_net"]


def _quantize_weight(w):
    """fp32 NDArray -> (int8 NDArray, min, max NDArrays), symmetric."""
    import numpy as np
    from .. import ndarray as F
    a = w.asnumpy()
    mx = float(np.max(np.abs(a))) or 1e-8
    q = np.clip(np.round(a / (mx / 127.0)), -127, 127).astype(np.int8)
    ctx = w.context
    return (F.array(q, ctx=ctx, dtype="int8"),
            F.array(np.float32(-mx), ctx=ctx),
            F.array(np.float32(mx), ctx=ctx))


class _QuantizedBase(HybridBlock):
    """Shared int8 wrapper state: quantized weight + ranges + float bias."""

    def __init__(self, weight, bias, act_type, calib_range, **kwargs):
        super().__init__(**kwargs)
        self._qw, self._wmin, self._wmax = _quantize_weight(weight)
        self._fbias = bias.data() if bias is not None else None
        self._act_type = act_type        # activation name string or None
        self._calib = calib_range        # (min, max) floats or None

    def _quantize_input(self, F, x):
        if self._calib is not None:
            return F.quantize_v2(x, min_calib_range=float(self._calib[0]),
                                 max_calib_range=float(self._calib[1]))
        return F.quantize_v2(x)


class QuantizedDense(_QuantizedBase):
    """int8 Dense: quantize input -> int8 matmul on the MXU (int32
    accumulate) -> dequantize -> float bias/activation."""

    def __init__(self, dense: nn.Dense, calib_range=None, **kwargs):
        super().__init__(dense.weight.data(),
                         getattr(dense, "bias", None),
                         dense._act_type, calib_range, **kwargs)
        self._units = dense._units
        self._flatten = dense._flatten

    def hybrid_forward(self, F, x):
        q, mn, mx = self._quantize_input(F, x)
        out32, omn, omx = F.quantized_fully_connected(
            q, self._qw, mn, mx, self._wmin, self._wmax,
            num_hidden=self._units, no_bias=True, flatten=self._flatten)
        y = F.dequantize(out32, omn, omx)
        if self._fbias is not None:
            y = y + self._fbias
        if self._act_type:
            y = F.Activation(y, act_type=self._act_type)
        return y


class QuantizedConv2D(_QuantizedBase):
    """int8 Conv2D via the MXU integer conv path."""

    def __init__(self, conv: nn.Conv2D, calib_range=None, **kwargs):
        super().__init__(conv.weight.data(),
                         getattr(conv, "bias", None),
                         conv._act_type, calib_range, **kwargs)
        self._kernel = conv._kwargs["kernel"]
        self._stride = conv._kwargs["stride"]
        self._pad = conv._kwargs["pad"]
        self._dilate = conv._kwargs.get("dilate", (1, 1))
        self._groups = conv._kwargs.get("num_group", 1)
        self._channels = conv._channels

    def hybrid_forward(self, F, x):
        q, mn, mx = self._quantize_input(F, x)
        out32, omn, omx = F.quantized_conv(
            q, self._qw, mn, mx, self._wmin, self._wmax,
            kernel=self._kernel, stride=self._stride, pad=self._pad,
            dilate=self._dilate, num_filter=self._channels,
            num_group=self._groups, no_bias=True)
        y = F.dequantize(out32, omn, omx)
        if self._fbias is not None:
            y = y + self._fbias.reshape((1, -1, 1, 1))
        if self._act_type:
            y = F.Activation(y, act_type=self._act_type)
        return y


def _entropy_threshold(hist, bin_width, num_quantized_bins=255):
    """Pick the |x| clip threshold minimizing KL(P||Q) between the
    observed activation distribution and its int8-quantized rendition
    (reference: src/operator/quantization/calibrate.cc
    GetOptimalThreshold — the TensorRT-style algorithm)."""
    import numpy as np
    nbins = len(hist)
    if nbins <= num_quantized_bins:
        return nbins * bin_width
    best_kl, best_i = np.inf, nbins
    total = hist.sum()
    if total == 0:
        return nbins * bin_width
    for i in range(num_quantized_bins, nbins + 1,
                   max(1, (nbins - num_quantized_bins) // 64)):
        # reference dist: first i bins, outliers folded into the edge
        p = hist[:i].astype(np.float64).copy()
        p[i - 1] += hist[i:].sum()
        # quantized dist: the FOLDED p grouped into num_quantized_bins
        # levels and expanded back (building q from the raw hist would
        # zero the folded edge bin and wrongly veto every clipping
        # candidate via the q==0 guard)
        factor = i / num_quantized_bins
        q = np.zeros(i, np.float64)
        for j in range(num_quantized_bins):
            lo = int(np.floor(j * factor))
            hi = int(np.ceil((j + 1) * factor))
            chunk = p[lo:hi]
            live = chunk > 0
            if live.any():
                q[lo:hi][live] = chunk[live].sum() / live.sum()
        pm = p > 0
        ps = p[pm] / p.sum()
        qs = q[pm]
        if (qs == 0).any():
            continue
        qs = qs / q.sum()
        kl = float(np.sum(ps * np.log(ps / qs)))
        if kl < best_kl:
            best_kl, best_i = kl, i
    return best_i * bin_width


def _collect_ranges(net: HybridBlock, calib_data: Iterable,
                    targets, calib_mode: str = "minmax",
                    num_bins: int = 8001) -> Dict[int, tuple]:
    """Run the fp32 net over the batches recording each target layer's
    input range.  calib_mode='minmax' (reference 'naive') takes the raw
    extrema; 'entropy' collects |x| histograms and picks the
    KL-optimal clip threshold (reference calib_mode='entropy')."""
    import numpy as np
    if calib_mode == "entropy":
        calib_data = list(calib_data)   # two passes need replay
    stats: Dict[int, list] = {}       # id -> [lo, hi] or histogram state
    hooks = []

    def make_minmax_hook(block):
        def hook(blk, args, out):
            x = args[0].asnumpy()
            lo, hi = float(np.min(x)), float(np.max(x))
            cur = stats.get(id(blk))
            if cur is None:
                stats[id(blk)] = [lo, hi]
            else:
                cur[0] = min(cur[0], lo)
                cur[1] = max(cur[1], hi)
        return hook

    def make_hist_hook(block, max_abs):
        def hook(blk, args, out):
            x = np.abs(args[0].asnumpy()).ravel()
            h, _ = np.histogram(x, bins=num_bins,
                                range=(0.0, max_abs[id(blk)]))
            cur = stats.get(id(blk))
            if cur is None:
                stats[id(blk)] = h.astype(np.int64)
            else:
                stats[id(blk)] = cur + h
        return hook

    def attach(block, mk):
        for child in block._children.values():
            if isinstance(child, targets):
                child.register_forward_hook(mk(child))
                hooks.append(child)
            else:
                attach(child, mk)

    if calib_mode == "entropy":
        # pass 1: per-layer max |x| fixes the histogram range
        attach(net, make_minmax_hook)
        for batch in calib_data:
            net(batch)
        for blk in hooks:
            blk._forward_hooks.clear()
        max_abs = {k: max(abs(v[0]), abs(v[1])) or 1e-8
                   for k, v in stats.items()}
        stats.clear()
        hooks.clear()
        # pass 2: histograms → KL-optimal thresholds
        attach(net, lambda b: make_hist_hook(b, max_abs))
        for batch in calib_data:
            net(batch)
        for blk in hooks:
            blk._forward_hooks.clear()
        out = {}
        for k, hist in stats.items():
            thr = _entropy_threshold(hist, max_abs[k] / num_bins)
            out[k] = (-thr, thr)
        return out

    attach(net, make_minmax_hook)
    for batch in calib_data:
        net(batch)
    for blk in hooks:
        blk._forward_hooks.clear()
    return {k: tuple(v) for k, v in stats.items()}


def quantize_net(net: HybridBlock, calib_data: Optional[Iterable] = None,
                 exclude_layers: Sequence[str] = (),
                 quantize_conv: bool = True,
                 calib_mode: str = "minmax") -> HybridBlock:
    """Rewrite ``net`` in place: Dense (and optionally Conv2D) layers
    become int8 blocks.  Returns ``net``.

    With ``calib_data`` (an iterable of input batches), activation
    ranges are calibrated and frozen — ``calib_mode='minmax'`` takes raw
    extrema (reference 'naive'); ``'entropy'`` picks KL-optimal clip
    thresholds, robust to outliers (reference 'entropy').  Without
    calib_data, activations are quantized dynamically per batch.
    """
    if calib_mode not in ("minmax", "naive", "entropy"):
        raise MXNetError(f"unknown calib_mode {calib_mode!r}")
    targets = (nn.Dense, nn.Conv2D) if quantize_conv else (nn.Dense,)
    ranges: Dict[int, tuple] = {}
    if calib_data is not None:
        ranges = _collect_ranges(
            net, calib_data, targets,
            "entropy" if calib_mode == "entropy" else "minmax")

    def swap(block):
        for name, child in list(block._children.items()):
            if name in exclude_layers:
                continue
            if isinstance(child, nn.Dense):
                q = QuantizedDense(child, ranges.get(id(child)))
            elif quantize_conv and isinstance(child, nn.Conv2D):
                q = QuantizedConv2D(child, ranges.get(id(child)))
            else:
                swap(child)
                continue
            block._children[name] = q
            if getattr(block, name, None) is child:
                object.__setattr__(block, name, q)
        return block
    return swap(net)
