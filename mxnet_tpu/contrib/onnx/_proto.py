"""Minimal protobuf wire-format codec for the ONNX subset we emit/read.

This image carries no `onnx` package, so the ModelProto/GraphProto/
NodeProto/TensorProto subset is serialized by hand against the public
ONNX schema (onnx/onnx.proto — field numbers below are that schema's).
Files written here load in stock onnx/onnxruntime; files produced by
other exporters load here as long as they stick to this op/field subset.

Wire format: each field is a varint key ``(field_number << 3) | wire_type``
followed by a varint (type 0), fixed32 (type 5), or length-delimited
payload (type 2).
"""
from __future__ import annotations

import struct
from typing import Dict, List, Tuple

# -- encoding ---------------------------------------------------------------


def varint(n: int) -> bytes:
    if n < 0:
        n += 1 << 64            # protobuf int64 negatives: 10-byte varint
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def tag(field: int, wire: int) -> bytes:
    return varint((field << 3) | wire)


def f_varint(field: int, v: int) -> bytes:
    return tag(field, 0) + varint(int(v))


def f_float(field: int, v: float) -> bytes:
    return tag(field, 5) + struct.pack("<f", float(v))


def f_bytes(field: int, payload: bytes) -> bytes:
    return tag(field, 2) + varint(len(payload)) + payload


def f_str(field: int, s: str) -> bytes:
    return f_bytes(field, s.encode("utf-8"))


# -- decoding ---------------------------------------------------------------


def parse(buf: bytes) -> Dict[int, List[Tuple[int, object]]]:
    """Parse one message into {field_number: [(wire_type, value), ...]}.
    Length-delimited values are returned as raw bytes (callers recurse)."""
    fields: Dict[int, List[Tuple[int, object]]] = {}
    i, n = 0, len(buf)
    while i < n:
        key, i = _read_varint(buf, i)
        field, wire = key >> 3, key & 7
        if wire == 0:
            v, i = _read_varint(buf, i)
        elif wire == 5:
            v = struct.unpack_from("<f", buf, i)[0]
            i += 4
        elif wire == 1:
            v = struct.unpack_from("<d", buf, i)[0]
            i += 8
        elif wire == 2:
            ln, i = _read_varint(buf, i)
            v = buf[i:i + ln]
            i += ln
        else:
            raise ValueError(f"unsupported wire type {wire}")
        fields.setdefault(field, []).append((wire, v))
    return fields


def _read_varint(buf: bytes, i: int) -> Tuple[int, int]:
    shift, result = 0, 0
    while True:
        b = buf[i]
        i += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            if result >= 1 << 63:
                result -= 1 << 64      # signed int64
            return result, i
        shift += 7


def get1(fields, num, default=None):
    vals = fields.get(num)
    return vals[0][1] if vals else default


def get_all(fields, num):
    return [v for _, v in fields.get(num, [])]


def get_str(fields, num, default=""):
    v = get1(fields, num)
    return v.decode("utf-8") if isinstance(v, (bytes, bytearray)) else \
        (v if v is not None else default)
