"""ONNX interop (reference: python/mxnet/contrib/onnx — mx2onnx
export_model + onnx2mx import_model; SURVEY.md §2.5 misc row).

Covers the conv-net op set the model zoo emits (Convolution, BatchNorm,
Activation, Pooling incl. global, FullyConnected, Flatten, Concat,
Dropout, softmax, elemwise/broadcast add-mul, Reshape).  Serialization is
the in-tree wire codec (_proto.py) — no onnx package needed; emitted
files follow the public ONNX schema (opset 12).
"""
from .export import export_model
from .import_ import import_model

__all__ = ["export_model", "import_model"]


class onnx:          # namespace parity: mx.contrib.onnx.onnx2mx style
    export_model = staticmethod(export_model)
    import_model = staticmethod(import_model)
