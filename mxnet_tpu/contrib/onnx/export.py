"""Symbol graph → ONNX ModelProto (reference:
python/mxnet/contrib/onnx/mx2onnx/export_model.py + _op_translations.py).

Walks the Symbol topo order, translating each node to ONNX ops via the
table below; parameters become initializers (raw little-endian), the
remaining free variable becomes the graph input.
"""
from __future__ import annotations

import ast
from typing import Dict, List

import numpy as _np

from ...base import MXNetError
from . import _proto as P

_OPSET = 12

# TensorProto.DataType
_F32, _I64 = 1, 7

_ACT = {"relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh",
        "softrelu": "Softplus", "softsign": "Softsign"}

# standalone elementwise ops with 1:1 ONNX duals (opset 12)
_UNARY_EXPORT = {
    "exp": "Exp", "log": "Log", "sqrt": "Sqrt", "tanh": "Tanh",
    "sigmoid": "Sigmoid", "abs": "Abs", "negative": "Neg",
    "floor": "Floor", "ceil": "Ceil", "round": "Round", "erf": "Erf",
    "relu": "Relu", "softsign": "Softsign", "sign": "Sign",
    "reciprocal": "Reciprocal",
}
_BINARY_EXPORT = {
    "broadcast_add": "Add", "broadcast_sub": "Sub",
    "broadcast_mul": "Mul", "broadcast_div": "Div",
    "broadcast_maximum": "Max", "broadcast_minimum": "Min",
    "broadcast_power": "Pow",
    # same-shape alias spellings (graphs built via the elemwise names)
    "elemwise_add": "Add", "_plus": "Add", "_add": "Add",
    "elemwise_sub": "Sub", "_sub": "Sub",
    "elemwise_mul": "Mul", "_mul": "Mul",
}
# LeakyReLU act_type -> (ONNX op, alpha-attr default); gelu needs opset
# >= 20 and is rejected explicitly rather than silently mistranslated
# alpha defaults MUST match the executor's slope defaults (ops_nn.py
# leaky_maker): exporting ONNX's usual 1.0 for an attr-less elu node
# would silently change numerics
_LEAKY_EXPORT = {"leaky": ("LeakyRelu", 0.25), "elu": ("Elu", 0.25),
                 "selu": ("Selu", None)}
# scalar-operand arithmetic: the scalar attr becomes a 0-d initializer
# feeding the binary ONNX node; (op, reversed) — reversed puts the
# scalar on the LEFT (rminus/rdiv)
_SCALAR_EXPORT = {
    "_plus_scalar": ("Add", False), "_minus_scalar": ("Sub", False),
    "_rminus_scalar": ("Sub", True), "_mul_scalar": ("Mul", False),
    "_div_scalar": ("Div", False), "_rdiv_scalar": ("Div", True),
    "_power_scalar": ("Pow", False), "_rpower_scalar": ("Pow", True),
    "_maximum_scalar": ("Max", False), "_minimum_scalar": ("Min", False),
}


def _attr(node_attrs, key, default=None):
    v = node_attrs.get(key, default)
    if isinstance(v, str):
        try:
            v = ast.literal_eval(v)
        except (ValueError, SyntaxError):
            pass
    return v


def _a_int(name, v):
    return P.f_bytes(5, P.f_str(1, name) + P.f_varint(20, 2) +
                     P.f_varint(3, int(v)))


def _a_float(name, v):
    return P.f_bytes(5, P.f_str(1, name) + P.f_varint(20, 1) +
                     P.f_float(2, float(v)))


def _a_ints(name, vals):
    body = P.f_str(1, name) + P.f_varint(20, 7)
    for v in vals:
        body += P.f_varint(8, int(v))
    return P.f_bytes(5, body)


def _node(op_type, inputs, outputs, name, attrs=b""):
    body = b""
    for i in inputs:
        body += P.f_str(1, i)
    for o in outputs:
        body += P.f_str(2, o)
    body += P.f_str(3, name) + P.f_str(4, op_type) + attrs
    return P.f_bytes(1, body)       # GraphProto.node = 1


def _tensor(name, arr):
    arr = _np.ascontiguousarray(arr)
    if arr.dtype == _np.int64:
        dt = _I64
    else:
        arr = arr.astype(_np.float32)
        dt = _F32
    body = b""
    for d in arr.shape:
        body += P.f_varint(1, d)
    body += P.f_varint(2, dt) + P.f_str(8, name) + \
        P.f_bytes(9, arr.tobytes())
    return body


def _value_info(name, shape, field=11):
    dims = b""
    for d in shape:
        dims += P.f_bytes(1, P.f_varint(1, int(d)))    # Dimension.dim_value
    tshape = P.f_bytes(2, dims)                        # Tensor.shape
    ttype = P.f_bytes(1, P.f_varint(1, _F32) + tshape)  # TypeProto.tensor
    return P.f_bytes(field, P.f_str(1, name) + P.f_bytes(2, ttype))


def export_model(sym, params, input_shape=None, input_type=_np.float32,
                 onnx_file_path="model.onnx"):
    """Export (symbol, params) to an ONNX file; returns the path.
    ``sym``/``params`` may be in-memory objects or file paths, as in the
    reference API."""
    from ...symbol import load as sym_load
    from ...model import load_params
    if isinstance(sym, str):
        sym = sym_load(sym)
    if isinstance(params, str):
        arg, aux = load_params(params)
        params = {**arg, **aux}
    params = {k.split(":", 1)[-1]: v for k, v in params.items()}
    np_params = {k: v.asnumpy() if hasattr(v, "asnumpy") else _np.asarray(v)
                 for k, v in params.items()}

    nodes_pb: List[bytes] = []
    inits_pb: List[bytes] = []
    inputs_pb: List[bytes] = []
    outputs_pb: List[bytes] = []
    name_of: Dict[int, List[str]] = {}     # id(node) -> output names
    extra = [0]                            # uniquifier for helper nodes

    def out_names(node):
        if node.num_outputs == 1:
            return [node.name]
        return [f"{node.name}_output{i}" for i in range(node.num_outputs)]

    def add_init(name, arr):
        inits_pb.append(P.f_bytes(5, _tensor(name, arr)))

    order = sym._topo()
    data_inputs = []
    for node in order:
        if node.is_var:
            name_of[id(node)] = [node.name]
            if node.name in np_params:
                add_init(node.name, np_params[node.name])
            else:
                data_inputs.append(node.name)
            continue
        ins = [name_of[id(p)][idx] for p, idx in node.inputs]
        outs = out_names(node)
        name_of[id(node)] = outs
        a = node.attrs
        op = node.op

        if op == "Convolution":
            kernel = _attr(a, "kernel")
            pads = list(_attr(a, "pad", (0,) * len(kernel)))
            attrs = _a_ints("kernel_shape", kernel) + \
                _a_ints("strides", _attr(a, "stride", (1,) * len(kernel))) +\
                _a_ints("dilations", _attr(a, "dilate",
                                           (1,) * len(kernel))) + \
                _a_ints("pads", pads + pads) + \
                _a_int("group", _attr(a, "num_group", 1))
            nodes_pb.append(_node("Conv", ins, outs, node.name, attrs))
        elif op == "BatchNorm":
            eps = _attr(a, "eps", 1e-3)
            mom = _attr(a, "momentum", 0.9)
            if _attr(a, "fix_gamma", True):
                # reference semantics: gamma pinned to 1 — bake it in
                gname = node.inputs[1][0].name
                if gname in np_params:
                    add_init(gname + "_fixed",
                             _np.ones_like(np_params[gname]))
                    ins = [ins[0], gname + "_fixed"] + ins[2:]
            attrs = _a_float("epsilon", eps) + _a_float("momentum", mom)
            nodes_pb.append(_node("BatchNormalization", ins, [outs[0]],
                                  node.name, attrs))
        elif op == "Activation":
            act = _attr(a, "act_type", "relu")
            if act not in _ACT:
                raise MXNetError(f"ONNX export: unsupported activation "
                                 f"{act!r}")
            nodes_pb.append(_node(_ACT[act], ins, outs, node.name))
        elif op == "Pooling":
            ptype = _attr(a, "pool_type", "max")
            if _attr(a, "global_pool", False):
                onnx_op = "GlobalMaxPool" if ptype == "max" else \
                    "GlobalAveragePool"
                nodes_pb.append(_node(onnx_op, ins, outs, node.name))
            else:
                kernel = _attr(a, "kernel")
                pads = list(_attr(a, "pad", (0,) * len(kernel)))
                attrs = _a_ints("kernel_shape", kernel) + \
                    _a_ints("strides",
                            _attr(a, "stride", (1,) * len(kernel))) + \
                    _a_ints("pads", pads + pads)
                if _attr(a, "pooling_convention", "valid") == "full":
                    attrs += _a_int("ceil_mode", 1)
                if ptype == "avg":
                    attrs += _a_int(
                        "count_include_pad",
                        1 if _attr(a, "count_include_pad", True) else 0)
                onnx_op = "MaxPool" if ptype == "max" else "AveragePool"
                nodes_pb.append(_node(onnx_op, ins, outs, node.name,
                                      attrs))
        elif op == "FullyConnected":
            src = ins[0]
            if _attr(a, "flatten", True):
                fname = f"{node.name}_flatten{extra[0]}"
                extra[0] += 1
                nodes_pb.append(_node("Flatten", [src], [fname], fname,
                                      _a_int("axis", 1)))
                src = fname
            attrs = _a_int("transB", 1) + _a_float("alpha", 1.0) + \
                _a_float("beta", 1.0)
            nodes_pb.append(_node("Gemm", [src] + ins[1:], outs,
                                  node.name, attrs))
        elif op in ("Flatten", "flatten"):
            nodes_pb.append(_node("Flatten", ins, outs, node.name,
                                  _a_int("axis", 1)))
        elif op in ("Concat", "concat"):
            nodes_pb.append(_node("Concat", ins, outs, node.name,
                                  _a_int("axis", _attr(a, "dim", 1))))
        elif op == "Dropout":
            nodes_pb.append(_node("Dropout", ins, [outs[0]], node.name,
                                  _a_float("ratio", _attr(a, "p", 0.5))))
        elif op in ("softmax", "Softmax"):
            nodes_pb.append(_node("Softmax", ins, outs, node.name,
                                  _a_int("axis", _attr(a, "axis", -1))))
        elif op in ("_random_uniform", "_random_normal"):
            # ONNX TensorProto dtype codes for the dtypes jax can draw
            _RAND_DT = {"float32": 1, "float16": 10, "float64": 11}
            dt = _attr(a, "dtype", "float32") or "float32"
            if dt not in _RAND_DT:
                raise MXNetError(
                    f"ONNX export: random op dtype {dt!r} unsupported")
            if op == "_random_uniform":
                attrs = _a_float("low", float(_attr(a, "low", 0.0))) + \
                    _a_float("high", float(_attr(a, "high", 1.0)))
                onnx_op = "RandomUniform"
            else:
                attrs = _a_float("mean", float(_attr(a, "loc", 0.0))) + \
                    _a_float("scale", float(_attr(a, "scale", 1.0)))
                onnx_op = "RandomNormal"
            attrs += _a_ints("shape", _attr(a, "shape", (1,))) + \
                _a_int("dtype", _RAND_DT[dt])
            nodes_pb.append(_node(onnx_op, [], outs, node.name, attrs))
        elif op in _UNARY_EXPORT:
            nodes_pb.append(_node(_UNARY_EXPORT[op], ins, outs,
                                  node.name))
        elif op in _BINARY_EXPORT:
            nodes_pb.append(_node(_BINARY_EXPORT[op], ins, outs,
                                  node.name))
        elif op in _SCALAR_EXPORT:
            onnx_op, rev = _SCALAR_EXPORT[op]
            sval = _np.asarray(_attr(a, "scalar", 0.0), _np.float32)
            sname = f"{node.name}_scalar{extra[0]}"
            extra[0] += 1
            add_init(sname, sval)
            pair = [sname, ins[0]] if rev else [ins[0], sname]
            nodes_pb.append(_node(onnx_op, pair, outs, node.name))
        elif op == "transpose":
            axes = _attr(a, "axes", None)
            attrs = _a_ints("perm", axes) if axes else b""
            nodes_pb.append(_node("Transpose", ins, outs, node.name,
                                  attrs))
        elif op == "LeakyReLU":
            act = _attr(a, "act_type", "leaky")
            if act not in _LEAKY_EXPORT:
                raise MXNetError(
                    f"ONNX export: LeakyReLU act_type {act!r} has no "
                    f"opset-{_OPSET} translation")
            onnx_op, alpha_dflt = _LEAKY_EXPORT[act]
            attrs = b""
            if alpha_dflt is not None:
                attrs = _a_float("alpha",
                                 float(_attr(a, "slope", alpha_dflt)))
            nodes_pb.append(_node(onnx_op, ins, outs, node.name, attrs))
        elif op in ("Reshape", "reshape"):
            shp = _np.asarray(_attr(a, "shape"), _np.int64)
            sname = f"{node.name}_shape{extra[0]}"
            extra[0] += 1
            add_init(sname, shp)
            nodes_pb.append(_node("Reshape", [ins[0], sname], outs,
                                  node.name))
        else:
            raise MXNetError(f"ONNX export: op {op!r} has no translation")

    if input_shape is not None and len(data_inputs) == 1:
        inputs_pb.append(_value_info(data_inputs[0], input_shape, 11))
    else:
        for n in data_inputs:
            inputs_pb.append(_value_info(n, (), 11))
    for node, idx in sym._heads:
        outputs_pb.append(_value_info(name_of[id(node)][idx], (), 12))

    graph = b"".join(nodes_pb) + P.f_str(2, "mxnet_tpu") + \
        b"".join(inits_pb) + b"".join(inputs_pb) + b"".join(outputs_pb)
    opset = P.f_bytes(8, P.f_str(1, "") + P.f_varint(2, _OPSET))
    model = P.f_varint(1, 7) + P.f_str(2, "mxnet_tpu") + opset + \
        P.f_bytes(7, graph)
    with open(onnx_file_path, "wb") as f:
        f.write(model)
    return onnx_file_path
