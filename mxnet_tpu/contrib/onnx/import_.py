"""ONNX ModelProto → (Symbol, arg_params, aux_params) (reference:
python/mxnet/contrib/onnx/onnx2mx/import_model.py + _op_translations.py).
"""
from __future__ import annotations

import struct
from typing import Dict

import numpy as _np

from ...base import MXNetError
from . import _proto as P

_UNARY_REV = {
    "Exp": "exp", "Log": "log", "Sqrt": "sqrt", "Abs": "abs",
    "Neg": "negative", "Floor": "floor", "Ceil": "ceil",
    "Round": "round", "Erf": "erf", "Sign": "sign",
    "Reciprocal": "reciprocal",
}
_BINARY_REV = {"Div": "broadcast_div", "Max": "broadcast_maximum",
               "Min": "broadcast_minimum", "Pow": "broadcast_power"}
_ACT_REV = {"Relu": "relu", "Sigmoid": "sigmoid", "Tanh": "tanh",
            "Softplus": "softrelu", "Softsign": "softsign"}


def _parse_tensor(buf):
    f = P.parse(buf)
    dims = tuple(int(d) for d in P.get_all(f, 1))
    dtype = P.get1(f, 2, 1)
    name = P.get_str(f, 8)
    raw = P.get1(f, 9)
    if raw is not None:
        np_dt = {1: _np.float32, 6: _np.int32, 7: _np.int64}.get(dtype)
        if np_dt is None:
            raise MXNetError(f"ONNX import: tensor dtype {dtype} "
                             f"unsupported")
        arr = _np.frombuffer(bytes(raw), np_dt).reshape(dims)
    elif 4 in f:        # float_data (packed or repeated)
        vals = []
        for wire, v in f[4]:
            if wire == 5:
                vals.append(v)
            else:       # packed floats in one LEN payload
                vals.extend(struct.unpack(f"<{len(v)//4}f", v))
        arr = _np.asarray(vals, _np.float32).reshape(dims)
    elif 7 in f:        # int64_data
        arr = _np.asarray([v for _, v in f[7]], _np.int64).reshape(dims)
    else:
        arr = _np.zeros(dims, _np.float32)
    return name, arr


def _parse_attrs(node_fields) -> Dict[str, object]:
    attrs = {}
    for buf in P.get_all(node_fields, 5):
        f = P.parse(buf)
        name = P.get_str(f, 1)
        atype = P.get1(f, 20, 0)
        if atype == 1:
            attrs[name] = P.get1(f, 2)
        elif atype == 2:
            attrs[name] = int(P.get1(f, 3))
        elif atype == 3:
            attrs[name] = P.get_str(f, 4)
        elif atype == 4:
            attrs[name] = _parse_tensor(P.get1(f, 5))
        elif atype == 7:
            attrs[name] = tuple(int(v) for v in P.get_all(f, 8))
        elif atype == 6:
            attrs[name] = tuple(P.get_all(f, 2))
        else:
            attrs[name] = None
    return attrs


def import_model(model_file: str):
    """Load an ONNX file → (sym, arg_params, aux_params), the reference
    API contract."""
    from ...symbol.symbol import Symbol, _Node
    from ... import ndarray as F

    with open(model_file, "rb") as fh:
        model = P.parse(fh.read())
    graph = P.parse(P.get1(model, 7, b""))

    inits: Dict[str, _np.ndarray] = {}
    for buf in P.get_all(graph, 5):
        name, arr = _parse_tensor(buf)
        inits[name] = arr

    # producers: name -> (node, out_idx)
    prod: Dict[str, tuple] = {}
    aux_names = set()

    def var(name, aux=False):
        if name not in prod:
            attrs = {"__aux__": True} if aux else {}
            if name in inits:
                attrs["__shape__"] = tuple(inits[name].shape)
            prod[name] = (_Node(None, name, attrs, []), 0)
            if aux:
                aux_names.add(name)
        return prod[name]

    for buf in P.get_all(graph, 11):        # graph inputs
        f = P.parse(buf)
        nm = P.get_str(f, 1)
        if nm not in inits:
            var(nm)

    def emit(op, name, attrs, in_names, num_outputs=1, aux_idx=()):
        ins = [prod[nm] if nm in prod else var(nm, aux=i in aux_idx)
               for i, nm in enumerate(in_names)]
        return _Node(op, name, attrs, ins, num_outputs)

    counter = [0]

    def uniq(base):
        counter[0] += 1
        return f"{base.lower()}_onnx{counter[0]}"

    for buf in P.get_all(graph, 1):          # nodes, topological in ONNX
        f = P.parse(buf)
        in_names = [v.decode() for _, v in f.get(1, [])]
        out_names = [v.decode() for _, v in f.get(2, [])]
        name = P.get_str(f, 3) or uniq(P.get_str(f, 4))
        op_type = P.get_str(f, 4)
        a = _parse_attrs(f)

        if op_type == "Conv":
            k = a.get("kernel_shape")
            pads = a.get("pads", (0,) * (2 * len(k)))
            if tuple(pads[:len(k)]) != tuple(pads[len(k):]):
                raise MXNetError("ONNX import: asymmetric Conv pads "
                                 "unsupported")
            attrs = {"kernel": tuple(k),
                     "stride": tuple(a.get("strides", (1,) * len(k))),
                     "dilate": tuple(a.get("dilations", (1,) * len(k))),
                     "pad": tuple(pads[:len(k)]),
                     "num_filter": int(inits[in_names[1]].shape[0])
                     if in_names[1] in inits else 0,
                     "num_group": a.get("group", 1),
                     "no_bias": len(in_names) == 2}
            node = emit("Convolution", name, attrs, in_names)
        elif op_type == "BatchNormalization":
            attrs = {"eps": a.get("epsilon", 1e-5),
                     "momentum": a.get("momentum", 0.9),
                     "fix_gamma": False, "use_global_stats": False}
            node = emit("BatchNorm", name, attrs, in_names,
                        aux_idx=(3, 4))
        elif op_type in _ACT_REV:
            node = emit("Activation", name,
                        {"act_type": _ACT_REV[op_type]}, in_names)
        elif op_type in ("MaxPool", "AveragePool"):
            k = a.get("kernel_shape")
            pads = a.get("pads", (0,) * (2 * len(k)))
            attrs = {"kernel": tuple(k),
                     "stride": tuple(a.get("strides", (1,) * len(k))),
                     "pad": tuple(pads[:len(k)]),
                     "pool_type": "max" if op_type == "MaxPool" else "avg",
                     "pooling_convention":
                         "full" if a.get("ceil_mode", 0) else "valid"}
            if op_type == "AveragePool":
                attrs["count_include_pad"] = \
                    bool(a.get("count_include_pad", 0))
            node = emit("Pooling", name, attrs, in_names)
        elif op_type in ("GlobalMaxPool", "GlobalAveragePool"):
            attrs = {"kernel": (1, 1), "global_pool": True,
                     "pool_type": "max" if "Max" in op_type else "avg"}
            node = emit("Pooling", name, attrs, in_names)
        elif op_type == "Gemm":
            if a.get("transB", 0) != 1 or a.get("transA", 0) != 0:
                raise MXNetError("ONNX import: only transB=1 Gemm "
                                 "supported")
            w = inits.get(in_names[1])
            attrs = {"num_hidden": int(w.shape[0]) if w is not None else 0,
                     "no_bias": len(in_names) == 2, "flatten": False}
            node = emit("FullyConnected", name, attrs, in_names)
        elif op_type == "Flatten":
            node = emit("Flatten", name, {}, in_names)
        elif op_type == "Add":
            node = emit("broadcast_add", name, {}, in_names)
        elif op_type == "Mul":
            node = emit("broadcast_mul", name, {}, in_names)
        elif op_type == "Sub":
            node = emit("broadcast_sub", name, {}, in_names)
        elif op_type == "Concat":
            node = emit("Concat", name, {"dim": a.get("axis", 1),
                                         "num_args": len(in_names)},
                        in_names)
        elif op_type == "Dropout":
            # inference graphs only: ONNX Dropout is identity at
            # inference, and our Dropout op wants an RNG key input —
            # alias the output straight to the input
            prod[out_names[0]] = prod[in_names[0]] if in_names[0] in prod \
                else var(in_names[0])
            continue
        elif op_type in ("RandomUniform", "RandomNormal"):
            _RAND_DT = {1: "float32", 10: "float16", 11: "float64"}
            code = int(a.get("dtype", 1))
            if code not in _RAND_DT:
                raise MXNetError(
                    f"ONNX import: random op dtype code {code} unsupported")
            common = {"shape": tuple(a.get("shape", (1,))),
                      "dtype": _RAND_DT[code]}
            if op_type == "RandomUniform":
                node = emit("_random_uniform", name,
                            dict(common, low=a.get("low", 0.0),
                                 high=a.get("high", 1.0)), [])
            else:
                node = emit("_random_normal", name,
                            dict(common, loc=a.get("mean", 0.0),
                                 scale=a.get("scale", 1.0)), [])
        elif op_type in _UNARY_REV:
            node = emit(_UNARY_REV[op_type], name, {}, in_names)
        elif op_type in _BINARY_REV:
            node = emit(_BINARY_REV[op_type], name, {}, in_names)
        elif op_type == "Transpose":
            attrs = {}
            if "perm" in a:
                attrs["axes"] = tuple(a["perm"])
            node = emit("transpose", name, attrs, in_names)
        elif op_type == "LeakyRelu":
            node = emit("LeakyReLU", name,
                        {"act_type": "leaky",
                         "slope": a.get("alpha", 0.01)}, in_names)
        elif op_type == "Elu":
            node = emit("LeakyReLU", name,
                        {"act_type": "elu",
                         "slope": a.get("alpha", 1.0)}, in_names)
        elif op_type == "Selu":
            # the executor's selu uses the fixed paper constants; a
            # third-party node with DIFFERENT attrs must not be silently
            # reinterpreted
            al = a.get("alpha", 1.67326319)
            gm = a.get("gamma", 1.05070102)
            if abs(al - 1.67326319) > 1e-5 or abs(gm - 1.05070102) > 1e-5:
                raise MXNetError(
                    f"ONNX import: Selu with non-default alpha/gamma "
                    f"({al}, {gm}) has no executor translation")
            node = emit("LeakyReLU", name, {"act_type": "selu"},
                        in_names)
        elif op_type == "Softmax":
            node = emit("softmax", name, {"axis": a.get("axis", -1)},
                        in_names)
        elif op_type == "Reshape":
            shp = inits.get(in_names[1])
            if shp is None:
                raise MXNetError("ONNX import: dynamic Reshape shape "
                                 "unsupported")
            node = emit("Reshape", name,
                        {"shape": tuple(int(v) for v in shp)},
                        in_names[:1])
        else:
            raise MXNetError(f"ONNX import: op {op_type!r} has no "
                             f"translation")
        for i, nm in enumerate(out_names):
            prod[nm] = (node, i)

    heads = []
    for buf in P.get_all(graph, 12):
        f = P.parse(buf)
        heads.append(prod[P.get_str(f, 1)])
    sym = Symbol(heads)

    arg_params, aux_params = {}, {}
    used = {n.name for n in sym._topo() if n.is_var}
    for name, arr in inits.items():
        if name not in used:
            continue
        nd = F.array(arr)
        (aux_params if name in aux_names else arg_params)[name] = nd
    return sym, arg_params, aux_params
