"""Automatic mixed precision (reference: python/mxnet/contrib/amp/amp.py).

``amp.init()`` installs a pre-dispatch hook on the single op-dispatch funnel
(ndarray.register.invoke) that inserts ``amp_cast`` around listed ops — the
TPU-native equivalent of the reference's import-time monkey-patch of the
generated op namespaces.  Because Gluon ``hybridize()`` traces through the
same funnel, one hook covers the imperative, hybridized, and Symbol-executor
paths; under jit the inserted casts are fused by XLA into the surrounding
ops (a bf16 matmul with fused operand casts IS the MXU fast path, so AMP
here costs zero extra kernels).

Default low dtype is **bfloat16** — fp16's dynamic-range problems (and thus
most of the reference's loss-scaling machinery) do not exist on TPU, but
both the fp16 mode and the scaler are provided for parity.
"""
from __future__ import annotations

import contextlib
from typing import List, Optional

import numpy as _np

from ...base import MXNetError
from ...ndarray import NDArray
from ...ndarray.register import invoke_by_name, set_invoke_hook
from .loss_scaler import DynamicLossScaler
from . import lists

__all__ = ["init", "init_trainer", "scale_loss", "unscale",
           "convert_symbol", "convert_model", "convert_hybrid_block"]

_state = {"active": False, "target_dtype": None}


def _cast_nd(x, dtype_name: str):
    if not isinstance(x, NDArray):
        return x
    kind = getattr(x.dtype, "kind", None)
    name = getattr(x.dtype, "name", "")
    if kind != "f" and name != "bfloat16":
        return x                       # ints/bools pass through
    if name == dtype_name or str(x.dtype) == dtype_name:
        return x
    return invoke_by_name("amp_cast", [x], {"dtype": dtype_name})


def _make_hook(target: str):
    lp16 = set(lists.LP16_OPS)
    fp32 = set(lists.FP32_OPS)
    widest = set(lists.WIDEST_TYPE_CASTS)

    def hook(op_name: str, inputs):
        if op_name in ("amp_cast", "cast", "Cast"):
            return inputs
        if op_name in lp16:
            return [_cast_nd(x, target) for x in inputs]
        if op_name in fp32:
            return [_cast_nd(x, "float32") for x in inputs]
        if op_name in widest:
            names = {getattr(x.dtype, "name", str(x.dtype))
                     for x in inputs if isinstance(x, NDArray)}
            if "float32" in names and len(names) > 1:
                return [_cast_nd(x, "float32") for x in inputs]
        return inputs
    return hook


def init(target_dtype: str = "bfloat16") -> None:
    """Turn on AMP process-wide (reference: amp.init()).  Call before
    building the network, exactly like the reference requires."""
    if target_dtype not in ("bfloat16", "float16"):
        raise MXNetError("target_dtype must be 'bfloat16' or 'float16'")
    _state["active"] = True
    _state["target_dtype"] = target_dtype
    set_invoke_hook(_make_hook(target_dtype))


def disable() -> None:
    """Turn AMP back off (test hook; no reference analog)."""
    _state["active"] = False
    _state["target_dtype"] = None
    set_invoke_hook(None)


def active() -> bool:
    return _state["active"]


def init_trainer(trainer, loss_scaler: Optional[DynamicLossScaler] = None):
    """Attach a dynamic loss scaler to a Gluon Trainer
    (reference: amp.init_trainer)."""
    scaler = loss_scaler or DynamicLossScaler()
    trainer._amp_loss_scaler = scaler
    trainer._amp_original_scale = trainer._scale
    return trainer


@contextlib.contextmanager
def scale_loss(loss, trainer):
    """``with amp.scale_loss(loss, trainer) as L: L.backward()`` —
    multiplies the loss by the current scale and arranges for
    ``trainer.step`` to unscale gradients (reference: amp.scale_loss)."""
    scaler = getattr(trainer, "_amp_loss_scaler", None)
    if scaler is None:
        raise MXNetError("call amp.init_trainer(trainer) first")
    trainer._scale = trainer._amp_original_scale / scaler.loss_scale
    from ... import autograd as _ag
    # record the scaling multiply so backward reaches the original graph
    # even when scale_loss is entered outside the record() block
    with _ag.record():
        if isinstance(loss, (list, tuple)):
            scaled = [l * scaler.loss_scale for l in loss]
        else:
            scaled = loss * scaler.loss_scale
    yield scaled


def unscale(trainer) -> bool:
    """Check grads for overflow and update the scaler; returns True if the
    step must be SKIPPED.  Call between backward() and trainer.step() when
    training fp16 (bf16 training normally never overflows)."""
    scaler = getattr(trainer, "_amp_loss_scaler", None)
    if scaler is None:
        raise MXNetError("call amp.init_trainer(trainer) first")
    overflow = scaler.has_overflow(trainer._params)
    scaler.update_scale(overflow)
    if overflow:
        # consume the stale grads so the next step doesn't error
        for p in trainer._params:
            for d in p._data.values():
                if d._ag is not None:
                    d._ag.fresh = True
    return overflow


# ---------------------------------------------------------------------------
# graph conversion (symbolic path)
# ---------------------------------------------------------------------------

def convert_symbol(sym, target_dtype: str = "bfloat16",
                   target_dtype_ops: Optional[List[str]] = None,
                   fp32_ops: Optional[List[str]] = None,
                   widest_dtype_ops: Optional[List[str]] = None,
                   excluded_sym_names: Optional[List[str]] = None):
    """Insert amp_cast nodes into a Symbol graph
    (reference: amp.convert_symbol)."""
    from ...symbol.symbol import Symbol, _Node
    lp16 = set(target_dtype_ops if target_dtype_ops is not None
               else lists.LP16_OPS)
    fp32 = set(fp32_ops if fp32_ops is not None else lists.FP32_OPS)
    widest = set(widest_dtype_ops if widest_dtype_ops is not None
                 else lists.WIDEST_TYPE_CASTS)
    excluded = set(excluded_sym_names or [])

    order = sym._topo()
    mapping = {}

    def casted(node_out, dtype_name, tag):
        node, idx = node_out
        cast = _Node("amp_cast", f"{node.name}_amp_{tag}",
                     {"dtype": dtype_name}, [(node, idx)])
        return (cast, 0)

    for node in order:
        if node.is_var:
            mapping[id(node)] = node
            continue
        new_inputs = [(mapping[id(p)], i) for p, i in node.inputs]
        if node.name not in excluded:
            if node.op in lp16:
                new_inputs = [casted(pi, target_dtype, "lp") for pi in
                              new_inputs]
            elif node.op in fp32:
                new_inputs = [casted(pi, "float32", "f32") for pi in
                              new_inputs]
            elif node.op in widest and len(new_inputs) > 1:
                # runtime widest-dtype resolution (reference amp_multicast)
                mc = _Node("amp_multicast", f"{node.name}_amp_widest",
                           {"num_outputs": len(new_inputs)}, new_inputs,
                           num_outputs=len(new_inputs))
                new_inputs = [(mc, i) for i in range(len(new_inputs))]
        new_node = _Node(node.op, node.name, dict(node.attrs), new_inputs,
                        node.num_outputs)
        mapping[id(node)] = new_node
    heads = [(mapping[id(n)], i) for n, i in sym._heads]
    return Symbol(heads)


def convert_model(sym, arg_params, aux_params,
                  target_dtype: str = "bfloat16", **kwargs):
    """Convert a Module-style checkpoint triple (reference:
    amp.convert_model).  Params stay fp32 (master copies); low-precision
    entry happens at the inserted casts."""
    return convert_symbol(sym, target_dtype, **kwargs), arg_params, \
        aux_params


@contextlib.contextmanager
def _scoped_hook(target: str):
    """Enable the AMP cast hook only for the duration of a call — used by
    per-block conversion so unrelated models keep full precision."""
    from ...ndarray import register as _reg
    prev = _reg._invoke_hook
    set_invoke_hook(_make_hook(target))
    try:
        yield
    finally:
        set_invoke_hook(prev)


def convert_hybrid_block(block, target_dtype: str = "bfloat16"):
    """Mixed-precision ONE block (reference: amp.convert_hybrid_block) —
    its forward (and the hybridize trace, which runs through the hooked
    funnel) executes under the cast hook; other models are untouched."""
    if target_dtype not in ("bfloat16", "float16"):
        raise MXNetError("target_dtype must be 'bfloat16' or 'float16'")
    inner = block.forward

    def amp_forward(*args):
        if _state["active"]:          # process-wide AMP already covers it
            return inner(*args)
        with _scoped_hook(target_dtype):
            return inner(*args)

    block.forward = amp_forward       # instance attr shadows class method
    block.hybridize()
    return block
