"""Dynamic loss scaling (reference: python/mxnet/contrib/amp/loss_scaler.py).

Scale up the loss so small gradients survive low-precision storage; on
overflow skip the step and halve the scale; after ``scale_window`` clean
steps double it.  With bf16 (TPU default) overflow is rare — bf16 shares
fp32's exponent range — so the scaler mostly idles; it earns its keep under
fp16 parity mode.
"""
from __future__ import annotations

__all__ = ["LossScaler", "DynamicLossScaler", "StaticLossScaler"]


class LossScaler:
    loss_scale = 1.0

    def has_overflow(self, params) -> bool:
        """True if any gradient element is non-finite.  Elementwise check —
        a finite fp16 gradient can SUM to inf, which must not count."""
        import numpy as np
        for p in params:
            for g in p.list_grad():
                arr = np.asarray(g._read())
                if not np.isfinite(arr).all():
                    return True
        return False

    def update_scale(self, overflow: bool) -> None:
        pass


class StaticLossScaler(LossScaler):
    def __init__(self, init_scale: float = 2 ** 16):
        self.loss_scale = float(init_scale)


class DynamicLossScaler(LossScaler):
    def __init__(self, init_scale: float = 2 ** 16,
                 scale_factor: float = 2.0, scale_window: int = 2000):
        self.loss_scale = float(init_scale)
        self.scale_factor = scale_factor
        self.scale_window = scale_window
        self._unskipped = 0

    def update_scale(self, overflow: bool) -> None:
        if overflow:
            self.loss_scale = max(1.0, self.loss_scale / self.scale_factor)
            self._unskipped = 0
        else:
            self._unskipped += 1
            if self._unskipped >= self.scale_window:
                self.loss_scale *= self.scale_factor
                self._unskipped = 0
