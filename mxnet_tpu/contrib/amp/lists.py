"""AMP op cast lists (reference: python/mxnet/contrib/amp/lists/symbol_fp16.py).

Three classes, same policy as the reference:
- LP16: compute-bound ops that are safe and fast in low precision — the MXU
  ops (matmul/conv families).  On TPU the low-precision dtype is bfloat16
  by default (fp16 supported for parity); bf16 matmuls are the MXU's native
  mode, so this list is exactly "what should hit the MXU in bf16".
- FP32: numerically-sensitive ops forced to fp32 (reductions through exp/
  log, norms, losses).
- WIDEST: multi-input elementwise ops run in the widest input dtype.
Everything else runs in whatever dtype its inputs already have.
"""

LP16_OPS = [
    "FullyConnected", "Convolution", "Deconvolution", "RNN",
    "dot", "batch_dot", "linalg_gemm2",
]

# BatchNorm is deliberately NOT in FP32_OPS: the op computes batch
# statistics in fp32 internally (ops_nn.py batchnorm) while keeping its
# input/output in the activation dtype — casting its INPUT to fp32 (as the
# fp16-era reference list does) forces every conv→BN edge in a ResNet to
# materialize fp32 activations, doubling HBM traffic on the elementwise
# chain.  bf16 activations + fp32 stats is the TPU-native policy.
FP32_OPS = [
    "LayerNorm", "InstanceNorm", "L2Normalization",
    "softmax", "log_softmax", "softmin", "SoftmaxOutput",
    "exp", "expm1", "log", "log1p", "log2", "log10",
    "sum", "nansum", "prod", "nanprod", "mean", "norm",
    "gamma", "gammaln", "erf", "erfinv",
    "square", "sqrt", "rsqrt", "cbrt", "rcbrt", "reciprocal",
    "smooth_l1", "make_loss", "power", "broadcast_power",
    # round-5 tail: ops whose math runs through exp/log ladders where
    # bf16's 8-bit mantissa visibly degrades (same rationale as softmax)
    "logsumexp", "masked_log_softmax", "masked_softmax",
    "erfc", "erfcinv", "gammainc", "gammaincc", "zeta", "polygamma",
    "bessel_i0", "bessel_i1", "bessel_i0e", "bessel_i1e",
]

# note: LP16 takes precedence over WIDEST in both the hook and
# convert_symbol, so LP16 ops must not be repeated here
WIDEST_TYPE_CASTS = [
    "broadcast_add", "broadcast_sub", "broadcast_mul", "broadcast_div",
    "broadcast_mod", "broadcast_hypot", "broadcast_maximum",
    "broadcast_minimum", "concat", "stack", "where",
]
