"""``mx.contrib.amp``: automatic mixed precision, bf16-first
(reference: python/mxnet/contrib/amp/ — SURVEY.md §2.5, BASELINE config #3).
"""
from .amp import (init, disable, active, init_trainer, scale_loss, unscale,
                  convert_symbol, convert_model, convert_hybrid_block)
from .loss_scaler import LossScaler, DynamicLossScaler, StaticLossScaler
from . import lists

__all__ = ["init", "disable", "active", "init_trainer", "scale_loss",
           "unscale", "convert_symbol", "convert_model",
           "convert_hybrid_block", "LossScaler", "DynamicLossScaler",
           "StaticLossScaler", "lists"]
