"""mx.monitor.Monitor — layer output/weight statistics during training.

Reference parity: python/mxnet/monitor.py (SURVEY.md §2.5 frontend).  The
reference installs a callback on every executor so each op's outputs get a
stat computed when the monitor is active.  TPU-native design: the engine's
listener hook (engine.py on_push) is the analog seam — every imperative /
Module op dispatch passes through it, so the monitor taps the same stream
the profiler does, with zero cost while uninstalled.  Stats stay as 0-d
device arrays until toc() (no host sync in the hot loop).
"""
from __future__ import annotations

import logging
import re
from typing import Callable, List, Optional, Tuple

from .engine import engine

__all__ = ["Monitor"]


def _default_stat(x):
    import jax.numpy as jnp
    return jnp.linalg.norm(x.astype(jnp.float32)) / jnp.sqrt(
        jnp.asarray(x.size, jnp.float32))


class Monitor:
    """Collect per-op output statistics every ``interval`` batches.

    Parameters mirror the reference: ``interval`` (batches between
    collections), ``stat_func`` (array -> 0-d stat; default mean |norm|),
    ``pattern`` (regex over op/param names), ``sort`` (sort toc output by
    name).  Usage::

        mon = Monitor(interval=10, pattern=".*")
        mon.install()             # or pass monitor=mon to Module.fit
        ... training ...
        mon.tic()                 # start collecting this batch
        ... forward/backward ...
        for name, batch, stat in mon.toc():
            print(name, stat)
    """

    def __init__(self, interval: int = 1,
                 stat_func: Optional[Callable] = None,
                 pattern: str = ".*", sort: bool = False):
        self.interval = max(1, int(interval))
        self.stat_func = stat_func or _default_stat
        self.re = re.compile(pattern)
        self.sort = sort
        self.activated = False
        self.step = 0
        self.queue: List[Tuple[int, str, object]] = []
        self._installed = False

    # -- engine tap --------------------------------------------------------
    def _listener(self, op_name: str, outputs, dispatch_us: float) -> None:
        if not self.activated or not self.re.match(op_name):
            return
        outs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
        for i, o in enumerate(outs):
            name = op_name if len(outs) == 1 else f"{op_name}_output{i}"
            try:
                self.queue.append((self.step, name, self.stat_func(o)))
            except Exception:          # non-numeric outputs (edges, tuples)
                pass

    def install(self) -> None:
        if not self._installed:
            engine().add_listener(self._listener)
            self._installed = True

    def uninstall(self) -> None:
        if self._installed:
            engine().remove_listener(self._listener)
            self._installed = False

    # reference API: install on an executor — the engine tap already sees
    # every dispatch, so this just ensures the listener is live
    def install_to_executor(self, executor=None) -> None:
        self.install()

    # -- batch protocol ----------------------------------------------------
    def tic(self) -> None:
        if self.step % self.interval == 0:
            self.activated = True
            self.queue = []

    def toc(self) -> List[Tuple[int, str, str]]:
        if not self.activated:
            self.step += 1
            return []
        self.activated = False
        import numpy as np
        res = []
        for step, name, arr in self.queue:
            try:
                val = np.asarray(arr)
                s = str(float(val)) if val.size == 1 else str(val)
            except Exception:
                s = str(arr)
            res.append((step, name, s))
        if self.sort:
            res.sort(key=lambda t: t[1])
        self.queue = []
        self.step += 1
        return res

    def toc_print(self) -> None:
        for step, name, stat in self.toc():
            logging.getLogger().info("Batch: %7d %30s %s", step, name, stat)
