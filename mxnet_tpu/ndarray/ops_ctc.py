"""CTC loss operator.

Reference parity: src/operator/nn/ctc_loss.cc (the `CTCLoss` /
`_contrib_CTCLoss` op; SURVEY.md §2.5 gluon loss row) — data in TNC
layout ``(max_seq_len, batch, alphabet)``, labels ``(batch, max_label)``
padded with negative values (or explicit ``label_lengths``), optional
``data_lengths``, ``blank_label`` ∈ {'first','last'}.

TPU-native design: the standard log-domain alpha recursion over the
extended label sequence (blanks interleaved), run as ONE ``lax.scan``
over time for the whole batch — static shapes, no host sync — and
differentiated by JAX autodiff straight through the scan (exact CTC
gradients; the reference hand-codes the beta recursion instead because
it has no autodiff at op granularity).
"""
from __future__ import annotations

from .register import register_op


def _register():
    import jax
    import jax.numpy as jnp
    from jax import lax

    NEG = -1e30          # -inf stand-in that survives arithmetic

    def ctc_maker(use_data_lengths=False, use_label_lengths=False,
                  blank_label="first"):
        def fn(data, label, *lengths):
            # data (T, N, C); label (N, L) class indices
            T, N, C = data.shape
            L = label.shape[1]
            S = 2 * L + 1
            li = 0
            data_len = None
            label_len = None
            if use_data_lengths:
                data_len = lengths[li].astype(jnp.int32)
                li += 1
            if use_label_lengths:
                label_len = lengths[li].astype(jnp.int32)
            lab = label.astype(jnp.int32)
            if label_len is None:
                # negative (or, for blank_label='first', zero) entries pad
                valid = (lab >= 0) if blank_label == "last" else (lab > 0)
                label_len = jnp.sum(valid.astype(jnp.int32), axis=1)
            if data_len is None:
                data_len = jnp.full((N,), T, jnp.int32)

            blank = 0 if blank_label == "first" else C - 1
            if blank_label == "first":
                # labels are 1-based with 0 = blank/padding
                lab_idx = lab
            else:
                lab_idx = lab
            lab_safe = jnp.clip(lab_idx, 0, C - 1)

            # extended sequence z: (N, S) = blank, l0, blank, l1, ... blank
            z = jnp.full((N, S), blank, jnp.int32)
            z = z.at[:, 1::2].set(lab_safe)
            pos = jnp.arange(S)[None, :]                     # (1, S)
            in_seq = pos < (2 * label_len[:, None] + 1)      # (N, S)

            # allow skip (s-2 -> s) where z_s is a real label differing
            # from z_{s-2}
            z_m2 = jnp.concatenate(
                [jnp.full((N, 2), -1, jnp.int32), z[:, :-2]], axis=1)
            can_skip = (pos % 2 == 1) & (z != z_m2)

            logp = jax.nn.log_softmax(data.astype(jnp.float32), axis=2)
            # per-step label log-probs: (T, N, S)
            lp_z = jnp.take_along_axis(
                logp, jnp.broadcast_to(z[None], (T, N, S)), axis=2)

            alpha0 = jnp.full((N, S), NEG, jnp.float32)
            alpha0 = alpha0.at[:, 0].set(lp_z[0, :, 0])
            alpha0 = alpha0.at[:, 1].set(
                jnp.where(label_len > 0, lp_z[0, :, 1], NEG))

            def step(alpha, inp):
                lp_t, t = inp
                a_m1 = jnp.concatenate(
                    [jnp.full((N, 1), NEG), alpha[:, :-1]], axis=1)
                a_m2 = jnp.concatenate(
                    [jnp.full((N, 2), NEG), alpha[:, :-2]], axis=1)
                a_m2 = jnp.where(can_skip, a_m2, NEG)
                m = jnp.maximum(jnp.maximum(alpha, a_m1), a_m2)
                new = m + jnp.log(
                    jnp.exp(alpha - m) + jnp.exp(a_m1 - m) +
                    jnp.exp(a_m2 - m)) + lp_t
                new = jnp.where(in_seq, new, NEG)
                # freeze past each sample's sequence end
                new = jnp.where((t < data_len)[:, None], new, alpha)
                return new, None

            ts = jnp.arange(1, T)
            alphaT, _ = lax.scan(step, alpha0, (lp_z[1:], ts))

            # loss = -log(alpha_T[2L] + alpha_T[2L-1])
            endb = jnp.take_along_axis(
                alphaT, (2 * label_len)[:, None], axis=1)[:, 0]
            endl = jnp.take_along_axis(
                alphaT, jnp.maximum(2 * label_len - 1, 0)[:, None],
                axis=1)[:, 0]
            endl = jnp.where(label_len > 0, endl, NEG)
            m = jnp.maximum(endb, endl)
            ll = m + jnp.log(jnp.exp(endb - m) + jnp.exp(endl - m))
            return -ll
        return fn

    register_op("CTCLoss", ctc_maker,
                aliases=("ctc_loss", "_contrib_CTCLoss",
                         "_contrib_ctc_loss"))


_register()
