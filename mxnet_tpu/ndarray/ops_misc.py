"""Long-tail operators: fused loss layers, AMP finiteness checks, pdf ops,
and contrib extras.

Reference parity (SURVEY.md §2.2 top-level/contrib long tail):
  - ElementWiseSum / add_n           src/operator/tensor/elemwise_sum.cc
  - all_finite / multi_all_finite    src/operator/contrib/all_finite.cc
    (the loss-scaler's overflow probe)
  - softmax_cross_entropy            src/operator/loss_binary_op.cc
  - *RegressionOutput / SVMOutput    src/operator/regression_output.cc,
    svm_output.cc — fused loss layers whose data-gradient ignores the head
    gradient, like SoftmaxOutput
  - _random_pdf_*                    src/operator/random/pdf_op.cc
  - contrib fft/ifft                 src/operator/contrib/fft.cc (cuFFT
    there; jnp.fft lowers to XLA FFT here, same unnormalized-inverse
    convention)
  - boolean_mask                     src/operator/contrib/boolean_mask.cc —
    data-dependent output shape, so it runs eagerly (use_jit=False) rather
    than under trace
  - arange_like, quadratic, gradientmultiplier   src/operator/contrib/
  - Crop                             src/operator/crop.cc

TPU-first notes: every fixed-shape op here is an ordinary jitted XLA
computation; the one dynamic-shape op (boolean_mask) is kept off the jit
path by design instead of faking it with padding.
"""
from __future__ import annotations

import numpy as _np

from .register import register_op, simple_op


def normalize_split_indices(indices):
    """Canonical split points for jnp.split: the reference's raw _split_v2
    op passes segment STARTS (leading 0 included); np.split-style split
    points omit it.  One helper shared by the op maker and the symbol-side
    output-count logic so the convention cannot drift."""
    idx = list(indices)
    if idx and idx[0] == 0:
        idx = idx[1:]
    return idx


def _register():
    import jax
    import jax.numpy as jnp
    import jax.scipy.special as jsp

    # ---- ElementWiseSum --------------------------------------------------
    def add_n_maker(num_args=None):
        def fn(*xs):
            out = xs[0]
            for x in xs[1:]:
                out = out + x
            return out
        return fn
    register_op("add_n", add_n_maker,
                aliases=("ElementWiseSum", "elemwise_sum"))

    # ---- all_finite / multi_all_finite (AMP overflow probe) --------------
    def all_finite_maker(init_output=True):
        def fn(data):
            return jnp.all(jnp.isfinite(data.astype(jnp.float32))).astype(
                jnp.float32).reshape(1)
        return fn
    register_op("all_finite", all_finite_maker, differentiable=False)

    def multi_all_finite_maker(num_arrays=1, init_output=True):
        def fn(*arrays):
            ok = jnp.array(True)
            for a in arrays:
                ok = jnp.logical_and(
                    ok, jnp.all(jnp.isfinite(a.astype(jnp.float32))))
            return ok.astype(jnp.float32).reshape(1)
        return fn
    register_op("multi_all_finite", multi_all_finite_maker,
                differentiable=False)

    # ---- softmax_cross_entropy ------------------------------------------
    def softmax_cross_entropy_maker():
        def fn(data, label):
            logp = jax.nn.log_softmax(data, axis=1)
            lab = label.astype(jnp.int32)
            picked = jnp.take_along_axis(logp, lab[:, None], axis=1)
            return -jnp.sum(picked).reshape(1)
        return fn
    register_op("softmax_cross_entropy", softmax_cross_entropy_maker)

    # ---- fused regression loss layers -----------------------------------
    # Forward is the prediction; the gradient of data is the loss gradient
    # scaled by grad_scale, ignoring the head gradient (reference contract).
    def _loss_layer(fwd_fn, grad_fn, grad_scale):
        @jax.custom_vjp
        def op(x, label):
            return fwd_fn(x)

        def op_fwd(x, label):
            y = fwd_fn(x)
            return y, (y, label)

        def op_bwd(res, g):
            y, label = res
            grad = grad_fn(y, label) * jnp.asarray(grad_scale, y.dtype)
            return (grad, jnp.zeros_like(label))

        op.defvjp(op_fwd, op_bwd)
        return op

    def linear_regression_maker(grad_scale=1.0):
        return _loss_layer(lambda x: x, lambda y, t: y - t, grad_scale)
    register_op("LinearRegressionOutput", linear_regression_maker,
                aliases=("linear_regression_output",))

    def mae_regression_maker(grad_scale=1.0):
        return _loss_layer(lambda x: x, lambda y, t: jnp.sign(y - t),
                           grad_scale)
    register_op("MAERegressionOutput", mae_regression_maker,
                aliases=("mae_regression_output",))

    def logistic_regression_maker(grad_scale=1.0):
        import jax.nn as jnn
        return _loss_layer(jnn.sigmoid, lambda y, t: y - t, grad_scale)
    register_op("LogisticRegressionOutput", logistic_regression_maker,
                aliases=("logistic_regression_output",))

    def svm_output_maker(margin=1.0, regularization_coefficient=1.0,
                         use_linear=False):
        # L2-SVM by default, L1 (hinge) with use_linear — svm_output-inl.h.
        # t = ±1 one-vs-rest encoding of the integer label.
        def grad_fn(y, label):
            lab = label.astype(jnp.int32)
            oh = jax.nn.one_hot(lab, y.shape[1], dtype=y.dtype)
            t = 2.0 * oh - 1.0
            viol = margin - t * y          # >0 where the margin is violated
            active = (viol > 0).astype(y.dtype)
            if use_linear:
                return -regularization_coefficient * t * active
            return -2.0 * regularization_coefficient * t * viol * active

        return _loss_layer(lambda x: x, grad_fn, 1.0)
    register_op("SVMOutput", svm_output_maker, aliases=("svm_output",))

    # ---- IdentityAttachKLSparseReg --------------------------------------
    # Identity forward; backward adds the KL sparsity-penalty gradient
    # (reference: src/operator/identity_attach_KL_sparse_reg.cc).
    #
    # Intentional deviation (ADVICE r3): the reference keeps a momentum
    # moving average of rho_hat across batches in mutable op state.  Ops
    # here are pure functions traced once under jit, so cross-call mutable
    # state is not representable; rho_hat is computed from the current
    # batch only and `momentum` is accepted for signature parity but
    # unused.  Users needing the smoothed estimate can carry rho_hat as an
    # explicit model state (the functional idiom for all such statistics).
    def kl_sparse_reg_maker(sparseness_target=0.1, penalty=0.001,
                            momentum=0.9):
        rho = float(sparseness_target)

        @jax.custom_vjp
        def op(x):
            return x

        def op_fwd(x):
            return x, x

        def op_bwd(x, g):
            rho_hat = jnp.mean(jax.nn.sigmoid(x), axis=0, keepdims=True)
            kl_grad = penalty * (-rho / rho_hat + (1 - rho) / (1 - rho_hat))
            return (g + kl_grad * jnp.ones_like(x),)

        op.defvjp(op_fwd, op_bwd)
        return op
    register_op("IdentityAttachKLSparseReg", kl_sparse_reg_maker)

    # ---- pdf ops (src/operator/random/pdf_op.cc) -------------------------
    # Params have shape s; samples have shape s + (m,).  Broadcast params
    # over the trailing sample axis.
    def _bcast(p, sample):
        p = jnp.asarray(p)
        while p.ndim < sample.ndim:
            p = p[..., None]
        return p

    def _pdf_op(name, logpdf, n_params):
        def maker(is_log=False):
            def fn(sample, *params):
                ps = [_bcast(p, sample) for p in params]
                lp = logpdf(sample, *ps)
                return lp if is_log else jnp.exp(lp)
            return fn
        register_op(f"_random_pdf_{name}", maker,
                    aliases=(f"pdf_{name}",))

    _pdf_op("uniform",
            lambda x, low, high: jnp.where(
                (x >= low) & (x <= high),
                -jnp.log(high - low), -jnp.inf), 2)
    _pdf_op("normal",
            lambda x, mu, sigma: (-0.5 * ((x - mu) / sigma) ** 2
                                  - jnp.log(sigma)
                                  - 0.5 * _np.log(2 * _np.pi)), 2)
    # gamma: alpha = shape, beta = scale (matches sample_gamma's params)
    _pdf_op("gamma",
            lambda x, alpha, beta: ((alpha - 1) * jnp.log(x) - x / beta
                                    - jsp.gammaln(alpha)
                                    - alpha * jnp.log(beta)), 2)
    _pdf_op("exponential",
            lambda x, lam: jnp.log(lam) - lam * x, 1)
    _pdf_op("poisson",
            lambda x, lam: (x * jnp.log(lam) - lam
                            - jsp.gammaln(x + 1.0)), 1)
    _pdf_op("negative_binomial",
            lambda x, k, p: (jsp.gammaln(x + k) - jsp.gammaln(x + 1.0)
                             - jsp.gammaln(k) + k * jnp.log(p)
                             + x * jnp.log1p(-p)), 2)
    _pdf_op("generalized_negative_binomial",
            lambda x, mu, alpha: (
                jsp.gammaln(x + 1.0 / alpha) - jsp.gammaln(x + 1.0)
                - jsp.gammaln(1.0 / alpha)
                - (1.0 / alpha) * jnp.log1p(alpha * mu)
                + x * (jnp.log(alpha) + jnp.log(mu)
                       - jnp.log1p(alpha * mu))), 2)

    def dirichlet_maker(is_log=False):
        # sample (..., m, k) on the simplex; alpha (..., k) concentration
        def fn(sample, alpha):
            a = jnp.asarray(alpha)
            # insert the draw axis: alpha (..., k) -> (..., 1, k)
            a = a[..., None, :]
            lp = (jnp.sum((a - 1) * jnp.log(sample), axis=-1)
                  + jsp.gammaln(jnp.sum(a, axis=-1))
                  - jnp.sum(jsp.gammaln(a), axis=-1))
            return lp if is_log else jnp.exp(lp)
        return fn
    register_op("_random_pdf_dirichlet", dirichlet_maker,
                aliases=("pdf_dirichlet",))

    # ---- contrib fft / ifft ---------------------------------------------
    # MXNet packs complex output as interleaved (re, im) pairs on the last
    # axis: fft of (..., d) real -> (..., 2d).  The inverse is unnormalized
    # (cuFFT convention): ifft(fft(x)) == d * x.
    def fft_maker(compute_size=128):
        def fn(x):
            c = jnp.fft.fft(x.astype(jnp.float32), axis=-1)
            out = jnp.stack([c.real, c.imag], axis=-1)
            return out.reshape(x.shape[:-1] + (2 * x.shape[-1],))
        return fn
    register_op("_contrib_fft", fft_maker, aliases=("fft",))

    def ifft_maker(compute_size=128):
        def fn(x):
            d = x.shape[-1] // 2
            pairs = x.astype(jnp.float32).reshape(x.shape[:-1] + (d, 2))
            c = jax.lax.complex(pairs[..., 0], pairs[..., 1])
            return jnp.fft.ifft(c, axis=-1).real * d
        return fn
    register_op("_contrib_ifft", ifft_maker, aliases=("ifft",))

    # ---- boolean_mask (dynamic output shape => eager) --------------------
    # The reference op HAS a backward (scatter the cotangent rows back to
    # the kept positions); jax.vjp cannot trace a value-dependent output
    # shape, so the gradient is hand-built via the registry's vjp_maker
    # escape hatch.
    def _boolean_mask_apply(data, index, axis):
        keep = _np.asarray(index).astype(bool)
        idxs = jnp.asarray(_np.nonzero(keep)[0])
        return idxs, jnp.take(data, idxs, axis=axis)

    def boolean_mask_maker(axis=0):
        def fn(data, index):
            return _boolean_mask_apply(data, index, axis)[1]
        return fn

    def boolean_mask_vjp_maker(axis=0):
        def wrapper(data, index):
            idxs, out = _boolean_mask_apply(data, index, axis)

            def vjp_fn(g):
                at = (slice(None),) * axis + (idxs,)
                grad = jnp.zeros_like(data).at[at].set(g)
                return (grad, jnp.zeros_like(index))
            return out, vjp_fn
        return wrapper
    register_op("_contrib_boolean_mask", boolean_mask_maker,
                aliases=("boolean_mask",), use_jit=False,
                vjp_maker=boolean_mask_vjp_maker)

    # ---- arange_like -----------------------------------------------------
    def arange_like_maker(start=0.0, step=1.0, repeat=1, axis=None):
        def fn(data):
            if axis is None:
                n = int(_np.prod(data.shape))
                vals = start + step * (jnp.arange(n) // repeat)
                return vals.reshape(data.shape).astype(data.dtype)
            n = data.shape[axis]
            vals = (start + step * (jnp.arange(n) // repeat)).astype(
                data.dtype)
            shape = [1] * data.ndim
            shape[axis] = n
            return jnp.broadcast_to(vals.reshape(shape), data.shape)
        return fn
    register_op("_contrib_arange_like", arange_like_maker,
                aliases=("arange_like",), differentiable=False)

    # ---- quadratic -------------------------------------------------------
    def quadratic_maker(a=0.0, b=0.0, c=0.0):
        def fn(x):
            return a * x * x + b * x + c
        return fn
    register_op("_contrib_quadratic", quadratic_maker,
                aliases=("quadratic",))

    # ---- gradientmultiplier ---------------------------------------------
    def gradmult_maker(scalar=1.0):
        @jax.custom_vjp
        def op(x):
            return x

        def op_fwd(x):
            return x, None

        def op_bwd(_, g):
            return (g * scalar,)

        op.defvjp(op_fwd, op_bwd)
        return op
    register_op("_contrib_gradientmultiplier", gradmult_maker,
                aliases=("gradientmultiplier",))

    # ---- Crop (legacy src/operator/crop.cc) ------------------------------
    def crop_maker(num_args=1, offset=(0, 0), h_w=(0, 0),
                   center_crop=False):
        offset = tuple(offset)
        h_w = tuple(h_w)

        def fn(data, *crop_like):
            th, tw = h_w
            if crop_like:
                th, tw = crop_like[0].shape[2], crop_like[0].shape[3]
            H, W = data.shape[2], data.shape[3]
            if center_crop:
                y0, x0 = (H - th) // 2, (W - tw) // 2
            else:
                y0, x0 = offset
            return data[:, :, y0:y0 + th, x0:x0 + tw]
        return fn
    register_op("Crop", crop_maker, aliases=("crop_2d",))

    # ---- im2col / col2im (src/operator/nn/im2col.h frontends) ------------
    # im2col unfolds conv patches to (N, C*prod(kernel), L); col2im is its
    # exact adjoint, obtained from XLA's transpose of the patch gather —
    # no hand-written scatter kernel needed.
    def _conv_geom(shape, kernel, stride, dilate, pad):
        outs = []
        for i, k in enumerate(kernel):
            eff = dilate[i] * (k - 1) + 1
            outs.append((shape[2 + i] + 2 * pad[i] - eff) // stride[i] + 1)
        return tuple(outs)

    def _im2col(data, kernel, stride, dilate, pad):
        from jax import lax
        n, c = data.shape[:2]
        patches = lax.conv_general_dilated_patches(
            data, filter_shape=tuple(kernel),
            window_strides=tuple(stride),
            padding=[(p, p) for p in pad],
            rhs_dilation=tuple(dilate))
        outs = _conv_geom(data.shape, kernel, stride, dilate, pad)
        L = 1
        for o in outs:
            L *= o
        k = 1
        for kk in kernel:
            k *= kk
        return patches.reshape(n, c * k, L)

    def im2col_maker(kernel=(3, 3), stride=None, dilate=None, pad=None):
        kernel = tuple(kernel)
        nd_ = len(kernel)
        stride = tuple(stride) if stride else (1,) * nd_
        dilate = tuple(dilate) if dilate else (1,) * nd_
        pad = tuple(pad) if pad else (0,) * nd_

        def fn(data):
            return _im2col(data, kernel, stride, dilate, pad)
        return fn
    register_op("im2col", im2col_maker)

    def col2im_maker(output_size=None, kernel=(3, 3), stride=None,
                     dilate=None, pad=None):
        kernel = tuple(kernel)
        nd_ = len(kernel)
        stride = tuple(stride) if stride else (1,) * nd_
        dilate = tuple(dilate) if dilate else (1,) * nd_
        pad = tuple(pad) if pad else (0,) * nd_
        out_sz = tuple(output_size)

        def fn(col):
            k = 1
            for kk in kernel:
                k *= kk
            n = col.shape[0]
            c = col.shape[1] // k
            x_shape = (n, c) + out_sz
            zero = jnp.zeros(x_shape, col.dtype)
            _, vjp = jax.vjp(
                lambda d: _im2col(d, kernel, stride, dilate, pad), zero)
            return vjp(col)[0]
        return fn
    register_op("col2im", col2im_maker)

    # ---- histogram -------------------------------------------------------
    def histogram_maker(bin_cnt=None, range=None):
        def fn(data, *maybe_bins):
            if maybe_bins:
                edges = maybe_bins[0]
                hist, e = jnp.histogram(data.reshape(-1), bins=edges)
            else:
                lo, hi = range if range is not None else (None, None)
                hist, e = jnp.histogram(
                    data.reshape(-1), bins=bin_cnt or 10,
                    range=(lo, hi) if lo is not None else None)
            # int32 counts: int64 is truncated (with a warning) unless
            # jax_enable_x64 is on; the reference's int64 contract is a
            # documented deviation
            return (hist.astype(jnp.int32), e)
        return fn
    register_op("histogram", histogram_maker, differentiable=False,
                use_jit=False)

    # ---- multi_sum_sq (contrib, feeds multi_lars) ------------------------
    def multi_sum_sq_maker(num_arrays=1):
        def fn(*arrays):
            return jnp.stack([jnp.sum(jnp.square(a.astype(jnp.float32)))
                              for a in arrays])
        return fn
    register_op("multi_sum_sq", multi_sum_sq_maker, differentiable=False)

    # ---- choose/fill_element_0index (legacy RL-era ops) ------------------
    def choose_element_0index_maker():
        def fn(lhs, rhs):
            idx = rhs.astype(jnp.int32)
            return jnp.take_along_axis(lhs, idx[:, None], axis=1)[:, 0]
        return fn
    register_op("choose_element_0index", choose_element_0index_maker)

    def fill_element_0index_maker():
        def fn(lhs, mhs, rhs):
            idx = rhs.astype(jnp.int32)
            return lhs.at[jnp.arange(lhs.shape[0]), idx].set(mhs)
        return fn
    register_op("fill_element_0index", fill_element_0index_maker)

    # ---- split_v2 (matrix_op.cc SplitV2: sections OR explicit indices) ---
    def split_v2_maker(indices=(), axis=0, squeeze_axis=False,
                      sections=0):
        idx = normalize_split_indices(indices)

        def fn(data):
            if sections:
                parts = jnp.split(data, int(sections), axis=axis)
            else:
                parts = jnp.split(data, idx, axis=axis)
            if squeeze_axis:
                parts = [jnp.squeeze(p, axis=axis) for p in parts]
            if len(parts) == 1:
                return parts[0]           # single output stays an array
            return tuple(parts)
        return fn
    register_op("split_v2", split_v2_maker, aliases=("_split_v2",))

    # ---- interleaved fused self/enc-dec attention primitives -------------
    # (src/operator/contrib/transformer.cc interleaved_matmul_* — the
    # reference's own fused-attention surface, introduced for GluonNLP's
    # fast transformer.)  Layouts follow the reference: projections are
    # (L, B, H*3*D) with q,k,v interleaved PER HEAD; attention matrices
    # are (B*H, Lq, Lk); qk scales q by 1/sqrt(D).
    def _split_interleaved_qkv(qkv, heads):
        L, B, E = qkv.shape
        d = E // (3 * heads)
        x = qkv.reshape(L, B, heads, 3, d)
        # (B*H, L, d) each
        def take(i):
            t = x[:, :, :, i, :]
            return t.transpose(1, 2, 0, 3).reshape(B * heads, L, d)
        return take(0), take(1), take(2)

    def imm_selfatt_qk_maker(heads=1):
        def fn(qkv):
            q, k, _ = _split_interleaved_qkv(qkv, heads)
            # python-float scale: weak typing keeps f16/bf16 inputs in
            # their own dtype (the reference's fp16 fast-attention path)
            scale = 1.0 / float(q.shape[-1]) ** 0.5
            return jnp.einsum("nqd,nkd->nqk", q * scale, k)
        return fn
    register_op("_contrib_interleaved_matmul_selfatt_qk",
                imm_selfatt_qk_maker,
                aliases=("interleaved_matmul_selfatt_qk",))

    def imm_selfatt_valatt_maker(heads=1):
        def fn(qkv, att):
            L, B, E = qkv.shape
            d = E // (3 * heads)
            _, _, v = _split_interleaved_qkv(qkv, heads)
            out = jnp.einsum("nqk,nkd->nqd", att, v)   # (B*H, L, d)
            return out.reshape(B, heads, L, d).transpose(2, 0, 1, 3) \
                .reshape(L, B, heads * d)
        return fn
    register_op("_contrib_interleaved_matmul_selfatt_valatt",
                imm_selfatt_valatt_maker,
                aliases=("interleaved_matmul_selfatt_valatt",))

    def _split_interleaved_kv(kv, heads):
        L, B, E = kv.shape
        d = E // (2 * heads)
        x = kv.reshape(L, B, heads, 2, d)

        def take(i):
            t = x[:, :, :, i, :]
            return t.transpose(1, 2, 0, 3).reshape(B * heads, L, d)
        return take(0), take(1)

    def imm_encdec_qk_maker(heads=1):
        def fn(q_proj, kv):
            Lq, B, E = q_proj.shape
            d = E // heads
            q = q_proj.reshape(Lq, B, heads, d).transpose(1, 2, 0, 3) \
                .reshape(B * heads, Lq, d)
            k, _ = _split_interleaved_kv(kv, heads)
            scale = 1.0 / float(d) ** 0.5
            return jnp.einsum("nqd,nkd->nqk", q * scale, k)
        return fn
    register_op("_contrib_interleaved_matmul_encdec_qk",
                imm_encdec_qk_maker,
                aliases=("interleaved_matmul_encdec_qk",))

    def imm_encdec_valatt_maker(heads=1):
        def fn(kv, att):
            Lk, B, E = kv.shape
            d = E // (2 * heads)
            _, v = _split_interleaved_kv(kv, heads)
            Lq = att.shape[1]
            out = jnp.einsum("nqk,nkd->nqd", att, v)
            return out.reshape(B, heads, Lq, d).transpose(2, 0, 1, 3) \
                .reshape(Lq, B, heads * d)
        return fn
    register_op("_contrib_interleaved_matmul_encdec_valatt",
                imm_encdec_valatt_maker,
                aliases=("interleaved_matmul_encdec_valatt",))

    # ---- hawkesll (src/operator/contrib/hawkes_ll.cc) --------------------
    # Log-likelihood of a marked multivariate Hawkes process with
    # exponential kernels, via the Ogata recursion over events:
    #   λ_m(t_i) = μ_m + α_m β_m r_m(i),
    #   r_m(i) = e^{-β_m Δt_i} (r_m(i-1) + 1{mark_{i-1}=m}),
    # compensator over [0, T]: Σ_m μ_m T + Σ_m α_m Σ_{i≤n} (1 − e^{−β_m
    # (T − t_i)}).  Returns (loglik (N,), final decayed states (N, K)).
    def hawkesll_maker():
        from jax import lax

        def fn(lda, alpha, beta, state, lags, marks, valid_length,
               max_time):
            N, T = lags.shape
            K = lda.shape[1]
            marks_i = marks.astype(jnp.int32)
            vl = valid_length.astype(jnp.int32)

            def one(mu, st, lag_row, mark_row, n, Tmax):
                def step(carry, inp):
                    r, t, ll, prev_mark = carry
                    lag, mark, idx = inp
                    decay = jnp.exp(-beta * lag)
                    r_new = decay * (r + jax.nn.one_hot(prev_mark, K,
                                                        dtype=r.dtype))
                    t_new = t + lag
                    lam = mu[mark] + alpha[mark] * beta[mark] * r_new[mark]
                    valid = idx < n
                    ll_new = ll + jnp.where(valid, jnp.log(lam), 0.0)
                    return ((jnp.where(valid, r_new, r),
                             jnp.where(valid, t_new, t), ll_new,
                             jnp.where(valid, mark, prev_mark)), t_new)

                init = (st, jnp.float32(0.0), jnp.float32(0.0),
                        jnp.int32(-1))
                # prev_mark starts at -1: one_hot(-1) is all-zero, so the
                # first event sees only the initial state
                (r, t, ll, last_mark), times = lax.scan(
                    step, init, (lag_row, mark_row, jnp.arange(T)))
                # compensator: background over [0, Tmax] + excitation of
                # each VALID event integrated to Tmax + the initial
                # state's decayed excitation ∫₀ᵀ αβ·st·e^{−βt}
                comp_bg = jnp.sum(mu) * Tmax
                comp_init = jnp.sum(alpha * st *
                                    (1.0 - jnp.exp(-beta * Tmax)))
                ev_valid = jnp.arange(T) < n
                contrib = alpha[mark_row] * (
                    1.0 - jnp.exp(-beta[mark_row] *
                                  jnp.maximum(Tmax - times, 0.0)))
                comp_ex = jnp.sum(jnp.where(ev_valid, contrib, 0.0))
                # final state decayed to Tmax (incl. the last event)
                r_final = jnp.exp(-beta * jnp.maximum(Tmax - t, 0.0)) * \
                    (r + jax.nn.one_hot(last_mark, K, dtype=r.dtype))
                return ll - comp_bg - comp_init - comp_ex, r_final

            ll, states = jax.vmap(one)(lda, state, lags, marks_i, vl,
                                       max_time)
            return ll, states
        return fn
    register_op("_contrib_hawkesll", hawkesll_maker,
                aliases=("hawkesll",))

    # ---- SoftmaxActivation (deprecated-but-present reference op) ---------
    def softmax_activation_maker(mode="instance"):
        def fn(x):
            if mode == "channel":
                return jax.nn.softmax(x, axis=1)
            return jax.nn.softmax(x.reshape(x.shape[0], -1),
                                  axis=-1).reshape(x.shape)
        return fn
    register_op("SoftmaxActivation", softmax_activation_maker,
                aliases=("softmax_activation",))

    # ---- _square_sum (reference: square_sum.cc — fused LARS ingredient) --
    def square_sum_maker(axis=None, keepdims=False, exclude=False):
        def fn(x):
            ax = tuple(axis) if isinstance(axis, (list, tuple)) else \
                (axis,) if axis is not None else None
            if ax is not None and exclude:
                norm = {a % x.ndim for a in ax}   # exclude needs
                ax = tuple(i for i in range(x.ndim)  # non-negative dims
                           if i not in norm)
            return jnp.sum(jnp.square(x), axis=ax, keepdims=keepdims)
        return fn
    register_op("_square_sum", square_sum_maker, aliases=("square_sum",))

    # ---- hypot + logical binaries (elemwise_binary_op_extended.cc /
    # elemwise_binary_op_logic.cc; scalar variants take the scalar as a
    # 0-d array input, per the registry convention) ------------------------
    def hypot_maker():
        def fn(lhs, rhs):
            return jnp.hypot(lhs, rhs)
        return fn
    register_op("_hypot", hypot_maker, aliases=("hypot",))
    register_op("_hypot_scalar",
                lambda: (lambda x, s: jnp.hypot(x, s.astype(x.dtype))))

    for lname, lop in (("and", jnp.logical_and), ("or", jnp.logical_or),
                       ("xor", jnp.logical_xor)):
        def _mk(lop=lop):
            def fn(lhs, rhs):
                return lop(lhs.astype(bool),
                           rhs.astype(bool)).astype(jnp.float32)
            return fn

        def _mk_scalar(lop=lop):
            def fn(x, s):
                return lop(x.astype(bool),
                           s.astype(bool)).astype(jnp.float32)
            return fn
        register_op(f"_logical_{lname}", _mk, differentiable=False)
        register_op(f"_logical_{lname}_scalar", _mk_scalar,
                    differentiable=False)

    # ---- MakeLoss (make_loss.cc): marks a loss head — identity forward,
    # constant grad_scale gradient ignoring the incoming head gradient
    # (BlockGrad, its graph-surgery sibling, is stop_gradient in
    # ops_matrix) ----------------------------------------------------------
    def make_loss_maker(grad_scale=1.0, valid_thresh=0.0,
                        normalization="null"):
        @jax.custom_vjp
        def op(x):
            return x

        def op_fwd(x):
            return x, x

        def op_bwd(x, g):
            scale = jnp.asarray(grad_scale, x.dtype)
            if normalization == "batch":
                scale = scale / x.shape[0]
            elif normalization == "valid":
                n_valid = jnp.maximum(
                    jnp.sum((x > valid_thresh).astype(x.dtype)), 1.0)
                scale = scale / n_valid
            return (jnp.full_like(x, 1.0) * scale,)

        op.defvjp(op_fwd, op_bwd)
        return op
    register_op("MakeLoss", make_loss_maker, aliases=("make_loss",))

    # ---- creation ops (init_op.cc _zeros/_ones/_full/_arange/_linspace/
    # _eye) — the registry forms behind mx.nd.zeros etc.; zero-input ops
    # so language bindings can create through MXImperativeInvoke alone.
    # Each op declares exactly its own parameters (bad kwargs error out)
    # and honors ``ctx`` via device placement — the reference ops carry
    # ctx as an op attribute for exactly this binding path -----------------
    def _place(fn_make, ctx):
        if ctx is None:
            return fn_make
        from ..context import Context
        dev = (ctx if isinstance(ctx, Context)
               else Context.from_str(ctx)).device

        def placed():
            import jax
            return jax.device_put(fn_make(), dev)
        return placed

    def zeros_maker(shape=(), ctx=None, dtype="float32"):
        shp, dt = tuple(int(s) for s in shape), jnp.dtype(dtype)
        return _place(lambda: jnp.zeros(shp, dt), ctx)
    register_op("_zeros", zeros_maker, differentiable=False)

    def ones_maker(shape=(), ctx=None, dtype="float32"):
        shp, dt = tuple(int(s) for s in shape), jnp.dtype(dtype)
        return _place(lambda: jnp.ones(shp, dt), ctx)
    register_op("_ones", ones_maker, differentiable=False)

    def full_maker(shape=(), ctx=None, dtype="float32", value=0.0):
        shp, dt = tuple(int(s) for s in shape), jnp.dtype(dtype)
        return _place(lambda: jnp.full(shp, value, dt), ctx)
    register_op("_full", full_maker, differentiable=False)

    def arange_maker(start=0.0, stop=None, step=1.0, repeat=1,
                     infer_range=False, ctx=None, dtype="float32"):
        dt = jnp.dtype(dtype)
        lo, hi = (0, start) if stop is None else (start, stop)

        def make():
            out = jnp.arange(lo, hi, step, dtype=dt)
            return jnp.repeat(out, int(repeat)) if repeat > 1 else out
        return _place(make, ctx)
    register_op("_arange", arange_maker, differentiable=False)

    def linspace_maker(start=0.0, stop=1.0, num=50, endpoint=True,
                       ctx=None, dtype="float32"):
        dt = jnp.dtype(dtype)
        return _place(lambda: jnp.linspace(start, stop, int(num),
                                           endpoint=endpoint, dtype=dt),
                      ctx)
    register_op("_linspace", linspace_maker, differentiable=False)

    def eye_maker(N=0, M=0, k=0, ctx=None, dtype="float32"):
        dt = jnp.dtype(dtype)
        return _place(lambda: jnp.eye(int(N), int(M) if M else None,
                                      k=int(k), dtype=dt), ctx)
    register_op("_eye", eye_maker, differentiable=False)

    # ---- _slice_assign / _slice_assign_scalar (matrix_op.cc — the
    # functional write behind x[a:b] = y) ---------------------------------
    def _assign_slices(begin, end, step, shape):
        # None passes through to Python slice() (like the sibling `slice`
        # op), which natively handles negative steps and open ends
        idx = []
        for i in range(len(shape)):
            b = begin[i] if i < len(begin) else None
            e = end[i] if i < len(end) else None
            st = step[i] if i < len(step) else None
            idx.append(slice(None if b is None else int(b),
                             None if e is None else int(e),
                             None if st is None else int(st)))
        return tuple(idx)

    def slice_assign_maker(begin=(), end=(), step=()):
        def fn(lhs, rhs):
            return lhs.at[_assign_slices(begin, end, step,
                                         lhs.shape)].set(rhs)
        return fn
    register_op("_slice_assign", slice_assign_maker,
                aliases=("_crop_assign",))

    def slice_assign_scalar_maker(begin=(), end=(), step=(), scalar=0.0):
        def fn(lhs):
            return lhs.at[_assign_slices(begin, end, step,
                                         lhs.shape)].set(
                jnp.asarray(scalar, lhs.dtype))
        return fn
    register_op("_slice_assign_scalar", slice_assign_scalar_maker,
                aliases=("_crop_assign_scalar",))

    # ---- _onehot_encode (legacy ndarray_function.cc): row i gets a
    # one-hot of indices[i] written into an out-shaped array --------------
    def onehot_encode_maker():
        def fn(indices, out):
            oh = jax.nn.one_hot(indices.astype(jnp.int32), out.shape[1],
                                dtype=out.dtype)
            return oh
        return fn
    register_op("_onehot_encode", onehot_encode_maker,
                differentiable=False)

    # ---- _scatter_set_nd (indexing_op.cc): functional write of rhs into
    # lhs at gather_nd-style indices — the storage op behind advanced
    # index assignment ----------------------------------------------------
    def scatter_set_nd_maker(shape=None):
        def fn(lhs, rhs, indices):
            idx = tuple(indices.astype(jnp.int32))
            return lhs.at[idx].set(rhs)
        return fn
    register_op("_scatter_set_nd", scatter_set_nd_maker,
                differentiable=False)


    # ---- small 1.x internals kept for name-level parity -----------------
    simple_op("_copyto", lambda x: x,
              doc="reference _copyto: device/dtype copy (placement is "
                  "handled by invoke's ctx logic; jit output is a fresh "
                  "buffer, preserving copy semantics)")

    def set_value_maker(src=0.0):
        # reference _set_value: fill the (out=) target with a scalar
        def fn(x):
            return jnp.full_like(x, src)
        return fn
    register_op("_set_value", set_value_maker, differentiable=False)

    simple_op("_identity_with_attr_like_rhs", lambda lhs, rhs: lhs,
              doc="reference: identity on lhs carrying rhs's storage "
                  "attrs (sparse-grad plumbing); dense XLA arrays make "
                  "it a plain identity")

    def rnn_param_concat_maker(dim=0, num_args=1):
        # reference _rnn_param_concat (rnn-inl.h): concat of per-layer
        # RNN parameter blobs — shape-inference-special in nnvm, a plain
        # concat under eval_shape
        def fn(*parts):
            return jnp.concatenate([p.reshape(-1) if dim == 0 and
                                    p.ndim > 1 else p for p in parts],
                                   axis=dim)
        return fn
    register_op("_rnn_param_concat", rnn_param_concat_maker)

    # straight-through estimators (reference contrib round_ste/sign_ste,
    # src/operator/contrib/stes_op.cc): quantization-aware training —
    # discrete forward, identity backward
    def _ste(fwd):
        def maker():
            @jax.custom_vjp
            def fn(x):
                return fwd(x)

            def fn_fwd(x):
                return fwd(x), None

            def fn_bwd(_, ct):
                return (ct,)          # gradient passes STRAIGHT THROUGH
            fn.defvjp(fn_fwd, fn_bwd)
            return fn
        return maker
    def _round_half_away(x):
        # reference stes_op.cc rounds half AWAY from zero (::roundf);
        # jnp.round is half-to-even — match the reference for QAT parity
        return jnp.where(x >= 0, jnp.floor(x + 0.5), jnp.ceil(x - 0.5))
    register_op("round_ste", _ste(_round_half_away),
                aliases=("_contrib_round_ste",),
                ref="src/operator/contrib/stes_op.cc")
    register_op("sign_ste", _ste(jnp.sign),
                aliases=("_contrib_sign_ste",),
                ref="src/operator/contrib/stes_op.cc")


_register()
