"""``mx.nd.image`` namespace (reference: the _image_* op frontends in
python/mxnet/ndarray/image.py) — thin wrappers over the registry ops in
ops_image.py, named without the underscore prefix."""
from __future__ import annotations

import sys as _sys

from .register import _registry, make_frontend

_PREFIX = "_image_"
_this_module = _sys.modules[__name__]

for _name, _op in list(_registry.items()):
    if _name.startswith(_PREFIX):
        setattr(_this_module, _name[len(_PREFIX):], make_frontend(_op))
