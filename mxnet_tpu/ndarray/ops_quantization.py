"""INT8 quantization operators.

Reference parity: src/operator/quantization/ — quantize.cc,
quantize_v2.cc, dequantize.cc, requantize.cc,
quantized_fully_connected.cc, quantized_conv.cc (SURVEY.md §2.2
quantization row).

TPU-native design: symmetric signed-int8 per-tensor quantization (the
reference's int8 flow), with the quantized matmul/conv lowered through
``lax.dot_general`` / ``lax.conv_general_dilated`` with
``preferred_element_type=int32`` — the MXU's native int8×int8→int32 path.
Ranges travel with the data as (min, max) scalar arrays, exactly like the
reference's three-tensor convention.
"""
from __future__ import annotations

from .register import register_op


def _register():
    import jax
    import jax.numpy as jnp
    from jax import lax

    def _scale_of(mn, mx):
        # symmetric int8: scale maps max(|min|,|max|) -> 127
        return jnp.maximum(jnp.abs(mn), jnp.abs(mx)) / 127.0

    # ---- quantize / quantize_v2 -----------------------------------------
    def quantize_maker(out_type="int8"):
        if out_type != "int8":
            from ..base import MXNetError
            raise MXNetError("only int8 quantization is supported (the "
                             "MXU's native integer path)")

        def fn(data, min_range, max_range):
            s = _scale_of(min_range, max_range)
            q = jnp.clip(jnp.round(data / s), -127, 127).astype(jnp.int8)
            return (q, min_range.reshape(()), max_range.reshape(()))
        return fn
    register_op("_contrib_quantize", quantize_maker,
                aliases=("quantize",), differentiable=False)

    def quantize_v2_maker(out_type="int8", min_calib_range=None,
                          max_calib_range=None):
        if out_type != "int8":
            from ..base import MXNetError
            raise MXNetError("only int8 quantization is supported")

        def fn(data):
            if min_calib_range is not None and max_calib_range is not None:
                mn = jnp.asarray(min_calib_range, data.dtype)
                mx = jnp.asarray(max_calib_range, data.dtype)
            else:
                mn = jnp.min(data)
                mx = jnp.max(data)
            s = _scale_of(mn, mx)
            q = jnp.clip(jnp.round(data / s), -127, 127).astype(jnp.int8)
            return (q, mn.reshape(()), mx.reshape(()))
        return fn
    register_op("_contrib_quantize_v2", quantize_v2_maker,
                aliases=("quantize_v2",), differentiable=False)

    # ---- dequantize ------------------------------------------------------
    def dequantize_maker(out_type="float32"):
        def fn(data, min_range, max_range):
            # the stored range is the REAL-value range; the divisor is the
            # integer type's own max (int8 -> 127, int32 accumulators ->
            # 2^31-1), as in the reference dequantize
            t = 127.0 if data.dtype == jnp.int8 else float(2 ** 31 - 1)
            s = jnp.maximum(jnp.abs(min_range), jnp.abs(max_range)) / t
            return data.astype(jnp.float32) * s
        return fn
    register_op("_contrib_dequantize", dequantize_maker,
                aliases=("dequantize",), differentiable=False)

    # ---- requantize (int32 accumulators -> int8) -------------------------
    def requantize_maker(min_calib_range=None, max_calib_range=None,
                         out_type="int8"):
        def fn(data, min_range, max_range):
            # data int32 with real-value range [min_range, max_range]
            s_in = jnp.maximum(jnp.abs(min_range), jnp.abs(max_range)) / \
                float(2 ** 31 - 1)
            if min_calib_range is not None and max_calib_range is not None:
                mn = jnp.asarray(min_calib_range, jnp.float32)
                mx = jnp.asarray(max_calib_range, jnp.float32)
            else:
                real = data.astype(jnp.float32) * s_in
                mn = jnp.min(real)
                mx = jnp.max(real)
            s_out = _scale_of(mn, mx)
            q = jnp.clip(jnp.round(data.astype(jnp.float32) * s_in / s_out),
                         -127, 127).astype(jnp.int8)
            return (q, mn.reshape(()), mx.reshape(()))
        return fn
    register_op("_contrib_requantize", requantize_maker,
                aliases=("requantize",), differentiable=False)

    # ---- quantized fully connected (int8 x int8 -> int32 on the MXU) -----
    def quantized_fc_maker(num_hidden=None, no_bias=False, flatten=True):
        def fn(data, weight, *rest):
            # rest: [bias,] min_data, max_data, min_w, max_w [, min_b,
            # max_b] — reference input convention
            if no_bias:
                bias = None
                mnd, mxd, mnw, mxw = rest[:4]
            else:
                bias, mnd, mxd, mnw, mxw = rest[:5]
            x = data.reshape((data.shape[0], -1)) if flatten else data
            out32 = lax.dot_general(
                x, weight,
                (((x.ndim - 1,), (1,)), ((), ())),
                preferred_element_type=jnp.int32)
            s_d = _scale_of(mnd, mxd)
            s_w = _scale_of(mnw, mxw)
            if bias is not None:
                # bias arrives int8 with its own scale; fold into the
                # int32 accumulator domain
                mnb, mxb = rest[5], rest[6]
                s_b = _scale_of(mnb, mxb)
                b32 = jnp.round(
                    bias.astype(jnp.float32) * s_b / (s_d * s_w)
                ).astype(jnp.int32)
                out32 = out32 + b32
            # real-value range of the int32 accumulator
            s_out = s_d * s_w
            bound = s_out * float(2 ** 31 - 1)
            return (out32, -bound.reshape(()), bound.reshape(()))
        return fn
    register_op("_contrib_quantized_fully_connected", quantized_fc_maker,
                aliases=("quantized_fully_connected",),
                differentiable=False)

    # ---- quantized 2d convolution ---------------------------------------
    def quantized_conv_maker(kernel=None, stride=(1, 1), pad=(0, 0),
                             dilate=(1, 1), num_filter=None, num_group=1,
                             no_bias=True, layout="NCHW"):
        def fn(data, weight, *rest):
            if no_bias:
                mnd, mxd, mnw, mxw = rest[:4]
                bias = None
            else:
                bias, mnd, mxd, mnw, mxw = rest[:5]
            out32 = lax.conv_general_dilated(
                data.astype(jnp.int8), weight.astype(jnp.int8),
                window_strides=tuple(stride),
                padding=[(pad[0], pad[0]), (pad[1], pad[1])],
                rhs_dilation=tuple(dilate),
                feature_group_count=num_group,
                dimension_numbers=("NCHW", "OIHW", "NCHW"),
                preferred_element_type=jnp.int32)
            s_d = _scale_of(mnd, mxd)
            s_w = _scale_of(mnw, mxw)
            if bias is not None:
                mnb, mxb = rest[5], rest[6]
                s_b = _scale_of(mnb, mxb)
                b32 = jnp.round(bias.astype(jnp.float32) * s_b /
                                (s_d * s_w)).astype(jnp.int32)
                out32 = out32 + b32.reshape(1, -1, 1, 1)
            s_out = s_d * s_w
            bound = s_out * float(2 ** 31 - 1)
            return (out32, -bound.reshape(()), bound.reshape(()))
        return fn
    register_op("_contrib_quantized_conv", quantized_conv_maker,
                aliases=("quantized_conv",), differentiable=False)

    # ---- quantized pooling (int8 in, int8 out, range unchanged) ----------
    def quantized_pooling_maker(kernel=(2, 2), stride=None, pad=(0, 0),
                                pool_type="max"):
        st = tuple(stride) if stride else tuple(kernel)

        def fn(data, min_range, max_range):
            if pool_type == "max":
                out = lax.reduce_window(
                    data, jnp.int8(-128), lax.max,
                    (1, 1) + tuple(kernel), (1, 1) + st,
                    [(0, 0), (0, 0), (pad[0], pad[0]), (pad[1], pad[1])])
            else:  # avg — accumulate in int32, divide, round back
                acc = lax.reduce_window(
                    data.astype(jnp.int32), jnp.int32(0), lax.add,
                    (1, 1) + tuple(kernel), (1, 1) + st,
                    [(0, 0), (0, 0), (pad[0], pad[0]), (pad[1], pad[1])])
                n = kernel[0] * kernel[1]
                out = jnp.clip(jnp.round(acc / n), -128, 127) \
                    .astype(jnp.int8)
            return (out, min_range.reshape(()), max_range.reshape(()))
        return fn
    register_op("_contrib_quantized_pooling", quantized_pooling_maker,
                aliases=("quantized_pooling",), differentiable=False)


_register()
