"""The ``_image_*`` operator namespace (reference: src/operator/image/
image_random.cc, resize.cc, crop.cc — the ops behind ``mx.nd.image.*``
and the gluon vision transforms; SURVEY.md §2.2 image/ row).

TPU-first notes: deterministic ops are ordinary jitted XLA computations
on HWC/NHWC uint8-or-float arrays.  The ``random_*`` variants draw their
factors HOST-side from the library's seeded stream (use_jit=False) — a
per-call scalar factor then parameterizes one jitted kernel, mirroring
how the reference draws on CPU and dispatches a deterministic kernel;
putting the draw on-device would force key plumbing through every
augmentation for no bandwidth win (factors are scalars).
"""
from __future__ import annotations

import numpy as _np

from .register import register_op

# ---------------------------------------------------------------------------
# shared photometric math — single source for these constants; the gluon
# vision transforms import them so op and transform cannot drift
# ---------------------------------------------------------------------------

#: ITU-R BT.601 luma weights (the reference's RGB2GRAY convention)
LUMA = _np.array([0.299, 0.587, 0.114], _np.float32)

#: AlexNet PCA lighting basis over ImageNet RGB
LIGHTING_EIGVAL = _np.array([55.46, 4.794, 1.148], _np.float32)
LIGHTING_EIGVEC = _np.array([[-0.5675, 0.7192, 0.4009],
                             [-0.5808, -0.0045, -0.8140],
                             [-0.5836, -0.6948, 0.4203]], _np.float32)

_T_YIQ = _np.array([[0.299, 0.587, 0.114],
                    [0.596, -0.274, -0.321],
                    [0.211, -0.523, 0.311]], _np.float64)


def hue_rotation_matrix(f: float) -> _np.ndarray:
    """RGB->RGB matrix rotating hue by f (in half-turns) in YIQ space.
    Uses the exact numeric inverse of the YIQ matrix — the textbook
    rounded t_rgb constants make even f=0 a visible non-identity."""
    u, w = _np.cos(f * _np.pi), _np.sin(f * _np.pi)
    rot = _np.array([[1.0, 0.0, 0.0],
                     [0.0, u, -w],
                     [0.0, w, u]], _np.float64)
    return (_np.linalg.inv(_T_YIQ) @ rot @ _T_YIQ).astype(_np.float32)


def _host_uniform(lo: float, hi: float) -> float:
    """Host-side augmentation draw from the LIBRARY key stream, so
    mx.random.seed() reproduces augmentation sequences (the module
    contract; plain np.random would escape it)."""
    from .. import random as _grandom
    key_bits = _np.asarray(_grandom.next_key()).ravel().astype(_np.uint32)
    rng = _np.random.default_rng(key_bits)
    return float(rng.uniform(lo, hi))


def _register():
    import jax
    import jax.numpy as jnp

    def _is_batch(x):
        return x.ndim == 4

    # ---- to_tensor: HWC [0,255] -> CHW float32 [0,1] ---------------------
    def to_tensor_maker():
        def fn(x):
            y = x.astype(jnp.float32) / 255.0
            axes = (0, 3, 1, 2) if _is_batch(x) else (2, 0, 1)
            return jnp.transpose(y, axes)
        return fn
    register_op("_image_to_tensor", to_tensor_maker)

    # ---- normalize: CHW (or NCHW) with per-channel mean/std --------------
    def normalize_maker(mean=(0.0,), std=(1.0,)):
        m = _np.asarray(mean, _np.float32)
        s = _np.asarray(std, _np.float32)

        def fn(x):
            shape = (1, -1, 1, 1) if _is_batch(x) else (-1, 1, 1)
            return (x - jnp.asarray(m).reshape(shape)) \
                / jnp.asarray(s).reshape(shape)
        return fn
    register_op("_image_normalize", normalize_maker)

    # ---- flips (HWC / NHWC: width is -2, height is -3) -------------------
    def flip_lr_maker():
        def fn(x):
            return jnp.flip(x, axis=-2)
        return fn
    register_op("_image_flip_left_right", flip_lr_maker)

    def flip_tb_maker():
        def fn(x):
            return jnp.flip(x, axis=-3)
        return fn
    register_op("_image_flip_top_bottom", flip_tb_maker)

    def random_flip_lr_maker():
        def fn(x):
            return jnp.flip(x, axis=-2) \
                if _host_uniform(0.0, 1.0) < 0.5 else x
        return fn
    register_op("_image_random_flip_left_right", random_flip_lr_maker,
                use_jit=False, differentiable=False)

    def random_flip_tb_maker():
        def fn(x):
            return jnp.flip(x, axis=-3) \
                if _host_uniform(0.0, 1.0) < 0.5 else x
        return fn
    register_op("_image_random_flip_top_bottom", random_flip_tb_maker,
                use_jit=False, differentiable=False)

    # ---- resize / crop (HWC) ---------------------------------------------
    def resize_maker(size=0, keep_ratio=False, interp=1):
        def fn(x):
            batch = _is_batch(x)
            hh, ww = (x.shape[1], x.shape[2]) if batch \
                else (x.shape[0], x.shape[1])
            if isinstance(size, (tuple, list)):
                w, h = int(size[0]), int(size[1])
            elif keep_ratio:
                # reference resize-inl.h: scalar size + keep_ratio scales
                # the SHORT edge to size
                scale = int(size) / min(ww, hh)
                w, h = int(round(ww * scale)), int(round(hh * scale))
            else:
                w = h = int(size)
            method = "nearest" if interp == 0 else "linear"
            dtype = x.dtype
            xf = x.astype(jnp.float32)
            shape = (x.shape[0], h, w, x.shape[3]) if batch \
                else (h, w, x.shape[2])
            out = jax.image.resize(xf, shape, method=method)
            return out.astype(dtype) if dtype != jnp.float32 else out
        return fn
    register_op("_image_resize", resize_maker)

    def crop_maker(x=0, y=0, width=0, height=0):
        from ..base import MXNetError

        def fn(data):
            hh, ww = (data.shape[1], data.shape[2]) if _is_batch(data) \
                else (data.shape[0], data.shape[1])
            if x < 0 or y < 0 or width <= 0 or height <= 0 \
                    or x + width > ww or y + height > hh:
                raise MXNetError(
                    f"crop window ({x},{y},{width},{height}) outside "
                    f"image ({hh}x{ww})")
            if _is_batch(data):
                return data[:, y:y + height, x:x + width, :]
            return data[y:y + height, x:x + width, :]
        return fn
    register_op("_image_crop", crop_maker, use_jit=False)

    # ---- photometric (reference image_random.cc semantics) ---------------
    def adjust_lighting_maker(alpha=()):
        from ..base import MXNetError
        a = _np.asarray(alpha, _np.float32)

        def fn(x):
            if not jnp.issubdtype(x.dtype, jnp.floating):
                raise MXNetError(
                    "adjust_lighting requires float input (the PCA delta "
                    "is signed; integer wraparound would corrupt pixels)")
            delta = (LIGHTING_EIGVEC * a * LIGHTING_EIGVAL).sum(axis=1)
            return x + jnp.asarray(delta, x.dtype)
        return fn
    register_op("_image_adjust_lighting", adjust_lighting_maker,
                use_jit=False)

    def random_brightness_maker(min_factor=0.0, max_factor=0.0):
        def fn(x):
            return x * _host_uniform(min_factor, max_factor)
        return fn
    register_op("_image_random_brightness", random_brightness_maker,
                use_jit=False, differentiable=False)

    def random_contrast_maker(min_factor=0.0, max_factor=0.0):
        def fn(x):
            f = _host_uniform(min_factor, max_factor)
            coef = jnp.asarray(LUMA, x.dtype)
            gray_mean = jnp.mean(jnp.sum(x * coef, axis=-1, keepdims=True),
                                 axis=(-3, -2), keepdims=True)
            return x * f + gray_mean * (1.0 - f)
        return fn
    register_op("_image_random_contrast", random_contrast_maker,
                use_jit=False, differentiable=False)

    def random_saturation_maker(min_factor=0.0, max_factor=0.0):
        def fn(x):
            f = _host_uniform(min_factor, max_factor)
            coef = jnp.asarray(LUMA, x.dtype)
            gray = jnp.sum(x * coef, axis=-1, keepdims=True)
            return x * f + gray * (1.0 - f)
        return fn
    register_op("_image_random_saturation", random_saturation_maker,
                use_jit=False, differentiable=False)

    def random_hue_maker(min_factor=0.0, max_factor=0.0):
        def fn(x):
            # the reference's YIQ rotation (image_random-inl.h RandomHue)
            m = hue_rotation_matrix(_host_uniform(min_factor, max_factor))
            return jnp.einsum("...c,dc->...d", x, jnp.asarray(m, x.dtype))
        return fn
    register_op("_image_random_hue", random_hue_maker,
                use_jit=False, differentiable=False)


_register()
