"""``mx.nd.contrib``: frontends for the _contrib_* ops plus the control-flow
operators (reference: python/mxnet/ndarray/contrib.py — generated wrappers +
foreach/while_loop/cond, src/operator/control_flow.cc).

Control flow is where the reference and XLA agree most deeply: the
reference added foreach/while_loop/cond precisely so RNNs could run inside
one graph; here they ARE ``lax.scan`` / ``lax.while_loop`` / ``lax.cond``,
the structured-control-flow primitives jit requires (SURVEY.md build rules:
no data-dependent Python control flow under jit).
"""
from __future__ import annotations

import sys as _sys

from .register import _registry, make_frontend

_PREFIX = "_contrib_"
_mod = _sys.modules[__name__]

for _name, _op in list(_registry.items()):
    if _name.startswith(_PREFIX):
        setattr(_mod, _name[len(_PREFIX):], make_frontend(_op))


def _to_vals(x):
    from .ndarray import NDArray
    if isinstance(x, NDArray):
        return x._read()
    if isinstance(x, (list, tuple)):
        return type(x)(_to_vals(v) for v in x)
    return x


def _to_nds(x, ctx):
    import jax
    from .ndarray import NDArray
    if isinstance(x, jax.Array):
        return NDArray(x, ctx=ctx)
    if isinstance(x, (list, tuple)):
        return type(x)(_to_nds(v, ctx) for v in x)
    return x


def _ctx_of(*xs):
    from .ndarray import NDArray
    from ..context import current_context
    for x in xs:
        if isinstance(x, NDArray):
            return x.context
        if isinstance(x, (list, tuple)):
            c = _ctx_of(*x)
            if c is not None:
                return c
    return current_context()


def rand_zipfian(true_classes, num_sampled, range_max):
    """Sampled-softmax candidate sampler (reference:
    python/mxnet/ndarray/contrib.py rand_zipfian over
    _sample_unique_zipfian): draws ``num_sampled`` unique classes from
    Zipf(range_max) and returns (samples, expected_count_true,
    expected_count_sampled) — the expected counts make the sampled
    softmax an unbiased estimator (log-uniform class prior
    p(c) = log((c+2)/(c+1)) / log(range_max+1))."""
    import numpy as np

    from .register import invoke_by_name
    from .ndarray import array as nd_array

    ctx = true_classes.context
    # ctx as an op attr: invoke() honors it for zero-input ops, so ALL
    # three outputs share true_classes' context (reference contract)
    samples, num_tries = invoke_by_name(
        "_sample_unique_zipfian", [],
        {"range_max": int(range_max), "shape": (1, int(num_sampled)),
         "ctx": ctx})
    samples = samples.reshape((int(num_sampled),))
    tries = float(num_tries.asnumpy()[0])
    log_rm1 = np.log(float(range_max) + 1.0)
    sv = samples.asnumpy().astype(np.float64)
    p_sampled = np.log((sv + 2.0) / (sv + 1.0)) / log_rm1
    tv = true_classes.asnumpy().astype(np.float64)
    p_true = np.log((tv + 2.0) / (tv + 1.0)) / log_rm1
    return (samples,
            nd_array((p_true * tries).astype(np.float32), ctx=ctx),
            nd_array((p_sampled * tries).astype(np.float32), ctx=ctx))


def _recording():
    from .. import autograd as _ag
    return _ag.is_recording()


def _stack0(rows):
    """Stack a list of per-step NDArray results along a new axis 0."""
    from .register import invoke_by_name
    return invoke_by_name("stack", rows, {"axis": 0})


def _probe_step_shapes(func, lv_vals, ctx):
    """Abstract-probe ``func``'s step outputs WITHOUT executing it (the body
    must not run when the loop never executes — jax.eval_shape traces with
    avals only).  Recording is paused so the trace leaves no tape nodes.
    Returns (list_of_ShapeDtypeStructs, outputs_were_single)."""
    import jax
    from .. import autograd as _ag
    single = [True]

    def _probe(*vals):
        outs, _ = func(*[_to_nds(v, ctx) for v in vals])
        ovals = _to_vals(outs)
        single[0] = not isinstance(ovals, (list, tuple))
        return [ovals] if single[0] else list(ovals)

    with _ag.pause():
        avals = jax.eval_shape(_probe, *lv_vals)
    return avals, single[0]


def foreach(body, data, init_states):
    """Run ``body(x_t, states) -> (out_t, states)`` over axis 0 of data —
    the reference's foreach (≡ lax.scan).  Returns (stacked_outs, states).

    Under ``autograd.record()`` this unrolls as a Python loop — exactly the
    reference's ndarray-mode foreach (python/mxnet/ndarray/contrib.py is a
    for loop) — so the tape sees every inner op and gradients flow to loop
    inputs AND closure-captured parameters.  Outside recording it is one
    fused ``lax.scan``.
    """
    import jax
    from .ndarray import NDArray
    ctx = _ctx_of(data, init_states)

    # zero-length data: the fused scan still yields correctly-shaped
    # (0, ...) outputs (scan traces the body abstractly); there is nothing
    # for the tape to record, so the fused path is right even when recording
    n_steps = (data.shape[0] if isinstance(data, NDArray)
               else list(data)[0].shape[0])
    if _recording() and n_steps > 0:
        data_single = isinstance(data, NDArray)
        data_list = [data] if data_single else list(data)
        n = n_steps
        states = init_states
        out_rows = None
        for t in range(n):
            xt = data_list[0][t] if data_single else [d[t] for d in data_list]
            outs, states = body(xt, states)
            outs_list = [outs] if isinstance(outs, NDArray) else list(outs)
            if out_rows is None:
                out_rows = [[] for _ in outs_list]
            for acc, o in zip(out_rows, outs_list):
                acc.append(o)
        stacked = [_stack0(acc) for acc in (out_rows or [])]
        single_out = out_rows is not None and not isinstance(outs, (list, tuple))
        return (stacked[0] if single_out else stacked), states

    def step(carry, x):
        outs, new_states = body(_to_nds(x, ctx), _to_nds(carry, ctx))
        return _to_vals(new_states), _to_vals(outs)

    def _fused():
        carry, ys = jax.lax.scan(step, _to_vals(init_states), _to_vals(data))
        return _to_nds(ys, ctx), _to_nds(carry, ctx)

    if _recording():                    # zero-length case only (see above):
        from .. import autograd as _ag  # trace must leave no tape nodes
        with _ag.pause():
            return _fused()
    return _fused()


def while_loop(cond, func, loop_vars, max_iterations=None):
    """reference: contrib.while_loop.  ``cond(*loop_vars) -> bool``,
    ``func(*loop_vars) -> (step_output, new_loop_vars)``.  To keep shapes
    static (XLA requirement), step outputs are buffered to
    ``max_iterations`` rows; returns (outputs, final_loop_vars)."""
    import jax
    import jax.numpy as jnp
    from ..base import MXNetError
    if max_iterations is None:
        raise MXNetError("while_loop requires max_iterations on TPU "
                         "(static shapes)")
    ctx = _ctx_of(loop_vars)

    if _recording():
        return _while_loop_eager(cond, func, loop_vars, int(max_iterations))
    lv0 = tuple(_to_vals(v) for v in loop_vars)
    probe_avals, single = _probe_step_shapes(func, lv0, ctx)
    bufs0 = tuple(jnp.zeros((max_iterations,) + v.shape, v.dtype)
                  for v in probe_avals)

    def cond_fn(state):
        i, lv, bufs = state
        c = cond(*[_to_nds(v, ctx) for v in lv])
        cval = c._read() if hasattr(c, "_read") else c
        return jnp.logical_and(i < max_iterations,
                               jnp.asarray(cval).reshape(()))

    def body_fn(state):
        i, lv, bufs = state
        outs, new_lv = func(*[_to_nds(v, ctx) for v in lv])
        ovals = _to_vals(outs)
        olist = [ovals] if single else list(ovals)
        bufs = tuple(b.at[i].set(o) for b, o in zip(bufs, olist))
        return (i + 1, tuple(_to_vals(v) for v in new_lv), bufs)

    i, lv, bufs = jax.lax.while_loop(cond_fn, body_fn,
                                     (jnp.asarray(0), lv0, bufs0))
    outs = _to_nds(bufs[0] if single else list(bufs), ctx)
    return outs, [_to_nds(v, ctx) for v in lv]


def _while_loop_eager(cond, func, loop_vars, max_iterations):
    """Reference ndarray-mode while_loop: host-evaluated condition, Python
    loop, tape-visible ops; outputs zero-padded to max_iterations rows so
    shapes match the fused path."""
    import numpy as np
    from .ndarray import NDArray
    from . import zeros as nd_zeros
    from .register import invoke_by_name
    lv = list(loop_vars)
    rows = None
    single = True
    it = 0
    while it < max_iterations and bool(np.asarray(
            cond(*lv).asnumpy()).reshape(())):
        outs, new_lv = func(*lv)
        lv = list(new_lv) if isinstance(new_lv, (list, tuple)) else [new_lv]
        single = isinstance(outs, NDArray)
        outs_list = [outs] if single else list(outs)
        if rows is None:
            rows = [[] for _ in outs_list]
        for acc, o in zip(rows, outs_list):
            acc.append(o)
        it += 1
    if rows is None:
        # zero executed steps: abstract shape probe (the body must not run)
        ctx = _ctx_of(lv)
        avals, single = _probe_step_shapes(
            func, [v._read() for v in lv], ctx)
        bufs = [nd_zeros((max_iterations,) + tuple(a.shape), dtype=a.dtype)
                for a in avals]
        return (bufs[0] if single else bufs), lv
    bufs = []
    for acc in rows:
        stacked = _stack0(acc)
        if it < max_iterations:
            pad = nd_zeros((max_iterations - it,) + acc[0].shape,
                           dtype=acc[0].dtype)
            stacked = invoke_by_name("concat", [stacked, pad],
                                     {"dim": 0})
        bufs.append(stacked)
    return (bufs[0] if single else bufs), lv


def cond(pred, then_func, else_func):
    """reference: contrib.cond ≡ lax.cond (both branches traced once).
    Under autograd recording the predicate is evaluated on the host and
    only the taken branch runs (reference ndarray-mode semantics — the
    tape then differentiates exactly the executed branch)."""
    import jax
    import jax.numpy as jnp
    if _recording():
        import numpy as np
        p = bool(np.asarray(
            pred.asnumpy() if hasattr(pred, "asnumpy") else pred).reshape(()))
        return then_func() if p else else_func()
    p = pred._read() if hasattr(pred, "_read") else pred
    ctx = _ctx_of(pred)

    def mk(fn):
        def wrapped(_):
            return _to_vals(fn())
        return wrapped

    out = jax.lax.cond(jnp.asarray(p).reshape(()).astype(bool),
                       mk(then_func), mk(else_func), operand=None)
    return _to_nds(out, ctx)


# ---- DGL graph-preparation family (host-side CSR ops; see dgl.py) -------
from .dgl import (                                          # noqa: E402
    edge_id, dgl_adjacency, dgl_subgraph, dgl_graph_compact,
    csr_neighbor_uniform_sample as dgl_csr_neighbor_uniform_sample,
    csr_neighbor_non_uniform_sample as dgl_csr_neighbor_non_uniform_sample,
)
