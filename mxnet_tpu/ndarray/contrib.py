"""``mx.nd.contrib``: frontends for the _contrib_* ops (reference:
python/mxnet/ndarray/contrib.py — generated from the registry's contrib
namespace).
"""
from __future__ import annotations

import sys as _sys

from .register import _registry, make_frontend

_PREFIX = "_contrib_"
_mod = _sys.modules[__name__]

for _name, _op in list(_registry.items()):
    if _name.startswith(_PREFIX):
        setattr(_mod, _name[len(_PREFIX):], make_frontend(_op))
