"""``mx.nd.contrib``: frontends for the _contrib_* ops plus the control-flow
operators (reference: python/mxnet/ndarray/contrib.py — generated wrappers +
foreach/while_loop/cond, src/operator/control_flow.cc).

Control flow is where the reference and XLA agree most deeply: the
reference added foreach/while_loop/cond precisely so RNNs could run inside
one graph; here they ARE ``lax.scan`` / ``lax.while_loop`` / ``lax.cond``,
the structured-control-flow primitives jit requires (SURVEY.md build rules:
no data-dependent Python control flow under jit).
"""
from __future__ import annotations

import sys as _sys

from .register import _registry, make_frontend

_PREFIX = "_contrib_"
_mod = _sys.modules[__name__]

for _name, _op in list(_registry.items()):
    if _name.startswith(_PREFIX):
        setattr(_mod, _name[len(_PREFIX):], make_frontend(_op))


def _to_vals(x):
    from .ndarray import NDArray
    if isinstance(x, NDArray):
        return x._read()
    if isinstance(x, (list, tuple)):
        return type(x)(_to_vals(v) for v in x)
    return x


def _to_nds(x, ctx):
    import jax
    from .ndarray import NDArray
    if isinstance(x, jax.Array):
        return NDArray(x, ctx=ctx)
    if isinstance(x, (list, tuple)):
        return type(x)(_to_nds(v, ctx) for v in x)
    return x


def _ctx_of(*xs):
    from .ndarray import NDArray
    from ..context import current_context
    for x in xs:
        if isinstance(x, NDArray):
            return x.context
        if isinstance(x, (list, tuple)):
            c = _ctx_of(*x)
            if c is not None:
                return c
    return current_context()


def foreach(body, data, init_states):
    """Run ``body(x_t, states) -> (out_t, states)`` over axis 0 of data —
    the reference's foreach (≡ lax.scan).  Returns (stacked_outs, states).
    """
    import jax
    from .ndarray import NDArray
    ctx = _ctx_of(data, init_states)

    def step(carry, x):
        outs, new_states = body(_to_nds(x, ctx), _to_nds(carry, ctx))
        return _to_vals(new_states), _to_vals(outs)

    carry, ys = jax.lax.scan(step, _to_vals(init_states), _to_vals(data))
    return _to_nds(ys, ctx), _to_nds(carry, ctx)


def while_loop(cond, func, loop_vars, max_iterations=None):
    """reference: contrib.while_loop.  ``cond(*loop_vars) -> bool``,
    ``func(*loop_vars) -> (step_output, new_loop_vars)``.  To keep shapes
    static (XLA requirement), step outputs are buffered to
    ``max_iterations`` rows; returns (outputs, final_loop_vars)."""
    import jax
    import jax.numpy as jnp
    from ..base import MXNetError
    if max_iterations is None:
        raise MXNetError("while_loop requires max_iterations on TPU "
                         "(static shapes)")
    ctx = _ctx_of(loop_vars)
    lv0 = tuple(_to_vals(v) for v in loop_vars)

    # abstract shape probe: trace func without executing it (the body must
    # not run — or run twice — when cond is initially false)
    _single = [True]

    def _probe(*vals):
        outs, _ = func(*[_to_nds(v, ctx) for v in vals])
        ovals = _to_vals(outs)
        _single[0] = not isinstance(ovals, (list, tuple))
        return [ovals] if _single[0] else list(ovals)

    probe_avals = jax.eval_shape(_probe, *lv0)
    single = _single[0]
    bufs0 = tuple(jnp.zeros((max_iterations,) + v.shape, v.dtype)
                  for v in probe_avals)

    def cond_fn(state):
        i, lv, bufs = state
        c = cond(*[_to_nds(v, ctx) for v in lv])
        cval = c._read() if hasattr(c, "_read") else c
        return jnp.logical_and(i < max_iterations,
                               jnp.asarray(cval).reshape(()))

    def body_fn(state):
        i, lv, bufs = state
        outs, new_lv = func(*[_to_nds(v, ctx) for v in lv])
        ovals = _to_vals(outs)
        olist = [ovals] if single else list(ovals)
        bufs = tuple(b.at[i].set(o) for b, o in zip(bufs, olist))
        return (i + 1, tuple(_to_vals(v) for v in new_lv), bufs)

    i, lv, bufs = jax.lax.while_loop(cond_fn, body_fn,
                                     (jnp.asarray(0), lv0, bufs0))
    outs = _to_nds(bufs[0] if single else list(bufs), ctx)
    return outs, [_to_nds(v, ctx) for v in lv]


def cond(pred, then_func, else_func):
    """reference: contrib.cond ≡ lax.cond (both branches traced once)."""
    import jax
    import jax.numpy as jnp
    p = pred._read() if hasattr(pred, "_read") else pred
    ctx = _ctx_of(pred)

    def mk(fn):
        def wrapped(_):
            return _to_vals(fn())
        return wrapped

    out = jax.lax.cond(jnp.asarray(p).reshape(()).astype(bool),
                       mk(then_func), mk(else_func), operand=None)
    return _to_nds(out, ctx)
