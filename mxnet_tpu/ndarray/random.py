"""Sampling frontends: ``mx.nd.random.*`` / ``mx.nd.random_*``.

Reference parity: src/operator/random/sample_op.cc (SURVEY.md §2.2) — the
same distributions (uniform/normal/gamma/exponential/poisson/negative
binomial/randint/multinomial), with shapes/dtypes/ctx semantics of the
reference frontends.  Every draw routes through the ``_random_*`` /
``_sample_*`` registry ops (ops_random.py) — the same ops the symbol
frontends and the C ABI dispatch — with the PRNG key split off the
process-global stream (mxnet_tpu.random) and passed as the op's last
input.  Draws are not differentiable (as in the reference).
"""
from __future__ import annotations

import numpy as _np

from ..context import current_context
from .. import random as _grandom
from .ndarray import NDArray
from .register import invoke_by_name

__all__ = ["uniform", "normal", "randn", "randint", "exponential", "gamma",
           "poisson", "negative_binomial", "generalized_negative_binomial",
           "multinomial", "shuffle", "bernoulli"]


from .ops_random import _canon_shape as _shape_attr  # shared rule


def _dtype_attr(dtype):
    """Canonical string form for the op's dtype attribute."""
    return dtype if isinstance(dtype, str) else str(_np.dtype(dtype))


def _is_tensor_param(p):
    import jax
    return isinstance(p, (NDArray, _np.ndarray, list, jax.Array))


def _dispatch(scalar_op, sample_op, params, names, shape, dtype, ctx, out,
              **scalar_extra):
    """Reference frontend rule (python/mxnet/ndarray/random.py
    _random_helper): all-scalar parameters -> the ``_random_*`` op;
    tensor parameters -> the per-element ``_sample_*`` op (output shape
    = param shape + draw shape)."""
    if any(_is_tensor_param(p) for p in params):
        return _sample(sample_op, list(params), shape, dtype, out=out)
    kw = dict(zip(names, (float(p) for p in params)))
    kw.update(scalar_extra)
    return _draw(scalar_op, shape, dtype, ctx, out, **kw)


def _draw(op_name, shape, dtype, ctx, out, **params):
    attrs = {"shape": _shape_attr(shape), **params}
    # always pin the device (nd.zeros places on current_context() too) —
    # otherwise the buffer would land on jax's default device while the
    # NDArray is tagged with the current context
    attrs["ctx"] = str(ctx if ctx is not None else current_context())
    if dtype is not None:
        attrs["dtype"] = _dtype_attr(dtype)
    return invoke_by_name(op_name, [], attrs, out=out)


def uniform(low=0.0, high=1.0, shape=None, dtype=None, ctx=None, out=None,
            **kwargs):
    return _dispatch("_random_uniform", "_sample_uniform", [low, high],
                     ("low", "high"), shape, dtype, ctx, out)


def normal(loc=0.0, scale=1.0, shape=None, dtype=None, ctx=None, out=None,
           **kwargs):
    return _dispatch("_random_normal", "_sample_normal", [loc, scale],
                     ("loc", "scale"), shape, dtype, ctx, out)


def randn(*shape, loc=0.0, scale=1.0, dtype=None, ctx=None, **kwargs):
    return normal(loc=loc, scale=scale, shape=shape or (1,), dtype=dtype,
                  ctx=ctx)


def randint(low, high, shape=None, dtype="int32", ctx=None, out=None,
            **kwargs):
    return _draw("_random_randint", shape, dtype, ctx, out,
                 low=int(low), high=int(high))


def exponential(scale=1.0, shape=None, dtype=None, ctx=None, out=None,
                **kwargs):
    if _is_tensor_param(scale):
        lam = (1.0 / scale) if isinstance(scale, NDArray) \
            else 1.0 / _np.asarray(scale, _np.float32)
        return _sample("_sample_exponential", [lam], shape, dtype, out=out)
    return _draw("_random_exponential", shape, dtype, ctx, out,
                 scale=float(scale))


def gamma(alpha=1.0, beta=1.0, shape=None, dtype=None, ctx=None, out=None,
          **kwargs):
    return _dispatch("_random_gamma", "_sample_gamma", [alpha, beta],
                     ("alpha", "beta"), shape, dtype, ctx, out)


def poisson(lam=1.0, shape=None, dtype=None, ctx=None, out=None, **kwargs):
    return _dispatch("_random_poisson", "_sample_poisson", [lam],
                     ("lam",), shape, dtype, ctx, out)


def negative_binomial(k=1, p=1.0, shape=None, dtype=None, ctx=None,
                      out=None, **kwargs):
    return _dispatch("_random_negative_binomial",
                     "_sample_negative_binomial", [k, p], ("k", "p"),
                     shape, dtype, ctx, out)


def generalized_negative_binomial(mu=1.0, alpha=1.0, shape=None, dtype=None,
                                  ctx=None, out=None, **kwargs):
    return _dispatch("_random_generalized_negative_binomial",
                     "_sample_generalized_negative_binomial", [mu, alpha],
                     ("mu", "alpha"), shape, dtype, ctx, out)


def multinomial(data, shape=None, get_prob=False, dtype="int32", **kwargs):
    """Sample category indices from (batched) probability rows."""
    attrs = {"get_prob": bool(get_prob), "dtype": dtype}
    if shape is not None:
        attrs["shape"] = shape if isinstance(shape, int) else tuple(shape)
    return invoke_by_name("_sample_multinomial", [data], attrs)


def shuffle(data, **kwargs):
    return invoke_by_name("_shuffle", [data], {})


def bernoulli(prob=0.5, shape=None, dtype=None, ctx=None, **kwargs):
    # not a reference 1.x op; kept as a convenience frontend
    import jax
    import jax.random as jr
    from ..base import dtype_np
    ctx = ctx if ctx is not None else current_context()
    shape = _shape_attr(shape)
    val = jr.bernoulli(_grandom.next_key(), prob, shape).astype(
        dtype_np(dtype))
    return NDArray(jax.device_put(val, ctx.device), ctx=ctx)


# ---------------------------------------------------------------------------
# sample_* frontends: per-element distribution parameters
# (reference: src/operator/random/multisample_op.cc — params shape s,
# output s + shape, one draw block per parameter element)
# ---------------------------------------------------------------------------

def _sample(op_name, params, shape, dtype, out=None, **extra):
    arrs = [p if isinstance(p, NDArray) else _np.asarray(p, _np.float32)
            for p in params]
    attrs = dict(extra)
    if shape is not None:
        attrs["shape"] = shape if isinstance(shape, int) else tuple(shape)
    if dtype is not None:
        attrs["dtype"] = _dtype_attr(dtype)
    return invoke_by_name(op_name, arrs, attrs, out=out)


def sample_uniform(low, high, shape=None, dtype=None, **kwargs):
    return _sample("_sample_uniform", [low, high], shape, dtype)


def sample_normal(mu, sigma, shape=None, dtype=None, **kwargs):
    return _sample("_sample_normal", [mu, sigma], shape, dtype)


def sample_gamma(alpha, beta, shape=None, dtype=None, **kwargs):
    return _sample("_sample_gamma", [alpha, beta], shape, dtype)


def sample_exponential(lam, shape=None, dtype=None, **kwargs):
    return _sample("_sample_exponential", [lam], shape, dtype)


def sample_poisson(lam, shape=None, dtype=None, **kwargs):
    return _sample("_sample_poisson", [lam], shape, dtype)


def sample_negative_binomial(k, p, shape=None, dtype=None, **kwargs):
    return _sample("_sample_negative_binomial", [k, p], shape, dtype)


def sample_generalized_negative_binomial(mu, alpha, shape=None, dtype=None,
                                         **kwargs):
    return _sample("_sample_generalized_negative_binomial", [mu, alpha],
                   shape, dtype)


def sample_multinomial(data, shape=None, get_prob=False, dtype="int32",
                       **kwargs):
    """Batched multinomial: data (..., k) probability rows."""
    return multinomial(data, shape=shape, get_prob=get_prob, dtype=dtype,
                       **kwargs)


__all__ += ["sample_uniform", "sample_normal", "sample_gamma",
            "sample_exponential", "sample_poisson",
            "sample_negative_binomial",
            "sample_generalized_negative_binomial", "sample_multinomial"]


# ---------------------------------------------------------------------------
# *_like draws (reference: sample_op.cc *_like variants — shape/ctx/dtype
# follow the input array)
# ---------------------------------------------------------------------------

def _like(op_name, data, out=None, dtype=None, **params):
    if dtype is not None:
        params["dtype"] = _dtype_attr(dtype)
    return invoke_by_name(op_name, [data], params, out=out)


def uniform_like(data, low=0.0, high=1.0, dtype=None, out=None, **kwargs):
    return _like("_random_uniform_like", data, out=out, dtype=dtype,
                 low=float(low), high=float(high))


def normal_like(data, loc=0.0, scale=1.0, dtype=None, out=None, **kwargs):
    return _like("_random_normal_like", data, out=out, dtype=dtype,
                 loc=float(loc), scale=float(scale))


def gamma_like(data, alpha=1.0, beta=1.0, dtype=None, out=None, **kwargs):
    return _like("_random_gamma_like", data, out=out, dtype=dtype,
                 alpha=float(alpha), beta=float(beta))


def exponential_like(data, lam=1.0, dtype=None, out=None, **kwargs):
    return _like("_random_exponential_like", data, out=out, dtype=dtype,
                 lam=float(lam))


def poisson_like(data, lam=1.0, dtype=None, out=None, **kwargs):
    return _like("_random_poisson_like", data, out=out, dtype=dtype,
                 lam=float(lam))


def randint_like(data, low=0, high=10, dtype="int32", out=None, **kwargs):
    return _draw("_random_randint", data.shape, dtype, data.context, out,
                 low=int(low), high=int(high))


__all__ += ["uniform_like", "normal_like", "gamma_like",
            "exponential_like", "poisson_like", "randint_like"]
