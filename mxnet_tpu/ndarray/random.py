"""Sampling frontends: ``mx.nd.random.*`` / ``mx.nd.random_*``.

Reference parity: src/operator/random/sample_op.cc (SURVEY.md §2.2) — the
same distributions (uniform/normal/gamma/exponential/poisson/negative
binomial/randint/multinomial), with shapes/dtypes/ctx semantics of the
reference frontends.  Keys come from the process-global stream in
mxnet_tpu.random; draws are not differentiable (as in the reference).
"""
from __future__ import annotations

import numpy as _np

from ..base import dtype_np
from ..context import current_context
from .. import random as _grandom
from .ndarray import NDArray

__all__ = ["uniform", "normal", "randn", "randint", "exponential", "gamma",
           "poisson", "negative_binomial", "generalized_negative_binomial",
           "multinomial", "shuffle", "bernoulli"]


def _prep(shape, ctx, dtype):
    import jax
    ctx = ctx if ctx is not None else current_context()
    if shape is None:
        shape = (1,)
    if isinstance(shape, int):
        shape = (shape,)
    return tuple(shape), ctx, dtype_np(dtype)


def _wrap(val, ctx):
    import jax
    return NDArray(jax.device_put(val, ctx.device), ctx=ctx)


def uniform(low=0.0, high=1.0, shape=None, dtype=None, ctx=None, out=None,
            **kwargs):
    import jax.random as jr
    shape, ctx, dt = _prep(shape, ctx, dtype)
    val = jr.uniform(_grandom.next_key(), shape, dt, low, high)
    r = _wrap(val, ctx)
    if out is not None:
        out._set_data(r._read())
        return out
    return r


def normal(loc=0.0, scale=1.0, shape=None, dtype=None, ctx=None, out=None,
           **kwargs):
    import jax.random as jr
    shape, ctx, dt = _prep(shape, ctx, dtype)
    val = jr.normal(_grandom.next_key(), shape, dt) * scale + loc
    r = _wrap(val, ctx)
    if out is not None:
        out._set_data(r._read())
        return out
    return r


def randn(*shape, loc=0.0, scale=1.0, dtype=None, ctx=None, **kwargs):
    return normal(loc=loc, scale=scale, shape=shape or (1,), dtype=dtype,
                  ctx=ctx)


def randint(low, high, shape=None, dtype="int32", ctx=None, out=None,
            **kwargs):
    import jax.random as jr
    shape, ctx, dt = _prep(shape, ctx, dtype)
    val = jr.randint(_grandom.next_key(), shape, int(low), int(high), dt)
    r = _wrap(val, ctx)
    if out is not None:
        out._set_data(r._read())
        return out
    return r


def exponential(scale=1.0, shape=None, dtype=None, ctx=None, out=None,
                **kwargs):
    import jax.random as jr
    shape, ctx, dt = _prep(shape, ctx, dtype)
    val = jr.exponential(_grandom.next_key(), shape, dt) * scale
    r = _wrap(val, ctx)
    if out is not None:
        out._set_data(r._read())
        return out
    return r


def gamma(alpha=1.0, beta=1.0, shape=None, dtype=None, ctx=None, out=None,
          **kwargs):
    import jax.random as jr
    import jax.numpy as jnp
    shape, ctx, dt = _prep(shape, ctx, dtype)
    a = jnp.asarray(alpha, dt)
    val = jr.gamma(_grandom.next_key(), a, shape, dt) * beta
    r = _wrap(val, ctx)
    if out is not None:
        out._set_data(r._read())
        return out
    return r


def poisson(lam=1.0, shape=None, dtype=None, ctx=None, out=None, **kwargs):
    import jax.random as jr
    shape, ctx, dt = _prep(shape, ctx, dtype)
    val = jr.poisson(_grandom.next_key(), lam, shape).astype(dt)
    r = _wrap(val, ctx)
    if out is not None:
        out._set_data(r._read())
        return out
    return r


def negative_binomial(k=1, p=1.0, shape=None, dtype=None, ctx=None,
                      out=None, **kwargs):
    import jax.random as jr
    import jax.numpy as jnp
    shape, ctx, dt = _prep(shape, ctx, dtype)
    # NB(k, p) = Poisson(Gamma(k, (1-p)/p))
    g = jr.gamma(_grandom.next_key(), jnp.asarray(float(k), jnp.float32),
                 shape) * ((1.0 - p) / p)
    val = jr.poisson(_grandom.next_key(), g, shape).astype(dt)
    return _wrap(val, ctx)


def generalized_negative_binomial(mu=1.0, alpha=1.0, shape=None, dtype=None,
                                  ctx=None, out=None, **kwargs):
    import jax.random as jr
    import jax.numpy as jnp
    shape, ctx, dt = _prep(shape, ctx, dtype)
    k = 1.0 / alpha
    p = k / (k + mu)
    g = jr.gamma(_grandom.next_key(), jnp.asarray(k, jnp.float32),
                 shape) * ((1.0 - p) / p)
    val = jr.poisson(_grandom.next_key(), g, shape).astype(dt)
    return _wrap(val, ctx)


def multinomial(data, shape=None, get_prob=False, dtype="int32", **kwargs):
    """Sample category indices from (batched) probability rows."""
    import jax.random as jr
    import jax.numpy as jnp
    n = 1 if shape is None else (shape if isinstance(shape, int)
                                 else int(_np.prod(shape)))
    p = data._read()
    logits = jnp.log(jnp.maximum(p, 1e-30))
    if p.ndim == 1:
        out_shape = (n,)
        samples = jr.categorical(_grandom.next_key(), logits, shape=(n,))
    else:
        samples = jr.categorical(_grandom.next_key(), logits[:, None, :],
                                 axis=-1, shape=(p.shape[0], n))
        out_shape = (p.shape[0], n)
    val = samples.reshape(out_shape).astype(dtype_np(dtype))
    if shape is None:
        val = val.reshape(val.shape[:-1] + ()) if p.ndim == 1 else \
            val.reshape((p.shape[0],))
        if p.ndim == 1:
            val = val.reshape(())
    r = _wrap(val, data.context)
    if get_prob:
        lp = jnp.take_along_axis(
            jnp.log(jnp.maximum(p, 1e-30)).reshape(-1, p.shape[-1]),
            val.reshape(-1, 1).astype(jnp.int32), axis=-1)
        return r, _wrap(lp.reshape(val.shape), data.context)
    return r


def shuffle(data, **kwargs):
    import jax.random as jr
    val = data._read()
    perm = jr.permutation(_grandom.next_key(), val.shape[0])
    return _wrap(val[perm], data.context)


def bernoulli(prob=0.5, shape=None, dtype=None, ctx=None, **kwargs):
    import jax.random as jr
    shape, ctx, dt = _prep(shape, ctx, dtype)
    val = jr.bernoulli(_grandom.next_key(), prob, shape).astype(dt)
    return _wrap(val, ctx)
