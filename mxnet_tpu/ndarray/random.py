"""Sampling frontends: ``mx.nd.random.*`` / ``mx.nd.random_*``.

Reference parity: src/operator/random/sample_op.cc (SURVEY.md §2.2) — the
same distributions (uniform/normal/gamma/exponential/poisson/negative
binomial/randint/multinomial), with shapes/dtypes/ctx semantics of the
reference frontends.  Keys come from the process-global stream in
mxnet_tpu.random; draws are not differentiable (as in the reference).
"""
from __future__ import annotations

import numpy as _np

from ..base import dtype_np
from ..context import current_context
from .. import random as _grandom
from .ndarray import NDArray

__all__ = ["uniform", "normal", "randn", "randint", "exponential", "gamma",
           "poisson", "negative_binomial", "generalized_negative_binomial",
           "multinomial", "shuffle", "bernoulli"]


def _prep(shape, ctx, dtype):
    import jax
    ctx = ctx if ctx is not None else current_context()
    if shape is None:
        shape = (1,)
    if isinstance(shape, int):
        shape = (shape,)
    return tuple(shape), ctx, dtype_np(dtype)


def _wrap(val, ctx):
    import jax
    return NDArray(jax.device_put(val, ctx.device), ctx=ctx)


def uniform(low=0.0, high=1.0, shape=None, dtype=None, ctx=None, out=None,
            **kwargs):
    import jax.random as jr
    shape, ctx, dt = _prep(shape, ctx, dtype)
    val = jr.uniform(_grandom.next_key(), shape, dt, low, high)
    r = _wrap(val, ctx)
    if out is not None:
        out._set_data(r._read())
        return out
    return r


def normal(loc=0.0, scale=1.0, shape=None, dtype=None, ctx=None, out=None,
           **kwargs):
    import jax.random as jr
    shape, ctx, dt = _prep(shape, ctx, dtype)
    val = jr.normal(_grandom.next_key(), shape, dt) * scale + loc
    r = _wrap(val, ctx)
    if out is not None:
        out._set_data(r._read())
        return out
    return r


def randn(*shape, loc=0.0, scale=1.0, dtype=None, ctx=None, **kwargs):
    return normal(loc=loc, scale=scale, shape=shape or (1,), dtype=dtype,
                  ctx=ctx)


def randint(low, high, shape=None, dtype="int32", ctx=None, out=None,
            **kwargs):
    import jax.random as jr
    shape, ctx, dt = _prep(shape, ctx, dtype)
    val = jr.randint(_grandom.next_key(), shape, int(low), int(high), dt)
    r = _wrap(val, ctx)
    if out is not None:
        out._set_data(r._read())
        return out
    return r


def exponential(scale=1.0, shape=None, dtype=None, ctx=None, out=None,
                **kwargs):
    import jax.random as jr
    shape, ctx, dt = _prep(shape, ctx, dtype)
    val = jr.exponential(_grandom.next_key(), shape, dt) * scale
    r = _wrap(val, ctx)
    if out is not None:
        out._set_data(r._read())
        return out
    return r


def gamma(alpha=1.0, beta=1.0, shape=None, dtype=None, ctx=None, out=None,
          **kwargs):
    import jax.random as jr
    import jax.numpy as jnp
    shape, ctx, dt = _prep(shape, ctx, dtype)
    a = jnp.asarray(alpha, dt)
    val = jr.gamma(_grandom.next_key(), a, shape, dt) * beta
    r = _wrap(val, ctx)
    if out is not None:
        out._set_data(r._read())
        return out
    return r


def poisson(lam=1.0, shape=None, dtype=None, ctx=None, out=None, **kwargs):
    import jax.random as jr
    shape, ctx, dt = _prep(shape, ctx, dtype)
    val = jr.poisson(_grandom.next_key(), lam, shape).astype(dt)
    r = _wrap(val, ctx)
    if out is not None:
        out._set_data(r._read())
        return out
    return r


def negative_binomial(k=1, p=1.0, shape=None, dtype=None, ctx=None,
                      out=None, **kwargs):
    import jax.random as jr
    import jax.numpy as jnp
    shape, ctx, dt = _prep(shape, ctx, dtype)
    # NB(k, p) = Poisson(Gamma(k, (1-p)/p))
    g = jr.gamma(_grandom.next_key(), jnp.asarray(float(k), jnp.float32),
                 shape) * ((1.0 - p) / p)
    val = jr.poisson(_grandom.next_key(), g, shape).astype(dt)
    return _wrap(val, ctx)


def generalized_negative_binomial(mu=1.0, alpha=1.0, shape=None, dtype=None,
                                  ctx=None, out=None, **kwargs):
    import jax.random as jr
    import jax.numpy as jnp
    shape, ctx, dt = _prep(shape, ctx, dtype)
    k = 1.0 / alpha
    p = k / (k + mu)
    g = jr.gamma(_grandom.next_key(), jnp.asarray(k, jnp.float32),
                 shape) * ((1.0 - p) / p)
    val = jr.poisson(_grandom.next_key(), g, shape).astype(dt)
    return _wrap(val, ctx)


def multinomial(data, shape=None, get_prob=False, dtype="int32", **kwargs):
    """Sample category indices from (batched) probability rows."""
    import jax.random as jr
    import jax.numpy as jnp
    n = 1 if shape is None else (shape if isinstance(shape, int)
                                 else int(_np.prod(shape)))
    p = data._read()
    logits = jnp.log(jnp.maximum(p, 1e-30))
    if p.ndim == 1:
        out_shape = (n,)
        samples = jr.categorical(_grandom.next_key(), logits, shape=(n,))
    else:
        samples = jr.categorical(_grandom.next_key(), logits[:, None, :],
                                 axis=-1, shape=(p.shape[0], n))
        out_shape = (p.shape[0], n)
    val = samples.reshape(out_shape).astype(dtype_np(dtype))
    if shape is None:
        val = val.reshape(val.shape[:-1] + ()) if p.ndim == 1 else \
            val.reshape((p.shape[0],))
        if p.ndim == 1:
            val = val.reshape(())
    r = _wrap(val, data.context)
    if get_prob:
        lp = jnp.take_along_axis(
            jnp.log(jnp.maximum(p, 1e-30)).reshape(-1, p.shape[-1]),
            val.reshape(-1, 1).astype(jnp.int32), axis=-1)
        return r, _wrap(lp.reshape(val.shape), data.context)
    return r


def shuffle(data, **kwargs):
    import jax.random as jr
    val = data._read()
    perm = jr.permutation(_grandom.next_key(), val.shape[0])
    return _wrap(val[perm], data.context)


def bernoulli(prob=0.5, shape=None, dtype=None, ctx=None, **kwargs):
    import jax.random as jr
    shape, ctx, dt = _prep(shape, ctx, dtype)
    val = jr.bernoulli(_grandom.next_key(), prob, shape).astype(dt)
    return _wrap(val, ctx)


# ---------------------------------------------------------------------------
# sample_* frontends: per-element distribution parameters
# (reference: src/operator/random/multisample_op.cc — params shape s,
# output s + shape, one draw block per parameter element)
# ---------------------------------------------------------------------------

def _sample_params(params, shape):
    """Common prep: read param arrays, broadcast them to a common shape
    (so scalar/array parameter mixes work and every parameter row gets
    its own independent draw block), normalize the draw shape."""
    vals = [p._read() if isinstance(p, NDArray) else _np.asarray(
        p, dtype=_np.float32) for p in params]
    if len(vals) > 1:
        vals = list(_np.broadcast_arrays(*[_np.asarray(v) for v in vals]))
    else:
        vals = [_np.asarray(vals[0])]
    if shape is None:
        shape = ()
    if isinstance(shape, int):
        shape = (shape,)
    ctx = next((p.context for p in params if isinstance(p, NDArray)),
               current_context())
    return vals, tuple(shape), ctx


def _sample_out_shape(pshape, shape):
    return tuple(pshape) + tuple(shape)


def sample_uniform(low, high, shape=None, dtype=None, **kwargs):
    import jax.random as jr
    import jax.numpy as jnp
    (lo, hi), shape, ctx = _sample_params([low, high], shape)
    dt = dtype_np(dtype)
    out_shape = _sample_out_shape(lo.shape, shape)
    u = jr.uniform(_grandom.next_key(), out_shape, dt or _np.float32)
    lo_b = jnp.reshape(lo, lo.shape + (1,) * len(shape))
    hi_b = jnp.reshape(hi, hi.shape + (1,) * len(shape))
    return _wrap((lo_b + u * (hi_b - lo_b)).astype(dt or lo.dtype), ctx)


def sample_normal(mu, sigma, shape=None, dtype=None, **kwargs):
    import jax.random as jr
    import jax.numpy as jnp
    (mu_v, sg), shape, ctx = _sample_params([mu, sigma], shape)
    dt = dtype_np(dtype)
    out_shape = _sample_out_shape(mu_v.shape, shape)
    z = jr.normal(_grandom.next_key(), out_shape, dt or _np.float32)
    mu_b = jnp.reshape(mu_v, mu_v.shape + (1,) * len(shape))
    sg_b = jnp.reshape(sg, sg.shape + (1,) * len(shape))
    return _wrap((mu_b + z * sg_b).astype(dt or mu_v.dtype), ctx)


def sample_gamma(alpha, beta, shape=None, dtype=None, **kwargs):
    import jax.random as jr
    import jax.numpy as jnp
    (al, be), shape, ctx = _sample_params([alpha, beta], shape)
    dt = dtype_np(dtype) or _np.float32
    out_shape = _sample_out_shape(al.shape, shape)
    al_b = jnp.broadcast_to(
        jnp.reshape(al, al.shape + (1,) * len(shape)), out_shape)
    g = jr.gamma(_grandom.next_key(), al_b.astype(dt), out_shape, dt)
    be_b = jnp.reshape(be, be.shape + (1,) * len(shape))
    return _wrap((g * be_b).astype(dt), ctx)   # beta is the scale


def sample_exponential(lam, shape=None, dtype=None, **kwargs):
    import jax.random as jr
    import jax.numpy as jnp
    (lv,), shape, ctx = _sample_params([lam], shape)
    dt = dtype_np(dtype) or _np.float32
    out_shape = _sample_out_shape(lv.shape, shape)
    e = jr.exponential(_grandom.next_key(), out_shape, dt)
    lam_b = jnp.reshape(lv, lv.shape + (1,) * len(shape))
    return _wrap((e / lam_b).astype(dt), ctx)


def sample_poisson(lam, shape=None, dtype=None, **kwargs):
    import jax.random as jr
    import jax.numpy as jnp
    (lv,), shape, ctx = _sample_params([lam], shape)
    dt = dtype_np(dtype) or _np.float32
    out_shape = _sample_out_shape(lv.shape, shape)
    lam_b = jnp.broadcast_to(
        jnp.reshape(lv, lv.shape + (1,) * len(shape)), out_shape)
    p = jr.poisson(_grandom.next_key(), lam_b.astype(_np.float32),
                   out_shape)
    return _wrap(p.astype(dt), ctx)


def sample_negative_binomial(k, p, shape=None, dtype=None, **kwargs):
    import jax.random as jr
    import jax.numpy as jnp
    (kv, pv), shape, ctx = _sample_params([k, p], shape)
    dt = dtype_np(dtype) or _np.float32
    out_shape = _sample_out_shape(kv.shape, shape)
    # NB(k,p) = Poisson(lambda), lambda ~ Gamma(k, (1-p)/p)
    k_b = jnp.broadcast_to(
        jnp.reshape(kv, kv.shape + (1,) * len(shape)), out_shape)
    p_b = jnp.broadcast_to(
        jnp.reshape(pv, pv.shape + (1,) * len(shape)), out_shape)
    g = jr.gamma(_grandom.next_key(), k_b.astype(_np.float32), out_shape)
    lam = g * (1.0 - p_b) / p_b
    draw = jr.poisson(_grandom.next_key(), lam, out_shape)
    return _wrap(draw.astype(dt), ctx)


def sample_generalized_negative_binomial(mu, alpha, shape=None, dtype=None,
                                         **kwargs):
    import jax.numpy as jnp
    (mv, av), shape, ctx = _sample_params([mu, alpha], shape)
    # gnb(mu, alpha) == NB(k=1/alpha, p=1/(1+alpha*mu))
    k = 1.0 / _np.maximum(av, 1e-12)
    p = 1.0 / (1.0 + av * mv)
    return sample_negative_binomial(
        _wrap(jnp.asarray(k), ctx), _wrap(jnp.asarray(p), ctx),
        shape=shape, dtype=dtype)


def sample_multinomial(data, shape=None, get_prob=False, dtype="int32",
                       **kwargs):
    """Batched multinomial: data (..., k) probability rows."""
    return multinomial(data, shape=shape, get_prob=get_prob, dtype=dtype,
                       **kwargs)


__all__ += ["sample_uniform", "sample_normal", "sample_gamma",
            "sample_exponential", "sample_poisson",
            "sample_negative_binomial",
            "sample_generalized_negative_binomial", "sample_multinomial"]


# ---------------------------------------------------------------------------
# *_like draws (reference: sample_op.cc *_like variants — shape/ctx/dtype
# follow the input array)
# ---------------------------------------------------------------------------

def _like(fn, data, dtype=None, out=None, **kw):
    r = fn(shape=data.shape, dtype=dtype or str(data.dtype),
           ctx=data.context, **kw)
    if out is not None:
        out._set_data(r._read())
        return out
    return r


def uniform_like(data, low=0.0, high=1.0, dtype=None, out=None, **kwargs):
    return _like(uniform, data, dtype=dtype, out=out, low=low, high=high)


def normal_like(data, loc=0.0, scale=1.0, dtype=None, out=None, **kwargs):
    return _like(normal, data, dtype=dtype, out=out, loc=loc, scale=scale)


def gamma_like(data, alpha=1.0, beta=1.0, dtype=None, out=None, **kwargs):
    return _like(gamma, data, dtype=dtype, out=out, alpha=alpha, beta=beta)


def exponential_like(data, lam=1.0, dtype=None, out=None, **kwargs):
    return _like(exponential, data, dtype=dtype, out=out, scale=1.0 / lam)


def poisson_like(data, lam=1.0, dtype=None, out=None, **kwargs):
    return _like(poisson, data, dtype=dtype, out=out, lam=lam)


def randint_like(data, low=0, high=10, dtype="int32", out=None, **kwargs):
    r = randint(low, high, shape=data.shape, dtype=dtype,
                ctx=data.context)
    if out is not None:
        out._set_data(r._read())
        return out
    return r


__all__ += ["uniform_like", "normal_like", "gamma_like",
            "exponential_like", "poisson_like", "randint_like"]
