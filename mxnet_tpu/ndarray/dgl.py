"""DGL graph-preparation operators over CSR graphs.

Reference parity: src/operator/contrib/dgl_graph.cc — the graph-sampling
family the DGL integration drives (``edge_id``, ``dgl_adjacency``,
``dgl_subgraph``, ``dgl_graph_compact``, the CSR neighbor samplers).
These are HOST-side prep ops in the reference too (CPU kernels feeding
minibatches to the accelerator); here they run on numpy over
``CSRNDArray`` — the same sparse host plane as sparse.py — because their
output shapes are value-dependent (sampled subgraphs), which XLA cannot
trace.  Exposed as ``mx.nd.contrib.dgl_*`` / ``mx.nd.contrib.edge_id``,
the reference's user-facing surface.

Graph convention (reference dgl_graph.cc): a graph is a square CSR
adjacency whose DATA entries are edge ids; vertices are row/column
indices.
"""
from __future__ import annotations

import numpy as _np

from ..base import MXNetError
from ..sparse import CSRNDArray
from .ndarray import NDArray, array as nd_array

__all__ = ["edge_id", "dgl_adjacency", "dgl_subgraph",
           "dgl_graph_compact", "csr_neighbor_uniform_sample",
           "csr_neighbor_non_uniform_sample"]


def _host_seed() -> int:
    """Fold the framework RNG stream into a host numpy seed, so
    mx.random.seed() reproduces sampling like every other draw."""
    import jax.random as jr
    import numpy as np
    from .. import random as _grandom
    return int(np.asarray(jr.randint(_grandom.next_key(), (), 0,
                                     _np.int32(2 ** 31 - 1))))


def _check_graph(g) -> CSRNDArray:
    if not isinstance(g, CSRNDArray):
        raise MXNetError("DGL graph ops take a CSRNDArray adjacency")
    if g.shape[0] != g.shape[1]:
        raise MXNetError(f"graph CSR must be square, got {g.shape}")
    return g


def edge_id(g, u, v):
    """Edge ids for vertex pairs (u[i], v[i]); -1 where no edge exists
    (reference: _contrib_edge_id)."""
    g = _check_graph(g)
    uu = _np.asarray(u.asnumpy() if hasattr(u, "asnumpy") else u,
                     _np.int64).ravel()
    vv = _np.asarray(v.asnumpy() if hasattr(v, "asnumpy") else v,
                     _np.int64).ravel()
    out = _np.full(uu.shape, -1.0, _np.float32)
    for i, (a, b) in enumerate(zip(uu, vv)):
        lo, hi = g.indptr[a], g.indptr[a + 1]
        cols = g.indices[lo:hi]
        hit = _np.nonzero(cols == b)[0]
        if hit.size:
            out[i] = float(g.data[lo + hit[0]])
    return nd_array(out)


def dgl_adjacency(g):
    """Adjacency with unit edge weights from an edge-id CSR (reference:
    _contrib_dgl_adjacency — used to build the normalized propagation
    matrix; structure is kept, data becomes 1.0)."""
    g = _check_graph(g)
    return CSRNDArray(_np.ones_like(g.data, _np.float32),
                      g.indices.copy(), g.indptr.copy(), g.shape)


def _induced_subgraph(g: CSRNDArray, vids: _np.ndarray,
                      return_mapping: bool, n_rows: int = None):
    """Rows/cols restricted to ``vids`` (order-preserving relabel),
    vectorized (one membership test over the gathered row block — the
    sparse.py host-pass style; this sits on the sampling hot path).
    ``n_rows`` pads the output CSR to a FIXED square size (the
    reference's max_num_vertices layout for sampler outputs)."""
    n = vids.size
    out_n = n if n_rows is None else int(n_rows)
    # gather all selected rows' column/data spans in one pass
    lo, hi = g.indptr[vids], g.indptr[vids + 1]
    counts = hi - lo
    gather = _np.concatenate(
        [_np.arange(a, b) for a, b in zip(lo, hi)]) if n else \
        _np.zeros(0, _np.int64)
    cols_old = g.indices[gather]
    eids_old = g.data[gather]
    row_of = _np.repeat(_np.arange(n), counts)
    # relabel: membership + new index via a parent-sized lookup table
    lut = _np.full(g.shape[1], -1, _np.int64)
    lut[vids] = _np.arange(n)
    new_cols = lut[cols_old]
    keep = new_cols >= 0
    cols = new_cols[keep]
    eids = eids_old[keep]
    rows = row_of[keep]
    indptr = _np.zeros(out_n + 1, _np.int64)
    _np.cumsum(_np.bincount(rows, minlength=out_n), out=indptr[1:])
    sub = CSRNDArray(
        _np.arange(1, cols.size + 1, dtype=_np.float32),
        cols.astype(_np.int64), indptr, (out_n, out_n))
    if not return_mapping:
        return sub, None
    mapping = CSRNDArray(eids.astype(_np.float32),
                         sub.indices.copy(), sub.indptr.copy(),
                         (out_n, out_n))
    return sub, mapping


def dgl_subgraph(g, *vid_arrays, return_mapping: bool = False):
    """Induced subgraph per vertex-id array (reference:
    _contrib_dgl_subgraph).  Returns one relabeled subgraph CSR per
    input array (edge ids renumbered 1..nnz), followed — when
    ``return_mapping`` — by one mapping CSR per array whose data are the
    PARENT edge ids in the same positions."""
    g = _check_graph(g)
    subs, maps = [], []
    for va in vid_arrays:
        vids = _np.asarray(
            va.asnumpy() if hasattr(va, "asnumpy") else va,
            _np.int64).ravel()
        sub, mapping = _induced_subgraph(g, vids, return_mapping)
        subs.append(sub)
        if return_mapping:
            maps.append(mapping)
    return subs + maps


def dgl_graph_compact(*args, return_mapping: bool = False,
                      graph_sizes=None):
    """Remove never-referenced trailing vertex slots from sampled
    subgraphs (reference: _contrib_dgl_graph_compact).  ``graph_sizes``
    gives each input's live vertex count; rows/cols beyond it are
    dropped and edge ids renumbered."""
    if graph_sizes is None:
        raise MXNetError("dgl_graph_compact requires graph_sizes")
    sizes = [int(s) for s in _np.asarray(
        graph_sizes.asnumpy() if hasattr(graph_sizes, "asnumpy")
        else graph_sizes).ravel()]
    if len(sizes) != len(args):
        raise MXNetError("graph_sizes must name one size per graph")
    outs, maps = [], []
    for g, n in zip(args, sizes):
        g = _check_graph(g)
        keep = _np.arange(n, dtype=_np.int64)
        sub, mapping = _induced_subgraph(g, keep, return_mapping)
        outs.append(sub)
        if return_mapping:
            maps.append(mapping)
    return outs + maps


def _neighbor_sample(g: CSRNDArray, seeds, num_hops: int,
                     num_neighbor: int, max_num_vertices: int,
                     probability=None, rng=None):
    rng = rng or _np.random.default_rng()
    seeds = _np.asarray(
        seeds.asnumpy() if hasattr(seeds, "asnumpy") else seeds,
        _np.int64).ravel()
    # the vertex BUDGET covers seeds too: excess seeds are dropped (the
    # caller sized the minibatch; overflowing the fixed layout instead
    # would corrupt the count slot)
    frontier = list(dict.fromkeys(int(s) for s in seeds))[
        :max_num_vertices]
    visited = list(frontier)
    seen = set(frontier)
    for _ in range(num_hops):
        nxt = []
        for u in frontier:
            lo, hi = g.indptr[u], g.indptr[u + 1]
            nbrs = g.indices[lo:hi]
            if nbrs.size == 0:
                continue
            if probability is not None:
                p = probability[nbrs]
                tot = p.sum()
                if tot <= 0:
                    continue
                # cannot draw more distinct neighbors than have mass
                k = min(num_neighbor, int(_np.count_nonzero(p)))
                take = rng.choice(nbrs, size=k, replace=False,
                                  p=p / tot)
            else:
                take = rng.choice(nbrs,
                                  size=min(num_neighbor, nbrs.size),
                                  replace=False)
            for v in take:
                v = int(v)
                if v not in seen and \
                        len(visited) < max_num_vertices:
                    seen.add(v)
                    visited.append(v)
                    nxt.append(v)
        frontier = nxt
        if not frontier:
            break
    vids = _np.asarray(visited, _np.int64)
    # reference layout: the subgraph CSR is FIXED max_num_vertices-square
    # (trailing slots empty — dgl_graph_compact removes them), and the
    # vertex vector is max_num_vertices+1 with the live count LAST
    sub, _ = _induced_subgraph(g, vids, return_mapping=False,
                               n_rows=max_num_vertices)
    padded = _np.full(max_num_vertices + 1, -1, _np.int64)
    padded[:vids.size] = vids
    padded[-1] = vids.size
    return nd_array(padded), sub


def csr_neighbor_uniform_sample(g, *seed_arrays, num_hops: int = 1,
                                num_neighbor: int = 2,
                                max_num_vertices: int = 100):
    """Uniform neighborhood sampling per seed array (reference:
    _contrib_dgl_csr_neighbor_uniform_sample).  Per input: a padded
    vertex vector (live count in the last slot) and the induced sampled
    subgraph CSR."""
    g = _check_graph(g)
    rng = _np.random.default_rng(_host_seed())
    outs = []
    for s in seed_arrays:
        outs.extend(_neighbor_sample(g, s, num_hops, num_neighbor,
                                     max_num_vertices, rng=rng))
    return outs


def csr_neighbor_non_uniform_sample(g, probability, *seed_arrays,
                                    num_hops: int = 1,
                                    num_neighbor: int = 2,
                                    max_num_vertices: int = 100):
    """Importance-weighted variant (reference:
    _contrib_dgl_csr_neighbor_non_uniform_sample): per-vertex
    ``probability`` biases neighbor choice."""
    g = _check_graph(g)
    p = _np.asarray(probability.asnumpy()
                    if hasattr(probability, "asnumpy") else probability,
                    _np.float64).ravel()
    if p.size != g.shape[0]:
        raise MXNetError("probability must have one entry per vertex")
    rng = _np.random.default_rng(_host_seed())
    outs = []
    for s in seed_arrays:
        outs.extend(_neighbor_sample(g, s, num_hops, num_neighbor,
                                     max_num_vertices, probability=p,
                                     rng=rng))
    return outs
