"""Detection operators (reference: src/operator/contrib/ — multibox_prior.cc,
multibox_target.cc, multibox_detection.cc, bounding_box.cc, roi_align.cc;
SURVEY.md §2.2).  These back the GluonCV-style SSD/Mask-RCNN models
(BASELINE config #5).

TPU-native design: every op is static-shaped pad-and-mask — suppressed/
invalid entries are marked (score −1 / label −1) instead of shrinking the
tensor, NMS is a fixed-iteration greedy scan over a topk-pruned candidate
set (`lax.scan`), and ROIAlign is a vmapped gather+bilinear kernel.  No
dynamic shapes ever reach XLA.
"""
from __future__ import annotations

import numpy as _np

from .register import register_op


def _register():
    import jax
    import jax.numpy as jnp
    from jax import lax

    # ---- multibox_prior --------------------------------------------------
    def multibox_prior_maker(sizes=(1.0,), ratios=(1.0,), clip=False,
                             steps=(-1.0, -1.0), offsets=(0.5, 0.5)):
        sizes = tuple(float(s) for s in _astuple(sizes))
        ratios = tuple(float(r) for r in _astuple(ratios))
        steps_ = tuple(float(s) for s in _astuple(steps))
        offs = tuple(float(o) for o in _astuple(offsets))

        def fn(data):
            h, w = data.shape[2], data.shape[3]
            step_y = steps_[0] if steps_[0] > 0 else 1.0 / h
            step_x = steps_[1] if steps_[1] > 0 else 1.0 / w
            cy = (jnp.arange(h, dtype=jnp.float32) + offs[0]) * step_y
            cx = (jnp.arange(w, dtype=jnp.float32) + offs[1]) * step_x
            cyg, cxg = jnp.meshgrid(cy, cx, indexing="ij")
            # anchor set: (sizes[0], every ratio) + (sizes[1:], ratios[0]) —
            # reference ordering: size-ratio pairs (s_i, r_0) first, then
            # (s_0, r_j>0): multibox_prior.cc uses sizes-first enumeration
            whs = []
            for s in sizes:
                r = ratios[0]
                whs.append((s * _np.sqrt(r), s / _np.sqrt(r)))
            for r in ratios[1:]:
                s = sizes[0]
                whs.append((s * _np.sqrt(r), s / _np.sqrt(r)))
            boxes = []
            for bw, bh in whs:
                boxes.append(jnp.stack([cxg - bw / 2, cyg - bh / 2,
                                        cxg + bw / 2, cyg + bh / 2],
                                       axis=-1))
            out = jnp.stack(boxes, axis=2).reshape(1, -1, 4)
            if clip:
                out = jnp.clip(out, 0.0, 1.0)
            return out
        return fn
    register_op("_contrib_MultiBoxPrior", multibox_prior_maker,
                aliases=("MultiBoxPrior", "multibox_prior"))

    # ---- box_iou ---------------------------------------------------------
    def _iou_corner(lhs, rhs):
        """IoU of (..., 4) corner boxes broadcast over leading dims."""
        tl = jnp.maximum(lhs[..., :2], rhs[..., :2])
        br = jnp.minimum(lhs[..., 2:], rhs[..., 2:])
        wh = jnp.clip(br - tl, 0.0)
        inter = wh[..., 0] * wh[..., 1]
        area_l = jnp.clip(lhs[..., 2] - lhs[..., 0], 0.0) * \
            jnp.clip(lhs[..., 3] - lhs[..., 1], 0.0)
        area_r = jnp.clip(rhs[..., 2] - rhs[..., 0], 0.0) * \
            jnp.clip(rhs[..., 3] - rhs[..., 1], 0.0)
        return inter / jnp.maximum(area_l + area_r - inter, 1e-12)

    def box_iou_maker(format="corner"):
        def fn(lhs, rhs):
            if format == "center":
                lhs = _center_to_corner(lhs)
                rhs = _center_to_corner(rhs)
            return _iou_corner(lhs[..., :, None, :], rhs[..., None, :, :])
        return fn

    def _center_to_corner(b):
        x, y, w, h = b[..., 0], b[..., 1], b[..., 2], b[..., 3]
        return jnp.stack([x - w / 2, y - h / 2, x + w / 2, y + h / 2],
                         axis=-1)

    def _corner_to_center(b):
        x1, y1, x2, y2 = b[..., 0], b[..., 1], b[..., 2], b[..., 3]
        return jnp.stack([(x1 + x2) / 2, (y1 + y2) / 2, x2 - x1, y2 - y1],
                         axis=-1)

    register_op("_contrib_box_iou", box_iou_maker,
                aliases=("box_iou",))

    # ---- box_nms ---------------------------------------------------------
    def box_nms_maker(overlap_thresh=0.5, valid_thresh=0.0, topk=-1,
                      coord_start=2, score_index=1, id_index=-1,
                      background_id=-1, force_suppress=False,
                      in_format="corner", out_format="corner"):
        def fn(data):
            # data: (..., N, K); returns same shape, suppressed score = -1
            shape = data.shape
            flat = data.reshape((-1,) + shape[-2:])

            def one(batch):
                n = batch.shape[0]
                scores = batch[:, score_index]
                boxes = batch[:, coord_start:coord_start + 4]
                if in_format == "center":
                    boxes = _center_to_corner(boxes)
                valid = scores > valid_thresh
                if background_id >= 0 and id_index >= 0:
                    valid &= batch[:, id_index] != background_id
                k = n if topk <= 0 else min(int(topk), n)
                order = jnp.argsort(
                    jnp.where(valid, scores, -jnp.inf))[::-1][:k]
                cand_boxes = boxes[order]
                cand_valid = valid[order]
                iou = _iou_corner(cand_boxes[:, None, :],
                                  cand_boxes[None, :, :])
                if not force_suppress and id_index >= 0:
                    ids = batch[order, id_index]
                    same = ids[:, None] == ids[None, :]
                    iou = jnp.where(same, iou, 0.0)

                # greedy: walk candidates best-first; each kept box kills
                # its high-IoU successors (fixed k iterations — jit-safe)
                def step(keep, i):
                    keep_i = keep[i]
                    kill = (iou[i] > overlap_thresh) & \
                        (jnp.arange(k) > i) & keep_i
                    return keep & ~kill, None
                keep0 = cand_valid
                keep, _ = lax.scan(step, keep0, jnp.arange(k))
                # scatter the keep decision back to original positions
                kept_full = jnp.zeros(n, dtype=bool).at[order].set(keep)
                out = batch.at[:, score_index].set(
                    jnp.where(kept_full, scores, -1.0))
                if out_format != in_format:
                    conv = _center_to_corner if out_format == "corner" \
                        else _corner_to_center
                    cs = coord_start
                    out = out.at[:, cs:cs + 4].set(
                        conv(out[:, cs:cs + 4]))
                return out
            out = jax.vmap(one)(flat)
            return out.reshape(shape)
        return fn
    register_op("_contrib_box_nms", box_nms_maker,
                aliases=("box_nms",))

    # ---- multibox_target -------------------------------------------------
    def multibox_target_maker(overlap_threshold=0.5, ignore_label=-1.0,
                              negative_mining_ratio=-1.0,
                              negative_mining_thresh=0.5,
                              minimum_negative_samples=0,
                              variances=(0.1, 0.1, 0.2, 0.2)):
        var = _np.asarray(_astuple(variances), dtype=_np.float32)

        def fn(anchor, label, cls_pred):
            # anchor (1,N,4) corner; label (B,M,5) [cls,x1,y1,x2,y2], pad=-1
            # cls_pred (B, num_class+1, N) — used for hard negative mining
            anchors = anchor.reshape(-1, 4)
            n = anchors.shape[0]

            def one(lab, cpred):
                gt_valid = lab[:, 0] >= 0
                gt_boxes = lab[:, 1:5]
                iou = _iou_corner(anchors[:, None, :],
                                  gt_boxes[None, :, :])         # (N, M)
                iou = jnp.where(gt_valid[None, :], iou, 0.0)
                best_gt = jnp.argmax(iou, axis=1)               # (N,)
                best_iou = jnp.max(iou, axis=1)
                matched = best_iou >= overlap_threshold
                # force-match: every valid GT claims its best anchor.
                # Padded GTs are routed to a sacrificial slot n so their
                # scatter can never clobber a real GT's claim on anchor 0
                best_anchor = jnp.argmax(iou, axis=0)           # (M,)
                m = gt_boxes.shape[0]
                ba = jnp.where(gt_valid, best_anchor, n)
                forced = jnp.zeros(n + 1, dtype=bool).at[ba].set(
                    True)[:n]
                forced_gt = jnp.zeros(n + 1, dtype=jnp.int32).at[ba].set(
                    jnp.arange(m, dtype=jnp.int32))[:n]
                gt_idx = jnp.where(forced, forced_gt, best_gt)
                pos = matched | forced

                g = gt_boxes[gt_idx]                            # (N,4)
                acx = (anchors[:, 0] + anchors[:, 2]) / 2
                acy = (anchors[:, 1] + anchors[:, 3]) / 2
                aw = jnp.maximum(anchors[:, 2] - anchors[:, 0], 1e-8)
                ah = jnp.maximum(anchors[:, 3] - anchors[:, 1], 1e-8)
                gcx = (g[:, 0] + g[:, 2]) / 2
                gcy = (g[:, 1] + g[:, 3]) / 2
                gw = jnp.maximum(g[:, 2] - g[:, 0], 1e-8)
                gh = jnp.maximum(g[:, 3] - g[:, 1], 1e-8)
                loc = jnp.stack([(gcx - acx) / aw / var[0],
                                 (gcy - acy) / ah / var[1],
                                 jnp.log(gw / aw) / var[2],
                                 jnp.log(gh / ah) / var[3]], axis=-1)
                loc_target = jnp.where(pos[:, None], loc, 0.0).reshape(-1)
                loc_mask = jnp.where(pos[:, None],
                                     jnp.ones((n, 4)), 0.0).reshape(-1)
                cls_target = jnp.where(
                    pos, lab[gt_idx, 0] + 1.0, 0.0)   # 0 = background
                if negative_mining_ratio > 0:
                    # hard negatives: highest background-loss negatives up
                    # to ratio×num_pos; everything else ignored
                    bg_prob = jax.nn.softmax(cpred, axis=0)[0]
                    # exclude positives AND near-misses (IoU above the
                    # mining threshold) BEFORE ranking, so ignored anchors
                    # never consume negative slots (reference
                    # multibox_target.cc candidate filtering)
                    ineligible = pos | \
                        (best_iou >= negative_mining_thresh)
                    neg_score = jnp.where(ineligible, -jnp.inf, -jnp.log(
                        jnp.maximum(bg_prob, 1e-12)))
                    num_pos = jnp.sum(pos)
                    max_neg = jnp.maximum(
                        (negative_mining_ratio * num_pos).astype(jnp.int32),
                        minimum_negative_samples)
                    rank = jnp.argsort(jnp.argsort(-neg_score))
                    keep_neg = (~ineligible) & (rank < max_neg)
                    cls_target = jnp.where(
                        pos | keep_neg, cls_target, float(ignore_label))
                return loc_target, loc_mask, cls_target
            loc_t, loc_m, cls_t = jax.vmap(one)(label, cls_pred)
            return loc_t, loc_m, cls_t
        return fn
    register_op("_contrib_MultiBoxTarget", multibox_target_maker,
                aliases=("MultiBoxTarget", "multibox_target"),
                differentiable=False)

    # ---- multibox_detection ----------------------------------------------
    def multibox_detection_maker(clip=True, threshold=0.01,
                                 background_id=0, nms_threshold=0.5,
                                 force_suppress=False,
                                 variances=(0.1, 0.1, 0.2, 0.2),
                                 nms_topk=-1):
        var = _np.asarray(_astuple(variances), dtype=_np.float32)

        def fn(cls_prob, loc_pred, anchor):
            # cls_prob (B, num_classes+1, N); loc_pred (B, N*4);
            # anchor (1, N, 4) -> out (B, N, 6) [id, score, x1,y1,x2,y2]
            anchors = anchor.reshape(-1, 4)
            n = anchors.shape[0]
            acx = (anchors[:, 0] + anchors[:, 2]) / 2
            acy = (anchors[:, 1] + anchors[:, 3]) / 2
            aw = anchors[:, 2] - anchors[:, 0]
            ah = anchors[:, 3] - anchors[:, 1]

            def one(cp, lp):
                loc = lp.reshape(n, 4)
                cx = loc[:, 0] * var[0] * aw + acx
                cy = loc[:, 1] * var[1] * ah + acy
                w = jnp.exp(loc[:, 2] * var[2]) * aw
                h = jnp.exp(loc[:, 3] * var[3]) * ah
                boxes = jnp.stack([cx - w / 2, cy - h / 2,
                                   cx + w / 2, cy + h / 2], axis=-1)
                if clip:
                    boxes = jnp.clip(boxes, 0.0, 1.0)
                # best non-background class per anchor
                fg = jnp.concatenate([cp[:background_id],
                                      cp[background_id + 1:]], axis=0)
                cls_id = jnp.argmax(fg, axis=0).astype(jnp.float32)
                score = jnp.max(fg, axis=0)
                keep = score > threshold
                out = jnp.concatenate(
                    [jnp.where(keep, cls_id, -1.0)[:, None],
                     jnp.where(keep, score, -1.0)[:, None], boxes], axis=1)
                return out
            det = jax.vmap(one)(cls_prob, loc_pred)
            nms = box_nms_maker(overlap_thresh=nms_threshold,
                                valid_thresh=0.0, topk=nms_topk,
                                coord_start=2, score_index=1, id_index=0,
                                force_suppress=force_suppress)
            return nms(det)
        return fn
    register_op("_contrib_MultiBoxDetection", multibox_detection_maker,
                aliases=("MultiBoxDetection", "multibox_detection"),
                differentiable=False)

    # ---- ROIAlign --------------------------------------------------------
    def roi_align_maker(pooled_size=(7, 7), spatial_scale=1.0,
                        sample_ratio=2, position_sensitive=False,
                        aligned=False):
        ph, pw = _astuple(pooled_size)
        sr = max(int(sample_ratio), 1)

        def fn(data, rois):
            # data (B,C,H,W); rois (R,5) [batch_idx, x1,y1,x2,y2]
            _, c, h, w = data.shape

            def one(roi):
                bidx = roi[0].astype(jnp.int32)
                img = data[bidx]                          # (C,H,W)
                off = 0.5 if aligned else 0.0
                x1 = roi[1] * spatial_scale - off
                y1 = roi[2] * spatial_scale - off
                x2 = roi[3] * spatial_scale - off
                y2 = roi[4] * spatial_scale - off
                rw = jnp.maximum(x2 - x1, 1.0 if not aligned else 1e-6)
                rh = jnp.maximum(y2 - y1, 1.0 if not aligned else 1e-6)
                bin_w = rw / pw
                bin_h = rh / ph
                # sr×sr bilinear samples per output bin, averaged
                iy = jnp.arange(ph * sr, dtype=jnp.float32)
                ix = jnp.arange(pw * sr, dtype=jnp.float32)
                sy = y1 + (iy + 0.5) * bin_h / sr         # (ph*sr,)
                sx = x1 + (ix + 0.5) * bin_w / sr         # (pw*sr,)

                def bilinear(yy, xx):
                    y0 = jnp.clip(jnp.floor(yy), 0, h - 1)
                    x0 = jnp.clip(jnp.floor(xx), 0, w - 1)
                    y1_ = jnp.clip(y0 + 1, 0, h - 1)
                    x1_ = jnp.clip(x0 + 1, 0, w - 1)
                    ly = jnp.clip(yy - y0, 0.0, 1.0)
                    lx = jnp.clip(xx - x0, 0.0, 1.0)
                    y0i, x0i = y0.astype(jnp.int32), x0.astype(jnp.int32)
                    y1i, x1i = y1_.astype(jnp.int32), x1_.astype(jnp.int32)
                    v00 = img[:, y0i, :][:, :, x0i]
                    v01 = img[:, y0i, :][:, :, x1i]
                    v10 = img[:, y1i, :][:, :, x0i]
                    v11 = img[:, y1i, :][:, :, x1i]
                    wy = ly[None, :, None]
                    wx = lx[None, None, :]
                    return (v00 * (1 - wy) * (1 - wx) +
                            v01 * (1 - wy) * wx +
                            v10 * wy * (1 - wx) + v11 * wy * wx)
                samples = bilinear(sy, sx)                # (C,ph*sr,pw*sr)
                pooled = samples.reshape(c, ph, sr, pw, sr).mean((2, 4))
                return pooled
            return jax.vmap(one)(rois)
        return fn
    register_op("_contrib_ROIAlign", roi_align_maker,
                aliases=("ROIAlign", "roi_align"))

    # ---- DeformablePSROIPooling (Deformable ConvNets; reference:
    # src/operator/contrib/deformable_psroi_pooling.cc).  Position-
    # sensitive score maps (C = output_dim*group_size^2) pooled per roi
    # bin with learned per-part offsets.  TPU-first: one vmapped
    # gather+bilinear over a static (pooled, pooled, samples) grid —
    # the same shape discipline as ROIAlign above; gradients (data,
    # rois-stop, trans) come from autodiff. ------------------------------
    def deformable_psroi_maker(spatial_scale=1.0, output_dim=1,
                               group_size=1, pooled_size=7,
                               part_size=0, sample_per_part=1,
                               trans_std=0.0, no_trans=False):
        ps = int(pooled_size)
        gs = int(group_size)
        pt = int(part_size) or ps
        sp = max(int(sample_per_part), 1)
        d_out = int(output_dim)

        def fn(data, rois, *trans_opt):
            b, c, h, w = data.shape
            trans = trans_opt[0] if trans_opt and not no_trans else None

            # bin -> position-sensitive group / offset-part index (static)
            gi = jnp.clip((jnp.arange(ps) * gs) // ps, 0, gs - 1)
            pi = jnp.clip((jnp.arange(ps) * pt) // ps, 0, pt - 1)

            def one(roi, tr):
                bidx = roi[0].astype(jnp.int32)
                # reference rounding: rois snap to the input grid with C
                # round() semantics — half-away-from-zero, which for the
                # non-negative roi coords is floor(x + 0.5); jnp.round's
                # half-to-even would shift .5-coordinate windows a pixel
                def c_round(v):
                    return jnp.floor(v + 0.5)
                x1 = c_round(roi[1]) * spatial_scale - 0.5
                y1 = c_round(roi[2]) * spatial_scale - 0.5
                x2 = (c_round(roi[3]) + 1.0) * spatial_scale - 0.5
                y2 = (c_round(roi[4]) + 1.0) * spatial_scale - 0.5
                rw = jnp.maximum(x2 - x1, 0.1)
                rh = jnp.maximum(y2 - y1, 0.1)
                bin_h, bin_w = rh / ps, rw / ps
                sub_h, sub_w = bin_h / sp, bin_w / sp

                if trans is not None:
                    # offset channel PAIRS are (x, y) per class (the
                    # reference reads trans_x at 2*class, trans_y at
                    # 2*class+1); output channel c belongs to class
                    # c // (output_dim / num_classes)
                    n_cls = tr.shape[0] // 2
                    per_cls = max(d_out // max(n_cls, 1), 1)
                    cls_of = jnp.arange(d_out) // per_cls     # (D,)
                    dx_all = tr[0::2][:, pi[:, None], pi[None, :]] \
                        * trans_std * rw                      # (ncls,ps,ps)
                    dy_all = tr[1::2][:, pi[:, None], pi[None, :]] \
                        * trans_std * rh
                    dx = dx_all[cls_of]                       # (D,ps,ps)
                    dy = dy_all[cls_of]
                else:
                    dy = jnp.zeros((d_out, ps, ps), data.dtype)
                    dx = jnp.zeros((d_out, ps, ps), data.dtype)

                iy = jnp.arange(ps, dtype=jnp.float32)
                off = jnp.arange(sp, dtype=jnp.float32)
                # (D, ps, ps, sp) sample coordinates per class and bin —
                # reference grid: wstart + iw*sub_bin (no half-sample
                # centering, unlike ROIAlign)
                ys = (y1 + iy[None, :, None, None] * bin_h
                      + dy[:, :, :, None]
                      + off[None, None, None, :] * sub_h)
                xs = (x1 + iy[None, None, :, None] * bin_w
                      + dx[:, :, :, None]
                      + off[None, None, None, :] * sub_w)
                full = (d_out, ps, ps, sp, sp)
                ysb = jnp.broadcast_to(ys[..., :, None], full)
                xsb = jnp.broadcast_to(xs[..., None, :], full)
                valid = ((ysb > -0.5) & (ysb < h - 0.5) &
                         (xsb > -0.5) & (xsb < w - 0.5))
                yc = jnp.clip(ysb, 0.0, h - 1.0)
                xc = jnp.clip(xsb, 0.0, w - 1.0)
                y0 = jnp.floor(yc)
                x0 = jnp.floor(xc)
                y0i = y0.astype(jnp.int32)
                x0i = x0.astype(jnp.int32)
                y1i = jnp.clip(y0i + 1, 0, h - 1)
                x1i = jnp.clip(x0i + 1, 0, w - 1)
                ly = (yc - y0)
                lx = (xc - x0)

                # flat channel index per (class, bin): (c*gs+gi)*gs+gj
                # — gathered DIRECTLY from (C, H, W), never materializing
                # the (D, ps, ps, H, W) per-bin map stack (which at
                # R-FCN scale would be gigabytes per roi batch)
                imgC = data[bidx]                  # (C, H, W)
                ch = ((jnp.arange(d_out)[:, None, None] * gs
                       + gi[None, :, None]) * gs
                      + gi[None, None, :])         # (D, ps, ps)
                chb = ch[:, :, :, None, None]      # (D,ps,ps,1,1)
                v00 = imgC[chb, y0i, x0i]
                v01 = imgC[chb, y0i, x1i]
                v10 = imgC[chb, y1i, x0i]
                v11 = imgC[chb, y1i, x1i]
                vals = (v00 * (1 - ly) * (1 - lx) + v01 * (1 - ly) * lx +
                        v10 * ly * (1 - lx) + v11 * ly * lx)
                vmask = valid.astype(vals.dtype)
                # count clamp makes empty bins exact zeros already
                count = jnp.maximum(vmask.sum((-1, -2)), 1.0)
                return (vals * vmask).sum((-1, -2)) / count  # (D,ps,ps)

            if trans is not None:
                return jax.vmap(one)(rois, trans)
            dummy = jnp.zeros((rois.shape[0],), data.dtype)
            return jax.vmap(lambda r, _:
                            one(r, None))(rois, dummy)
        return fn
    register_op("_contrib_DeformablePSROIPooling",
                deformable_psroi_maker,
                aliases=("DeformablePSROIPooling",))

    # ---- ROIPooling (legacy top-level op) --------------------------------
    def roi_pooling_maker(pooled_size=(7, 7), spatial_scale=1.0):
        ph, pw = _astuple(pooled_size)

        def fn(data, rois):
            _, c, h, w = data.shape

            def one(roi):
                bidx = roi[0].astype(jnp.int32)
                img = data[bidx]
                x1 = jnp.round(roi[1] * spatial_scale)
                y1 = jnp.round(roi[2] * spatial_scale)
                x2 = jnp.round(roi[3] * spatial_scale)
                y2 = jnp.round(roi[4] * spatial_scale)
                rw = jnp.maximum(x2 - x1 + 1, 1.0)
                rh = jnp.maximum(y2 - y1 + 1, 1.0)
                ys = jnp.arange(h, dtype=jnp.float32)
                xs = jnp.arange(w, dtype=jnp.float32)

                def bin_val(py, px):
                    by0 = y1 + jnp.floor(py * rh / ph)
                    by1 = y1 + jnp.ceil((py + 1) * rh / ph)
                    bx0 = x1 + jnp.floor(px * rw / pw)
                    bx1 = x1 + jnp.ceil((px + 1) * rw / pw)
                    my = (ys >= by0) & (ys < jnp.maximum(by1, by0 + 1))
                    mx = (xs >= bx0) & (xs < jnp.maximum(bx1, bx0 + 1))
                    mask = my[:, None] & mx[None, :]
                    neg = jnp.full((h, w), -jnp.inf)
                    return jnp.max(jnp.where(mask[None], img, neg),
                                   axis=(1, 2))
                pys, pxs = jnp.meshgrid(jnp.arange(ph), jnp.arange(pw),
                                        indexing="ij")
                vals = jax.vmap(jax.vmap(bin_val))(
                    pys.astype(jnp.float32), pxs.astype(jnp.float32))
                return jnp.transpose(vals, (2, 0, 1))     # (C,ph,pw)
            return jax.vmap(one)(rois)
        return fn
    register_op("ROIPooling", roi_pooling_maker)

    # ---- RPN proposal (reference: src/operator/contrib/proposal.cc) ------
    def _decode_deltas(anchors, deltas):  # noqa: F811 (module fn below)
        """Standard RCNN box transform: anchors+(dx,dy,dw,dh) -> corners."""
        aw = anchors[:, 2] - anchors[:, 0] + 1.0
        ah = anchors[:, 3] - anchors[:, 1] + 1.0
        ax = anchors[:, 0] + 0.5 * (aw - 1.0)
        ay = anchors[:, 1] + 0.5 * (ah - 1.0)
        dx, dy, dw, dh = (deltas[:, 0], deltas[:, 1], deltas[:, 2],
                          deltas[:, 3])
        cx = dx * aw + ax
        cy = dy * ah + ay
        w = jnp.exp(dw) * aw
        h = jnp.exp(dh) * ah
        return jnp.stack([cx - 0.5 * (w - 1.0), cy - 0.5 * (h - 1.0),
                          cx + 0.5 * (w - 1.0), cy + 0.5 * (h - 1.0)],
                         axis=1)

    _base_anchors = base_anchors  # module-level helper (shared with rcnn)

    def proposal_maker(rpn_pre_nms_top_n=6000, rpn_post_nms_top_n=300,
                       threshold=0.7, rpn_min_size=16,
                       scales=(4.0, 8.0, 16.0, 32.0), ratios=(0.5, 1.0, 2.0),
                       feature_stride=16, output_score=False,
                       iou_loss=False):
        scales = _astuple(scales)
        ratios = _astuple(ratios)

        def fn(cls_prob, bbox_pred, im_info):
            # cls_prob (B, 2A, H, W) — [A:] are foreground scores;
            # bbox_pred (B, 4A, H, W); im_info (B, 3) = (h, w, scale)
            B, _, H, W = cls_prob.shape
            base = jnp.asarray(_base_anchors(scales, ratios))  # (A,4)
            A = base.shape[0]
            sx = jnp.arange(W, dtype=jnp.float32) * feature_stride
            sy = jnp.arange(H, dtype=jnp.float32) * feature_stride
            shift = jnp.stack(
                jnp.meshgrid(sx, sy, indexing="xy"), axis=-1)   # (H,W,2)
            shift = jnp.tile(shift, (1, 1, 2))                  # (H,W,4)
            anchors = (shift[:, :, None, :] + base).reshape(-1, 4)

            def one(cls, deltas, info):
                scores = jnp.transpose(cls[A:], (1, 2, 0)).reshape(-1)
                d = deltas.reshape(A, 4, H, W)
                d = jnp.transpose(d, (2, 3, 0, 1)).reshape(-1, 4)
                boxes = _decode_deltas(anchors, d)
                boxes = jnp.stack([
                    jnp.clip(boxes[:, 0], 0, info[1] - 1.0),
                    jnp.clip(boxes[:, 1], 0, info[0] - 1.0),
                    jnp.clip(boxes[:, 2], 0, info[1] - 1.0),
                    jnp.clip(boxes[:, 3], 0, info[0] - 1.0)], axis=1)
                ws = boxes[:, 2] - boxes[:, 0] + 1.0
                hs = boxes[:, 3] - boxes[:, 1] + 1.0
                min_sz = rpn_min_size * info[2]
                valid = (ws >= min_sz) & (hs >= min_sz)
                scores = jnp.where(valid, scores, -jnp.inf)

                k = min(int(rpn_pre_nms_top_n), H * W * A)
                order = jnp.argsort(scores)[::-1][:k]
                cboxes = boxes[order]
                cscores = scores[order]
                iou = _iou_corner(cboxes[:, None, :], cboxes[None, :, :])

                def step(keep, i):
                    kill = (iou[i] > threshold) & \
                        (jnp.arange(k) > i) & keep[i]
                    return keep & ~kill, None
                keep, _ = lax.scan(step, cscores > -jnp.inf,
                                   jnp.arange(k))
                fscores = jnp.where(keep, cscores, -jnp.inf)
                p = min(int(rpn_post_nms_top_n), k)
                sel = jnp.argsort(fscores)[::-1][:p]
                out_boxes = cboxes[sel]
                out_scores = jnp.where(jnp.isfinite(fscores[sel]),
                                       fscores[sel], 0.0)
                live = jnp.isfinite(fscores[sel])[:, None]
                return jnp.where(live, out_boxes, 0.0), \
                    out_scores[:, None]
            boxes, scores = jax.vmap(one)(cls_prob, bbox_pred, im_info)
            p = boxes.shape[1]
            bidx = jnp.repeat(jnp.arange(B, dtype=boxes.dtype), p)
            rois = jnp.concatenate(
                [bidx[:, None], boxes.reshape(-1, 4)], axis=1)
            if output_score:
                return (rois, scores.reshape(-1, 1))
            return rois
        return fn
    register_op("_contrib_Proposal", proposal_maker,
                aliases=("_contrib_MultiProposal", "Proposal"))

    # ---- bounding_box.cc long tail: encode/decode/matching ---------------
    def box_decode_maker(std0=1.0, std1=1.0, std2=1.0, std3=1.0, clip=-1.0,
                         format="corner"):
        def fn(data, anchors):
            # data (B,N,4) deltas; anchors (1,N,4) in `format`
            a = anchors
            if format == "corner":
                wh = a[..., 2:] - a[..., :2]
                ctr = a[..., :2] + 0.5 * wh
            else:
                ctr, wh = a[..., :2], a[..., 2:]
            std = jnp.asarray([std0, std1, std2, std3], data.dtype)
            d = data * std
            xy = d[..., :2] * wh + ctr
            dwh = d[..., 2:]
            if clip > 0:
                # reference clips the dw/dh DELTA pre-exp (bounding_box.cc)
                dwh = jnp.minimum(dwh, clip)
            new_wh = jnp.exp(dwh) * wh
            half = 0.5 * new_wh
            return jnp.concatenate([xy - half, xy + half], axis=-1)
        return fn
    register_op("_contrib_box_decode", box_decode_maker,
                aliases=("box_decode",))

    def box_encode_maker(**_ignored):
        def fn(samples, matches, anchors, refs, means, stds):
            # samples (B,N) in {+1 pos, -1 neg/ignore}; matches (B,N) gt
            # index; anchors (B,N,4) corner; refs (B,M,4) corner gt;
            # means/stds (4,) — returns (targets (B,N,4), masks (B,N,4))
            gt = jnp.take_along_axis(
                refs, matches.astype(jnp.int32)[..., None]
                .clip(0, refs.shape[1] - 1).repeat(4, axis=-1), axis=1)
            awh = anchors[..., 2:] - anchors[..., :2]
            actr = anchors[..., :2] + 0.5 * awh
            gwh = gt[..., 2:] - gt[..., :2]
            gctr = gt[..., :2] + 0.5 * gwh
            eps = 1e-8
            t_xy = (gctr - actr) / (awh + eps)
            t_wh = jnp.log((gwh + eps) / (awh + eps))
            t = jnp.concatenate([t_xy, t_wh], axis=-1)
            t = (t - means.reshape(1, 1, 4)) / stds.reshape(1, 1, 4)
            mask = (samples > 0.5)[..., None].astype(t.dtype)
            return (t * mask, jnp.broadcast_to(mask, t.shape))
        return fn
    register_op("_contrib_box_encode", box_encode_maker,
                aliases=("box_encode",))

    def bipartite_matching_maker(threshold=0.5, is_ascend=False, topk=-1):
        def fn(data):
            # data (B,N,M) pairwise scores; greedy bipartite matching.
            # Returns (row_match (B,N) col idx or -1, col_match (B,M)).
            B, N, M = data.shape
            steps = min(N, M) if topk <= 0 else min(topk, min(N, M))
            sgn = -1.0 if is_ascend else 1.0

            def one(s):
                s = s * sgn  # maximize
                thr = threshold * sgn

                def step(carry, _):
                    s_cur, rows, cols = carry
                    flat = jnp.argmax(s_cur)
                    i, j = flat // M, flat % M
                    ok = s_cur[i, j] >= thr
                    rows = lax.cond(
                        ok, lambda r: r.at[i].set(j.astype(r.dtype)),
                        lambda r: r, rows)
                    cols = lax.cond(
                        ok, lambda c: c.at[j].set(i.astype(c.dtype)),
                        lambda c: c, cols)
                    s_cur = s_cur.at[i, :].set(-jnp.inf)
                    s_cur = s_cur.at[:, j].set(-jnp.inf)
                    return (s_cur, rows, cols), None
                init = (s, jnp.full((N,), -1.0, data.dtype),
                        jnp.full((M,), -1.0, data.dtype))
                (_, rows, cols), _ = lax.scan(step, init,
                                              jnp.arange(steps))
                return rows, cols
            rows, cols = jax.vmap(one)(data)
            return (rows, cols)
        return fn
    register_op("_contrib_bipartite_matching", bipartite_matching_maker,
                aliases=("bipartite_matching",))


def base_anchors(scales, ratios, base_size=16.0):
    """(A,4) corner anchors centered on a base_size cell (numpy,
    trace-time constant; reference: proposal.cc GenerateAnchors)."""
    out = []
    cx = cy = (base_size - 1.0) / 2.0
    area = base_size * base_size
    for r in ratios:
        w = _np.round(_np.sqrt(area / r))
        h = _np.round(w * r)
        for s in scales:
            ws, hs = w * s, h * s
            out.append([cx - 0.5 * (ws - 1), cy - 0.5 * (hs - 1),
                        cx + 0.5 * (ws - 1), cy + 0.5 * (hs - 1)])
    return _np.array(out, _np.float32)


def rpn_anchors(height, width, stride, scales, ratios):
    """All (H*W*A, 4) corner anchors of a feature map, (H, W, A)-ordered —
    the exact enumeration the Proposal op uses."""
    base = base_anchors(tuple(scales), tuple(ratios))
    sx = _np.arange(width, dtype=_np.float32) * stride
    sy = _np.arange(height, dtype=_np.float32) * stride
    gx, gy = _np.meshgrid(sx, sy)                   # (H,W)
    shift = _np.stack([gx, gy, gx, gy], axis=-1)    # (H,W,4)
    return (shift[:, :, None, :] + base).reshape(-1, 4)


def _astuple(v):
    if isinstance(v, (list, tuple)):
        return tuple(v)
    if isinstance(v, str):
        return tuple(float(x) for x in
                     v.strip("()[] ").split(",") if x.strip())
    return (v,)


def _register_misc():
    """Long-tail contrib ops (reference: src/operator/correlation.cc,
    src/operator/contrib/index_copy.cc, src/operator/contrib/
    count_sketch.cc — SURVEY.md §2.2 long-tail row)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    # ---- Correlation (FlowNet cost volume) -------------------------------
    def correlation_maker(kernel_size=1, max_displacement=1, stride1=1,
                          stride2=1, pad_size=0, is_multiply=True):
        k = int(kernel_size)
        md = int(max_displacement)
        s1, s2, pad = int(stride1), int(stride2), int(pad_size)
        rad = (k - 1) // 2
        border = md + rad
        grid_rad = md // s2           # displacements per side
        D = 2 * grid_rad + 1

        def fn(data1, data2):
            # out[d][n,y,x] = mean over kxk window and channels of
            # p1 * shifted(p2) — ONE lax.scan over the D*D displacement
            # grid (graph size independent of D; FlowNet's D=21 would
            # otherwise unroll 441 ways), with the window sum as a
            # reduce_window per scan step.
            n, c, h, w = data1.shape
            p1 = jnp.pad(data1, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
            p2 = jnp.pad(data2, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
            ph, pw = h + 2 * pad, w + 2 * pad
            out_h = int(_np.ceil((ph - 2 * border) / float(s1)))
            out_w = int(_np.ceil((pw - 2 * border) / float(s1)))
            # static patch of data1 covering every window position
            eh = (out_h - 1) * s1 + k
            ew = (out_w - 1) * s1 + k
            lo = border - rad
            a = lax.slice(p1, (0, 0, lo, lo), (n, c, lo + eh, lo + ew))

            offs = jnp.asarray(
                [(dy * s2, dx * s2)
                 for dy in range(-grid_rad, grid_rad + 1)
                 for dx in range(-grid_rad, grid_rad + 1)], jnp.int32)

            def step(_, off):
                b = lax.dynamic_slice(
                    p2, (0, 0, lo + off[0], lo + off[1]), (n, c, eh, ew))
                q = a * b if is_multiply else jnp.abs(a - b)
                summed = lax.reduce_window(
                    q, jnp.asarray(0, q.dtype), lax.add,
                    (1, 1, k, k), (1, 1, s1, s1), "valid")
                return None, jnp.sum(summed, axis=1) / float(k * k * c)

            _, maps = lax.scan(step, None, offs)   # (D*D, n, oh, ow)
            return jnp.transpose(maps, (1, 0, 2, 3))
        return fn
    register_op("Correlation", correlation_maker,
                aliases=("correlation",))

    # ---- index_copy ------------------------------------------------------
    def index_copy_maker():
        def fn(old, idx, new):
            return old.at[idx.astype(jnp.int32)].set(new)
        return fn
    register_op("_contrib_index_copy", index_copy_maker,
                aliases=("index_copy",))

    # ---- count_sketch ----------------------------------------------------
    def count_sketch_maker(out_dim=None, processing_batch_size=32):
        if out_dim is None:
            from ..base import MXNetError
            raise MXNetError("count_sketch requires out_dim")
        od = int(out_dim)

        def fn(data, h, s):
            # h: target bucket per input dim; s: +-1 signs
            hh = h.reshape(-1).astype(jnp.int32)
            ss = s.reshape(-1).astype(data.dtype)
            signed = data * ss[None, :]
            out = jnp.zeros((data.shape[0], od), data.dtype)
            return out.at[:, hh].add(signed)
        return fn
    register_op("_contrib_count_sketch", count_sketch_maker,
                aliases=("count_sketch",), differentiable=False)


def _register_round3b():
    """Late round-3 contrib additions: adaptive pooling, position-sensitive
    ROI pooling (R-FCN, src/operator/contrib/psroi_pooling.cc), deformable
    convolution (src/operator/contrib/deformable_convolution.cc), index_array,
    allclose.  TPU-first: deformable conv is a bilinear-gather im2col followed
    by one MXU matmul; PSROIPooling is a vmapped static-shape gather."""
    import jax
    import jax.numpy as jnp

    # ---- AdaptiveAvgPooling2D -------------------------------------------
    def adaptive_avg_pool_maker(output_size=1):
        if isinstance(output_size, int):
            oh = ow = int(output_size)
        else:
            oh, ow = (int(s) for s in output_size)

        def fn(data):
            n, c, h, w = data.shape
            # static per-output-cell ranges (numpy loop unrolls at trace
            # time; output sizes are small by construction)
            rows = []
            for i in range(oh):
                y0, y1 = (i * h) // oh, -(-((i + 1) * h) // oh)
                cols = []
                for j in range(ow):
                    x0, x1 = (j * w) // ow, -(-((j + 1) * w) // ow)
                    cols.append(jnp.mean(data[:, :, y0:y1, x0:x1],
                                         axis=(2, 3)))
                rows.append(jnp.stack(cols, axis=-1))
            return jnp.stack(rows, axis=-2)
        return fn
    register_op("_contrib_AdaptiveAvgPooling2D", adaptive_avg_pool_maker,
                aliases=("AdaptiveAvgPooling2D",))

    # ---- PSROIPooling (R-FCN) -------------------------------------------
    # data channels laid out (output_dim, group_size, group_size); each
    # output bin (i,j) reads its own score-map channel.
    def psroi_pooling_maker(spatial_scale=1.0, output_dim=1, pooled_size=7,
                            group_size=0):
        ps = int(pooled_size)
        gs = int(group_size) if group_size else ps
        sr = 2   # fixed sample grid per bin (static shapes for XLA)

        def fn(data, rois):
            _, c, h, w = data.shape

            def one(roi):
                bidx = roi[0].astype(jnp.int32)
                img = data[bidx]
                x1 = roi[1] * spatial_scale
                y1 = roi[2] * spatial_scale
                x2 = roi[3] * spatial_scale
                y2 = roi[4] * spatial_scale
                rw = jnp.maximum(x2 - x1, 0.1)
                rh = jnp.maximum(y2 - y1, 0.1)
                iy = jnp.arange(ps * sr, dtype=jnp.float32)
                ix = jnp.arange(ps * sr, dtype=jnp.float32)
                sy = y1 + (iy + 0.5) * rh / (ps * sr)
                sx = x1 + (ix + 0.5) * rw / (ps * sr)
                yi = jnp.clip(jnp.floor(sy), 0, h - 1).astype(jnp.int32)
                xi = jnp.clip(jnp.floor(sx), 0, w - 1).astype(jnp.int32)
                # grid of sampled values for every channel: (C, ps*sr, ps*sr)
                sampled = img[:, yi, :][:, :, xi]
                pooled = sampled.reshape(c, ps, sr, ps, sr).mean((2, 4))
                # position-sensitive channel selection
                pooled = pooled.reshape(output_dim, gs, gs, ps, ps)
                gi = (jnp.arange(ps) * gs) // ps
                sel = pooled[:, gi[:, None], gi[None, :],
                             jnp.arange(ps)[:, None],
                             jnp.arange(ps)[None, :]]
                return sel                                 # (output_dim,ps,ps)
            return jax.vmap(one)(rois)
        return fn
    register_op("_contrib_PSROIPooling", psroi_pooling_maker,
                aliases=("PSROIPooling",))

    # ---- DeformableConvolution ------------------------------------------
    # Bilinear-gather im2col with learned offsets, then one matmul (the
    # FLOPs ride the MXU; the gather is the only scatter/gather stage).
    def deformable_conv_maker(kernel=(3, 3), stride=(1, 1), dilate=(1, 1),
                              pad=(0, 0), num_filter=1, num_group=1,
                              num_deformable_group=1, no_bias=False,
                              workspace=0, layout=None):
        kh, kw = _astuple(kernel)
        sh, sw = _astuple(stride)
        dh, dw = _astuple(dilate)
        ph, pw = _astuple(pad)
        dg = int(num_deformable_group)

        def fn(data, offset, weight, *maybe_bias):
            n, c, h, w = data.shape
            oh = (h + 2 * ph - (dh * (kh - 1) + 1)) // sh + 1
            ow = (w + 2 * pw - (dw * (kw - 1) + 1)) // sw + 1
            K = kh * kw

            # base sampling grid: (K, OH, OW)
            ky, kx = jnp.meshgrid(jnp.arange(kh), jnp.arange(kw),
                                  indexing="ij")
            base_y = (jnp.arange(oh)[None, :, None] * sh - ph
                      + (ky.reshape(-1) * dh)[:, None, None])
            base_x = (jnp.arange(ow)[None, None, :] * sw - pw
                      + (kx.reshape(-1) * dw)[:, None, None])
            base_y = jnp.broadcast_to(base_y, (K, oh, ow)).astype(jnp.float32)
            base_x = jnp.broadcast_to(base_x, (K, oh, ow)).astype(jnp.float32)

            def one(img, off):
                # img (C,H,W); off (2*dg*K, OH, OW) ordered
                # (dg, K, [y,x], OH, OW) as in the reference layout
                off = off.reshape(dg, K, 2, oh, ow)

                def sample_group(off_g, img_g):
                    # off_g (K,2,OH,OW); img_g (Cg,H,W)
                    yy = base_y + off_g[:, 0]
                    xx = base_x + off_g[:, 1]
                    y0 = jnp.floor(yy)
                    x0 = jnp.floor(xx)
                    ly = yy - y0
                    lx = xx - x0
                    # zero-pad out-of-range samples via validity masks
                    def gather(yi, xi):
                        valid = ((yi >= 0) & (yi < h) &
                                 (xi >= 0) & (xi < w))
                        yc = jnp.clip(yi, 0, h - 1).astype(jnp.int32)
                        xc = jnp.clip(xi, 0, w - 1).astype(jnp.int32)
                        vals = img_g[:, yc, xc]        # (Cg,K,OH,OW)
                        return vals * valid[None].astype(img_g.dtype)
                    v00 = gather(y0, x0)
                    v01 = gather(y0, x0 + 1)
                    v10 = gather(y0 + 1, x0)
                    v11 = gather(y0 + 1, x0 + 1)
                    wy = ly[None]
                    wx = lx[None]
                    return (v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx
                            + v10 * wy * (1 - wx) + v11 * wy * wx)

                cg = c // dg
                cols = [sample_group(off[g_], img[g_ * cg:(g_ + 1) * cg])
                        for g_ in range(dg)]
                return jnp.concatenate(cols, axis=0)   # (C,K,OH,OW)

            col = jax.vmap(one)(data, offset)          # (N,C,K,OH,OW)
            wmat = weight.reshape(num_filter, -1)      # (O, C/g*K)
            g = int(num_group)
            if g == 1:
                out = jnp.einsum("ok,nkhw->nohw", wmat,
                                 col.reshape(n, c * K, oh, ow))
            else:
                cpg, opg = c // g, num_filter // g
                colg = col.reshape(n, g, cpg * K, oh, ow)
                wg = wmat.reshape(g, opg, cpg * K)
                out = jnp.einsum("gok,ngkhw->ngohw", wg, colg).reshape(
                    n, num_filter, oh, ow)
            if maybe_bias and not no_bias:
                out = out + maybe_bias[0][None, :, None, None]
            return out
        return fn
    register_op("_contrib_DeformableConvolution", deformable_conv_maker,
                aliases=("DeformableConvolution",))

    # ---- index_array -----------------------------------------------------
    def index_array_maker(axes=None):
        def fn(data):
            sel = tuple(axes) if axes is not None else \
                tuple(range(data.ndim))
            grids = jnp.meshgrid(*[jnp.arange(s) for s in data.shape],
                                 indexing="ij")
            # int32 (reference returns int64; jax truncates int64 to int32
            # under the default config, warning on every call)
            return jnp.stack([grids[a] for a in sel],
                             axis=-1).astype(jnp.int32)
        return fn
    register_op("_contrib_index_array", index_array_maker,
                aliases=("index_array",), differentiable=False)

    # ---- flash attention (kernels/flash_attention.py Pallas kernel) ------
    # DIFFERENTIABLE: the Pallas forward carries a custom VJP that
    # differentiates an equivalent chunked jnp formulation, so neither
    # direction materializes the (Lq, Lk) score matrix.  Eager dispatch
    # (use_jit=False) keeps the Mosaic-vs-interpret choice keyed on the
    # data's actual device.
    def flash_attention_maker(causal=False, scale=None):
        from ..kernels import flash_attention as _fa

        def fn(q, k, v, valid_len=None):
            # optional 4th input: per-sequence key-padding lengths
            return _fa(q, k, v, causal=causal, scale=scale,
                       valid_len=valid_len)
        return fn

    def flash_attention_vjp_maker(causal=False, scale=None):
        # recording path: jax.vjp traces the op, so the Mosaic-vs-
        # interpret choice must be made HERE on the concrete arrays,
        # before tracing (the multi_sgd static-kwarg rule)
        from ..kernels import flash_attention as _fa
        from ..kernels.flash_attention import _interpret as _interp

        def wrapper(q, k, v, valid_len=None):
            interp = _interp(q)
            if valid_len is None:
                return jax.vjp(
                    lambda a, b, c: _fa(a, b, c, causal=causal,
                                        scale=scale, interpret=interp),
                    q, k, v)
            out, vjp3 = jax.vjp(
                lambda a, b, c: _fa(a, b, c, causal=causal, scale=scale,
                                    interpret=interp, valid_len=valid_len),
                q, k, v)

            def vjp4(g):
                # the tape sees 4 parents; valid_len is a mask, zero grad
                dq, dk, dv = vjp3(g)
                return dq, dk, dv, jnp.zeros_like(valid_len)
            return out, vjp4
        return wrapper
    register_op("_contrib_flash_attention", flash_attention_maker,
                aliases=("flash_attention",), use_jit=False,
                vjp_maker=flash_attention_vjp_maker)

    # ---- allclose --------------------------------------------------------
    def allclose_maker(rtol=1e-5, atol=1e-8, equal_nan=False):
        def fn(a, b):
            return jnp.allclose(a, b, rtol=rtol, atol=atol,
                                equal_nan=equal_nan).astype(
                jnp.float32).reshape(1)
        return fn
    register_op("_contrib_allclose", allclose_maker,
                aliases=("allclose",), differentiable=False)

    # ---- getnnz (src/operator/contrib/nnz.cc; csr there, storage-generic
    # here: the count is the same question on any layout) ------------------
    def getnnz_maker(axis=None):
        from ..base import jax_compute_dtype

        def fn(data):
            # int64 counts under enable_large_tensor(), int32 otherwise
            # (the documented contract, applied without jax's warning)
            return jnp.sum((data != 0).astype(jax_compute_dtype("int64")),
                           axis=axis)
        return fn
    register_op("_contrib_getnnz", getnnz_maker, differentiable=False)

    # ---- div_sqrt_dim (src/operator/contrib/transformer.cc): divide by
    # sqrt of the last dim — the attention-scaling helper -----------------
    def div_sqrt_dim_maker():
        def fn(data):
            return data / jnp.sqrt(jnp.asarray(data.shape[-1],
                                               data.dtype))
        return fn
    register_op("_contrib_div_sqrt_dim", div_sqrt_dim_maker,
                aliases=("div_sqrt_dim",))

    # ---- _sample_unique_zipfian (sample_op.cc, the sampled-softmax
    # candidate sampler): per batch row, n draws from Zipf(range_max) with
    # rejection-dedup; returns (samples, num_tries).  Host-side sampling
    # by design: data-dependent rejection loops do not belong under trace
    # (same stance as boolean_mask), and candidates feed CPU-side lookup
    # anyway ---------------------------------------------------------------
    def sample_unique_zipfian_maker(range_max=None, shape=None, ctx=None):
        import numpy as onp

        from ..base import MXNetError
        rm = int(range_max)
        shp = tuple(int(s) for s in shape)
        if shp[1] > rm:
            raise MXNetError(
                f"_sample_unique_zipfian: cannot draw {shp[1]} unique "
                f"candidates from range_max={rm}")
        dev = None
        if ctx is not None:
            from ..context import Context
            dev = (ctx if isinstance(ctx, Context)
                   else Context.from_str(ctx)).device

        def fn():
            # seeded from the library key stream so mx.random.seed()
            # covers this sampler like every other random op
            from .. import random as _grandom
            key_bits = onp.asarray(_grandom.next_key()).ravel()
            rng = onp.random.default_rng(key_bits.astype(onp.uint32))
            out = onp.empty(shp, onp.int64)
            tries = onp.empty(shp[0], onp.int64)
            log_rm1 = onp.log(rm + 1.0)
            for b in range(shp[0]):
                seen, t = [], 0
                seen_set = set()
                while len(seen) < shp[1]:
                    # inverse-CDF zipfian: floor(exp(u*log(rm+1)))-1
                    cand = int(onp.exp(rng.random() * log_rm1)) - 1
                    cand = min(max(cand, 0), rm - 1)
                    t += 1
                    if cand not in seen_set:
                        seen_set.add(cand)
                        seen.append(cand)
                out[b] = seen
                tries[b] = t
            o, tr = jnp.asarray(out), jnp.asarray(tries)
            if dev is not None:
                o = jax.device_put(o, dev)
                tr = jax.device_put(tr, dev)
            return o, tr
        return fn
    register_op("_sample_unique_zipfian", sample_unique_zipfian_maker,
                differentiable=False, use_jit=False)

    # ---- backward_gradientmultiplier (gradient_multiplier_op.cc): the
    # explicit backward of gradientmultiplier — a scalar scale ------------
    def backward_gradmult_maker(scalar=1.0):
        def fn(x):
            return x * jnp.asarray(scalar, x.dtype)
        return fn
    register_op("_contrib_backward_gradientmultiplier",
                backward_gradmult_maker)


_register()
_register_misc()
_register_round3b()
