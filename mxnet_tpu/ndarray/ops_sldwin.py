"""Sliding-window (banded) attention operators.

Reference parity: src/operator/contrib/transformer.cc
``_sldwin_atten_score`` / ``_sldwin_atten_mask_like`` /
``_sldwin_atten_context`` — the Longformer-style banded attention
primitives: the (L, L) score matrix never materializes; only the
``2w+1`` (symmetric) or ``w+1`` (one-sided) band per query does.

Layouts follow the reference: query/key/value are (B, L, H, D);
``dilation`` is an (H,)-shaped integer TENSOR input (per-head dilation);
scores/masks are (B, L, H, W) with W = 2w+1 or w+1.

TPU-first design: the band is a static offset enumeration — a gather of
the W dilated key/value rows per position followed by one einsum, so XLA
sees fixed-shape batched matmuls for the MXU and the band tensors
(B, H, L, W, D) stay O(L·W·D), never O(L²).  Gradients come from
autodiff through gather+einsum (the reference hand-writes backward
kernels).  Out-of-range band slots are exact zeros in the score op and
0 in the mask — matching the reference's zero-filled band convention.
"""
from __future__ import annotations

from .register import register_op


def _offsets(w: int, symmetric: bool):
    import numpy as np
    return (np.arange(2 * w + 1) - w) if symmetric else \
        (np.arange(w + 1) - w)


def _band_gather(jnp, x_blhd, idx_hlw, valid_hlw):
    """Gather (B, L, H, D) rows into the (B, H, L, W, D) band."""
    b, l, h, d = x_blhd.shape
    w = idx_hlw.shape[-1]
    xt = jnp.transpose(x_blhd, (0, 2, 1, 3))          # (B, H, L, D)
    idx = jnp.broadcast_to(
        idx_hlw.reshape(1, h, l * w, 1), (b, h, l * w, d))
    g = jnp.take_along_axis(xt, idx, axis=2).reshape(b, h, l, w, d)
    return g * valid_hlw.reshape(1, h, l, w, 1).astype(g.dtype)


def _band_index(jnp, l, dilation, w: int, symmetric: bool):
    """(H, L, W) absolute key index per band slot + in-range validity."""
    offs = jnp.asarray(_offsets(w, symmetric))         # (W,)
    dil = dilation.astype(jnp.int32).reshape(-1, 1, 1)  # (H, 1, 1)
    pos = jnp.arange(l).reshape(1, -1, 1)               # (1, L, 1)
    idx = pos + offs.reshape(1, 1, -1) * dil            # (H, L, W)
    valid = (idx >= 0) & (idx < l)
    return jnp.clip(idx, 0, l - 1), valid


def _register():
    import jax.numpy as jnp

    def score_maker(w=1, symmetric=True):
        w = int(w)

        def fn(query, key, dilation):
            b, l, h, d = query.shape
            idx, valid = _band_index(jnp, l, dilation, w, bool(symmetric))
            kband = _band_gather(jnp, key, idx, valid)   # (B,H,L,W,D)
            qt = jnp.transpose(query, (0, 2, 1, 3))      # (B,H,L,D)
            s = jnp.einsum("bhld,bhlwd->bhlw", qt, kband)
            return jnp.transpose(s, (0, 2, 1, 3))        # (B,L,H,W)
        return fn
    register_op("_contrib_sldwin_atten_score", score_maker,
                aliases=("_sldwin_atten_score",))

    def mask_like_maker(w=1, symmetric=True):
        w = int(w)

        def fn(score, dilation, valid_length):
            b, l, h, _ = score.shape
            idx, valid = _band_index(jnp, l, dilation, w, bool(symmetric))
            vl = valid_length.reshape(-1, 1, 1, 1).astype(jnp.int32)
            # a slot is live when the KEY row is in range and unpadded
            # AND the query row itself is unpadded
            key_ok = valid[None] & (idx[None] < vl)
            q_ok = (jnp.arange(l).reshape(1, 1, -1, 1) < vl)
            m = (key_ok & q_ok).astype(score.dtype)      # (B,H,L,W)
            return jnp.transpose(m, (0, 2, 1, 3))        # (B,L,H,W)
        return fn
    register_op("_contrib_sldwin_atten_mask_like", mask_like_maker,
                aliases=("_sldwin_atten_mask_like",),
                differentiable=False)

    def context_maker(w=1, symmetric=True):
        w = int(w)

        def fn(score, value, dilation):
            b, l, h, _ = score.shape
            idx, valid = _band_index(jnp, l, dilation, w, bool(symmetric))
            vband = _band_gather(jnp, value, idx, valid)  # (B,H,L,W,D)
            st = jnp.transpose(score, (0, 2, 1, 3))       # (B,H,L,W)
            c = jnp.einsum("bhlw,bhlwd->bhld", st, vband)
            return jnp.transpose(c, (0, 2, 1, 3))         # (B,L,H,D)
        return fn
    register_op("_contrib_sldwin_atten_context", context_maker,
                aliases=("_sldwin_atten_context",))


_register()
